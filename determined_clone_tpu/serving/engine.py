"""Iteration-level continuous batching over the paged KV cache.

The Orca-style scheduler loop at the heart of ``dct serve``: requests
enter a bounded thread-safe queue; every scheduler iteration first
admits queued requests into the running batch (one bucketed prefill call
for the newcomers), then runs ONE decode step for every active sequence
(one bucketed T=1 call), retiring finished sequences immediately so
their pool blocks and batch slots free up for the next iteration. No
sequence ever waits for a stranger's completion — the property that
makes continuous batching beat run-to-completion batching on tokens/sec
under load (bench.py's ``serving`` section measures exactly that, with
:meth:`InferenceEngine.run_static` as the same-program baseline).

Compile discipline: all device work funnels through ONE jitted
``forward_paged`` whose shapes are padded to :class:`BucketSpec` buckets,
so the XLA program count is bounded by ``buckets.program_budget`` for
the lifetime of the engine — asserted by the tier-1 compile-discipline
test via :meth:`InferenceEngine.programs_compiled` (the PR 2 retrace
probe).

Backpressure: a full queue raises :class:`ServerOverloaded`;
:meth:`InferenceEngine.submit_with_backoff` wraps admission in the
repo-standard ``RetryPolicy`` (utils/retry.py) so clients back off with
full jitter instead of hammering. KV-pool exhaustion is *deferred*
admission (requests wait in queue until blocks free), never mid-decode
eviction.

Three raw-speed optimisations ride on the same loop, each individually
optional and all preserving the bit-identical-greedy-parity pin
(docs/serving.md has the full protocols):

- **copy-on-write prefix sharing** (``prefix_cache=True``): admission
  content-hashes the prompt's blocks against the
  :class:`~determined_clone_tpu.serving.kv_cache.PrefixCache` and
  aliases resident blocks through the block table, so prefill skips the
  shared prefix entirely; the one block a new owner could ever write (the
  block holding the re-scored last prompt token) is COW-forked first.
- **draft-model speculative decoding** (``speculative_k=k`` plus a tiny
  draft GPT): the draft proposes k tokens per iteration with T=1 calls,
  the target scores all of them in ONE k+1-token verify call
  (``forward_paged_logits``), and the accepted-prefix rule emits exactly
  the tokens one-at-a-time greedy decode would — a disagreeing draft
  costs speed, never correctness.
- **chunked prefill** (``chunk_prefill_len=n``): long prompts prefill n
  tokens per scheduler iteration, interleaved with decode steps, so one
  huge prompt can't head-of-line-block every running sequence's next
  token (and prompts longer than the largest prefill bucket become
  servable at all).
"""
from __future__ import annotations

import collections
import contextlib
import dataclasses
import math
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from determined_clone_tpu import faults
from determined_clone_tpu.models import gpt
from determined_clone_tpu.serving.bucketing import BucketSpec, bucket_for
from determined_clone_tpu.serving.kv_cache import (
    BlockAllocator,
    KVCacheConfig,
    PrefixCache,
    init_kv_pools,
)
from determined_clone_tpu.serving.kv_store import (
    PrefixInventory,
    params_fingerprint,
)
from determined_clone_tpu.telemetry import MetricsRegistry
from determined_clone_tpu.utils.retry import RetryPolicy, retry_call


class ServerOverloaded(RuntimeError):
    """Admission rejected: queue full. Retryable — clients should back
    off (see :meth:`InferenceEngine.submit_with_backoff`)."""


class ReplicaFailed(RuntimeError):
    """The engine serving this request died (scheduler crash) or was
    condemned by the fleet supervisor. The fleet front door treats this
    as "requeue to a surviving replica"; ``active`` distinguishes
    requests that were *running* on the dead engine (they count toward
    the poison-pill strike budget — one of them may be what killed it)
    from ones that merely sat in its queue (innocent orphans, requeued
    without a strike)."""

    def __init__(self, msg: str, *, active: bool = False) -> None:
        super().__init__(msg)
        self.active = active


ADMISSION_RETRY = RetryPolicy(
    name="serving_admission", max_attempts=6, base_delay_s=0.05,
    multiplier=2.0, max_delay_s=2.0, retryable=(ServerOverloaded,))


def _ambient_exec_cache() -> Any:
    """The process-default persistent executable cache (storage/
    exec_cache.py), or None. Resolution must never fail engine
    construction."""
    try:
        from determined_clone_tpu.storage import exec_cache as exec_mod

        return exec_mod.default_cache()
    except Exception:  # pragma: no cover - defensive
        return None


def _maybe_dispatch(fn: Any, exec_cache: Any, program: str) -> Any:
    """Wrap a jitted entry point in an AotDispatcher when a persistent
    executable cache is in play (explicit ``exec_cache``, or the ambient
    default). ``exec_cache=False`` forces the plain jit wrapper; with no
    cache anywhere the jit wrapper comes back unchanged — the seed
    behavior, byte-for-byte."""
    if exec_cache is False:
        return fn
    cache = exec_cache if exec_cache is not None else _ambient_exec_cache()
    if cache is None:
        return fn
    from determined_clone_tpu.telemetry.xla import AotDispatcher

    return AotDispatcher(fn, program=program, exec_cache=cache)


def _sum_cache_summaries(dispatchers: Sequence[Any]) -> Optional[
        Dict[str, Any]]:
    """Merge ``AotDispatcher.cache_summary()`` dicts (None with no
    dispatchers — plain jit everywhere, nothing to report).
    ``compile_time_saved_s`` stays None until at least one hit so "no
    cache traffic" and "cache saved 0s" read differently downstream."""
    totals: Optional[Dict[str, Any]] = None
    for d in dispatchers:
        s = d.cache_summary()
        if totals is None:
            totals = dict(s)
            continue
        for k, v in s.items():
            if v is None:
                continue
            totals[k] = (totals.get(k) or 0) + v
    if totals is not None and not totals.get("exec_cache_hits"):
        totals["compile_time_saved_s"] = None
    return totals


def make_paged_forward(exec_cache: Any = None) -> Any:
    """The jitted paged forward an engine runs everything through.
    Replica fleets pass ONE of these to every engine (``fwd=``) so the
    whole fleet shares a single XLA program cache: replica N>1 warms up
    for free, and scale-up never pays a compile (all replicas serve the
    same model config and bucket ladder, so the shapes are identical).

    With a persistent executable cache (``exec_cache=``, or the ambient
    default from storage/exec_cache.py) the wrapper is an
    :class:`~determined_clone_tpu.telemetry.xla.AotDispatcher`: warmup
    loads previously-compiled programs from the CAS ``cas/exec/``
    namespace instead of compiling, so even the FIRST process of a
    restart leg starts warm. ``exec_cache=False`` opts out."""
    fwd = jax.jit(gpt.forward_paged, static_argnums=(1,),
                  donate_argnums=(6, 7))
    return _maybe_dispatch(fwd, exec_cache, "serving_forward_paged")


def make_paged_verify(exec_cache: Any = None) -> Any:
    """The jitted multi-logit forward the speculative verify step runs
    through: one [B, k+1] call scores the last committed token plus all
    k drafts; compiles one program per batch bucket."""
    fwd = jax.jit(gpt.forward_paged_logits, static_argnums=(1,),
                  donate_argnums=(5, 6))
    return _maybe_dispatch(fwd, exec_cache, "serving_verify")


def _block_copy(k_pool: jax.Array, v_pool: jax.Array,
                src: jax.Array, dst: jax.Array):
    """COW fork: duplicate one pool block (all layers) into another."""
    return (k_pool.at[:, dst].set(k_pool[:, src]),
            v_pool.at[:, dst].set(v_pool[:, src]))


def make_block_copy(exec_cache: Any = None) -> Any:
    """Jitted :func:`_block_copy` — src/dst are dynamic scalars, so the
    whole COW protocol costs exactly one XLA program per pool pair."""
    fwd = jax.jit(_block_copy, donate_argnums=(0, 1))
    return _maybe_dispatch(fwd, exec_cache, "serving_block_copy")


def _block_write(k_pool: jax.Array, v_pool: jax.Array, dst: jax.Array,
                 k_blk: jax.Array, v_blk: jax.Array):
    """KV-tier promotion: scatter one host-gathered block payload (all
    layers) into a pool slot — the exact inverse of the spill gather."""
    return (k_pool.at[:, dst].set(k_blk), v_pool.at[:, dst].set(v_blk))


def make_block_write(exec_cache: Any = None) -> Any:
    """Jitted :func:`_block_write` — dst is a dynamic scalar, so tier
    promotion costs exactly one XLA program per pool pair."""
    fwd = jax.jit(_block_write, donate_argnums=(0, 1))
    return _maybe_dispatch(fwd, exec_cache, "serving_block_write")


@dataclasses.dataclass(frozen=True)
class Request:
    """One generation request. Greedy decoding (argmax) — the serving
    contract that keeps paged output token-identical to the uncached
    forward, which the tier-1 parity test pins."""
    prompt: Tuple[int, ...]
    max_new_tokens: int
    eos_token_id: Optional[int] = None
    request_id: str = ""
    # cross-process trace identity minted at the front door; rides every
    # per-request span so the stitched trace shows one request end to end
    trace_id: Optional[str] = None
    # absolute monotonic deadline (time.monotonic() clock). Expired work
    # is retired with finish_reason "expired" at the next iteration
    # boundary — never decoded into the void — and its blocks freed
    deadline_t: Optional[float] = None


@dataclasses.dataclass
class RequestResult:
    request_id: str
    prompt_len: int
    tokens: List[int]
    finish_reason: str          # "length" | "eos" | "aborted" | "expired"
    queue_wait_s: float
    prefill_s: float            # total prefill device time it rode
    decode_s: float             # prefill-done → last token
    total_s: float              # submit → last token
    prefix_hit_blocks: int = 0   # prompt blocks aliased from the cache
    prefix_miss_blocks: int = 0  # prompt blocks prefilled from scratch
    spec_proposed: int = 0       # draft tokens offered for this request
    spec_accepted: int = 0       # draft tokens the target agreed with
    trace_id: Optional[str] = None  # front-door trace identity, if minted

    @property
    def spec_acceptance(self) -> Optional[float]:
        if self.spec_proposed <= 0:
            return None
        return self.spec_accepted / self.spec_proposed


@dataclasses.dataclass
class EngineStats:
    submitted: int
    rejected: int
    completed: int
    tokens_generated: int
    peak_active: int
    queue_depth: int
    free_blocks: int
    programs_compiled: int
    program_budget: int
    prefix_hit_blocks: int = 0
    prefix_miss_blocks: int = 0
    prefix_cached_entries: int = 0
    spec_tokens_proposed: int = 0
    spec_tokens_accepted: int = 0
    spec_acceptance_rate: Optional[float] = None
    kv_host_hit_blocks: int = 0
    kv_cas_hit_blocks: int = 0
    kv_miss_blocks: int = 0
    kv_promoted_blocks: int = 0
    kv_spilled_blocks: int = 0


class _Handle:
    """Future for one in-flight request.

    Settlement is first-write-wins: once either `_finish` or `_fail`
    lands, later calls are no-ops. The fleet supervisor can fail a
    wedged replica's handles (so waiters requeue immediately) while the
    wedged scheduler thread is still alive — when that thread finally
    wakes and tears down, it must not clobber the verdict the client
    already acted on.
    """

    def __init__(self, req: Request) -> None:
        self.req = req
        self._done = threading.Event()
        self._lk = threading.Lock()  # leaf: guards the settle race only
        self._result: Optional[RequestResult] = None
        self._error: Optional[BaseException] = None
        # timestamps stamped by the engine (monotonic)
        self.submit_t = 0.0
        self.admit_t = 0.0
        self.prefill_s = 0.0
        self.prefill_done_t = 0.0
        self.cancelled = False  # set by InferenceEngine.abort

    def _finish(self, result: RequestResult) -> bool:
        with self._lk:
            if self._done.is_set():
                return False
            self._result = result
            self._done.set()
        return True

    def _fail(self, exc: BaseException) -> bool:
        with self._lk:
            if self._done.is_set():
                return False
            self._error = exc
            self._done.set()
        return True

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None) -> RequestResult:
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"request {self.req.request_id!r} not done in {timeout}s")
        if self._error is not None:
            raise self._error
        assert self._result is not None
        return self._result


class _Active:
    """Scheduler-private state of one running sequence."""

    __slots__ = ("handle", "blocks", "prompt_len", "out", "last_token",
                 "prefill_pos", "pending_copy", "hit_blocks", "miss_blocks",
                 "spec_proposed", "spec_accepted")

    def __init__(self, handle: _Handle, blocks: List[int],
                 prompt_len: int) -> None:
        self.handle = handle
        self.blocks = blocks
        self.prompt_len = prompt_len
        self.out: List[int] = []
        self.last_token = -1
        # next un-prefilled prompt position: 0 for a cold prompt, the
        # shared-prefix length after a cache hit, prompt_len once done
        self.prefill_pos = 0
        # (src, dst) COW fork to execute before this row's first device
        # call; the src block keeps a caller reference until then
        self.pending_copy: Optional[Tuple[int, int]] = None
        self.hit_blocks = 0
        self.miss_blocks = 0
        self.spec_proposed = 0
        self.spec_accepted = 0


class InferenceEngine:
    """Continuous-batching GPT server over a paged KV cache.

    One scheduler thread (named ``serving-engine`` — the conftest
    thread-leak fixture knows it) owns all device work; request threads
    only touch the queue and their handle. Use as a context manager or
    call :meth:`close` — the thread must be joined.
    """

    def __init__(self, params: gpt.Params, model_cfg: gpt.GPTConfig, *,
                 buckets: Optional[BucketSpec] = None,
                 cache: Optional[KVCacheConfig] = None,
                 max_queue_depth: int = 64,
                 telemetry: Any = None,
                 fwd: Any = None,
                 iteration_floor_s: float = 0.0,
                 prefix_cache: bool = False,
                 chunk_prefill_len: int = 0,
                 speculative_k: int = 0,
                 draft_params: Optional[gpt.Params] = None,
                 draft_cfg: Optional[gpt.GPTConfig] = None,
                 kv_store: Any = None,
                 fault_scope: str = "") -> None:
        self.model_cfg = model_cfg
        # chaos targeting: with a scope (the fleet passes the replica
        # id) the scheduler also hits "engine.step.<scope>" /
        # "engine.admit.<request_id>" so a seeded FaultPlan can kill ONE
        # replica or poison ONE request by fnmatch pattern. Built by
        # concatenation on purpose: scoped names stay out of the static
        # CONTRACT001 catalog, which lists the constant base points.
        self._fault_scope = str(fault_scope)
        self.buckets = buckets or BucketSpec.build(
            8, min(128, model_cfg.max_seq_len))
        if self.buckets.max_prefill_len > model_cfg.max_seq_len:
            raise ValueError(
                f"prefill bucket {self.buckets.max_prefill_len} exceeds "
                f"model max_seq_len {model_cfg.max_seq_len}")
        if cache is None:
            block = 16
            cache = KVCacheConfig(
                num_blocks=self.buckets.max_batch
                * max(1, math.ceil(model_cfg.max_seq_len / block)),
                block_size=block)
        self.cache = cache
        self.max_queue_depth = int(max_queue_depth)

        self._params = params
        self._pending_params: Optional[gpt.Params] = None
        self._allocator = BlockAllocator(cache)
        self._k_pool, self._v_pool = init_kv_pools(model_cfg, cache)
        # fixed block-table width: every call sees the same W, so table
        # shape never causes a retrace
        self._table_width = max(
            1, math.ceil(model_cfg.max_seq_len / cache.block_size))
        self._fwd = fwd if fwd is not None else make_paged_forward()

        # -- optional raw-speed features (module docstring) --------------
        self.chunk_prefill_len = int(chunk_prefill_len)
        if self.chunk_prefill_len:
            self.buckets.validate_chunk_len(self.chunk_prefill_len)
        self._spec_k = int(speculative_k)
        if self._spec_k < 0:
            raise ValueError(f"speculative_k must be >= 0, got {speculative_k}")
        if self._spec_k:
            if draft_params is None or draft_cfg is None:
                raise ValueError(
                    "speculative_k > 0 needs draft_params and draft_cfg")
            if draft_cfg.vocab_size != model_cfg.vocab_size:
                raise ValueError(
                    f"draft vocab {draft_cfg.vocab_size} != target vocab "
                    f"{model_cfg.vocab_size} (the tokenizer is shared)")
            self._draft_params = draft_params
            self.draft_cfg = draft_cfg
            # the draft's pools share block ids (and hence block tables
            # and the allocator) with the target's — only the per-block
            # payload shape differs — so prefix sharing and COW cover
            # the draft KV with zero extra bookkeeping
            self._dk_pool, self._dv_pool = init_kv_pools(draft_cfg, cache)
            # an AotDispatcher keys on (cfg, shapes), so target and draft
            # lanes SHARE one dispatcher — their executables land in one
            # table and programs_compiled() counts them once, exactly as
            # the shared jit cache always did
            self._draft_fwd = (self._fwd if hasattr(self._fwd, "warm")
                               else make_paged_forward(exec_cache=False))
            self._verify_fwd = make_paged_verify()
        else:
            self._draft_params = None
            self.draft_cfg = None
            self._draft_fwd = None
            self._verify_fwd = None
        # -- KV memory hierarchy (serving/kv_store.py) -------------------
        # the host/CAS tiers below the prefix cache: eviction demotes
        # blocks into the store, admission promotes tier hits back into
        # pool blocks before prefilling only the uncovered tail
        if kv_store is not None and not prefix_cache:
            raise ValueError("kv_store requires prefix_cache=True — the "
                             "tier is keyed by the prefix cache's chain "
                             "hashes")
        self._kv_store = kv_store
        self._prefix = PrefixCache(
            cache, self._allocator,
            spill=(self._spill_block if kv_store is not None else None)) \
            if prefix_cache else None
        self._copy = make_block_copy() if prefix_cache else None
        self._write = make_block_write() if kv_store is not None else None
        # tier-key scope: cached K/V is a function of the params, so a
        # weight change (hot_swap/rollout) switches fingerprints and can
        # never be served another set of weights' blocks
        self._params_fp = (params_fingerprint(params)
                           if kv_store is not None else "")
        # host→pool promotion writes queued at admission; each dst block
        # carries an extra allocator reference until the write lands in
        # _do_writes (so no eviction/teardown race can free it first)
        self._pending_writes: List[Tuple[int, Dict[str, Any]]] = []

        # simulated device-step floor: pad every scheduler iteration that
        # did device work up to this many seconds. 0.0 (the default) is a
        # no-op. Fleet benches on a single host set it so per-replica
        # capacity is bounded by the floor rather than by the one CPU the
        # replicas share — the same stand-in-for-hardware idiom as
        # loadgen's simulated agents (see docs/serving.md).
        self.iteration_floor_s = float(iteration_floor_s)

        registry = getattr(telemetry, "registry", telemetry)
        self.registry: MetricsRegistry = (
            registry if isinstance(registry, MetricsRegistry)
            else MetricsRegistry())
        tracer = getattr(telemetry, "tracer", None)
        self._span = (tracer.span if tracer is not None
                      else lambda name, **kw: contextlib.nullcontext())
        # per-request event recording (queue admission, prefill chunks,
        # speculative rounds, COW forks, retirement): None when telemetry
        # is off, so the disabled path pays one `is not None` per step and
        # nothing per request
        self._tracer = (tracer if tracer is not None
                        and getattr(tracer, "enabled", False) else None)
        # exec-cache-backed dispatchers export their compile records
        # (xla_compile spans, xla_exec_cache_* counters) through this
        # replica's registry/tracer; a fleet-shared dispatcher rebinds to
        # whichever replica is currently warming
        for entry in (self._fwd, self._draft_fwd, self._verify_fwd,
                      self._copy, self._write):
            bind = getattr(entry, "bind_telemetry", None)
            if callable(bind):
                bind(self.registry, self._tracer)
        m = self.registry
        self._h_queue_wait = m.histogram(
            "serving_queue_wait_seconds", "submit → admitted into the batch")
        self._h_prefill = m.histogram(
            "serving_prefill_seconds", "one bucketed prefill call")
        self._h_decode = m.histogram(
            "serving_decode_step_seconds", "one bucketed decode step")
        self._h_total = m.histogram(
            "serving_request_total_seconds", "submit → last token")
        self._c_admitted = m.counter(
            "serving_requests_admitted_total", "requests accepted into queue")
        self._c_rejected = m.counter(
            "serving_requests_rejected_total",
            "admission rejections (queue full → ServerOverloaded)")
        self._c_completed = m.counter(
            "serving_requests_completed_total", "requests fully generated")
        self._c_tokens = m.counter(
            "serving_tokens_generated_total", "decoded tokens (all requests)")
        self._g_active = m.gauge(
            "serving_active_sequences", "sequences in the running batch")
        self._g_queue = m.gauge(
            "serving_queue_depth", "requests waiting for admission")
        self._g_free_blocks = m.gauge(
            "serving_free_kv_blocks", "unallocated KV pool blocks")
        self._g_free_blocks.set(self._allocator.free_blocks())
        self._c_prefix_hit = m.counter(
            "prefix_cache_hit_blocks_total",
            "prompt blocks aliased from the prefix cache (prefill skipped)")
        self._c_prefix_miss = m.counter(
            "prefix_cache_miss_blocks_total",
            "prompt blocks prefilled from scratch")
        self._c_spec_proposed = m.counter(
            "serving_spec_tokens_proposed_total",
            "draft tokens offered to the verify step")
        self._c_spec_accepted = m.counter(
            "serving_spec_tokens_accepted_total",
            "draft tokens the target model agreed with")
        self._g_spec_rate = m.gauge(
            "spec_acceptance_rate",
            "cumulative accepted/proposed draft-token ratio")
        self._h_spec_accept = m.histogram(
            "serving_spec_request_acceptance_rate",
            "per-request draft acceptance rate at retirement")
        self._c_expired = m.counter(
            "serving_requests_expired_total",
            "requests retired at their deadline (blocks freed, not decoded)")
        self._c_kv_host_hit = m.counter(
            "kv_tier_host_hit_blocks_total",
            "prompt blocks promoted from the host KV tier")
        self._c_kv_cas_hit = m.counter(
            "kv_tier_cas_hit_blocks_total",
            "prompt blocks promoted from the CAS KV tier")
        self._c_kv_miss = m.counter(
            "kv_tier_miss_blocks_total",
            "prompt blocks absent from every KV tier (prefilled fresh)")
        self._c_kv_promoted = m.counter(
            "kv_tier_promoted_blocks_total",
            "host→pool promotion writes landed (re-prefill avoided)")
        self._c_kv_spilled = m.counter(
            "kv_tier_spilled_blocks_total",
            "pool blocks demoted into the host tier instead of dropped")

        self._cond = threading.Condition()
        self._queue: collections.deque[_Handle] = collections.deque()
        self._active: List[_Active] = []
        self._prefilling: List[_Active] = []
        self._stop = False
        self._warming = False
        self._busy = False  # scheduler outside its wait with device work
        self._fatal: Optional[BaseException] = None
        # set by fail_inflight (the supervisor's condemn): the scheduler
        # raises it at the next iteration boundary so the crash teardown
        # — the only place that may release a possibly-mid-step row's
        # blocks — runs exactly once, on the owning thread
        self._condemned: Optional[BaseException] = None
        # scheduler-loop heartbeat watermark: stamped every pass, so a
        # *wedged* scheduler (alive but stuck mid-iteration) reads as
        # stale-beat-with-pending-work to the supervisor's liveness probe
        self._beat_t = time.monotonic()
        self._submitted = 0
        self._completed = 0
        self._total_tokens = 0
        self._peak_active = 0
        self._req_seq = 0
        self._thread = threading.Thread(target=self._run,
                                        name="serving-engine", daemon=True)
        self._thread.start()

    @classmethod
    def from_serving_config(cls, params: gpt.Params,
                            model_cfg: gpt.GPTConfig, scfg: Any, *,
                            telemetry: Any = None, fwd: Any = None,
                            iteration_floor_s: float = 0.0,
                            draft_params: Optional[gpt.Params] = None
                            ) -> "InferenceEngine":
        """Build an engine from a config/experiment.py ServingConfig
        (the `serving:` block of an experiment YAML). When the
        ``speculative:`` block is enabled the draft GPT shares the
        tokenizer/vocab and max_seq_len with the target; its weights
        come from ``draft_params`` or, absent one (no distilled draft
        checkpoint yet), a seeded random init — correct but slow, since
        the accept rule never trusts the draft."""
        buckets = BucketSpec.build(
            scfg.max_batch, min(scfg.max_prefill_len, model_cfg.max_seq_len))
        blocks = scfg.kv_blocks or scfg.max_batch * max(
            1, math.ceil(model_cfg.max_seq_len / scfg.kv_block_size))
        spec = getattr(scfg, "speculative", None)
        spec_k = 0
        draft_cfg = None
        if spec is not None and spec.enabled:
            spec_k = spec.k
            draft_cfg = dataclasses.replace(
                model_cfg, n_layers=spec.draft_layers,
                d_model=spec.draft_d_model, n_heads=spec.draft_n_heads,
                d_ff=spec.draft_d_ff, remat=False)
            if draft_params is None:
                draft_params = gpt.init(jax.random.PRNGKey(0), draft_cfg)
        return cls(params, model_cfg, buckets=buckets,
                   cache=KVCacheConfig(num_blocks=blocks,
                                       block_size=scfg.kv_block_size),
                   max_queue_depth=scfg.max_queue_depth,
                   telemetry=telemetry, fwd=fwd,
                   iteration_floor_s=iteration_floor_s,
                   prefix_cache=getattr(scfg, "prefix_cache", False),
                   chunk_prefill_len=getattr(scfg, "chunk_prefill_len", 0),
                   speculative_k=spec_k, draft_params=draft_params,
                   draft_cfg=draft_cfg)

    # -- client surface ----------------------------------------------------

    def __enter__(self) -> "InferenceEngine":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()

    def close(self, timeout: float = 30.0) -> None:
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        self._thread.join(timeout)

    @staticmethod
    def _req_args(req: Request, **extra: Any) -> Dict[str, Any]:
        """Span args identifying one request (per-request tracing)."""
        args: Dict[str, Any] = {"request_id": req.request_id, **extra}
        if req.trace_id:
            args["trace_id"] = req.trace_id
        return args

    def attach_tracer(self, tracer: Any) -> None:
        """Late-bind (or detach, with None) the per-request event tracer.
        A plain attribute swap is atomic, so flipping it while the
        scheduler runs is safe — the bench uses this to measure the same
        warm engine traced vs untraced (tracing_overhead)."""
        self._tracer = (tracer if tracer is not None
                        and getattr(tracer, "enabled", False) else None)

    def submit(self, prompt: Sequence[int], max_new_tokens: int = 16, *,
               eos_token_id: Optional[int] = None,
               request_id: Optional[str] = None,
               trace_id: Optional[str] = None,
               deadline_t: Optional[float] = None) -> _Handle:
        """Enqueue one request. Raises ValueError for never-servable
        requests and ServerOverloaded when the queue is full.
        ``deadline_t`` is an absolute ``time.monotonic()`` deadline:
        work still unfinished then is retired as "expired" at the next
        iteration boundary and its KV blocks freed."""
        prompt = tuple(int(t) for t in prompt)
        if not prompt:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, "
                             f"got {max_new_tokens}")
        if not self.chunk_prefill_len \
                and len(prompt) > self.buckets.max_prefill_len:
            # chunked prefill lifts this limit: any prompt that fits the
            # model context is served chunk_prefill_len tokens at a time
            raise ValueError(
                f"prompt length {len(prompt)} exceeds the largest prefill "
                f"bucket {self.buckets.max_prefill_len}")
        total = len(prompt) + max_new_tokens
        if total > self.model_cfg.max_seq_len:
            raise ValueError(
                f"prompt + max_new_tokens = {total} exceeds model "
                f"max_seq_len {self.model_cfg.max_seq_len}")
        with self._cond:
            if self._fatal is not None:
                # ReplicaFailed (a RuntimeError) so the router treats a
                # dead-but-not-yet-removed replica as a failover target,
                # not a client error; active=False — never admitted, so
                # no poison-pill strike
                raise ReplicaFailed("serving engine died",
                                    active=False) from self._fatal
            if self._stop:
                raise RuntimeError("serving engine is closed")
            if len(self._queue) >= self.max_queue_depth:
                self._c_rejected.inc()
                raise ServerOverloaded(
                    f"queue full ({self.max_queue_depth} waiting)")
            self._req_seq += 1
            rid = request_id or f"req-{self._req_seq}"
            handle = _Handle(Request(prompt, int(max_new_tokens),
                                     eos_token_id, rid, trace_id,
                                     deadline_t))
            handle.submit_t = time.monotonic()
            if not self._busy:
                # first work after an idle stretch: the parked scheduler's
                # beat is arbitrarily old — restart the liveness clock so
                # the supervisor grants it a fresh window to wake up in
                self._beat_t = handle.submit_t
            self._queue.append(handle)
            self._submitted += 1
            self._c_admitted.inc()
            self._g_queue.set(len(self._queue))
            self._cond.notify_all()
        return handle

    def submit_with_backoff(self, prompt: Sequence[int],
                            max_new_tokens: int = 16, *,
                            eos_token_id: Optional[int] = None,
                            request_id: Optional[str] = None,
                            trace_id: Optional[str] = None,
                            policy: RetryPolicy = ADMISSION_RETRY) -> _Handle:
        """submit() under the repo-standard retry/backoff policy: full-
        jitter exponential backoff on ServerOverloaded, re-raised on
        exhaustion. The client half of admission control."""
        return retry_call(self.submit, prompt, max_new_tokens,
                          eos_token_id=eos_token_id, request_id=request_id,
                          trace_id=trace_id, policy=policy)

    def generate(self, prompt: Sequence[int], max_new_tokens: int = 16, *,
                 eos_token_id: Optional[int] = None,
                 timeout: Optional[float] = 120.0) -> RequestResult:
        return self.submit(prompt, max_new_tokens,
                           eos_token_id=eos_token_id).result(timeout)

    def abort(self, handle: _Handle) -> bool:
        """Cancel one in-flight request (client disconnect). The
        scheduler retires it at the next iteration boundary — never
        mid-step — releasing its pool blocks exactly as a natural finish
        would (tests pin the allocator accounting). The handle resolves
        with whatever was generated so far and ``finish_reason ==
        "aborted"``. Returns False if the request already finished."""
        with self._cond:
            if handle.done():
                return False
            handle.cancelled = True
            self._cond.notify_all()
        return True

    # -- model hot-swap ----------------------------------------------------

    def hot_swap(self, params: gpt.Params) -> None:
        """Queue a new parameter pytree; the scheduler installs it at the
        next iteration boundary (never mid-step), so in-flight sequences
        finish under whichever params their next step sees — the standard
        online-swap semantics."""
        with self._cond:
            self._pending_params = params
            self._cond.notify_all()

    def hot_load(self, storage: Any, storage_id: str, *,
                 base_tmp: Optional[str] = None,
                 ckpt_subdir: str = "") -> float:
        """Hot-load a checkpoint from a StorageManager (CAS-backed
        managers reuse their chunk cache, making repeat loads cheap) and
        swap it in. Returns the load wall-time in seconds."""
        from determined_clone_tpu.core._serialization import load_pytree

        t0 = time.monotonic()
        with self._span("serving_hot_load", storage_id=storage_id):
            with storage.restore_path(storage_id, base_tmp) as d:
                src = os.path.join(d, ckpt_subdir) if ckpt_subdir else d
                new_params = load_pytree(src, like=self._params)
        self.hot_swap(new_params)
        dt = time.monotonic() - t0
        self.registry.histogram(
            "serving_hot_load_seconds",
            "checkpoint fetch + deserialize + swap").observe(dt)
        return dt

    def warmup(self) -> int:
        """Pre-compile the FULL bucket ladder — one prefill program per
        (batch-bucket, length-bucket) plus one decode program per
        batch-bucket — so no request ever pays an XLA compile. A warm
        burst only covers the shapes the burst happens to hit; paced
        arrivals later trickle into the running batch one or two at a
        time and exercise the small batch-bucket prefills for the first
        time, stalling the whole scheduler behind a mid-traffic compile
        that can dwarf the actual work. Serving stacks precompile at
        startup for exactly this reason.

        The dummy inputs are fully masked (``token_mask`` all False), so
        nothing is written to the KV pools — warmup is invisible to
        every later request (the COW copy program is warmed by copying
        block 0 onto itself: bit-identical values). Requires an idle
        engine; the scheduler is parked for the duration (racing submits
        queue up and are served once warmup finishes). Returns
        :meth:`programs_compiled`, which now equals
        :meth:`program_budget` — the full ladder includes the draft
        model's mirror ladder, the k+1-token verify program per batch
        bucket, and the COW copy when those features are on.
        """
        with self._cond:
            self._await_idle_locked("warmup")
            self._warming = True
        t0 = time.monotonic()

        def call(f: Any, *args: Any) -> Any:
            # exec-cache-backed dispatchers take the cache-first AOT path
            # (load the serialized executable, compile only on a miss);
            # plain jit wrappers compile implicitly as they always did
            warm = getattr(f, "warm", None)
            return warm(*args) if callable(warm) else f(*args)

        try:
            with self._span("serving_warmup"):
                lanes = [(self._fwd, self._params, self.model_cfg)]
                if self._spec_k:
                    lanes.append((self._draft_fwd, self._draft_params,
                                  self.draft_cfg))
                for b in self.buckets.batch_buckets:
                    tables = jnp.zeros((b, self._table_width), jnp.int32)
                    for fwd, params, cfg in lanes:
                        for t in (*self.buckets.prefill_len_buckets, 1):
                            logits, kp, vp = call(
                                fwd, params, cfg,
                                jnp.zeros((b, t), jnp.int32),
                                jnp.zeros((b, t), jnp.int32),
                                jnp.zeros((b, t), bool),
                                jnp.zeros((b,), jnp.int32),
                                *self._pools_for(cfg), tables)
                            self._set_pools_for(cfg, kp, vp)
                            # the sampling step is its own (tiny) program
                            # per batch bucket — leave it cold and the
                            # first real request pays its compile
                            jnp.argmax(logits, axis=-1).block_until_ready()
                    if self._spec_k:
                        t = self._spec_k + 1
                        logits, self._k_pool, self._v_pool = call(
                            self._verify_fwd,
                            self._params, self.model_cfg,
                            jnp.zeros((b, t), jnp.int32),
                            jnp.zeros((b, t), jnp.int32),
                            jnp.zeros((b, t), bool),
                            self._k_pool, self._v_pool, tables)
                        logits.block_until_ready()
                if self._copy is not None:
                    self._k_pool, self._v_pool = call(
                        self._copy, self._k_pool, self._v_pool, 0, 0)
                    if self._spec_k:
                        self._dk_pool, self._dv_pool = call(
                            self._copy, self._dk_pool, self._dv_pool, 0, 0)
                    jax.block_until_ready(self._k_pool)
                if self._write is not None:
                    # warmed by writing block 0's own contents back:
                    # materialize the slice BEFORE the donated call, so
                    # the write is bit-identical (all zeros at warmup)
                    kb = jnp.array(self._k_pool[:, 0])
                    vb = jnp.array(self._v_pool[:, 0])
                    self._k_pool, self._v_pool = call(
                        self._write, self._k_pool, self._v_pool, 0, kb, vb)
                    if self._spec_k:
                        dkb = jnp.array(self._dk_pool[:, 0])
                        dvb = jnp.array(self._dv_pool[:, 0])
                        self._dk_pool, self._dv_pool = call(
                            self._write, self._dk_pool, self._dv_pool, 0,
                            dkb, dvb)
                    jax.block_until_ready(self._k_pool)
        finally:
            with self._cond:
                self._warming = False
                self._cond.notify_all()
        self.registry.histogram(
            "serving_warmup_seconds",
            "full bucket-ladder precompile at startup"
        ).observe(time.monotonic() - t0)
        return self.programs_compiled()

    def _await_idle_locked(self, what: str) -> None:
        """Under ``self._cond``: refuse if traffic is queued or running,
        and wait out the scheduler's in-flight device call (queue and
        active both look empty while a prefill is on the device — the
        ``_busy`` flag covers that window, or donated pools would be
        used from two threads at once)."""
        if self._stop:
            raise RuntimeError("serving engine is closed")
        if self._fatal is not None:
            raise RuntimeError("serving engine died") from self._fatal
        if self._queue or self._active or self._prefilling:
            raise RuntimeError(f"{what} requires an idle engine")
        while self._busy and not self._stop and self._fatal is None:
            self._cond.wait()
        if self._stop:
            raise RuntimeError("serving engine is closed")
        if self._fatal is not None:
            raise RuntimeError("serving engine died") from self._fatal
        if self._queue or self._active or self._prefilling:
            raise RuntimeError(f"{what} requires an idle engine")

    def wait_idle(self, timeout: float = 60.0) -> None:
        """Block until nothing is queued, nothing is active, and the
        scheduler's in-flight device call (the ``_busy`` window) has
        finished — i.e. every request accepted so far has fully
        completed. This is the engine half of the fleet drain protocol:
        the caller stops routing new work here first, then waits out the
        in-flight decodes before swapping params or releasing the
        replica's slots. Raises TimeoutError if traffic never quiesces.
        """
        deadline = time.monotonic() + timeout
        with self._cond:
            while (self._queue or self._active or self._prefilling
                   or self._busy):
                if self._fatal is not None:
                    raise RuntimeError(
                        "serving engine died") from self._fatal
                if self._stop:
                    raise RuntimeError("serving engine is closed")
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"engine not idle after {timeout}s "
                        f"(queue={len(self._queue)} "
                        f"active={len(self._active)} "
                        f"prefilling={len(self._prefilling)})")
                self._cond.wait(remaining)

    # -- self-healing surface (fleet supervisor) ---------------------------

    def liveness(self) -> Dict[str, Any]:
        """Snapshot for the supervisor's liveness probe. The wedged
        verdict is the caller's: ``pending and beat_age_s > deadline``
        means the scheduler has had work for that long without
        completing a pass — stale-beat-while-idle is just a parked
        thread and perfectly healthy."""
        now = time.monotonic()
        with self._cond:
            return {
                "thread_alive": self._thread.is_alive(),
                "fatal": self._fatal,
                "condemned": self._condemned is not None,
                "warming": self._warming,
                "pending": bool(self._queue or self._active
                                or self._prefilling or self._busy),
                "beat_age_s": now - self._beat_t,
            }

    def fail_inflight(self, reason: str) -> int:
        """Condemn this engine: immediately fail every queued and
        running request with :class:`ReplicaFailed` (so front-door
        waiters requeue to surviving replicas without waiting out a
        wedged thread) and mark the scheduler to tear itself down at its
        next wakeup. Blocks are NOT released here — the scheduler thread
        may still be mid-device-call against the pools; it releases them
        exactly once in its own crash teardown. Returns the number of
        requests newly failed."""
        condemned = ReplicaFailed(f"replica condemned: {reason}",
                                  active=True)
        with self._cond:
            if self._fatal is None:
                self._fatal = condemned
            if self._condemned is None:
                self._condemned = condemned
            queued = list(self._queue)
            self._queue.clear()
            self._g_queue.set(0)
            inflight = [a.handle
                        for a in self._active + self._prefilling]
            self._cond.notify_all()
        n = 0
        orphaned = ReplicaFailed(f"replica condemned: {reason}",
                                 active=False)
        for h in queued:
            n += 1 if h._fail(orphaned) else 0
        for h in inflight:
            n += 1 if h._fail(condemned) else 0
        return n

    def kv_outstanding(self) -> int:
        """KV blocks currently owned (active sequences + prefix-cache
        retains). Zero on an idle engine with no prefix cache."""
        return self._allocator.outstanding()

    def assert_kv_balanced(self, expected_outstanding: int = 0) -> None:
        """Chaos/test audit: raise AssertionError unless exactly
        ``expected_outstanding`` blocks are held (see
        :meth:`BlockAllocator.assert_balanced`)."""
        self._allocator.assert_balanced(expected_outstanding)

    # -- introspection -----------------------------------------------------

    def programs_compiled(self) -> int:
        """XLA programs across ALL the engine's jitted entry points —
        shared forward, draft forward, k+1-token verify, COW copy (the
        PR 2 retrace probe). The tier-1 compile-discipline test asserts
        this never exceeds :meth:`program_budget`."""
        total = 0
        seen = []
        for f in (self._fwd, self._draft_fwd, self._verify_fwd,
                  self._copy, self._write):
            if f is None:
                continue
            # jax keys the jit cache on the underlying function: _fwd
            # and _draft_fwd both wrap gpt.forward_paged, so they SHARE
            # one cache (that is what lets the draft ladder ride the
            # fleet-shared forward) — count each distinct cache once or
            # the draft programs get double-counted
            wrapped = getattr(f, "__wrapped__", f)
            if any(wrapped is w for w in seen):
                continue
            seen.append(wrapped)
            probe = getattr(f, "_cache_size", None)
            if not callable(probe):
                return -1
            total += int(probe())
        return total

    def program_budget(self) -> int:
        """Worst-case :meth:`programs_compiled` for the feature set this
        engine was built with; :meth:`warmup` compiles exactly this many."""
        return self.buckets.extended_budget(
            speculative=self._spec_k > 0,
            prefix_cache=self._prefix is not None,
            kv_store=self._kv_store is not None)

    def exec_dispatchers(self) -> List[Any]:
        """The engine's distinct AOT dispatchers (empty when the engine
        runs plain jit — the persistent executable cache is not in play).
        The fleet dedups these across replicas: the shared forward is ONE
        dispatcher no matter how many engines run through it."""
        out: List[Any] = []
        for f in (self._fwd, self._draft_fwd, self._verify_fwd,
                  self._copy, self._write):
            if callable(getattr(f, "cache_summary", None)) and not any(
                    f is s for s in out):
                out.append(f)
        return out

    def exec_cache_summary(self) -> Optional[Dict[str, Any]]:
        """Aggregated persistent-executable-cache accounting across the
        engine's dispatchers (None when the engine runs plain jit — the
        cache is not in play). ``fallback_compiles`` > 0 on a supposedly
        warm engine means some program was compiled instead of loaded."""
        return _sum_cache_summaries(self.exec_dispatchers())

    def stats(self) -> EngineStats:
        with self._cond:
            proposed = int(self._c_spec_proposed.value)
            accepted = int(self._c_spec_accepted.value)
            return EngineStats(
                submitted=self._submitted,
                rejected=int(self._c_rejected.value),
                completed=self._completed,
                tokens_generated=self._total_tokens,
                peak_active=self._peak_active,
                queue_depth=len(self._queue),
                free_blocks=self._allocator.free_blocks(),
                programs_compiled=self.programs_compiled(),
                program_budget=self.program_budget(),
                prefix_hit_blocks=int(self._c_prefix_hit.value),
                prefix_miss_blocks=int(self._c_prefix_miss.value),
                prefix_cached_entries=(len(self._prefix)
                                       if self._prefix is not None else 0),
                spec_tokens_proposed=proposed,
                spec_tokens_accepted=accepted,
                spec_acceptance_rate=(accepted / proposed
                                      if proposed else None),
                kv_host_hit_blocks=int(self._c_kv_host_hit.value),
                kv_cas_hit_blocks=int(self._c_kv_cas_hit.value),
                kv_miss_blocks=int(self._c_kv_miss.value),
                kv_promoted_blocks=int(self._c_kv_promoted.value),
                kv_spilled_blocks=int(self._c_kv_spilled.value))

    # -- scheduler ---------------------------------------------------------

    def _run(self) -> None:
        try:
            while True:
                with self._cond:
                    self._busy = False
                    self._beat_t = time.monotonic()
                    self._cond.notify_all()  # wakes warmup's idle wait
                    while (not self._stop and self._condemned is None
                           and (self._warming
                                or (not self._queue and not self._active
                                    and not self._prefilling
                                    and self._pending_params is None))):
                        self._cond.wait()
                    if self._stop:
                        closed = RuntimeError("serving engine closed")
                        for h, _was_active in self._teardown_locked():
                            h._fail(closed)
                        return
                    if self._condemned is not None:
                        raise self._condemned
                    if self._pending_params is not None:
                        self._params = self._pending_params
                        self._pending_params = None
                        # cached KV is a function of the params
                        if self._prefix is not None:
                            self._prefix.flush()
                            self._g_free_blocks.set(
                                self._allocator.free_blocks())
                        if self._kv_store is not None:
                            # new weights, new tier scope: old-params
                            # blocks stay fetchable under the old
                            # fingerprint (rollback warms), never here
                            self._params_fp = params_fingerprint(
                                self._params)
                    admitted = self._admit_locked()
                    self._busy = True
                # fault points fire OUTSIDE the condition (a delay rule
                # must wedge only this scheduler, never a lock every
                # client thread needs), and only with a plan active
                if faults.active_plan() is not None:
                    for rid in admitted:
                        faults.point("engine.admit")
                        faults.point("engine.admit." + rid)
                    if self._pending_writes:
                        faults.point("kv_store.promote")
                    faults.point("engine.step")
                    if self._fault_scope:
                        faults.point("engine.step." + self._fault_scope)
                iter_t0 = time.monotonic()
                worked = self._reap_expired()
                if self._pending_writes:
                    self._do_writes()
                    worked = True
                if self._prefilling:
                    self._prefill_step()
                    worked = True
                if self._active:
                    if self._spec_k:
                        self._spec_step()
                    else:
                        self._decode_step()
                    worked = True
                self._beat_t = time.monotonic()
                if worked and self.iteration_floor_s > 0.0:
                    pad = self.iteration_floor_s \
                        - (time.monotonic() - iter_t0)
                    if pad > 0.0:
                        time.sleep(pad)
        except BaseException as exc:  # noqa: BLE001 — fail every waiter
            queued = ReplicaFailed(f"serving engine died: {exc!r}",
                                   active=False)
            queued.__cause__ = exc
            running = ReplicaFailed(f"serving engine died: {exc!r}",
                                    active=True)
            running.__cause__ = exc
            with self._cond:
                if self._fatal is None:
                    self._fatal = exc
                self._busy = False
                handles = self._teardown_locked()
                self._cond.notify_all()
            # settle outside the condition: nothing here needs it, and
            # the waiters woken by these events immediately requeue
            for h, was_active in handles:
                h._fail(running if was_active else queued)

    def _teardown_locked(self):
        """Under ``self._cond``: the abnormal-retirement path. Releases
        every in-flight row's pool blocks (including pending COW source
        references) and the prefix cache's retains, clears the batch,
        and returns the handles to fail. Run only on the scheduler
        thread — it is the sole owner of the rows, so nothing can race
        the releases — and exactly once per row, keeping the allocator
        balanced (``assert_balanced``) through any crash or close.

        Returns ``(handle, was_active)`` pairs: the crash path needs to
        tell running rows (poison-pill strike candidates) from queued
        orphans; the stop path ignores the flag.
        """
        pairs = [(h, False) for h in self._queue]
        self._queue.clear()
        for block, _payload in self._pending_writes:
            self._allocator.release([block])
        self._pending_writes.clear()
        for a in self._active + self._prefilling:
            if a.pending_copy is not None:
                self._allocator.release([a.pending_copy[0]])
                a.pending_copy = None
            self._allocator.release(a.blocks)
            pairs.append((a.handle, True))
        self._active.clear()
        self._prefilling.clear()
        if self._prefix is not None:
            self._prefix.flush()
        self._g_active.set(0)
        self._g_queue.set(0)
        self._g_free_blocks.set(self._allocator.free_blocks())
        return pairs

    def _admit_locked(self) -> List[str]:
        """Move queued requests into the prefilling set while slots AND
        pool blocks allow. FIFO — a head-of-line request the pool can't
        fit yet blocks later ones (no starvation by bypass). With the
        prefix cache on, each admission first aliases the longest
        resident prefix (retaining those blocks) and only allocates
        fresh blocks for the remainder; under pool pressure LRU cache
        entries are evicted (dropping the cache's references — blocks
        shared with running sequences survive) before admission defers.
        Returns the admitted request ids (the scheduler hits their
        admission fault points outside the lock).
        """
        now = time.monotonic()
        admitted: List[str] = []
        while self._queue and (len(self._active) + len(self._prefilling)
                               < self.buckets.max_batch):
            head = self._queue[0]
            if head.cancelled or (head.req.deadline_t is not None
                                  and now >= head.req.deadline_t):
                expired = not head.cancelled
                if expired:
                    self._c_expired.inc()
                self._queue.popleft()
                head._finish(RequestResult(
                    request_id=head.req.request_id,
                    prompt_len=len(head.req.prompt), tokens=[],
                    finish_reason="expired" if expired else "aborted",
                    queue_wait_s=0.0,
                    prefill_s=0.0, decode_s=0.0,
                    total_s=now - head.submit_t))
                continue
            plen = len(head.req.prompt)
            total = plen + head.req.max_new_tokens
            need_total = self.cache.blocks_needed(total)
            shared: List[int] = []
            fork_src: Optional[int] = None
            if self._prefix is not None:
                if self._kv_store is not None:
                    # warm the prefix cache from the lower tiers first,
                    # so the ordinary match below aliases promoted
                    # blocks exactly like always-resident ones
                    self._promote_locked(head.req.prompt)
                match = self._prefix.match(head.req.prompt)
                # always leave >= 1 prompt token to process: the last
                # prompt token is re-scored through the model to produce
                # the first sampled token (its K/V rewrite is what the
                # COW fork isolates from the shared block)
                skip = min(match.shared_len, plen - 1)
                shared = match.blocks
                if skip < match.shared_len:
                    # fully-shared prompt: the final shared block holds
                    # position plen-1 and WILL be written — fork it
                    fork_src = shared.pop()
                kept = len(shared)
                need = need_total - kept
            else:
                skip = 0
                kept = 0
                need = need_total
            if self._allocator.free_blocks() < need:
                if self._prefix is not None:
                    self._prefix.evict(need)
                if self._allocator.free_blocks() < need:
                    # defer admission; hand back the match references
                    if shared:
                        self._allocator.release(shared)
                    if fork_src is not None:
                        self._allocator.release([fork_src])
                    break
            self._queue.popleft()
            head.admit_t = now
            if self._tracer is not None:
                self._h_queue_wait.observe(now - head.submit_t,
                                           exemplar=head.req.request_id)
                self._tracer.instant(
                    "request_admitted", **self._req_args(
                        head.req,
                        queue_wait_s=round(now - head.submit_t, 6),
                        prompt_len=plen))
            else:
                self._h_queue_wait.observe(now - head.submit_t)
            fresh = self._allocator.allocate_blocks(need)
            a = _Active(head, shared + fresh, plen)
            a.prefill_pos = skip
            if fork_src is not None:
                # fresh[0] backs the forked block's position range
                a.pending_copy = (fork_src, fresh[0])
            a.hit_blocks = kept + (1 if fork_src is not None else 0)
            a.miss_blocks = self.cache.blocks_needed(plen) - a.hit_blocks
            self._c_prefix_hit.inc(a.hit_blocks)
            self._c_prefix_miss.inc(a.miss_blocks)
            self._prefilling.append(a)
            admitted.append(head.req.request_id)
            self._peak_active = max(
                self._peak_active,
                len(self._active) + len(self._prefilling))
            self._g_active.set(len(self._active) + len(self._prefilling))
        self._g_queue.set(len(self._queue))
        self._g_free_blocks.set(self._allocator.free_blocks())
        return admitted

    # -- KV memory hierarchy (serving/kv_store.py) -------------------------

    def _payload_ok(self, payload: Dict[str, Any]) -> bool:
        """A tier payload is adoptable iff its arrays exactly match the
        pool slot shape/dtype (a config change or foreign entry must be
        a plain miss, never a bad scatter) and cover the draft pools
        when speculation is on."""
        want = [("k", self._k_pool), ("v", self._v_pool)]
        if self._spec_k:
            want += [("dk", self._dk_pool), ("dv", self._dv_pool)]
        for name, pool in want:
            arr = payload.get(name) if isinstance(payload, dict) else None
            if arr is None:
                return False
            slot = pool.shape[:1] + pool.shape[2:]
            if (tuple(getattr(arr, "shape", ())) != tuple(slot)
                    or str(getattr(arr, "dtype", "")) != str(pool.dtype)):
                return False
        return True

    def _promote_locked(self, prompt: Tuple[int, ...]) -> None:
        """Under ``self._cond``: warm the prefix cache from the
        host/CAS tiers before matching one prompt. Walks the prompt's
        full blocks in chain order; for each key not already resident,
        fetches the exact payload, allocates a pool block, indexes it
        (the cache adopts the allocator reference) and queues the
        host→pool write — which lands in :meth:`_do_writes` before any
        admitted row's first forward, so a matched row always reads the
        promoted bytes. Chain continuity: the first miss ends the walk
        — a later hit would alias a block whose predecessors are
        absent. Tail blocks never promote (they never spilled)."""
        bs = self.cache.block_size
        prev = b""
        for i in range(len(prompt) // bs):
            key = PrefixCache._chain(prev, prompt[i * bs:(i + 1) * bs])
            prev = key
            if self._prefix.has_key(key):
                continue
            key_hex = key.hex()
            from_host = self._kv_store.contains(self._params_fp, key_hex)
            payload = self._kv_store.get(self._params_fp, key_hex)
            if payload is None or not self._payload_ok(payload):
                self._c_kv_miss.inc()
                break
            if self._allocator.free_blocks() < 1:
                self._prefix.evict(1)
                if self._allocator.free_blocks() < 1:
                    break
            block = self._allocator.allocate_blocks(1)[0]
            self._prefix.adopt(key, block, i)
            # extra reference pins the dst until the write lands — no
            # eviction or teardown between queue and write may free it
            self._allocator.retain([block])
            self._pending_writes.append((block, payload))
            (self._c_kv_host_hit if from_host
             else self._c_kv_cas_hit).inc()

    def _do_writes(self) -> None:
        """Land queued promotion writes before any prefill or decode
        touches the pools — a matched row's first forward must read the
        promoted bytes, not zeros. Drops each dst block's pinning
        reference once its scatter lands."""
        writes, self._pending_writes = self._pending_writes, []
        for block, payload in writes:
            self._k_pool, self._v_pool = self._write(
                self._k_pool, self._v_pool, block,
                jnp.asarray(payload["k"]), jnp.asarray(payload["v"]))
            if self._spec_k:
                self._dk_pool, self._dv_pool = self._write(
                    self._dk_pool, self._dv_pool, block,
                    jnp.asarray(payload["dk"]), jnp.asarray(payload["dv"]))
            self._allocator.release([block])
            self._c_kv_promoted.inc()

    def _spill_block(self, key: bytes, block: int, depth: int) -> bool:
        """PrefixCache demotion hook: capture one full block's exact
        K/V into the host tier. Runs on the scheduler thread while the
        cache still holds the block's reference, so the pool contents
        are intact and no donated call is in flight. Never raises — a
        failed spill just means the block is gone, as before the tier
        existed."""
        try:
            payload = {"k": np.asarray(self._k_pool[:, block]),
                       "v": np.asarray(self._v_pool[:, block])}
            if self._spec_k:
                payload["dk"] = np.asarray(self._dk_pool[:, block])
                payload["dv"] = np.asarray(self._dv_pool[:, block])
            self._kv_store.put(self._params_fp, key.hex(), payload)
        except Exception:  # noqa: BLE001 — demotion is best-effort
            return False
        self._c_kv_spilled.inc()
        return True

    def flush_kv_to_tier(self) -> int:
        """Demote every full-block prefix-cache entry into the
        host/CAS tiers, so a teardown (rollout, replace, stop)
        preserves the fleet's warm state instead of dropping it.
        Requires an idle engine (the fleet calls this after its drain;
        a dead or wedged engine raises, and the fleet degrades to a
        cold teardown). Entries stay resident afterwards — the tier
        holds copies; the usual flush/teardown still releases the
        blocks. Returns blocks spilled."""
        if self._prefix is None or self._kv_store is None:
            return 0
        n = 0
        with self._cond:
            self._await_idle_locked("flush_kv_to_tier")
            for key, block, depth in self._prefix.entries():
                if self._spill_block(key, block, depth):
                    n += 1
        return n

    def prefix_inventory(self) -> Optional[Dict[str, Any]]:
        """Router-facing digest of the chain keys this replica can
        serve cheaply: resident prefix-cache entries (roots first —
        a missed root zeroes coverage, so roots deserve the exact
        top-K slots) followed by this fingerprint's host-tier keys.
        None when the prefix cache is off."""
        if self._prefix is None:
            return None
        # the scheduler thread may be registering entries concurrently
        # (dict iteration can raise RuntimeError mid-insert) — retry a
        # couple of times, then serve an empty digest; the inventory is
        # a routing hint, never correctness
        for _ in range(3):
            try:
                resident = sorted(self._prefix.entries(),
                                  key=lambda e: e[2])
                break
            except RuntimeError:
                continue
        else:
            resident = []
        keys = [k.hex() for k, _block, _depth in resident]
        if self._kv_store is not None:
            seen = set(keys)
            keys += [k for k in self._kv_store.keys(self._params_fp)
                     if k not in seen]
        return PrefixInventory.build(keys).to_dict()

    def _reap_expired(self) -> bool:
        """Retire cancelled and deadline-expired rows at the iteration
        boundary, releasing their blocks (and a pending COW source's
        extra reference) exactly like a natural finish — expired work is
        aborted, never decoded into the void."""
        now = time.monotonic()
        doomed: List[Tuple[_Active, str]] = []
        for a in self._active + self._prefilling:
            if a.handle.cancelled:
                doomed.append((a, "aborted"))
            elif (a.handle.req.deadline_t is not None
                  and now >= a.handle.req.deadline_t):
                self._c_expired.inc()
                doomed.append((a, "expired"))
        if not doomed:
            return False
        dead = {id(a) for a, _r in doomed}
        for a, reason in doomed:
            if a.pending_copy is not None:
                self._allocator.release([a.pending_copy[0]])
                a.pending_copy = None
            self._retire(a, reason)
        with self._cond:
            self._active = [a for a in self._active if id(a) not in dead]
            self._prefilling = [a for a in self._prefilling
                                if id(a) not in dead]
            self._g_active.set(len(self._active) + len(self._prefilling))
            self._g_free_blocks.set(self._allocator.free_blocks())
        return True

    def _do_copies(self, rows: Sequence[_Active]) -> None:
        """Execute pending COW forks before the rows' first device call,
        then drop the extra reference that kept each source alive."""
        for a in rows:
            if a.pending_copy is None:
                continue
            src, dst = a.pending_copy
            self._k_pool, self._v_pool = self._copy(
                self._k_pool, self._v_pool, src, dst)
            if self._spec_k:
                self._dk_pool, self._dv_pool = self._copy(
                    self._dk_pool, self._dv_pool, src, dst)
            self._allocator.release([src])
            a.pending_copy = None
            if self._tracer is not None:
                self._tracer.instant(
                    "request_cow_fork", **self._req_args(
                        a.handle.req, src_block=src, dst_block=dst))

    def _pools_for(self, cfg: gpt.GPTConfig) -> Tuple[jnp.ndarray,
                                                      jnp.ndarray]:
        if cfg is self.model_cfg:
            return self._k_pool, self._v_pool
        return self._dk_pool, self._dv_pool

    def _set_pools_for(self, cfg: gpt.GPTConfig, k_pool: jnp.ndarray,
                       v_pool: jnp.ndarray) -> None:
        if cfg is self.model_cfg:
            self._k_pool, self._v_pool = k_pool, v_pool
        else:
            self._dk_pool, self._dv_pool = k_pool, v_pool

    def _tables_for(self, rows: Sequence[_Active], padded_b: int
                    ) -> jnp.ndarray:
        tables = np.zeros((padded_b, self._table_width), np.int32)
        for i, a in enumerate(rows):
            tables[i, :len(a.blocks)] = a.blocks
        return jnp.asarray(tables)

    def _prefill_step(self) -> None:
        """One bucketed prefill call covering every prefilling row's
        next slice of prompt. Without chunking a row's slice is its
        whole remaining prompt (one call, as before); with chunking each
        row advances at most ``chunk_prefill_len`` positions per
        iteration, so the decode step below never waits behind a long
        prompt. Rows whose slice reaches the end of the prompt sample
        their first token from the slice's last logits and graduate to
        the decode set; prefix-cache rows start at ``prefill_pos > 0``
        and their completed prompts are registered for future sharing.
        """
        rows = list(self._prefilling)
        self._do_copies(rows)
        cnt = []
        for a in rows:
            remaining = a.prompt_len - a.prefill_pos
            if self.chunk_prefill_len:
                remaining = min(remaining, self.chunk_prefill_len)
            cnt.append(remaining)
        b = bucket_for(len(rows), self.buckets.batch_buckets)
        t = bucket_for(max(cnt), self.buckets.prefill_len_buckets)
        tok = np.zeros((b, t), np.int32)
        pos = np.zeros((b, t), np.int32)
        msk = np.zeros((b, t), bool)
        last = np.zeros((b,), np.int32)
        for i, a in enumerate(rows):
            lo, n = a.prefill_pos, cnt[i]
            tok[i, :n] = a.handle.req.prompt[lo:lo + n]
            pos[i, :n] = np.arange(lo, lo + n)
            msk[i, :n] = True
            last[i] = n - 1
        jt = (jnp.asarray(tok), jnp.asarray(pos), jnp.asarray(msk),
              jnp.asarray(last))
        tables = self._tables_for(rows, b)
        t0 = time.monotonic()
        pt0 = time.perf_counter() if self._tracer is not None else 0.0
        with self._span("serving_prefill", batch=b, length=t):
            logits, self._k_pool, self._v_pool = self._fwd(
                self._params, self.model_cfg, *jt,
                self._k_pool, self._v_pool, tables)
            if self._spec_k:
                # mirror the slice into the draft pools so the proposal
                # loop sees the same context the target does
                dl, self._dk_pool, self._dv_pool = self._draft_fwd(
                    self._draft_params, self.draft_cfg, *jt,
                    self._dk_pool, self._dv_pool, tables)
                dl.block_until_ready()
            first = np.asarray(jnp.argmax(logits, axis=-1))
        dt = time.monotonic() - t0
        self._h_prefill.observe(dt)
        if self._tracer is not None:
            for i, a in enumerate(rows):
                self._tracer.record_span(
                    "request_prefill_chunk", pt0, dt, **self._req_args(
                        a.handle.req, pos=a.prefill_pos, tokens=cnt[i]))
        done_t = time.monotonic()
        still_prefilling: List[_Active] = []
        graduated: List[_Active] = []
        for i, a in enumerate(rows):
            a.handle.prefill_s += dt
            a.prefill_pos += cnt[i]
            if a.prefill_pos < a.prompt_len:
                still_prefilling.append(a)
                continue
            a.handle.prefill_done_t = done_t
            if self._prefix is not None:
                self._prefix.register(
                    a.handle.req.prompt,
                    a.blocks[:self.cache.blocks_needed(a.prompt_len)])
            a.out.append(int(first[i]))
            a.last_token = int(first[i])
            if not self._maybe_finish(a):
                graduated.append(a)
        with self._cond:
            self._prefilling = still_prefilling
            self._active.extend(graduated)
            self._g_active.set(len(self._active) + len(self._prefilling))
            self._g_free_blocks.set(self._allocator.free_blocks())

    def _decode_step(self) -> None:
        """One decode iteration for every active sequence: append each
        row's last sampled token to the pool, sample the next."""
        rows = list(self._active)
        b = bucket_for(len(rows), self.buckets.batch_buckets)
        tok = np.zeros((b, 1), np.int32)
        pos = np.zeros((b, 1), np.int32)
        msk = np.zeros((b, 1), bool)
        for i, a in enumerate(rows):
            tok[i, 0] = a.last_token
            pos[i, 0] = a.prompt_len + len(a.out) - 1
            msk[i, 0] = True
        t0 = time.monotonic()
        with self._span("serving_decode_step", batch=b, rows=len(rows)):
            logits, self._k_pool, self._v_pool = self._fwd(
                self._params, self.model_cfg, jnp.asarray(tok),
                jnp.asarray(pos), jnp.asarray(msk),
                jnp.zeros((b,), jnp.int32),
                self._k_pool, self._v_pool, self._tables_for(rows, b))
            nxt = np.asarray(jnp.argmax(logits, axis=-1))
        self._h_decode.observe(time.monotonic() - t0)
        survivors: List[_Active] = []
        for i, a in enumerate(rows):
            a.out.append(int(nxt[i]))
            a.last_token = int(nxt[i])
            if not self._maybe_finish(a):
                survivors.append(a)
        with self._cond:
            self._active = survivors
            self._g_active.set(len(self._active) + len(self._prefilling))
            self._g_free_blocks.set(self._allocator.free_blocks())

    def _spec_step(self) -> None:
        """One speculative iteration for every active sequence: the
        draft proposes k tokens with k T=1 calls, the target scores
        [last committed token, draft_1..draft_k] in ONE k+1-token verify
        call, and each row emits the target's own greedy picks up to and
        including the first draft disagreement (plus the bonus token on
        full agreement) — 1..k+1 tokens per iteration, bit-identical to
        one-at-a-time decode for ANY draft output.

        Per-row ``allow`` masks draft/verify slots past the row's
        remaining ``max_new_tokens`` allowance, so speculation never
        writes K/V beyond the row's allocated blocks; rejected drafts
        leave stale pool entries past the accepted frontier, which
        position-masked attention never reads and the next iteration's
        scatter overwrites (models/gpt.py:forward_paged_logits).
        """
        rows = list(self._active)
        k = self._spec_k
        b = bucket_for(len(rows), self.buckets.batch_buckets)
        tables = self._tables_for(rows, b)
        n0 = np.array([a.prompt_len + len(a.out) for a in rows])
        allow = np.array([min(k + 1,
                              a.handle.req.max_new_tokens - len(a.out))
                          for a in rows])
        t0 = time.monotonic()
        pt0 = time.perf_counter() if self._tracer is not None else 0.0
        with self._span("serving_spec_step", batch=b, rows=len(rows),
                        k=k):
            drafts = np.zeros((len(rows), k), np.int64)
            cur = np.array([a.last_token for a in rows])
            zero_last = jnp.zeros((b,), jnp.int32)
            for j in range(k):
                tok = np.zeros((b, 1), np.int32)
                pos = np.zeros((b, 1), np.int32)
                msk = np.zeros((b, 1), bool)
                tok[:len(rows), 0] = cur
                pos[:len(rows), 0] = n0 - 1 + j
                msk[:len(rows), 0] = j < allow
                dl, self._dk_pool, self._dv_pool = self._draft_fwd(
                    self._draft_params, self.draft_cfg, jnp.asarray(tok),
                    jnp.asarray(pos), jnp.asarray(msk), zero_last,
                    self._dk_pool, self._dv_pool, tables)
                cur = np.asarray(jnp.argmax(dl, axis=-1))[:len(rows)]
                drafts[:, j] = cur
            tok = np.zeros((b, k + 1), np.int32)
            pos = np.zeros((b, k + 1), np.int32)
            msk = np.zeros((b, k + 1), bool)
            for i, a in enumerate(rows):
                tok[i, 0] = a.last_token
                tok[i, 1:] = drafts[i]
                pos[i] = np.arange(n0[i] - 1, n0[i] + k)
                msk[i] = np.arange(k + 1) < allow[i]
            logits, self._k_pool, self._v_pool = self._verify_fwd(
                self._params, self.model_cfg, jnp.asarray(tok),
                jnp.asarray(pos), jnp.asarray(msk),
                self._k_pool, self._v_pool, tables)
            target = np.asarray(jnp.argmax(logits, axis=-1))
        step_dt = time.monotonic() - t0
        self._h_decode.observe(step_dt)
        survivors: List[_Active] = []
        step_proposed = step_accepted = 0
        for i, a in enumerate(rows):
            # accept while the draft echoes the target's own greedy pick;
            # target[i, j] is trustworthy for j < allow[i] because all of
            # its conditioning tokens are committed-or-accepted by then
            emitted = [int(target[i, 0])]
            j = 0
            while (j < allow[i] - 1 and j < k
                   and int(drafts[i, j]) == int(target[i, j])):
                j += 1
                emitted.append(int(target[i, j]))
            usable = int(min(k, allow[i] - 1))
            a.spec_proposed += usable
            a.spec_accepted += len(emitted) - 1
            step_proposed += usable
            step_accepted += len(emitted) - 1
            if self._tracer is not None:
                self._tracer.record_span(
                    "request_spec_round", pt0, step_dt, **self._req_args(
                        a.handle.req, proposed=usable,
                        accepted=len(emitted) - 1, emitted=len(emitted)))
            for tk in emitted:
                a.out.append(tk)
                a.last_token = tk
                if (a.handle.req.eos_token_id is not None
                        and tk == a.handle.req.eos_token_id):
                    break
            if not self._maybe_finish(a):
                survivors.append(a)
        self._c_spec_proposed.inc(step_proposed)
        self._c_spec_accepted.inc(step_accepted)
        proposed = self._c_spec_proposed.value
        if proposed:
            self._g_spec_rate.set(
                self._c_spec_accepted.value / proposed)
        with self._cond:
            self._active = survivors
            self._g_active.set(len(self._active) + len(self._prefilling))
            self._g_free_blocks.set(self._allocator.free_blocks())

    def _maybe_finish(self, a: _Active) -> bool:
        req = a.handle.req
        reason = None
        if req.eos_token_id is not None and a.last_token == req.eos_token_id:
            reason = "eos"
        elif len(a.out) >= req.max_new_tokens:
            reason = "length"
        if reason is None:
            return False
        self._retire(a, reason)
        return True

    def _retire(self, a: _Active, reason: str) -> None:
        now = time.monotonic()
        self._allocator.release(a.blocks)
        h = a.handle
        result = RequestResult(
            request_id=h.req.request_id,
            prompt_len=a.prompt_len,
            tokens=list(a.out),
            finish_reason=reason,
            queue_wait_s=max(0.0, h.admit_t - h.submit_t),
            prefill_s=h.prefill_s,
            decode_s=(now - h.prefill_done_t if h.prefill_done_t else 0.0),
            total_s=now - h.submit_t,
            prefix_hit_blocks=a.hit_blocks,
            prefix_miss_blocks=a.miss_blocks,
            spec_proposed=a.spec_proposed,
            spec_accepted=a.spec_accepted,
            trace_id=h.req.trace_id)
        if self._tracer is not None:
            self._h_total.observe(result.total_s,
                                  exemplar=h.req.request_id)
            self._tracer.instant(
                "request_retired", **self._req_args(
                    h.req, finish_reason=reason, tokens=len(a.out),
                    total_s=round(result.total_s, 6),
                    queue_wait_s=round(result.queue_wait_s, 6),
                    prefix_hit_blocks=a.hit_blocks,
                    spec_proposed=a.spec_proposed,
                    spec_accepted=a.spec_accepted))
        else:
            self._h_total.observe(result.total_s)
        self._c_completed.inc()
        self._c_tokens.inc(len(a.out))
        if a.spec_proposed:
            self._h_spec_accept.observe(a.spec_accepted / a.spec_proposed)
        with self._cond:
            self._completed += 1
            self._total_tokens += len(a.out)
        h._finish(result)

    # -- static (run-to-completion) baseline -------------------------------

    def run_static(self, requests: Sequence[Tuple[Sequence[int], int]], *,
                   arrivals: Optional[Sequence[float]] = None,
                   timeout: Optional[float] = 300.0
                   ) -> List[RequestResult]:
        """Serve ``requests`` [(prompt, max_new_tokens), ...] the
        pre-continuous-batching way: FIFO groups of up to ``max_batch``,
        each run to completion (every decode step runs until the LAST
        member of the group finishes — early finishers burn batch slots),
        and no one joins a running group. Uses the very same jitted
        programs and pool as the continuous path, so bench comparisons
        isolate the *scheduling* policy. ``arrivals`` (seconds from call
        start, ascending) simulates offered load; latency for each
        request counts from its arrival instant.

        The engine must be idle (nothing queued or running) — this is a
        benchmarking harness, not a second serving mode.
        """
        with self._cond:
            self._await_idle_locked("run_static")
        arrivals = list(arrivals) if arrivals is not None \
            else [0.0] * len(requests)
        if len(arrivals) != len(requests):
            raise ValueError("arrivals must match requests")
        pending = sorted(
            ((arr, i, tuple(int(t) for t in p), int(mx))
             for i, ((p, mx), arr) in enumerate(zip(requests, arrivals))),
            key=lambda x: (x[0], x[1]))
        results: List[Optional[RequestResult]] = [None] * len(requests)
        t0 = time.monotonic()
        while pending:
            now = time.monotonic() - t0
            if pending[0][0] > now:
                time.sleep(min(pending[0][0] - now, 0.05))
                continue
            group = []
            while (pending and len(group) < self.buckets.max_batch
                   and pending[0][0] <= now):
                group.append(pending.pop(0))
            rows = []
            for arr, i, prompt, max_new in group:
                h = _Handle(Request(prompt, max_new, None, f"static-{i}"))
                h.submit_t = t0 + arr
                h.admit_t = time.monotonic()
                rows.append(_Active(h, self._allocator.allocate(
                    len(prompt) + max_new), len(prompt)))
            self._static_group(rows)
            for (arr, i, _, _), a in zip(group, rows):
                end = time.monotonic()
                self._allocator.release(a.blocks)
                results[i] = RequestResult(
                    request_id=f"static-{i}", prompt_len=a.prompt_len,
                    tokens=list(a.out), finish_reason="length",
                    queue_wait_s=a.handle.admit_t - a.handle.submit_t,
                    prefill_s=a.handle.prefill_s,
                    decode_s=end - a.handle.prefill_done_t,
                    total_s=end - a.handle.submit_t)
            if timeout is not None and time.monotonic() - t0 > timeout:
                raise TimeoutError("run_static exceeded its timeout")
        return [r for r in results if r is not None]

    def _static_group(self, rows: List[_Active]) -> None:
        """Prefill + decode one group run-to-completion: every step runs
        at the full group batch until the slowest member finishes;
        finished rows are masked (no pool writes) but keep burning their
        slot — the static-batching cost the continuous scheduler
        eliminates."""
        b = bucket_for(len(rows), self.buckets.batch_buckets)
        # chunked prefill applies to the static path too (same programs;
        # without it a chunked-engine workload could not be replayed) —
        # run-to-completion means chunks of ONE group interleave with
        # nothing, so the whole prompt still lands before any decode
        chunk = self.chunk_prefill_len or self.buckets.prefill_len_buckets[-1]
        tables = self._tables_for(rows, b)
        t0 = time.monotonic()
        offs = [0] * len(rows)
        first = np.zeros((b,), np.int64)
        while True:
            cnts = [min(chunk, a.prompt_len - offs[i])
                    for i, a in enumerate(rows)]
            t = bucket_for(max(cnts), self.buckets.prefill_len_buckets)
            tok = np.zeros((b, t), np.int32)
            pos = np.zeros((b, t), np.int32)
            msk = np.zeros((b, t), bool)
            last = np.zeros((b,), np.int32)
            for i, a in enumerate(rows):
                n = cnts[i]
                if n > 0:
                    tok[i, :n] = a.handle.req.prompt[offs[i]:offs[i] + n]
                    pos[i, :n] = np.arange(offs[i], offs[i] + n)
                    msk[i, :n] = True
                    last[i] = n - 1
            logits, self._k_pool, self._v_pool = self._fwd(
                self._params, self.model_cfg, jnp.asarray(tok),
                jnp.asarray(pos), jnp.asarray(msk), jnp.asarray(last),
                self._k_pool, self._v_pool, tables)
            picks = np.asarray(jnp.argmax(logits, axis=-1))
            for i, a in enumerate(rows):
                offs[i] += cnts[i]
                if cnts[i] > 0 and offs[i] >= a.prompt_len:
                    first[i] = picks[i]
            if all(offs[i] >= a.prompt_len for i, a in enumerate(rows)):
                break
        dt = time.monotonic() - t0
        done_t = time.monotonic()
        for i, a in enumerate(rows):
            a.handle.prefill_s = dt
            a.handle.prefill_done_t = done_t
            a.out.append(int(first[i]))
            a.last_token = int(first[i])
        group_max = max(a.handle.req.max_new_tokens for a in rows)
        for _ in range(group_max - 1):
            tok1 = np.zeros((b, 1), np.int32)
            pos1 = np.zeros((b, 1), np.int32)
            msk1 = np.zeros((b, 1), bool)
            for i, a in enumerate(rows):
                running = len(a.out) < a.handle.req.max_new_tokens
                tok1[i, 0] = a.last_token
                pos1[i, 0] = a.prompt_len + len(a.out) - 1
                msk1[i, 0] = running
            logits, self._k_pool, self._v_pool = self._fwd(
                self._params, self.model_cfg, jnp.asarray(tok1),
                jnp.asarray(pos1), jnp.asarray(msk1),
                jnp.zeros((b,), jnp.int32),
                self._k_pool, self._v_pool, tables)
            nxt = np.asarray(jnp.argmax(logits, axis=-1))
            for i, a in enumerate(rows):
                if len(a.out) < a.handle.req.max_new_tokens:
                    a.out.append(int(nxt[i]))
                    a.last_token = int(nxt[i])
