"""Powers-of-two padding buckets — the serving compile-discipline core.

Every jitted call the engine makes pads its dynamic dimensions (batch
rows, prompt length) UP to a bucket from a small fixed ladder, so XLA
compiles at most ``len(batch_buckets) * len(length_buckets)`` prefill
programs plus ``len(batch_buckets)`` decode programs — ever. The tier-1
compile-discipline test (tests/test_serving.py) asserts the jit cache
never exceeds that budget; without bucketing every new (batch, length)
pair would retrace (the PR 2 retrace detector fires on exactly this).
"""
from __future__ import annotations

import dataclasses
from typing import Sequence, Tuple


def pow2_buckets(lo: int, hi: int) -> Tuple[int, ...]:
    """Ascending powers of two covering [lo, hi]: first bucket >= lo,
    last bucket >= hi. pow2_buckets(1, 8) -> (1, 2, 4, 8);
    pow2_buckets(4, 100) -> (4, 8, 16, 32, 64, 128)."""
    if lo < 1 or hi < lo:
        raise ValueError(f"need 1 <= lo <= hi, got lo={lo} hi={hi}")
    buckets = []
    b = 1
    while b < lo:
        b *= 2
    while True:
        buckets.append(b)
        if b >= hi:
            return tuple(buckets)
        b *= 2


def bucket_for(n: int, buckets: Sequence[int]) -> int:
    """Smallest bucket >= n. Raises when n overflows the ladder — the
    caller (admission control) must reject before reaching here."""
    for b in buckets:
        if b >= n:
            return b
    raise ValueError(f"{n} exceeds the largest bucket {buckets[-1]}")


@dataclasses.dataclass(frozen=True)
class BucketSpec:
    """The fixed shape ladder one engine instance serves.

    batch_buckets:      padded batch sizes, ascending pow2.
    prefill_len_buckets: padded prompt lengths, ascending pow2.
    The decode path always runs at T=1, so its only dynamic dim is the
    batch — program_budget is the worst-case jit cache size and the
    number the tier-1 probe compares against.
    """
    batch_buckets: Tuple[int, ...]
    prefill_len_buckets: Tuple[int, ...]

    def __post_init__(self) -> None:
        for name, ladder in (("batch_buckets", self.batch_buckets),
                             ("prefill_len_buckets", self.prefill_len_buckets)):
            if not ladder:
                raise ValueError(f"{name} must be non-empty")
            if list(ladder) != sorted(set(ladder)):
                raise ValueError(f"{name} must be strictly ascending: {ladder}")
            for b in ladder:
                if b & (b - 1):
                    raise ValueError(f"{name} entries must be powers of two "
                                     f"(got {b})")

    @property
    def max_batch(self) -> int:
        return self.batch_buckets[-1]

    @property
    def max_prefill_len(self) -> int:
        return self.prefill_len_buckets[-1]

    @property
    def program_budget(self) -> int:
        """Worst-case number of XLA programs one shared jitted forward
        can compile: every (batch, prefill-length) pair plus a T=1
        decode shape per batch bucket."""
        return (len(self.batch_buckets) * len(self.prefill_len_buckets)
                + len(self.batch_buckets))

    def validate_chunk_len(self, chunk_len: int) -> int:
        """Chunked prefill reuses the prefill ladder: a chunk call pads
        to ``chunk_len`` exactly, so requiring the chunk length to BE a
        ladder entry means chunking adds zero new programs."""
        if chunk_len not in self.prefill_len_buckets:
            raise ValueError(
                f"chunk_prefill_len {chunk_len} must be one of the "
                f"prefill buckets {self.prefill_len_buckets} so chunked "
                f"prefill stays inside the program budget")
        return chunk_len

    def extended_budget(self, *, speculative: bool = False,
                        prefix_cache: bool = False,
                        kv_store: bool = False) -> int:
        """Worst-case jit cache size across ALL the engine's jitted
        entry points (the number warmup precompiles to and the tier-1
        probe asserts against):

        - base ladder (target prefill x batch + T=1 decode x batch);
        - speculative: the draft model runs the same ladder through its
          own jit (its prefill mirrors every target prefill shape, its
          k-token proposal loop is T=1 decode), plus one k+1-token
          verify program per batch bucket on the target;
        - prefix sharing: one copy-on-write block-copy program per pool
          pair (target, and draft when speculative);
        - KV tier: one host→pool block-write (promotion scatter)
          program per pool pair (target, and draft when speculative).
        """
        budget = self.program_budget
        if speculative:
            budget += self.program_budget + len(self.batch_buckets)
        if prefix_cache:
            budget += 2 if speculative else 1
        if kv_store:
            budget += 2 if speculative else 1
        return budget

    @staticmethod
    def build(max_batch: int, max_prefill_len: int, *,
              min_batch: int = 1, min_prefill_len: int = 8) -> "BucketSpec":
        return BucketSpec(
            batch_buckets=pow2_buckets(min_batch, max_batch),
            prefill_len_buckets=pow2_buckets(min(min_prefill_len,
                                                 max_prefill_len),
                                             max_prefill_len))
