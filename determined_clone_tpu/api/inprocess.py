"""In-process master — the control-plane surface without the C++ binary.

The cluster e2e path runs trials against the compiled ``dct-master``; the
*observability* plane also needs a master that test harnesses, the
LocalExperimentRunner, and ``bench.py`` can embed in-process: something
that speaks the same ``/api/v1/trials/{id}/profiler`` ingestion route and
serves the aggregated cluster view (`GET /metrics`, experiment traces)
without a build step. :class:`InProcessMaster` is that surface, built on
:class:`~determined_clone_tpu.telemetry.aggregate.ClusterMetricsAggregator`.

Three ways in, same routing table:

- direct calls (``master.ingest_trial(...)``) for same-process callers;
- :class:`InProcessSession` — a ``MasterSession``-compatible shim (same
  ``get``/``post``/``request`` signatures, same :class:`MasterError` on
  failure) so the ProfilerAgent and CLI code paths run unmodified;
- :func:`serve_http` — a stdlib ThreadingHTTPServer front-end on an
  ephemeral port, so real-HTTP round-trip tests (and ``dct metrics``
  against ``--master localhost:PORT``) exercise the wire format.
"""
from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple
from urllib.parse import parse_qs

from determined_clone_tpu.api.client import MasterError
from determined_clone_tpu.telemetry.aggregate import (
    ClusterMetricsAggregator,
    format_summary,
)


class InProcessMaster:
    """Routes observability traffic into a cluster aggregator.

    With :meth:`enable_timeseries` the master also grows a history
    layer: a :class:`~determined_clone_tpu.telemetry.tsdb.TimeSeriesDB`
    scraped from the aggregator plus a
    :class:`~determined_clone_tpu.telemetry.rules.RuleEngine`, exposed
    as ``GET /api/v1/timeseries`` and ``GET /api/v1/alerts``. Tests and
    the bench drive :meth:`scrape_tick` deterministically; production
    callers start the ``dct-tsdb-scrape`` loop.
    """

    def __init__(self, *,
                 clock: Callable[[], float] = time.time) -> None:
        self._clock = clock
        self.aggregator = ClusterMetricsAggregator(clock=clock)
        self._lock = threading.Lock()
        self._trial_experiment: Dict[int, int] = {}
        self.tsdb: Any = None
        self.rules: Any = None
        self._scraper: Any = None

    # -- time-series layer --------------------------------------------------

    def enable_timeseries(self, config: Optional[Any] = None, *,
                          tsdb: Any = None, rules: Any = None) -> Any:
        """Attach the TSDB + rule engine. ``config`` is an
        ObservabilityConfig (or its mapping form): ``timeseries:`` sizes
        the store, ``rules:`` declares the alert rules, and
        ``stock_slo_rules: true`` adds the PR 13 fast/slow burn pair.
        Returns the TSDB."""
        from determined_clone_tpu.telemetry.rules import (
            RuleEngine,
            stock_slo_rules,
        )
        from determined_clone_tpu.telemetry.tsdb import TimeSeriesDB

        raw: Dict[str, Any] = {}
        if config is not None:
            raw = (config.to_dict() if hasattr(config, "to_dict")
                   else dict(config))
        self.tsdb = tsdb if tsdb is not None else TimeSeriesDB.from_dict(
            raw.get("timeseries"), clock=self._clock)
        if rules is not None:
            self.rules = rules
        else:
            engine = RuleEngine.from_config(raw.get("rules"),
                                            clock=self._clock)
            if raw.get("stock_slo_rules"):
                for r in stock_slo_rules():
                    engine.add(r)
            self.rules = engine
        return self.tsdb

    def scrape_tick(self, now: Optional[float] = None) -> Dict[str, Any]:
        """One deterministic history tick: scrape the aggregator into
        the TSDB, evaluate the rules against it, and publish firing
        states back into the master registry (so the *next* scrape
        stores the alert gauges too)."""
        if self.tsdb is None:
            raise RuntimeError("time-series layer not enabled "
                               "(call enable_timeseries first)")
        now = self._clock() if now is None else float(now)
        stored = self.tsdb.scrape(self.aggregator, now=now)
        states = (self.rules.evaluate(self.tsdb, now=now)
                  if self.rules is not None else [])
        if self.rules is not None:
            self.rules.publish(self.aggregator.registry)
        return {"stored": stored, "rules": states}

    def start_scraper(self, period_s: float = 5.0) -> None:
        """Start the ``dct-tsdb-scrape`` background loop."""
        from determined_clone_tpu.telemetry.tsdb import TSDBScraper

        if self.tsdb is None:
            raise RuntimeError("time-series layer not enabled "
                               "(call enable_timeseries first)")
        if self._scraper is not None:
            raise RuntimeError("scraper already started")
        self._scraper = TSDBScraper(self.scrape_tick, period_s).start()

    def stop_scraper(self) -> None:
        if self._scraper is not None:
            self._scraper.close()
            self._scraper = None
        if self.tsdb is not None:
            self.tsdb.close()

    # -- direct (same-process) surface -------------------------------------

    def register_trial(self, trial_id: int, experiment_id: int) -> None:
        with self._lock:
            self._trial_experiment[int(trial_id)] = int(experiment_id)
        self.aggregator.register_trial(trial_id, experiment_id)

    def experiment_of(self, trial_id: int) -> Optional[int]:
        with self._lock:
            return self._trial_experiment.get(int(trial_id))

    def ingest_trial(self, trial_id: int, samples: List[Dict[str, Any]], *,
                     idempotency_key: Optional[str] = None) -> int:
        return self.aggregator.ingest(
            trial_id, samples, idempotency_key=idempotency_key,
            experiment_id=self.experiment_of(trial_id))

    def ingest_component(self, name: str, registry: Any) -> None:
        self.aggregator.ingest_component(name, registry)

    def ingest_component_spans(self, name: str,
                               samples: List[Dict[str, Any]], *,
                               experiment_id: Optional[int] = None) -> int:
        return self.aggregator.ingest_component_spans(
            name, samples, experiment_id=experiment_id)

    def metrics_text(self) -> str:
        return self.aggregator.dump()

    def summary(self, top_n: int = 10) -> Dict[str, Any]:
        return self.aggregator.summary(top_n)

    def spans(self, *, trial_id: Optional[int] = None,
              experiment_id: Optional[int] = None) -> List[Dict[str, Any]]:
        return self.aggregator.spans(trial_id=trial_id,
                                     experiment_id=experiment_id)

    # -- routing (shared by the session shim and the HTTP front-end) --------

    def handle(self, method: str, path: str,
               body: Optional[Dict[str, Any]] = None
               ) -> Tuple[int, Any, str]:
        """Dispatch one request; returns (status, payload, content_type).

        JSON payloads are dicts; ``/metrics`` returns Prometheus text.
        """
        path, _, query = path.partition("?")
        params = {k: v[-1] for k, v in parse_qs(query).items()}
        path = path.rstrip("/") or "/"
        parts = [p for p in path.split("/") if p]
        if method == "GET" and path == "/metrics":
            return 200, self.metrics_text(), "text/plain; version=0.0.4"
        if (method == "POST" and len(parts) == 5 and parts[:2] ==
                ["api", "v1"] and parts[2] == "trials"
                and parts[4] == "profiler"):
            body = body or {}
            samples = body.get("samples")
            if samples is None:
                return 400, {"error": "missing samples"}, "application/json"
            accepted = self.ingest_trial(
                int(parts[3]), samples,
                idempotency_key=body.get("idempotency_key"))
            return 200, {"accepted": accepted}, "application/json"
        if (method == "POST" and len(parts) == 5 and parts[:2] ==
                ["api", "v1"] and parts[2] == "components"
                and parts[4] == "profiler"):
            body = body or {}
            name = parts[3]
            accepted = 0
            metrics = body.get("metrics")
            if metrics is not None:
                self.ingest_component(name, metrics)
                accepted += 1
            spans = body.get("spans")
            if spans is not None:
                exp = body.get("experiment_id")
                accepted += self.ingest_component_spans(
                    name, spans,
                    experiment_id=int(exp) if exp is not None else None)
            return 200, {"accepted": accepted}, "application/json"
        if (method == "GET" and len(parts) == 4 and parts[:2] ==
                ["api", "v1"] and parts[2] == "cluster"
                and parts[3] == "metrics"):
            return 200, self.summary(), "application/json"
        if (method == "GET" and len(parts) == 4 and parts[:2] ==
                ["api", "v1"] and parts[2] == "cluster"
                and parts[3] == "goodput"):
            return 200, self.aggregator.goodput_rollup(), "application/json"
        if (method == "GET" and len(parts) == 4 and parts[:2] ==
                ["api", "v1"] and parts[2] == "cluster"
                and parts[3] == "slo"):
            return 200, {"slo": self.aggregator.slo_rollup()}, \
                "application/json"
        if (method == "GET" and len(parts) == 3 and parts[:2] ==
                ["api", "v1"] and parts[2] == "timeseries"):
            return self._handle_timeseries(params)
        if (method == "GET" and len(parts) == 3 and parts[:2] ==
                ["api", "v1"] and parts[2] == "alerts"):
            if self.rules is None:
                return 404, {"error": "alert rules not enabled on this "
                             "master"}, "application/json"
            return 200, self.rules.alerts(), "application/json"
        if (method == "GET" and len(parts) == 5 and parts[:2] ==
                ["api", "v1"] and parts[2] == "experiments"
                and parts[4] == "trace"):
            spans = self.spans(experiment_id=int(parts[3]))
            return 200, {"samples": spans}, "application/json"
        if (method == "GET" and len(parts) == 5 and parts[:2] ==
                ["api", "v1"] and parts[2] == "trials"
                and parts[4] == "trace"):
            spans = self.spans(trial_id=int(parts[3]))
            return 200, {"samples": spans}, "application/json"
        return 404, {"error": f"no route for {method} {path}"}, \
            "application/json"

    def _handle_timeseries(self, params: Dict[str, str]
                           ) -> Tuple[int, Any, str]:
        """``GET /api/v1/timeseries[?name=...&window=...&reduce=...&
        labels=k=v,k=v&q=...]`` — no ``name`` lists series + store
        stats; with one, runs a windowed query."""
        if self.tsdb is None:
            return 404, {"error": "time-series layer not enabled on "
                         "this master"}, "application/json"
        name = params.get("name")
        if not name:
            return 200, {"series": self.tsdb.series_names(),
                         "stats": self.tsdb.stats()}, "application/json"
        labels: Dict[str, str] = {}
        for part in (params.get("labels") or "").split(","):
            if not part:
                continue
            key, eq, value = part.partition("=")
            if not eq:
                return 400, {"error": f"bad labels matcher {part!r} "
                             "(want k=v,k2=v2)"}, "application/json"
            labels[key] = value
        try:
            payload = self.tsdb.query(
                name, labels or None,
                window_s=float(params.get("window", 300.0)),
                reduce=params.get("reduce", "raw"),
                q=float(params.get("q", 0.95)))
        except ValueError as e:
            return 400, {"error": str(e)}, "application/json"
        return 200, payload, "application/json"


class InProcessSession:
    """``MasterSession``-shaped handle onto an :class:`InProcessMaster`.

    Code written against the REST client (ProfilerAgent, CLI commands)
    runs against the in-process master unchanged; non-2xx responses raise
    :class:`MasterError` exactly like the HTTP client does.
    """

    def __init__(self, master: InProcessMaster) -> None:
        self.master = master
        self.host = "in-process"
        self.port = 0

    def request(self, method: str, path: str,
                body: Optional[Dict[str, Any]] = None, *,
                retryable: bool = False,
                idempotency_key: Optional[str] = None) -> Dict[str, Any]:
        if idempotency_key and body is not None:
            body = {**body, "idempotency_key": idempotency_key}
        status, payload, _ctype = self.master.handle(method, path, body)
        if status >= 400:
            msg = (payload.get("error", str(payload))
                   if isinstance(payload, dict) else str(payload))
            raise MasterError(status, msg)
        if isinstance(payload, str):
            return {"text": payload}
        return payload

    def get(self, path: str) -> Dict[str, Any]:
        return self.request("GET", path)

    def post(self, path: str, body: Optional[Dict[str, Any]] = None, *,
             retryable: bool = False,
             idempotency_key: Optional[str] = None) -> Dict[str, Any]:
        return self.request("POST", path, body, retryable=retryable,
                            idempotency_key=idempotency_key)


class _Handler(BaseHTTPRequestHandler):
    master: InProcessMaster  # set on the subclass by serve_http

    def _dispatch(self, method: str) -> None:
        body = None
        length = int(self.headers.get("Content-Length") or 0)
        if length:
            raw = self.rfile.read(length)
            try:
                body = json.loads(raw.decode("utf-8"))
            except (ValueError, UnicodeDecodeError):
                self._reply(400, {"error": "invalid JSON body"},
                            "application/json")
                return
        try:
            status, payload, ctype = self.master.handle(
                method, self.path, body)
        except Exception as e:  # noqa: BLE001 - surface, don't kill server
            status, payload, ctype = 500, {"error": str(e)}, \
                "application/json"
        self._reply(status, payload, ctype)

    def _reply(self, status: int, payload: Any, ctype: str) -> None:
        data = (payload if isinstance(payload, str)
                else json.dumps(payload)).encode("utf-8")
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self) -> None:  # noqa: N802 - http.server API
        self._dispatch("GET")

    def do_POST(self) -> None:  # noqa: N802 - http.server API
        self._dispatch("POST")

    def log_message(self, fmt: str, *args: Any) -> None:
        return None  # tests drive this at high rate; stay quiet


class MasterHTTPServer:
    """A running HTTP front-end; use as a context manager in tests."""

    def __init__(self, master: InProcessMaster, port: int = 0) -> None:
        handler = type("_BoundHandler", (_Handler,), {"master": master})
        self.master = master
        self._server = ThreadingHTTPServer(("127.0.0.1", port), handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self.host = "127.0.0.1"
        self._thread = threading.Thread(
            target=self._server.serve_forever, kwargs={"poll_interval": 0.1},
            name="dct-inprocess-master", daemon=True)

    def start(self) -> "MasterHTTPServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5)

    def __enter__(self) -> "MasterHTTPServer":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.stop()


def serve_http(master: InProcessMaster, port: int = 0) -> MasterHTTPServer:
    """Expose an in-process master over real HTTP on an ephemeral port."""
    return MasterHTTPServer(master, port).start()
