"""REST client to the master (≈ determined.common.api.Session + the
generated bindings.py — hand-written against the master's JSON API instead
of swagger codegen)."""
from __future__ import annotations

import json
import os
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Dict, Optional

from determined_clone_tpu import faults
from determined_clone_tpu.utils import retry as retry_util

# transport errors only: an HTTPError is an answer from the master, never
# retried (it subclasses URLError, so it must be converted before this
# tuple is consulted)
_TRANSPORT_ERRORS = (urllib.error.URLError, ConnectionError, TimeoutError)


def _q(segment: Any) -> str:
    """Percent-encode one URL path segment (names may contain spaces etc.)."""
    return urllib.parse.quote(str(segment), safe="")


def _b():
    """The generated bindings module (lazy: keeps client import-light for
    the in-task data plane)."""
    from determined_clone_tpu.api import bindings

    return bindings


class MasterError(RuntimeError):
    def __init__(self, status: int, message: str) -> None:
        super().__init__(f"master returned {status}: {message}")
        self.status = status


class MasterSession:
    def __init__(self, host: str = "127.0.0.1", port: int = 8080, *,
                 timeout: float = 70.0, retries: int = 3) -> None:
        self.host = host
        self.port = port
        self.timeout = timeout
        self.retries = retries
        # set by login(); inside an allocation the task's data-plane
        # credential (DCT_ALLOC_TOKEN, injected by the agent) authenticates
        # harness→master calls under --auth-required
        self.token: Optional[str] = os.environ.get("DCT_ALLOC_TOKEN") or None

    @property
    def base_url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def request(self, method: str, path: str,
                body: Optional[Dict[str, Any]] = None, *,
                retryable: Optional[bool] = None,
                timeout: Optional[float] = None,
                idempotency_key: Optional[str] = None) -> Dict[str, Any]:
        """``retryable`` controls transport-error retries. Default: GETs are
        retried, POSTs are not — a POST the master already processed must not
        be silently duplicated (create_experiment, completed_op). Idempotent
        POSTs (heartbeat, rendezvous, register) opt in; non-idempotent ones
        become safe by passing a client-generated ``idempotency_key`` (sent
        in the body, letting the master dedup replays). ``timeout``
        overrides the session timeout (long-poll follow requests outlive
        it by design)."""
        if retryable is None:
            retryable = method == "GET"
        if idempotency_key and body is not None:
            body = {**body, "idempotency_key": idempotency_key}
        data = json.dumps(body).encode() if body is not None else None

        def attempt() -> Dict[str, Any]:
            faults.point("api.request")
            headers = {"Content-Type": "application/json"}
            if self.token:
                headers["Authorization"] = f"Bearer {self.token}"
            req = urllib.request.Request(
                self.base_url + path, data=data, method=method,
                headers=headers,
            )
            try:
                with urllib.request.urlopen(
                        req, timeout=timeout or self.timeout) as resp:
                    payload = resp.read().decode()
                    return json.loads(payload) if payload else {}
            except urllib.error.HTTPError as e:
                detail = e.read().decode(errors="replace")
                try:
                    detail = json.loads(detail).get("error", detail)
                except Exception:
                    pass  # error body wasn't JSON; surface it raw
                raise MasterError(e.code, detail) from None

        policy = retry_util.RetryPolicy(
            name="api_request",
            max_attempts=max(1, self.retries) if retryable else 1,
            base_delay_s=0.2, max_delay_s=5.0,
            retryable=_TRANSPORT_ERRORS)
        try:
            return retry_util.retry_call(attempt, policy=policy)
        except _TRANSPORT_ERRORS as e:
            raise MasterError(
                0, f"master unreachable at {self.base_url}: {e}") from None

    def get(self, path: str) -> Dict[str, Any]:
        return self.request("GET", path)

    def post(self, path: str, body: Optional[Dict[str, Any]] = None, *,
             retryable: bool = False,
             idempotency_key: Optional[str] = None) -> Dict[str, Any]:
        return self.request("POST", path, body or {}, retryable=retryable,
                            idempotency_key=idempotency_key)

    # -- convenience wrappers ----------------------------------------------
    # These run on the GENERATED bindings (api/bindings.py, from
    # proto/dct/api/v1/api.proto) and convert back to plain dicts so
    # callers keep the wire shapes. New code can call bindings directly.

    def master_info(self) -> Dict[str, Any]:
        return _b().get_master(self, _b().V1GetMasterRequest()).to_json()

    def create_experiment(self, config: Dict[str, Any],
                          context: Optional[list] = None) -> Dict[str, Any]:
        b = _b()
        resp = b.create_experiment(self, b.V1CreateExperimentRequest(
            config=config, context=context or []))
        return resp.experiment.to_json()

    def list_experiments(self) -> list:
        b = _b()
        resp = b.list_experiments(self, b.V1ListExperimentsRequest())
        return [e.to_json() for e in resp.experiments]

    def get_experiment(self, exp_id: int) -> Dict[str, Any]:
        b = _b()
        return b.get_experiment(
            self, b.V1GetExperimentRequest(id=exp_id)).to_json()

    def pause_experiment(self, exp_id: int) -> Dict[str, Any]:
        return self.post(f"/api/v1/experiments/{exp_id}/pause")["experiment"]

    def activate_experiment(self, exp_id: int) -> Dict[str, Any]:
        return self.post(
            f"/api/v1/experiments/{exp_id}/activate")["experiment"]

    def archive_experiment(self, exp_id: int, archive: bool = True
                           ) -> Dict[str, Any]:
        action = "archive" if archive else "unarchive"
        return self.post(
            f"/api/v1/experiments/{exp_id}/{action}")["experiment"]

    def delete_experiment(self, exp_id: int) -> None:
        self.request("DELETE", f"/api/v1/experiments/{exp_id}")

    def kill_experiment(self, exp_id: int) -> Dict[str, Any]:
        b = _b()
        return b.kill_experiment(
            self, b.V1KillExperimentRequest(id=exp_id)).to_json()

    def get_trial(self, trial_id: int) -> Dict[str, Any]:
        b = _b()
        return b.get_trial(self, b.V1GetTrialRequest(id=trial_id)
                           ).trial.to_json()

    def trial_log_allocations(self, trial_id: int) -> list:
        """All of a trial's allocation leg ids, oldest first — the server
        names legs (trial-<id>.<leg> managed, unmanaged-<id>.<leg>
        unmanaged), so clients never reconstruct the scheme."""
        b = _b()
        resp = b.get_trial(self, b.V1GetTrialRequest(id=trial_id))
        latest = resp.latest_allocation
        trial = resp.trial.to_json()
        legs = int(trial.get("legs") or
                   int(trial.get("restarts", 0)) + 1)
        if not latest:
            return [f"trial-{trial_id}.{i}" for i in range(legs)]
        prefix = latest.rsplit(".", 1)[0]
        return [f"{prefix}.{i}" for i in range(max(legs, 1))]

    def kill_trial(self, trial_id: int) -> Dict[str, Any]:
        return self.post(f"/api/v1/trials/{trial_id}/kill")["trial"]

    def trial_metrics(self, trial_id: int, limit: int = 1000) -> list:
        # raw dicts, not V1MetricsRecord: metric records carry arbitrary
        # harness-defined keys the typed message would drop
        return self.get(f"/api/v1/trials/{trial_id}/metrics?limit={limit}")[
            "metrics"]

    def trial_metric_summary(self, trial_id: int) -> list:
        """Materialized per-(group, name) aggregates — flat-cost regardless
        of history depth (store.cc metric_summary)."""
        return self.get(f"/api/v1/trials/{trial_id}/metrics/summary")[
            "summary"]

    def trial_profiler_samples(self, trial_id: int, limit: int = 1000) -> list:
        return self.get(
            f"/api/v1/trials/{trial_id}/profiler?limit={limit}")["samples"]

    def list_agents(self) -> list:
        b = _b()
        resp = b.list_agents(self, b.V1ListAgentsRequest())
        return [a.to_json() for a in resp.agents]

    def job_queue(self) -> list:
        b = _b()
        resp = b.get_job_queue(self, b.V1GetJobQueueRequest())
        return [t.to_json() for t in resp.queue]

    def allgather(self, allocation_id: str, rank: int, data: Any, *,
                  round: int = 0, timeout: float = 300.0,
                  interval: float = 0.2) -> list:
        """Master-mediated allgather barrier: post our payload, poll until
        every member of the gang has posted, return the rank-ordered list
        (≈ master/internal/task/allgather)."""
        import time as _time

        deadline = _time.monotonic() + timeout
        while True:
            resp = self.post(
                f"/api/v1/allocations/{_q(allocation_id)}/allgather",
                {"rank": rank, "round": round, "data": data},
                retryable=True)  # idempotent re-registration
            if resp.get("ready"):
                return list(resp.get("data", []))
            if _time.monotonic() > deadline:
                raise MasterError(
                    408, f"allgather round {round} timed out with "
                         f"{resp.get('world_size')} members expected")
            _time.sleep(interval)

    def set_job_priority(self, allocation_id: str, priority: int) -> Dict[str, Any]:
        return self.post(f"/api/v1/job-queue/{_q(allocation_id)}/priority",
                         {"priority": priority})["job"]

    def move_job(self, allocation_id: str, *, ahead_of: str = "",
                 behind: str = "") -> Dict[str, Any]:
        return self.post(f"/api/v1/job-queue/{_q(allocation_id)}/move",
                         {"ahead_of": ahead_of, "behind": behind})["job"]

    def task_logs(self, allocation_id: str, limit: int = 1000) -> list:
        return self.get(
            f"/api/v1/allocations/{allocation_id}/logs?limit={limit}")["logs"]

    def stream_task_logs(self, allocation_id: str, page_size: int = 1000):
        """Yield log records, paging until the stream is dry (the REST
        analogue of the reference's streaming TrialLogs, api.proto:781)."""
        b = _b()
        for page in b.get_task_logs(self, b.V1GetTaskLogsRequest(
                id=allocation_id, limit=page_size)):
            for rec in page.logs:
                yield rec.to_json()

    def follow_task_logs(self, allocation_id: str, offset: int = 0,
                         follow_seconds: int = 30, page_size: int = 1000):
        """Live tail: yield records as they land, long-polling the master
        (follow mode of GetTaskLogs) until the allocation is terminal and
        drained. Each empty poll blocks master-side up to
        ``follow_seconds`` — no reconnect-per-line, no tail re-fetch."""
        while True:
            out = self.request(
                "GET",
                f"/api/v1/allocations/{_q(allocation_id)}/logs"
                f"?limit={page_size}&offset={offset}"
                f"&follow={follow_seconds}",
                timeout=follow_seconds + 15)
            for rec in out.get("logs", []):
                yield rec
            offset = int(out.get("next_offset", offset))
            if out.get("end_of_stream"):
                return
            if not out.get("logs") and follow_seconds <= 0:
                return  # drain-only call on a live allocation: don't spin

    # -- NTSC tasks (notebooks/shells/commands/tensorboards) ---------------

    def create_task(self, task_type: str, **kwargs: Any) -> Dict[str, Any]:
        """kwargs: name, cmd (argv, command type), slots, resource_pool,
        priority, idle_timeout, env, experiment_ids (tensorboard)."""
        b = _b()
        resp = b.create_task(self, b.V1CreateTaskRequest(
            type=task_type, **kwargs))
        return resp.task.to_json()

    def list_tasks(self, task_type: Optional[str] = None) -> list:
        b = _b()
        resp = b.list_tasks(self, b.V1ListTasksRequest(type=task_type or ""))
        return [t.to_json() for t in resp.tasks]

    def get_task(self, task_id: str) -> Dict[str, Any]:
        b = _b()
        return b.get_task(self, b.V1GetTaskRequest(id=task_id)).task.to_json()

    def kill_task(self, task_id: str) -> Dict[str, Any]:
        b = _b()
        return b.kill_task(self, b.V1KillTaskRequest(id=task_id)
                           ).task.to_json()

    def proxy(self, task_id: str, path: str, method: str = "GET",
              body: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """Reach a task's HTTP app through the master's reverse proxy."""
        return self.request(method, f"/proxy/{task_id}{path}", body)

    # -- auth / users ------------------------------------------------------

    def login(self, username: str, password: str = "") -> Dict[str, Any]:
        b = _b()
        resp = b.login(self, b.V1LoginRequest(username=username,
                                              password=password))
        self.token = resp.token
        return resp.user.to_json()

    def logout(self) -> None:
        b = _b()
        b.logout(self, b.V1LogoutRequest())
        self.token = None

    def whoami(self) -> Dict[str, Any]:
        b = _b()
        return b.get_me(self, b.V1GetMeRequest()).user.to_json()

    def create_user(self, username: str, password: str = "", *,
                    admin: bool = False) -> Dict[str, Any]:
        b = _b()
        resp = b.create_user(self, b.V1CreateUserRequest(
            username=username, password=password, admin=admin))
        return resp.user.to_json()

    def list_users(self) -> list:
        b = _b()
        return [u.to_json() for u in
                b.list_users(self, b.V1ListUsersRequest()).users]

    # -- workspaces / projects ---------------------------------------------

    def create_workspace(self, name: str) -> Dict[str, Any]:
        b = _b()
        return b.create_workspace(self, b.V1CreateWorkspaceRequest(
            name=name)).workspace.to_json()

    def list_workspaces(self) -> list:
        b = _b()
        return [w.to_json() for w in
                b.list_workspaces(self, b.V1ListWorkspacesRequest()
                                  ).workspaces]

    def get_workspace(self, workspace_id: int) -> Dict[str, Any]:
        return self.get(f"/api/v1/workspaces/{workspace_id}")

    def create_project(self, workspace_id: int, name: str,
                       description: str = "") -> Dict[str, Any]:
        return self.post(f"/api/v1/workspaces/{workspace_id}/projects",
                         {"name": name, "description": description})["project"]

    # -- model registry ----------------------------------------------------

    def create_model(self, name: str, **kwargs: Any) -> Dict[str, Any]:
        return self.post("/api/v1/models", {"name": name, **kwargs})["model"]

    def get_model(self, name_or_id: Any) -> Dict[str, Any]:
        return self.get(f"/api/v1/models/{_q(name_or_id)}")["model"]

    def list_models(self, name: Optional[str] = None) -> list:
        path = "/api/v1/models"
        if name:
            path += f"?name={_q(name)}"
        return self.get(path)["models"]

    def register_model_version(self, model: Any, checkpoint_uuid: str,
                               **kwargs: Any) -> Dict[str, Any]:
        return self.post(f"/api/v1/models/{_q(model)}/versions",
                         {"checkpoint_uuid": checkpoint_uuid, **kwargs})[
            "version"]

    # -- templates / webhooks ----------------------------------------------

    def set_template(self, name: str, config: Dict[str, Any]) -> None:
        self.post("/api/v1/templates", {"name": name, "config": config})

    def list_templates(self) -> list:
        return self.get("/api/v1/templates")["templates"]

    def get_template(self, name: str) -> Dict[str, Any]:
        return self.get(f"/api/v1/templates/{_q(name)}")

    def delete_template(self, name: str) -> None:
        self.request("DELETE", f"/api/v1/templates/{_q(name)}")

    def create_webhook(self, url: str, triggers: Optional[list] = None,
                       webhook_type: str = "default",
                       log_pattern: str = "") -> Dict[str, Any]:
        return self.post("/api/v1/webhooks", {
            "url": url, "triggers": triggers or [],
            "webhook_type": webhook_type, "log_pattern": log_pattern,
        })["webhook"]

    # -- groups / rbac (≈ usergroup + rbac services) ------------------------

    def create_group(self, name: str,
                     user_ids: Optional[list] = None) -> Dict[str, Any]:
        return self.post("/api/v1/groups", {
            "name": name, "user_ids": user_ids or [],
        })["group"]

    def list_groups(self) -> list:
        return self.get("/api/v1/groups")["groups"]

    def update_group_members(self, group_id: int,
                             add: Optional[list] = None,
                             remove: Optional[list] = None) -> Dict[str, Any]:
        return self.post(f"/api/v1/groups/{group_id}/members", {
            "add": add or [], "remove": remove or [],
        })["group"]

    def delete_group(self, group_id: int) -> None:
        self.request("DELETE", f"/api/v1/groups/{group_id}")

    def list_roles(self) -> list:
        return self.get("/api/v1/rbac/roles")["roles"]

    def assign_role(self, role: str, *, user_id: int = 0, group_id: int = 0,
                    workspace_id: int = 0) -> Dict[str, Any]:
        return self.post("/api/v1/rbac/assignments", {
            "role": role, "user_id": user_id, "group_id": group_id,
            "workspace_id": workspace_id,
        })["assignment"]

    def list_role_assignments(self) -> list:
        return self.get("/api/v1/rbac/assignments")["assignments"]

    def remove_role_assignment(self, assignment_id: int) -> None:
        self.request("DELETE", f"/api/v1/rbac/assignments/{assignment_id}")

    def my_permissions(self, workspace_id: int = 0) -> Dict[str, Any]:
        return self.get(f"/api/v1/rbac/me?workspace_id={workspace_id}")
