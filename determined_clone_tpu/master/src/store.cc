#include "store.h"

#include <dirent.h>
#include <dlfcn.h>

#include <algorithm>
#include <cstdio>
#include <deque>
#include <fstream>
#include <iostream>
#include <map>
#include <sstream>

namespace dct {
namespace {

// ---------------------------------------------------------------------------
// shared metric aggregation (files backend scan + sqlite backfill)
// ---------------------------------------------------------------------------

struct MetricAgg {
  int64_t count = 0;
  double sum = 0, min = 0, max = 0, last = 0;
  int64_t last_step = 0;
};

// One reported metrics record → per-(group, name) aggregates. Only numeric
// values aggregate (the train context serializes NaN as the string "nan").
void aggregate_metric_record(
    const Json& rec,
    std::map<std::pair<std::string, std::string>, MetricAgg>& aggs) {
  std::string grp = rec["group"].as_string();
  if (grp.empty()) grp = "training";
  int64_t step = rec["steps_completed"].as_int(0);
  if (!rec["metrics"].is_object()) return;
  for (const auto& [name, val] : rec["metrics"].items()) {
    if (!val.is_number()) continue;
    double v = val.as_number();
    MetricAgg& a = aggs[{grp, name}];
    if (a.count == 0) {
      a.min = a.max = v;
    } else {
      a.min = std::min(a.min, v);
      a.max = std::max(a.max, v);
    }
    ++a.count;
    a.sum += v;
    a.last = v;
    a.last_step = step;
  }
}

Json summary_json(
    const std::map<std::pair<std::string, std::string>, MetricAgg>& aggs) {
  Json arr = Json::array();
  for (const auto& [key, a] : aggs) {
    Json row = Json::object();
    row.set("group", key.first).set("name", key.second)
        .set("count", a.count).set("min", a.min).set("max", a.max)
        .set("mean", a.count ? a.sum / a.count : 0.0)
        .set("last", a.last).set("last_step", a.last_step);
    arr.push_back(row);
  }
  Json j = Json::object();
  j.set("summary", arr);
  return j;
}

// ---------------------------------------------------------------------------
// files backend (the original persistence mode)
// ---------------------------------------------------------------------------

class FileStore : public Store {
 public:
  explicit FileStore(std::string data_dir) : data_dir_(std::move(data_dir)) {}

  void save_snapshot(const std::string& json) override {
    const std::string path = data_dir_ + "/snapshot.json";
    const std::string tmp = path + ".tmp";
    {
      std::ofstream out(tmp);
      out << json;
    }
    ::rename(tmp.c_str(), path.c_str());
  }

  std::string load_snapshot() override {
    std::ifstream in(data_dir_ + "/snapshot.json");
    if (!in.good()) return "";
    std::stringstream buf;
    buf << in.rdbuf();
    return buf.str();
  }

  void append(const std::string& stream, const Json& rec) override {
    std::ofstream out(data_dir_ + "/" + stream, std::ios::app);
    out << rec.dump() << "\n";
  }

  void append_many(const std::string& stream,
                   const std::vector<const Json*>& recs) override {
    if (recs.empty()) return;
    std::ofstream out(data_dir_ + "/" + stream, std::ios::app);
    for (const Json* rec : recs) out << rec->dump() << "\n";
  }

  std::vector<Json> read(const std::string& stream, size_t limit,
                         size_t offset) override {
    std::ifstream in(data_dir_ + "/" + stream);
    std::vector<Json> out;
    std::string line;
    size_t index = 0;
    // The offset cursor counts record SLOTS: non-empty {...}-shaped lines
    // (everything this store itself writes). Skipped lines are NOT
    // Json::parsed — log followers re-read from their cursor on every
    // wake, and parsing 100k skipped lines under the master's state lock
    // per appended line froze the whole API. A torn line (crash
    // mid-append) fails the shape check and is invisible; a torn line
    // that merged with the next append still takes its slot but parses
    // to nothing, costing at most one duplicated record at the client.
    while (std::getline(in, line)) {
      if (line.empty() || line.front() != '{' || line.back() != '}') {
        continue;
      }
      if (index++ < offset) continue;
      try {
        out.push_back(Json::parse(line));
      } catch (const std::exception&) {
        continue;  // counted the slot; nothing to return for it
      }
      if (out.size() >= limit) break;
    }
    return out;
  }

  std::vector<Json> read_tail(const std::string& stream,
                              size_t limit) override {
    std::ifstream in(data_dir_ + "/" + stream);
    std::deque<std::string> tail;
    std::string line;
    while (std::getline(in, line)) {
      if (line.empty()) continue;
      tail.push_back(std::move(line));
      if (tail.size() > limit) tail.pop_front();
    }
    std::vector<Json> out;
    for (const auto& l : tail) {
      try {
        out.push_back(Json::parse(l));
      } catch (const std::exception&) {
      }
    }
    return out;
  }

  const char* kind() const override { return "files"; }

  void append_metric(int64_t trial_id, const Json& rec) override {
    append(metric_stream(trial_id), rec);
  }

  std::vector<Json> read_metrics(int64_t trial_id, size_t limit,
                                 size_t offset) override {
    return read(metric_stream(trial_id), limit, offset);
  }

  Json metric_summary(int64_t trial_id) override {
    // no materialization on the files backend: scan-aggregate (the sqlite
    // backend is the history-scale path; this keeps the API uniform)
    std::ifstream in(data_dir_ + "/" + metric_stream(trial_id));
    std::map<std::pair<std::string, std::string>, MetricAgg> aggs;
    std::string line;
    while (std::getline(in, line)) {
      if (line.empty() || line.front() != '{') continue;
      try {
        Json rec = Json::parse(line);
        aggregate_metric_record(rec, aggs);
      } catch (const std::exception&) {
      }
    }
    return summary_json(aggs);
  }

  void retain_stream(const std::string& stream, size_t keep_last) override {
    const std::string path = data_dir_ + "/" + stream;
    std::deque<std::string> tail;
    bool trimmed = false;
    {
      std::ifstream in(path);
      if (!in.good()) return;
      std::string line;
      while (std::getline(in, line)) {
        tail.push_back(std::move(line));
        if (tail.size() > keep_last) {
          tail.pop_front();
          trimmed = true;
        }
      }
    }
    if (!trimmed) return;  // already within budget: skip the rewrite
    const std::string tmp = path + ".tmp";
    {
      std::ofstream out(tmp);
      for (const auto& l : tail) out << l << "\n";
    }
    ::rename(tmp.c_str(), path.c_str());
  }

  int schema_version() override { return 0; }

 private:
  static std::string metric_stream(int64_t trial_id) {
    return "trial-" + std::to_string(trial_id) + "-metrics.jsonl";
  }

  std::string data_dir_;
};

// ---------------------------------------------------------------------------
// sqlite backend (libsqlite3 via dlopen — the image ships the runtime .so
// but no -dev header, so the stable C API subset is declared here)
// ---------------------------------------------------------------------------

struct sqlite3;
struct sqlite3_stmt;
constexpr int kSqliteOk = 0;
constexpr int kSqliteRow = 100;
constexpr int kSqliteDone = 101;
// SQLITE_TRANSIENT: sqlite copies the bound text immediately
const auto kTransient = reinterpret_cast<void (*)(void*)>(-1);

struct SqliteApi {
  int (*open)(const char*, sqlite3**);
  int (*close)(sqlite3*);
  int (*exec)(sqlite3*, const char*, int (*)(void*, int, char**, char**),
              void*, char**);
  int (*prepare)(sqlite3*, const char*, int, sqlite3_stmt**, const char**);
  int (*step)(sqlite3_stmt*);
  int (*reset)(sqlite3_stmt*);
  int (*finalize)(sqlite3_stmt*);
  int (*bind_text)(sqlite3_stmt*, int, const char*, int, void (*)(void*));
  int (*bind_int64)(sqlite3_stmt*, int, long long);
  int (*bind_double)(sqlite3_stmt*, int, double);
  const unsigned char* (*column_text)(sqlite3_stmt*, int);
  double (*column_double)(sqlite3_stmt*, int);
  long long (*column_int64)(sqlite3_stmt*, int);
  const char* (*errmsg)(sqlite3*);

  bool load() {
    void* lib = ::dlopen("libsqlite3.so.0", RTLD_NOW | RTLD_GLOBAL);
    if (!lib) lib = ::dlopen("libsqlite3.so", RTLD_NOW | RTLD_GLOBAL);
    if (!lib) return false;
    auto sym = [&](const char* name) { return ::dlsym(lib, name); };
    open = reinterpret_cast<decltype(open)>(sym("sqlite3_open"));
    close = reinterpret_cast<decltype(close)>(sym("sqlite3_close"));
    exec = reinterpret_cast<decltype(exec)>(sym("sqlite3_exec"));
    prepare = reinterpret_cast<decltype(prepare)>(sym("sqlite3_prepare_v2"));
    step = reinterpret_cast<decltype(step)>(sym("sqlite3_step"));
    reset = reinterpret_cast<decltype(reset)>(sym("sqlite3_reset"));
    finalize = reinterpret_cast<decltype(finalize)>(sym("sqlite3_finalize"));
    bind_text =
        reinterpret_cast<decltype(bind_text)>(sym("sqlite3_bind_text"));
    bind_int64 =
        reinterpret_cast<decltype(bind_int64)>(sym("sqlite3_bind_int64"));
    bind_double =
        reinterpret_cast<decltype(bind_double)>(sym("sqlite3_bind_double"));
    column_text =
        reinterpret_cast<decltype(column_text)>(sym("sqlite3_column_text"));
    column_double =
        reinterpret_cast<decltype(column_double)>(sym("sqlite3_column_double"));
    column_int64 =
        reinterpret_cast<decltype(column_int64)>(sym("sqlite3_column_int64"));
    errmsg = reinterpret_cast<decltype(errmsg)>(sym("sqlite3_errmsg"));
    return open && close && exec && prepare && step && reset && finalize &&
           bind_text && bind_int64 && bind_double && column_text &&
           column_double && column_int64 && errmsg;
  }
};

class SqliteStore : public Store {
 public:
  SqliteStore(SqliteApi api, sqlite3* db, std::string data_dir)
      : api_(api), db_(db), data_dir_(std::move(data_dir)) {}

  ~SqliteStore() override {
    if (insert_stmt_) api_.finalize(insert_stmt_);
    if (metric_insert_stmt_) api_.finalize(metric_insert_stmt_);
    if (summary_upsert_stmt_) api_.finalize(summary_upsert_stmt_);
    if (db_) api_.close(db_);
  }

  void save_snapshot(const std::string& json) override {
    exec_bound("INSERT OR REPLACE INTO kv (key, value) VALUES "
               "('snapshot', ?1)",
               {json});
  }

  std::string load_snapshot() override {
    std::string out;
    sqlite3_stmt* stmt = nullptr;
    if (api_.prepare(db_, "SELECT value FROM kv WHERE key = 'snapshot'", -1,
                     &stmt, nullptr) == kSqliteOk) {
      if (api_.step(stmt) == kSqliteRow) {
        const unsigned char* text = api_.column_text(stmt, 0);
        if (text) out = reinterpret_cast<const char*>(text);
      }
      api_.finalize(stmt);
    }
    if (!out.empty()) return out;
    // migration: adopt a files-backend snapshot on first boot
    std::ifstream in(data_dir_ + "/snapshot.json");
    if (!in.good()) return "";
    std::stringstream buf;
    buf << in.rdbuf();
    return buf.str();
  }

  void append(const std::string& stream, const Json& rec) override {
    append_raw(stream, rec.dump());
  }

  void append_many(const std::string& stream,
                   const std::vector<const Json*>& recs) override {
    if (recs.empty()) return;
    exec_sql("BEGIN");
    for (const Json* rec : recs) append(stream, *rec);
    exec_sql("COMMIT");
  }

  std::vector<Json> read(const std::string& stream, size_t limit,
                         size_t offset) override {
    return query("SELECT body FROM records WHERE stream = ?1 "
                 "ORDER BY seq LIMIT ?2 OFFSET ?3",
                 stream, limit, offset);
  }

  std::vector<Json> read_tail(const std::string& stream,
                              size_t limit) override {
    // newest `limit`, returned oldest-first
    return query("SELECT body FROM (SELECT seq, body FROM records "
                 "WHERE stream = ?1 ORDER BY seq DESC LIMIT ?2 OFFSET ?3) "
                 "ORDER BY seq ASC",
                 stream, limit, 0);
  }

  const char* kind() const override { return "sqlite"; }

  void append_metric(int64_t trial_id, const Json& rec) override {
    exec_sql("BEGIN");
    append_metric_rows(trial_id, rec);
    exec_sql("COMMIT");
  }

  // row + summary-upsert writes, no transaction (callers own it — the hot
  // path wraps one record, the v2 backfill wraps the whole migration)
  void append_metric_rows(int64_t trial_id, const Json& rec) {
    const std::string body = rec.dump();
    std::string grp = rec["group"].as_string();
    if (grp.empty()) grp = "training";
    {
      sqlite3_stmt* stmt = nullptr;
      if (api_.prepare(db_,
                       "INSERT INTO metrics (trial_id, seq, grp, step, time, "
                       "body) VALUES (?1, (SELECT COALESCE(MAX(seq), 0) + 1 "
                       "FROM metrics WHERE trial_id = ?1), ?2, ?3, ?4, ?5)",
                       -1, &stmt, nullptr) == kSqliteOk) {
        api_.bind_int64(stmt, 1, trial_id);
        api_.bind_text(stmt, 2, grp.c_str(), static_cast<int>(grp.size()),
                       kTransient);
        api_.bind_int64(stmt, 3, rec["steps_completed"].as_int(0));
        api_.bind_double(stmt, 4, rec["time"].as_number(0));
        api_.bind_text(stmt, 5, body.c_str(), static_cast<int>(body.size()),
                       kTransient);
        if (api_.step(stmt) != kSqliteDone) {
          std::cerr << "[store] metric insert failed: " << api_.errmsg(db_)
                    << std::endl;
        }
        api_.finalize(stmt);
      }
    }
    // materialized summary: one upsert per numeric metric (the
    // experiment/trial pages read aggregates without scanning history —
    // ≈ the reference's calculate-full-trial-summary-metrics.sql, kept
    // incrementally instead of recomputed)
    std::map<std::pair<std::string, std::string>, MetricAgg> aggs;
    aggregate_metric_record(rec, aggs);
    for (const auto& [key, a] : aggs) {
      if (!summary_upsert_stmt_) {
        if (api_.prepare(db_,
                         "INSERT INTO metric_summary (trial_id, grp, name, "
                         "count, sum, min, max, last, last_step) VALUES "
                         "(?1, ?2, ?3, 1, ?4, ?4, ?4, ?4, ?5) "
                         "ON CONFLICT(trial_id, grp, name) DO UPDATE SET "
                         "count = count + 1, sum = sum + excluded.sum, "
                         "min = MIN(min, excluded.min), "
                         "max = MAX(max, excluded.max), "
                         "last = excluded.last, "
                         "last_step = excluded.last_step",
                         -1, &summary_upsert_stmt_, nullptr) != kSqliteOk) {
          std::cerr << "[store] summary upsert prepare failed: "
                    << api_.errmsg(db_) << std::endl;
          return;
        }
      }
      api_.reset(summary_upsert_stmt_);
      api_.bind_int64(summary_upsert_stmt_, 1, trial_id);
      api_.bind_text(summary_upsert_stmt_, 2, key.first.c_str(),
                     static_cast<int>(key.first.size()), kTransient);
      api_.bind_text(summary_upsert_stmt_, 3, key.second.c_str(),
                     static_cast<int>(key.second.size()), kTransient);
      api_.bind_double(summary_upsert_stmt_, 4, a.last);
      api_.bind_int64(summary_upsert_stmt_, 5, a.last_step);
      if (api_.step(summary_upsert_stmt_) != kSqliteDone) {
        std::cerr << "[store] summary upsert failed: " << api_.errmsg(db_)
                  << std::endl;
      }
    }
  }

  std::vector<Json> read_metrics(int64_t trial_id, size_t limit,
                                 size_t offset) override {
    std::vector<Json> out;
    sqlite3_stmt* stmt = nullptr;
    if (api_.prepare(db_,
                     "SELECT body FROM metrics WHERE trial_id = ?1 "
                     "ORDER BY seq LIMIT ?2 OFFSET ?3",
                     -1, &stmt, nullptr) != kSqliteOk) {
      return out;
    }
    api_.bind_int64(stmt, 1, trial_id);
    api_.bind_int64(stmt, 2, static_cast<long long>(limit));
    api_.bind_int64(stmt, 3, static_cast<long long>(offset));
    while (api_.step(stmt) == kSqliteRow) {
      const unsigned char* text = api_.column_text(stmt, 0);
      if (!text) continue;
      try {
        out.push_back(Json::parse(reinterpret_cast<const char*>(text)));
      } catch (const std::exception&) {
      }
    }
    api_.finalize(stmt);
    return out;
  }

  Json metric_summary(int64_t trial_id) override {
    Json arr = Json::array();
    sqlite3_stmt* stmt = nullptr;
    if (api_.prepare(db_,
                     "SELECT grp, name, count, sum, min, max, last, "
                     "last_step FROM metric_summary WHERE trial_id = ?1 "
                     "ORDER BY grp, name",
                     -1, &stmt, nullptr) == kSqliteOk) {
      api_.bind_int64(stmt, 1, trial_id);
      while (api_.step(stmt) == kSqliteRow) {
        auto text = [&](int c) {
          const unsigned char* t = api_.column_text(stmt, c);
          return t ? std::string(reinterpret_cast<const char*>(t)) : "";
        };
        int64_t count = api_.column_int64(stmt, 2);
        Json row = Json::object();
        row.set("group", text(0)).set("name", text(1)).set("count", count)
            .set("min", api_.column_double(stmt, 4))
            .set("max", api_.column_double(stmt, 5))
            .set("mean", count ? api_.column_double(stmt, 3) / count : 0.0)
            .set("last", api_.column_double(stmt, 6))
            .set("last_step", static_cast<int64_t>(api_.column_int64(stmt, 7)));
        arr.push_back(row);
      }
      api_.finalize(stmt);
    }
    Json j = Json::object();
    j.set("summary", arr);
    return j;
  }

  void retain_stream(const std::string& stream, size_t keep_last) override {
    sqlite3_stmt* stmt = nullptr;
    if (api_.prepare(db_,
                     "DELETE FROM records WHERE stream = ?1 AND seq <= "
                     "(SELECT COALESCE(MAX(seq), 0) FROM records WHERE "
                     "stream = ?1) - ?2",
                     -1, &stmt, nullptr) != kSqliteOk) {
      return;
    }
    api_.bind_text(stmt, 1, stream.c_str(), static_cast<int>(stream.size()),
                   kTransient);
    api_.bind_int64(stmt, 2, static_cast<long long>(keep_last));
    if (api_.step(stmt) != kSqliteDone) {
      std::cerr << "[store] retention delete failed: " << api_.errmsg(db_)
                << std::endl;
    }
    api_.finalize(stmt);
  }

  int schema_version() override { return schema_version_; }

  // Versioned forward migrations (≈ the reference's
  // master/static/migrations — 144 up/down pairs under go-migrate; here a
  // linear ladder stamped into PRAGMA user_version). Each entry runs in a
  // transaction; a fresh database replays the whole ladder.
  bool init_schema() {
    if (!exec_sql("PRAGMA journal_mode=WAL") ||
        !exec_sql("PRAGMA synchronous=NORMAL")) {
      return false;
    }
    struct Migration {
      int version;
      const char* description;
      bool (SqliteStore::*apply)();
    };
    static const Migration kMigrations[] = {
        {1, "base kv + record streams", &SqliteStore::migrate_v1_base},
        {2, "relational metrics + materialized summary",
         &SqliteStore::migrate_v2_metrics},
    };
    int version = read_user_version();
    for (const auto& m : kMigrations) {
      if (m.version <= version) continue;
      if (m.version == 2) {
        // ORDER MATTERS: the v2 backfill reads `records`, so a files→sqlite
        // switch must import the legacy .jsonl streams first or every
        // pre-switch metric row would be invisible to the typed tables
        migrate_legacy_streams();
      }
      exec_sql("BEGIN");
      if (!(this->*m.apply)()) {
        exec_sql("ROLLBACK");
        std::cerr << "[store] migration v" << m.version << " ("
                  << m.description << ") failed" << std::endl;
        return false;
      }
      std::string stamp =
          "PRAGMA user_version = " + std::to_string(m.version);
      if (!exec_sql(stamp.c_str())) {
        exec_sql("ROLLBACK");
        return false;
      }
      exec_sql("COMMIT");
      if (version > 0) {
        std::cerr << "[store] applied migration v" << m.version << ": "
                  << m.description << std::endl;
      }
      version = m.version;
    }
    schema_version_ = version;
    return true;
  }

  // files→sqlite migration: on an empty records table, import legacy
  // .jsonl streams so existing metric/log history stays visible through
  // the API after the backend switch.
  void migrate_legacy_streams() {
    sqlite3_stmt* stmt = nullptr;
    bool empty = true;
    if (api_.prepare(db_, "SELECT 1 FROM records LIMIT 1", -1, &stmt,
                     nullptr) == kSqliteOk) {
      empty = api_.step(stmt) != kSqliteRow;
      api_.finalize(stmt);
    }
    if (!empty) return;
    DIR* dir = ::opendir(data_dir_.c_str());
    if (!dir) return;
    std::vector<std::string> streams;
    while (dirent* entry = ::readdir(dir)) {
      const std::string name = entry->d_name;
      if (name.size() > 6 && name.rfind(".jsonl") == name.size() - 6) {
        streams.push_back(name);
      }
    }
    ::closedir(dir);
    for (const auto& stream : streams) {
      std::ifstream in(data_dir_ + "/" + stream);
      std::string line;
      size_t imported = 0;
      exec_sql("BEGIN");
      while (std::getline(in, line)) {
        if (line.empty()) continue;
        append_raw(stream, line);
        ++imported;
      }
      exec_sql("COMMIT");
      if (imported) {
        std::cerr << "[store] migrated " << imported << " records from "
                  << stream << std::endl;
      }
    }
  }

 private:
  int read_user_version() {
    int version = 0;
    sqlite3_stmt* stmt = nullptr;
    if (api_.prepare(db_, "PRAGMA user_version", -1, &stmt, nullptr) ==
        kSqliteOk) {
      if (api_.step(stmt) == kSqliteRow) {
        version = static_cast<int>(api_.column_int64(stmt, 0));
      }
      api_.finalize(stmt);
    }
    return version;
  }

  bool migrate_v1_base() {
    return exec_sql("CREATE TABLE IF NOT EXISTS kv ("
                    "key TEXT PRIMARY KEY, value TEXT NOT NULL)") &&
           exec_sql("CREATE TABLE IF NOT EXISTS records ("
                    "stream TEXT NOT NULL, seq INTEGER NOT NULL, "
                    "body TEXT NOT NULL, PRIMARY KEY (stream, seq))");
  }

  bool migrate_v2_metrics() {
    if (!exec_sql("CREATE TABLE IF NOT EXISTS metrics ("
                  "trial_id INTEGER NOT NULL, seq INTEGER NOT NULL, "
                  "grp TEXT NOT NULL, step INTEGER, time REAL, "
                  "body TEXT NOT NULL, PRIMARY KEY (trial_id, seq))") ||
        !exec_sql("CREATE INDEX IF NOT EXISTS idx_metrics_trial_grp_step "
                  "ON metrics (trial_id, grp, step)") ||
        !exec_sql("CREATE TABLE IF NOT EXISTS metric_summary ("
                  "trial_id INTEGER NOT NULL, grp TEXT NOT NULL, "
                  "name TEXT NOT NULL, count INTEGER NOT NULL, "
                  "sum REAL, min REAL, max REAL, last REAL, "
                  "last_step INTEGER, PRIMARY KEY (trial_id, grp, name))")) {
      return false;
    }
    // backfill: metric history reported before this schema existed lives
    // in the generic record streams — move it into the typed tables so
    // summaries cover the whole trial, not just post-upgrade reports
    sqlite3_stmt* stmt = nullptr;
    if (api_.prepare(db_,
                     "SELECT stream, body FROM records WHERE stream LIKE "
                     "'trial-%-metrics.jsonl' ORDER BY stream, seq",
                     -1, &stmt, nullptr) != kSqliteOk) {
      return false;
    }
    size_t imported = 0;
    while (api_.step(stmt) == kSqliteRow) {
      const unsigned char* stream_c = api_.column_text(stmt, 0);
      const unsigned char* body_c = api_.column_text(stmt, 1);
      if (!stream_c || !body_c) continue;
      const std::string stream = reinterpret_cast<const char*>(stream_c);
      // "trial-<id>-metrics.jsonl"
      int64_t trial_id = std::atoll(stream.c_str() + 6);
      if (trial_id <= 0) continue;
      try {
        append_metric_rows(trial_id,
                           Json::parse(reinterpret_cast<const char*>(body_c)));
        ++imported;
      } catch (const std::exception&) {
      }
    }
    api_.finalize(stmt);
    if (imported) {
      std::cerr << "[store] migration v2 backfilled " << imported
                << " metric records" << std::endl;
    }
    return true;
  }

  void append_raw(const std::string& stream, const std::string& body) {
    // one prepared statement for the hot write path (log batches of 100+)
    if (!insert_stmt_) {
      if (api_.prepare(db_,
                       "INSERT INTO records (stream, seq, body) VALUES (?1, "
                       "(SELECT COALESCE(MAX(seq), 0) + 1 FROM records "
                       " WHERE stream = ?1), ?2)",
                       -1, &insert_stmt_, nullptr) != kSqliteOk) {
        std::cerr << "[store] sqlite prepare failed: " << api_.errmsg(db_)
                  << std::endl;
        return;
      }
    }
    api_.reset(insert_stmt_);
    api_.bind_text(insert_stmt_, 1, stream.c_str(),
                   static_cast<int>(stream.size()), kTransient);
    api_.bind_text(insert_stmt_, 2, body.c_str(),
                   static_cast<int>(body.size()), kTransient);
    if (api_.step(insert_stmt_) != kSqliteDone) {
      std::cerr << "[store] sqlite write failed: " << api_.errmsg(db_)
                << std::endl;
    }
  }
  bool exec_sql(const char* sql) {
    char* err = nullptr;
    if (api_.exec(db_, sql, nullptr, nullptr, &err) != kSqliteOk) {
      std::cerr << "[store] sqlite: " << (err ? err : "error") << " in "
                << sql << std::endl;
      return false;
    }
    return true;
  }

  void exec_bound(const char* sql, const std::vector<std::string>& binds) {
    sqlite3_stmt* stmt = nullptr;
    if (api_.prepare(db_, sql, -1, &stmt, nullptr) != kSqliteOk) {
      std::cerr << "[store] sqlite prepare failed: " << api_.errmsg(db_)
                << std::endl;
      return;
    }
    for (size_t i = 0; i < binds.size(); ++i) {
      api_.bind_text(stmt, static_cast<int>(i + 1), binds[i].c_str(),
                     static_cast<int>(binds[i].size()), kTransient);
    }
    if (api_.step(stmt) != kSqliteDone) {
      std::cerr << "[store] sqlite write failed: " << api_.errmsg(db_)
                << std::endl;
    }
    api_.finalize(stmt);
  }

  std::vector<Json> query(const char* sql, const std::string& stream,
                          size_t limit, size_t offset) {
    std::vector<Json> out;
    sqlite3_stmt* stmt = nullptr;
    if (api_.prepare(db_, sql, -1, &stmt, nullptr) != kSqliteOk) {
      return out;
    }
    api_.bind_text(stmt, 1, stream.c_str(), static_cast<int>(stream.size()),
                   kTransient);
    api_.bind_int64(stmt, 2, static_cast<long long>(limit));
    api_.bind_int64(stmt, 3, static_cast<long long>(offset));
    while (api_.step(stmt) == kSqliteRow) {
      const unsigned char* text = api_.column_text(stmt, 0);
      if (!text) continue;
      try {
        out.push_back(Json::parse(reinterpret_cast<const char*>(text)));
      } catch (const std::exception&) {
      }
    }
    api_.finalize(stmt);
    return out;
  }

  SqliteApi api_;
  sqlite3* db_;
  std::string data_dir_;
  sqlite3_stmt* insert_stmt_ = nullptr;
  sqlite3_stmt* metric_insert_stmt_ = nullptr;
  sqlite3_stmt* summary_upsert_stmt_ = nullptr;
  int schema_version_ = 0;
};

}  // namespace

std::unique_ptr<Store> make_file_store(const std::string& data_dir) {
  return std::make_unique<FileStore>(data_dir);
}

std::unique_ptr<Store> make_sqlite_store(const std::string& data_dir) {
  SqliteApi api{};
  if (!api.load()) return nullptr;
  sqlite3* db = nullptr;
  if (api.open((data_dir + "/master.db").c_str(), &db) != kSqliteOk || !db) {
    if (db) api.close(db);
    return nullptr;
  }
  auto store = std::make_unique<SqliteStore>(api, db, data_dir);
  if (!store->init_schema()) return nullptr;
  store->migrate_legacy_streams();
  return store;
}

}  // namespace dct
