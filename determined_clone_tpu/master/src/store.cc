#include "store.h"

#include <dirent.h>
#include <dlfcn.h>

#include <cstdio>
#include <deque>
#include <fstream>
#include <iostream>
#include <sstream>

namespace dct {
namespace {

// ---------------------------------------------------------------------------
// files backend (the original persistence mode)
// ---------------------------------------------------------------------------

class FileStore : public Store {
 public:
  explicit FileStore(std::string data_dir) : data_dir_(std::move(data_dir)) {}

  void save_snapshot(const std::string& json) override {
    const std::string path = data_dir_ + "/snapshot.json";
    const std::string tmp = path + ".tmp";
    {
      std::ofstream out(tmp);
      out << json;
    }
    ::rename(tmp.c_str(), path.c_str());
  }

  std::string load_snapshot() override {
    std::ifstream in(data_dir_ + "/snapshot.json");
    if (!in.good()) return "";
    std::stringstream buf;
    buf << in.rdbuf();
    return buf.str();
  }

  void append(const std::string& stream, const Json& rec) override {
    std::ofstream out(data_dir_ + "/" + stream, std::ios::app);
    out << rec.dump() << "\n";
  }

  void append_many(const std::string& stream,
                   const std::vector<const Json*>& recs) override {
    if (recs.empty()) return;
    std::ofstream out(data_dir_ + "/" + stream, std::ios::app);
    for (const Json* rec : recs) out << rec->dump() << "\n";
  }

  std::vector<Json> read(const std::string& stream, size_t limit,
                         size_t offset) override {
    std::ifstream in(data_dir_ + "/" + stream);
    std::vector<Json> out;
    std::string line;
    size_t index = 0;
    // The offset cursor counts record SLOTS: non-empty {...}-shaped lines
    // (everything this store itself writes). Skipped lines are NOT
    // Json::parsed — log followers re-read from their cursor on every
    // wake, and parsing 100k skipped lines under the master's state lock
    // per appended line froze the whole API. A torn line (crash
    // mid-append) fails the shape check and is invisible; a torn line
    // that merged with the next append still takes its slot but parses
    // to nothing, costing at most one duplicated record at the client.
    while (std::getline(in, line)) {
      if (line.empty() || line.front() != '{' || line.back() != '}') {
        continue;
      }
      if (index++ < offset) continue;
      try {
        out.push_back(Json::parse(line));
      } catch (const std::exception&) {
        continue;  // counted the slot; nothing to return for it
      }
      if (out.size() >= limit) break;
    }
    return out;
  }

  std::vector<Json> read_tail(const std::string& stream,
                              size_t limit) override {
    std::ifstream in(data_dir_ + "/" + stream);
    std::deque<std::string> tail;
    std::string line;
    while (std::getline(in, line)) {
      if (line.empty()) continue;
      tail.push_back(std::move(line));
      if (tail.size() > limit) tail.pop_front();
    }
    std::vector<Json> out;
    for (const auto& l : tail) {
      try {
        out.push_back(Json::parse(l));
      } catch (const std::exception&) {
      }
    }
    return out;
  }

  const char* kind() const override { return "files"; }

 private:
  std::string data_dir_;
};

// ---------------------------------------------------------------------------
// sqlite backend (libsqlite3 via dlopen — the image ships the runtime .so
// but no -dev header, so the stable C API subset is declared here)
// ---------------------------------------------------------------------------

struct sqlite3;
struct sqlite3_stmt;
constexpr int kSqliteOk = 0;
constexpr int kSqliteRow = 100;
constexpr int kSqliteDone = 101;
// SQLITE_TRANSIENT: sqlite copies the bound text immediately
const auto kTransient = reinterpret_cast<void (*)(void*)>(-1);

struct SqliteApi {
  int (*open)(const char*, sqlite3**);
  int (*close)(sqlite3*);
  int (*exec)(sqlite3*, const char*, int (*)(void*, int, char**, char**),
              void*, char**);
  int (*prepare)(sqlite3*, const char*, int, sqlite3_stmt**, const char**);
  int (*step)(sqlite3_stmt*);
  int (*reset)(sqlite3_stmt*);
  int (*finalize)(sqlite3_stmt*);
  int (*bind_text)(sqlite3_stmt*, int, const char*, int, void (*)(void*));
  int (*bind_int64)(sqlite3_stmt*, int, long long);
  const unsigned char* (*column_text)(sqlite3_stmt*, int);
  const char* (*errmsg)(sqlite3*);

  bool load() {
    void* lib = ::dlopen("libsqlite3.so.0", RTLD_NOW | RTLD_GLOBAL);
    if (!lib) lib = ::dlopen("libsqlite3.so", RTLD_NOW | RTLD_GLOBAL);
    if (!lib) return false;
    auto sym = [&](const char* name) { return ::dlsym(lib, name); };
    open = reinterpret_cast<decltype(open)>(sym("sqlite3_open"));
    close = reinterpret_cast<decltype(close)>(sym("sqlite3_close"));
    exec = reinterpret_cast<decltype(exec)>(sym("sqlite3_exec"));
    prepare = reinterpret_cast<decltype(prepare)>(sym("sqlite3_prepare_v2"));
    step = reinterpret_cast<decltype(step)>(sym("sqlite3_step"));
    reset = reinterpret_cast<decltype(reset)>(sym("sqlite3_reset"));
    finalize = reinterpret_cast<decltype(finalize)>(sym("sqlite3_finalize"));
    bind_text =
        reinterpret_cast<decltype(bind_text)>(sym("sqlite3_bind_text"));
    bind_int64 =
        reinterpret_cast<decltype(bind_int64)>(sym("sqlite3_bind_int64"));
    column_text =
        reinterpret_cast<decltype(column_text)>(sym("sqlite3_column_text"));
    errmsg = reinterpret_cast<decltype(errmsg)>(sym("sqlite3_errmsg"));
    return open && close && exec && prepare && step && reset && finalize &&
           bind_text && bind_int64 && column_text && errmsg;
  }
};

class SqliteStore : public Store {
 public:
  SqliteStore(SqliteApi api, sqlite3* db, std::string data_dir)
      : api_(api), db_(db), data_dir_(std::move(data_dir)) {}

  ~SqliteStore() override {
    if (insert_stmt_) api_.finalize(insert_stmt_);
    if (db_) api_.close(db_);
  }

  void save_snapshot(const std::string& json) override {
    exec_bound("INSERT OR REPLACE INTO kv (key, value) VALUES "
               "('snapshot', ?1)",
               {json});
  }

  std::string load_snapshot() override {
    std::string out;
    sqlite3_stmt* stmt = nullptr;
    if (api_.prepare(db_, "SELECT value FROM kv WHERE key = 'snapshot'", -1,
                     &stmt, nullptr) == kSqliteOk) {
      if (api_.step(stmt) == kSqliteRow) {
        const unsigned char* text = api_.column_text(stmt, 0);
        if (text) out = reinterpret_cast<const char*>(text);
      }
      api_.finalize(stmt);
    }
    if (!out.empty()) return out;
    // migration: adopt a files-backend snapshot on first boot
    std::ifstream in(data_dir_ + "/snapshot.json");
    if (!in.good()) return "";
    std::stringstream buf;
    buf << in.rdbuf();
    return buf.str();
  }

  void append(const std::string& stream, const Json& rec) override {
    append_raw(stream, rec.dump());
  }

  void append_many(const std::string& stream,
                   const std::vector<const Json*>& recs) override {
    if (recs.empty()) return;
    exec_sql("BEGIN");
    for (const Json* rec : recs) append(stream, *rec);
    exec_sql("COMMIT");
  }

  std::vector<Json> read(const std::string& stream, size_t limit,
                         size_t offset) override {
    return query("SELECT body FROM records WHERE stream = ?1 "
                 "ORDER BY seq LIMIT ?2 OFFSET ?3",
                 stream, limit, offset);
  }

  std::vector<Json> read_tail(const std::string& stream,
                              size_t limit) override {
    // newest `limit`, returned oldest-first
    return query("SELECT body FROM (SELECT seq, body FROM records "
                 "WHERE stream = ?1 ORDER BY seq DESC LIMIT ?2 OFFSET ?3) "
                 "ORDER BY seq ASC",
                 stream, limit, 0);
  }

  const char* kind() const override { return "sqlite"; }

  bool init_schema() {
    return exec_sql("PRAGMA journal_mode=WAL") &&
           exec_sql("PRAGMA synchronous=NORMAL") &&
           exec_sql("CREATE TABLE IF NOT EXISTS kv ("
                    "key TEXT PRIMARY KEY, value TEXT NOT NULL)") &&
           exec_sql("CREATE TABLE IF NOT EXISTS records ("
                    "stream TEXT NOT NULL, seq INTEGER NOT NULL, "
                    "body TEXT NOT NULL, PRIMARY KEY (stream, seq))");
  }

  // files→sqlite migration: on an empty records table, import legacy
  // .jsonl streams so existing metric/log history stays visible through
  // the API after the backend switch.
  void migrate_legacy_streams() {
    sqlite3_stmt* stmt = nullptr;
    bool empty = true;
    if (api_.prepare(db_, "SELECT 1 FROM records LIMIT 1", -1, &stmt,
                     nullptr) == kSqliteOk) {
      empty = api_.step(stmt) != kSqliteRow;
      api_.finalize(stmt);
    }
    if (!empty) return;
    DIR* dir = ::opendir(data_dir_.c_str());
    if (!dir) return;
    std::vector<std::string> streams;
    while (dirent* entry = ::readdir(dir)) {
      const std::string name = entry->d_name;
      if (name.size() > 6 && name.rfind(".jsonl") == name.size() - 6) {
        streams.push_back(name);
      }
    }
    ::closedir(dir);
    for (const auto& stream : streams) {
      std::ifstream in(data_dir_ + "/" + stream);
      std::string line;
      size_t imported = 0;
      exec_sql("BEGIN");
      while (std::getline(in, line)) {
        if (line.empty()) continue;
        append_raw(stream, line);
        ++imported;
      }
      exec_sql("COMMIT");
      if (imported) {
        std::cerr << "[store] migrated " << imported << " records from "
                  << stream << std::endl;
      }
    }
  }

 private:
  void append_raw(const std::string& stream, const std::string& body) {
    // one prepared statement for the hot write path (log batches of 100+)
    if (!insert_stmt_) {
      if (api_.prepare(db_,
                       "INSERT INTO records (stream, seq, body) VALUES (?1, "
                       "(SELECT COALESCE(MAX(seq), 0) + 1 FROM records "
                       " WHERE stream = ?1), ?2)",
                       -1, &insert_stmt_, nullptr) != kSqliteOk) {
        std::cerr << "[store] sqlite prepare failed: " << api_.errmsg(db_)
                  << std::endl;
        return;
      }
    }
    api_.reset(insert_stmt_);
    api_.bind_text(insert_stmt_, 1, stream.c_str(),
                   static_cast<int>(stream.size()), kTransient);
    api_.bind_text(insert_stmt_, 2, body.c_str(),
                   static_cast<int>(body.size()), kTransient);
    if (api_.step(insert_stmt_) != kSqliteDone) {
      std::cerr << "[store] sqlite write failed: " << api_.errmsg(db_)
                << std::endl;
    }
  }
  bool exec_sql(const char* sql) {
    char* err = nullptr;
    if (api_.exec(db_, sql, nullptr, nullptr, &err) != kSqliteOk) {
      std::cerr << "[store] sqlite: " << (err ? err : "error") << " in "
                << sql << std::endl;
      return false;
    }
    return true;
  }

  void exec_bound(const char* sql, const std::vector<std::string>& binds) {
    sqlite3_stmt* stmt = nullptr;
    if (api_.prepare(db_, sql, -1, &stmt, nullptr) != kSqliteOk) {
      std::cerr << "[store] sqlite prepare failed: " << api_.errmsg(db_)
                << std::endl;
      return;
    }
    for (size_t i = 0; i < binds.size(); ++i) {
      api_.bind_text(stmt, static_cast<int>(i + 1), binds[i].c_str(),
                     static_cast<int>(binds[i].size()), kTransient);
    }
    if (api_.step(stmt) != kSqliteDone) {
      std::cerr << "[store] sqlite write failed: " << api_.errmsg(db_)
                << std::endl;
    }
    api_.finalize(stmt);
  }

  std::vector<Json> query(const char* sql, const std::string& stream,
                          size_t limit, size_t offset) {
    std::vector<Json> out;
    sqlite3_stmt* stmt = nullptr;
    if (api_.prepare(db_, sql, -1, &stmt, nullptr) != kSqliteOk) {
      return out;
    }
    api_.bind_text(stmt, 1, stream.c_str(), static_cast<int>(stream.size()),
                   kTransient);
    api_.bind_int64(stmt, 2, static_cast<long long>(limit));
    api_.bind_int64(stmt, 3, static_cast<long long>(offset));
    while (api_.step(stmt) == kSqliteRow) {
      const unsigned char* text = api_.column_text(stmt, 0);
      if (!text) continue;
      try {
        out.push_back(Json::parse(reinterpret_cast<const char*>(text)));
      } catch (const std::exception&) {
      }
    }
    api_.finalize(stmt);
    return out;
  }

  SqliteApi api_;
  sqlite3* db_;
  std::string data_dir_;
  sqlite3_stmt* insert_stmt_ = nullptr;
};

}  // namespace

std::unique_ptr<Store> make_file_store(const std::string& data_dir) {
  return std::make_unique<FileStore>(data_dir);
}

std::unique_ptr<Store> make_sqlite_store(const std::string& data_dir) {
  SqliteApi api{};
  if (!api.load()) return nullptr;
  sqlite3* db = nullptr;
  if (api.open((data_dir + "/master.db").c_str(), &db) != kSqliteOk || !db) {
    if (db) api.close(db);
    return nullptr;
  }
  auto store = std::make_unique<SqliteStore>(api, db, data_dir);
  if (!store->init_schema()) return nullptr;
  store->migrate_legacy_streams();
  return store;
}

}  // namespace dct
