// Control-plane scheduler telemetry (docs/observability.md).
//
// ≈ the reference's prom collectors over master internals
// (master/internal/prom/): lifecycle counters, decision-loop timing, and
// latency quantiles for the scheduling path, plus a bounded ring of
// master-lane events in Chrome-trace form so `dct trace export` can show
// submit→schedule→run next to the trial's own spans.
//
// Everything here is guarded by the master state lock (mu_): every
// mutation site (queue_trial_leg, the RM tick, task_event handlers, the
// job-queue routes) and every reader (metrics_route, the cluster routes)
// already holds it.
#pragma once

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <deque>
#include <map>
#include <string>
#include <utility>
#include <vector>

namespace dct {

// Reservoir quantile estimator matching telemetry/metrics.py Histogram:
// algorithm-R reservoir + numpy-default linear-interpolation percentiles,
// rendered as a Prometheus summary (quantile children + _sum/_count).
// Deterministic (fixed-seed xorshift) like the Python side's seeded RNG.
class SchedReservoir {
 public:
  explicit SchedReservoir(size_t cap = 4096) : cap_(cap) {}

  void observe(double v) {
    ++count_;
    sum_ += v;
    if (reservoir_.size() < cap_) {
      reservoir_.push_back(v);
    } else {
      uint64_t j = next_rand() % static_cast<uint64_t>(count_);
      if (j < cap_) reservoir_[static_cast<size_t>(j)] = v;
    }
  }

  int64_t count() const { return count_; }
  double sum() const { return sum_; }

  // NaN when empty — the exposition renders that as the literal "NaN",
  // exactly like the Python registry's empty histograms.
  double percentile(double q) const {
    if (reservoir_.empty()) return std::nan("");
    std::vector<double> s = reservoir_;
    std::sort(s.begin(), s.end());
    double pos = q * static_cast<double>(s.size() - 1);
    size_t lo = static_cast<size_t>(pos);
    size_t hi = std::min(lo + 1, s.size() - 1);
    double frac = pos - static_cast<double>(lo);
    return s[lo] * (1.0 - frac) + s[hi] * frac;
  }

 private:
  uint64_t next_rand() {
    // xorshift64*: deterministic replacement decay once the reservoir fills
    state_ ^= state_ >> 12;
    state_ ^= state_ << 25;
    state_ ^= state_ >> 27;
    return state_ * 0x2545F4914F6CDD1Dull;
  }

  size_t cap_;
  int64_t count_ = 0;
  double sum_ = 0;
  std::vector<double> reservoir_;
  uint64_t state_ = 0x9E3779B97F4A7C15ull;
};

// One master-lane scheduler event, Chrome-trace-ready: wall_epoch anchors
// the span start (stitch_chrome_trace re-bases each record onto the shared
// axis), dur_us is the span length (0 = instant).
struct SchedEvent {
  std::string name;        // submit | schedule | running | end | preempt |
                           // requeue | decision
  std::string alloc_id;
  int64_t trial_id = 0;
  int64_t experiment_id = 0;
  double wall_epoch = 0;   // epoch seconds of event start
  double dur_us = 0;
  std::string pool;
};

// The master's scheduling-path counters/gauges/latency reservoirs.
struct SchedTelemetry {
  // lifecycle counters
  int64_t submitted_total = 0;        // allocations entering the queue
  int64_t scheduled_total = 0;        // reservations granted
  int64_t running_total = 0;          // harness-confirmed running
  int64_t completed_total = 0;        // terminal transitions
  int64_t preemptions_total = 0;      // preempt requests issued
  int64_t reschedules_total = 0;      // requeues + operator queue reshuffles
  int64_t queue_moves_total = 0;      // job-queue move-ahead/behind ops
  int64_t priority_changes_total = 0; // job-queue reprioritize ops
  // serving-fleet counters (the `serving` allocation type: replica gangs
  // created through /api/v1/serving/fleets — docs/serving.md)
  int64_t serving_submitted_total = 0;  // replica allocations created
  int64_t serving_running_total = 0;    // replicas confirmed serving
  int64_t serving_completed_total = 0;  // replicas drained/terminated
  // decision-loop counters
  int64_t decisions_total = 0;        // schedule_pool passes
  int64_t considered_total = 0;       // pending allocations examined
  int64_t gangs_admitted_total = 0;   // multi-agent / multislice admissions
  int64_t gang_wait_ticks_total = 0;  // alloc-passes spent waiting for a fit
  // last-pass gauge: slot-requesting allocations that found no fit, by pool
  std::map<std::string, int64_t> gang_waiting_by_pool;
  // latency distributions (seconds)
  SchedReservoir decision_seconds;          // one schedule_pool call
  SchedReservoir queue_wait_seconds;        // queued -> scheduled
  SchedReservoir submit_to_running_seconds; // submitted -> running
  // master-lane event ring (oldest dropped; the per-experiment trace route
  // synthesizes from allocation timestamps instead, so eviction here only
  // affects the cluster-wide event dump)
  std::deque<SchedEvent> events;
  size_t events_cap = 4096;
  int64_t events_dropped = 0;

  void push_event(SchedEvent ev) {
    if (events.size() >= events_cap) {
      events.pop_front();
      ++events_dropped;
    }
    events.push_back(std::move(ev));
  }
};

}  // namespace dct
