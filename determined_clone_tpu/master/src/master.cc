#include "master.h"

#include "crypto.h"

#include <sys/socket.h>
#include <sys/stat.h>
#include <unistd.h>

#include <algorithm>
#include <chrono>
#include <deque>
#include <fstream>
#include <iostream>
#include <regex>
#include <set>
#include <sstream>

namespace dct {

double now_sec() {
  return std::chrono::duration<double>(
             std::chrono::system_clock::now().time_since_epoch())
      .count();
}

namespace {

void mkdirs(const std::string& path) {
  std::string cur;
  std::istringstream stream(path);
  std::string part;
  if (!path.empty() && path[0] == '/') cur = "/";
  while (std::getline(stream, part, '/')) {
    if (part.empty()) continue;
    cur += part + "/";
    ::mkdir(cur.c_str(), 0755);
  }
}

}  // namespace

Master::Master(MasterConfig config) : config_(std::move(config)) {
  server_ = std::make_unique<HttpServer>(
      [this](const HttpRequest& req) { return handle(req); });
  // the store exists from construction: unit tests drive handle() without
  // start(), and every route may read/append
  mkdirs(config_.data_dir);
  if (config_.db == "sqlite" || config_.db == "auto") {
    store_ = make_sqlite_store(config_.data_dir);
    if (!store_ && config_.db == "sqlite") {
      throw std::runtime_error("sqlite store requested but libsqlite3 "
                               "could not be loaded");
    }
  }
  if (!store_) store_ = make_file_store(config_.data_dir);
  if (config_.provisioner.enabled) {
    std::unique_ptr<CloudClient> client;
    if (config_.provisioner.dry_run) {
      client = std::make_unique<RecordingClient>();
    } else {
      client = std::make_unique<GcloudTpuVmClient>();
    }
    provisioner_ = std::make_unique<Provisioner>(config_.provisioner,
                                                 std::move(client));
  }
  // resource manager selection (≈ rm.New, master/internal/rm/setup.go:17)
  if (config_.rm == "kubernetes") {
    KubeRmConfig kube = config_.kube;
    kube.master_port = config_.port;
    std::unique_ptr<KubectlRunner> runner;
    if (kube.dry_run) {
      runner = std::make_unique<DryRunKubectl>(config_.data_dir + "/" +
                                               kube.state_dir);
    } else {
      // kubectl subprocesses must never run under the master lock
      runner = std::make_unique<AsyncKubectl>(
          std::make_unique<LiveKubectl>(kube.ns));
    }
    rm_ = std::make_unique<KubernetesRM>(std::move(kube), std::move(runner));
  } else {
    rm_ = std::make_unique<AgentRM>();
  }
}

Master::~Master() { stop(); }

void Master::start() {
  std::cerr << "[master] store: " << store_->kind() << std::endl;
  load_snapshot();
  {
    std::lock_guard<std::mutex> lock(mu_);
    bootstrap_users_locked();
  }
  // restore (≈ restoreNonTerminalExperiments, core.go:772 + reattach):
  // Running allocations KEEP their reservations — reconnecting agents
  // re-report them via heartbeat and the tasks carry on; if the agent never
  // returns, the agent-timeout path requeues them. Only Pulling allocations
  // (assigned but possibly never started) are requeued immediately.
  {
    std::lock_guard<std::mutex> lock(mu_);
    for (auto& [id, alloc] : allocations_) {
      if (alloc.state == RunState::Pulling) {
        alloc.state = RunState::Queued;
        alloc.reservations.clear();
        alloc.rendezvous.clear();
      }
    }
  }
  running_ = true;
  server_->start(config_.port);
  tick_thread_ = std::thread([this] {
    while (running_) {
      {
        std::lock_guard<std::mutex> lock(mu_);
        tick_locked();
        if (dirty_) save_snapshot_locked();
      }
      std::this_thread::sleep_for(
          std::chrono::milliseconds(static_cast<int>(
              config_.tick_interval_sec * 1000)));
    }
  });
}

void Master::stop() {
  if (!running_.exchange(false)) {
    if (server_) server_->stop();
    return;
  }
  // unblock held connections BEFORE joining the server's worker threads:
  // log followers park in logs_cv_.wait_until (they check running_ on
  // wake), and WebSocket relays block in recv() on upstream sockets that
  // conn_fds_ does not cover
  logs_cv_.notify_all();
  {
    std::lock_guard<std::mutex> lock(relay_mu_);
    for (int fd : relay_fds_) ::shutdown(fd, SHUT_RDWR);
  }
  server_->stop();
  if (tick_thread_.joinable()) tick_thread_.join();
  std::lock_guard<std::mutex> lock(mu_);
  save_snapshot_locked();
}

// ---------------------------------------------------------------------------
// persistence
// ---------------------------------------------------------------------------

void Master::save_snapshot_locked() {
  Json exps = Json::array();
  for (const auto& [id, e] : experiments_) {
    Experiment copy = e;
    auto mit = methods_.find(id);
    if (mit != methods_.end()) copy.searcher_snapshot = mit->second->snapshot();
    exps.push_back(copy.to_json());
  }
  Json trials = Json::array();
  for (const auto& [id, t] : trials_) trials.push_back(t.to_json());
  Json allocs = Json::array();
  for (const auto& [id, a] : allocations_) allocs.push_back(a.to_json(true));
  Json agents = Json::array();
  for (const auto& [id, a] : agents_) agents.push_back(a.to_json());
  Json ckpts = Json::array();
  for (const auto& c : checkpoints_) ckpts.push_back(c.to_json());
  Json fleets = Json::array();
  for (const auto& [name, f] : fleets_) fleets.push_back(f.to_json());
  Json req_map = Json::object();
  for (const auto& [eid, m] : request_to_trial_) {
    Json inner = Json::object();
    for (const auto& [rid, tid] : m) inner.set(std::to_string(rid), tid);
    req_map.set(std::to_string(eid), inner);
  }
  Json users = Json::array();
  for (const auto& [id, u] : users_) users.push_back(u.to_json(false));
  Json sessions = Json::array();
  for (const auto& [tok, s] : sessions_) sessions.push_back(s.to_json());
  Json workspaces = Json::array();
  for (const auto& [id, w] : workspaces_) workspaces.push_back(w.to_json());
  Json projects = Json::array();
  for (const auto& [id, p] : projects_) projects.push_back(p.to_json());
  Json models = Json::array();
  for (const auto& [id, m] : models_) models.push_back(m.to_json());
  Json templates = Json::object();
  for (const auto& [name, cfg] : templates_) templates.set(name, cfg);
  Json webhooks = Json::array();
  for (const auto& [id, w] : webhooks_) webhooks.push_back(w.to_json());
  Json groups = Json::array();
  for (const auto& [id, g] : groups_) groups.push_back(g.to_json());
  Json assignments = Json::array();
  for (const auto& [id, a] : role_assignments_) {
    assignments.push_back(a.to_json());
  }
  Json snap = Json::object();
  snap.set("next_experiment_id", next_experiment_id_)
      .set("next_trial_id", next_trial_id_)
      .set("next_task_id", next_task_id_)
      .set("next_user_id", next_user_id_)
      .set("next_workspace_id", next_workspace_id_)
      .set("next_project_id", next_project_id_)
      .set("next_model_id", next_model_id_)
      .set("next_webhook_id", next_webhook_id_)
      .set("next_group_id", next_group_id_)
      .set("next_assignment_id", next_assignment_id_)
      .set("experiments", exps).set("trials", trials)
      .set("allocations", allocs).set("agents", agents)
      .set("checkpoints", ckpts).set("fleets", fleets)
      .set("request_to_trial", req_map)
      .set("users", users).set("sessions", sessions)
      .set("user_settings", [this] {
        Json j = Json::object();
        for (const auto& [uid, bag] : user_settings_) {
          j.set(std::to_string(uid), bag);
        }
        return j;
      }())
      .set("workspaces", workspaces).set("projects", projects)
      .set("models", models).set("templates", templates)
      .set("webhooks", webhooks).set("groups", groups)
      .set("role_assignments", assignments);

  store_->save_snapshot(snap.dump());
  dirty_ = false;
}

void Master::load_snapshot() {
  const std::string raw = store_->load_snapshot();
  if (raw.empty()) return;
  Json snap;
  try {
    snap = Json::parse(raw);
  } catch (const std::exception&) {
    return;  // corrupt snapshot: start fresh rather than crash-loop
  }
  std::lock_guard<std::mutex> lock(mu_);
  next_experiment_id_ = snap["next_experiment_id"].as_int(1);
  next_trial_id_ = snap["next_trial_id"].as_int(1);
  next_task_id_ = snap["next_task_id"].as_int(1);
  for (const auto& e : snap["experiments"].elements()) {
    Experiment exp = Experiment::from_json(e);
    int64_t id = exp.id;
    experiments_[id] = std::move(exp);
  }
  for (const auto& t : snap["trials"].elements()) {
    Trial trial = Trial::from_json(t);
    trials_[trial.id] = std::move(trial);
  }
  for (const auto& a : snap["allocations"].elements()) {
    Allocation alloc = Allocation::from_json(a);
    if (alloc.token.empty()) {
      // pre-token snapshot: mint one so the proxy/data-plane gates work
      // (the already-running task holds no token, so its own server stays
      // in tokenless mode until the allocation is restarted)
      alloc.token = crypto::random_token();
    }
    allocations_[alloc.id] = std::move(alloc);
  }
  for (const auto& a : snap["agents"].elements()) {
    Agent agent = Agent::from_json(a);
    agents_[agent.id] = std::move(agent);
  }
  for (const auto& c : snap["checkpoints"].elements()) {
    checkpoints_.push_back(CheckpointRecord::from_json(c));
  }
  for (const auto& f : snap["fleets"].elements()) {
    ServingFleetRec fleet = ServingFleetRec::from_json(f);
    if (!fleet.name.empty()) fleets_[fleet.name] = std::move(fleet);
  }
  for (const auto& [eid, inner] : snap["request_to_trial"].items()) {
    for (const auto& [rid, tid] : inner.items()) {
      request_to_trial_[std::stoll(eid)][std::stoll(rid)] = tid.as_int();
    }
  }
  next_user_id_ = snap["next_user_id"].as_int(1);
  next_workspace_id_ = snap["next_workspace_id"].as_int(1);
  next_project_id_ = snap["next_project_id"].as_int(1);
  next_model_id_ = snap["next_model_id"].as_int(1);
  next_webhook_id_ = snap["next_webhook_id"].as_int(1);
  for (const auto& u : snap["users"].elements()) {
    User user = User::from_json(u);
    users_[user.id] = std::move(user);
  }
  for (const auto& s : snap["sessions"].elements()) {
    SessionToken tok = SessionToken::from_json(s);
    sessions_[tok.token] = std::move(tok);
  }
  if (snap["user_settings"].is_object()) {
    for (const auto& [uid, bag] : snap["user_settings"].items()) {
      try {
        user_settings_[std::stoll(uid)] = bag;
      } catch (const std::exception&) {
      }
    }
  }
  for (const auto& w : snap["workspaces"].elements()) {
    Workspace ws = Workspace::from_json(w);
    workspaces_[ws.id] = std::move(ws);
  }
  for (const auto& p : snap["projects"].elements()) {
    Project proj = Project::from_json(p);
    projects_[proj.id] = std::move(proj);
  }
  for (const auto& m : snap["models"].elements()) {
    RegisteredModel model = RegisteredModel::from_json(m);
    models_[model.id] = std::move(model);
  }
  for (const auto& [name, cfg] : snap["templates"].items()) {
    templates_[name] = cfg;
  }
  for (const auto& w : snap["webhooks"].elements()) {
    Webhook hook = Webhook::from_json(w);
    webhooks_[hook.id] = std::move(hook);
  }
  next_group_id_ = snap["next_group_id"].as_int(1);
  next_assignment_id_ = snap["next_assignment_id"].as_int(1);
  for (const auto& g : snap["groups"].elements()) {
    Group group = Group::from_json(g);
    groups_[group.id] = std::move(group);
  }
  for (const auto& a : snap["role_assignments"].elements()) {
    RoleAssignment ra = RoleAssignment::from_json(a);
    role_assignments_[ra.id] = std::move(ra);
  }
  // rebuild searcher methods from snapshots
  for (auto& [id, exp] : experiments_) {
    if (exp.state == RunState::Completed || exp.state == RunState::Errored ||
        exp.state == RunState::Canceled) {
      continue;
    }
    method_for(exp);
  }
}

void Master::sched_event_locked(const char* name, const Allocation& alloc,
                                double start, double end) {
  SchedEvent ev;
  ev.name = name;
  ev.alloc_id = alloc.id;
  ev.trial_id = alloc.trial_id;
  if (alloc.trial_id) {
    auto tit = trials_.find(alloc.trial_id);
    if (tit != trials_.end()) ev.experiment_id = tit->second.experiment_id;
  }
  ev.wall_epoch = start > 0 ? start : now_sec();
  ev.dur_us = end > start ? (end - start) * 1e6 : 0;
  ev.pool = alloc.resource_pool;
  sched_.push_event(std::move(ev));
}

// The jsonl-era names survive as the call sites' vocabulary; the bodies
// delegate to the pluggable Store (files or sqlite — store.h).
void Master::log_event(const std::string& level, const std::string& msg) {
  // callers hold mu_
  Json rec = Json::object();
  rec.set("time", now_sec()).set("level", level).set("log", msg);
  event_log_.push_back(rec);
  if (event_log_.size() > 5000) {
    event_log_.pop_front();
    ++event_log_head_seq_;
  }
}

void Master::append_jsonl(const std::string& file, const Json& record) {
  store_->append(file, record);
  ++stream_versions_[file];  // callers hold mu_
  logs_cv_.notify_all();     // wake followers; they check their version
}

void Master::append_jsonl_many(const std::string& file,
                               const std::vector<const Json*>& records) {
  store_->append_many(file, records);
  ++stream_versions_[file];
  logs_cv_.notify_all();
}

std::vector<Json> Master::read_jsonl_tail(const std::string& file,
                                          size_t limit) {
  return store_->read_tail(file, limit);
}

std::vector<Json> Master::read_jsonl(const std::string& file, size_t limit,
                                     size_t offset) {
  return store_->read(file, limit, offset);
}

// ---------------------------------------------------------------------------
// orchestration
// ---------------------------------------------------------------------------

SearchMethodCpp* Master::method_for(Experiment& exp) {
  auto it = methods_.find(exp.id);
  if (it != methods_.end()) return it->second.get();
  const Json& cfg = exp.config;
  uint64_t seed = 0;
  if (cfg["reproducibility"].is_object()) {
    seed = static_cast<uint64_t>(
        cfg["reproducibility"]["experiment_seed"].as_int(0));
  }
  auto method = build_search_method(cfg["searcher"], cfg["hyperparameters"],
                                    seed + static_cast<uint64_t>(exp.id));
  if (!exp.searcher_snapshot.is_null() && exp.searcher_snapshot.is_object() &&
      exp.searcher_snapshot.size() > 0) {
    method->restore(exp.searcher_snapshot);
  }
  auto* raw = method.get();
  methods_[exp.id] = std::move(method);
  return raw;
}

void Master::apply_search_ops(Experiment& exp, std::vector<SearchOp> ops) {
  auto* method = method_for(exp);
  // breadth-first processing to keep create/created ordering (adaptive asha
  // routes by FIFO)
  std::vector<SearchOp> queue = std::move(ops);
  size_t head = 0;
  while (head < queue.size()) {
    SearchOp op = queue[head++];
    switch (op.kind) {
      case SearchOp::Kind::Create: {
        int64_t rid = op.request_id >= 0 ? op.request_id
                                         : exp.next_request_id;
        if (request_to_trial_[exp.id].count(rid)) {
          // duplicate create (a restarted custom-search runner replaying the
          // event log): the trial exists — idempotent no-op
          break;
        }
        exp.next_request_id = std::max(exp.next_request_id, rid + 1);
        Trial trial;
        trial.id = next_trial_id_++;
        trial.experiment_id = exp.id;
        trial.request_id = rid;
        trial.hparams = op.hparams;
        trial.created_at = now_sec();
        trials_[trial.id] = trial;
        request_to_trial_[exp.id][rid] = trial.id;
        auto more = method->on_trial_created(rid);
        queue.insert(queue.end(), more.begin(), more.end());
        break;
      }
      case SearchOp::Kind::ValidateAfter: {
        auto tit = request_to_trial_[exp.id].find(op.request_id);
        if (tit == request_to_trial_[exp.id].end()) break;
        Trial& trial = trials_[tit->second];
        if (trial.state == RunState::Completed ||
            trial.state == RunState::Errored ||
            trial.state == RunState::Canceled) {
          // Canceled: killed via /trials/:id/kill — a straggling
          // ValidateAfter must not resurrect it with a fresh leg
          break;
        }
        trial.target_units = op.units;
        queue_trial_leg(trial);
        break;
      }
      case SearchOp::Kind::Close: {
        auto tit = request_to_trial_[exp.id].find(op.request_id);
        if (tit == request_to_trial_[exp.id].end()) break;
        Trial& trial = trials_[tit->second];
        if (trial.state != RunState::Errored &&
            trial.state != RunState::Canceled) {
          // (a killed trial already told the searcher via exited_early —
          // overwriting CANCELED with COMPLETED would double-account)
          bool was_terminal = trial.state == RunState::Completed;
          trial.state = RunState::Completed;
          trial.ended_at = now_sec();
          if (!was_terminal) {
            auto more = method->on_trial_closed(op.request_id);
            queue.insert(queue.end(), more.begin(), more.end());
          }
        }
        break;
      }
      case SearchOp::Kind::Shutdown: {
        finish_experiment(exp, op.failure ? RunState::Errored
                               : op.cancel ? RunState::Canceled
                                           : RunState::Completed);
        break;
      }
    }
  }
  exp.searcher_snapshot = method->snapshot();
  dirty_ = true;
}

void Master::queue_trial_leg(Trial& trial) {
  // one live allocation per trial
  for (const auto& [id, a] : allocations_) {
    if (a.trial_id == trial.id && a.state != RunState::Completed &&
        a.state != RunState::Errored && a.state != RunState::Canceled) {
      return;  // already queued/running; harness picks up the new target
    }
  }
  const Experiment& exp = experiments_[trial.experiment_id];
  if (exp.state == RunState::Paused) {
    // a paused experiment schedules nothing; searcher ops landing mid-pause
    // (e.g. a straggler's completed_op promoting an ASHA rung) park the
    // trial until activate re-queues it
    trial.state = RunState::Paused;
    return;
  }
  if (exp.config["unmanaged"].as_bool(false)) {
    // unmanaged trial (≈ harness core_v2/_unmanaged.py + the reference's
    // unmanaged experiments): the client runs the training itself and
    // reports in; the master records a zero-slot Running allocation so
    // logs/metrics/preemption ride the normal data-plane routes, and never
    // schedules anything
    Allocation alloc;
    alloc.id = "unmanaged-" + std::to_string(trial.id) + "." +
               std::to_string(trial.legs++);
    alloc.trial_id = trial.id;
    alloc.task_type = "unmanaged";
    alloc.state = RunState::Running;
    alloc.slots = 0;
    alloc.world_size = 1;
    alloc.resource_pool = "unmanaged";
    alloc.queued_at = now_sec();
    alloc.submitted_at = trial.legs == 1 ? trial.created_at : alloc.queued_at;
    alloc.running_at = alloc.queued_at;
    alloc.last_activity = alloc.queued_at;
    alloc.token = crypto::random_token();
    ++sched_.submitted_total;
    ++sched_.running_total;
    sched_event_locked("submit", alloc, alloc.submitted_at, alloc.queued_at);
    allocations_[alloc.id] = alloc;
    trial.state = RunState::Running;
    dirty_ = true;
    return;
  }
  const Json& resources = exp.config["resources"];
  Allocation alloc;
  alloc.id = "trial-" + std::to_string(trial.id) + "." +
             std::to_string(trial.legs++);
  alloc.trial_id = trial.id;
  alloc.task_type = "trial";
  alloc.state = RunState::Queued;
  alloc.slots = static_cast<int>(resources["slots_per_trial"].as_int(1));
  alloc.priority = static_cast<int>(resources["priority"].as_int(42));
  alloc.resource_pool = resources["resource_pool"].as_string().empty()
                            ? "default"
                            : resources["resource_pool"].as_string();
  // topology: "v5e-8" (one slice of that shape) or the multislice object
  // {slices: N, slice_shape: "v5e-8"} — N whole slices gang-scheduled as a
  // unit, DCN between them (≈ GCP multislice; the reference has no
  // equivalent, SURVEY §7.7)
  if (resources["topology"].is_object()) {
    alloc.n_slices = std::max(
        1, static_cast<int>(resources["topology"]["slices"].as_int(1)));
    alloc.topology = resources["topology"]["slice_shape"].as_string();
    if (alloc.slots < alloc.n_slices) {
      // a zero/under-sized multislice request would sail through the
      // zero-slot scheduling branch and hand the harness an impossible
      // DCT_N_SLICES; expconf rejects this at submit, but the master must
      // not trust clients (direct API posts bypass expconf)
      alloc.n_slices = 1;
    }
  } else {
    alloc.topology = resources["topology"].as_string();
  }
  alloc.queued_at = now_sec();
  // first leg: latency is charged from trial creation (the client's
  // submit); restart/requeue legs re-anchor at the requeue instant so a
  // long first run does not pollute submit->running quantiles
  alloc.submitted_at = trial.legs == 1 ? trial.created_at : alloc.queued_at;
  alloc.token = crypto::random_token();
  alloc.spec.set("entrypoint", exp.config["entrypoint"]);
  alloc.spec.set("experiment_id", trial.experiment_id);
  alloc.spec.set("trial_id", trial.id);
  ++sched_.submitted_total;
  if (trial.legs > 1) ++sched_.reschedules_total;
  sched_event_locked(trial.legs > 1 ? "requeue" : "submit", alloc,
                     alloc.submitted_at, alloc.queued_at);
  allocations_[alloc.id] = alloc;
  trial.state = RunState::Queued;
  dirty_ = true;
}

void Master::apply_log_policies(const Allocation& alloc, const Json& logs) {
  // cluster-level log-pattern webhooks fire for ANY task's logs
  // (≈ the reference's TRIGGER_TYPE_TASK_LOG webhooks)
  for (const auto& [wid, hook] : webhooks_) {
    if (hook.log_pattern.empty()) continue;
    auto wit = webhook_pattern_cache_.find(wid);
    if (wit == webhook_pattern_cache_.end()) {
      try {
        wit = webhook_pattern_cache_
                  .emplace(wid, std::regex(hook.log_pattern)).first;
      } catch (const std::regex_error&) {
        continue;  // validated at creation; restored bad state stays inert
      }
    }
    for (const auto& line : logs.elements()) {
      // bound the matching input: this path runs for EVERY task's logs
      // under the route lock, and std::regex backtracking is superlinear —
      // a truncated prefix caps the worst case (and error_complexity must
      // degrade to "no match", never 500 the whole log batch)
      std::string subject = line.as_string().substr(0, 512);
      bool hit = false;
      try {
        hit = std::regex_search(subject, wit->second);
      } catch (const std::regex_error&) {
      }
      if (!hit) continue;
      Json payload = Json::object();
      if (hook.webhook_type == "slack") {
        payload.set("text", "task " + alloc.id + " log matched '" +
                                hook.log_pattern + "': " + subject);
      } else {
        payload.set("event", "task_log_pattern")
            .set("allocation_id", alloc.id)
            .set("trial_id", alloc.trial_id)
            .set("pattern", hook.log_pattern)
            .set("line", line.as_string());
      }
      post_webhook(hook, payload);
      break;  // one firing per batch per hook, not per matching line
    }
  }

  if (alloc.trial_id == 0) return;
  auto tit = trials_.find(alloc.trial_id);
  if (tit == trials_.end()) return;
  Trial& trial = tit->second;
  auto eit = experiments_.find(trial.experiment_id);
  if (eit == experiments_.end()) return;
  Experiment& exp = eit->second;
  const Json& policies = exp.config["log_policies"];
  if (!policies.is_array() || policies.size() == 0) return;
  // compile once per experiment (validated at submission; log ingestion is
  // on the request path, so no per-batch regex construction)
  auto cit = log_policy_cache_.find(exp.id);
  if (cit == log_policy_cache_.end()) {
    std::vector<CompiledLogPolicy> compiled;
    for (const auto& policy : policies.elements()) {
      const std::string& pattern = policy["pattern"].as_string();
      // both spellings are valid config: "action": "cancel_retries" and
      // the reference's {"type": "cancel_retries"} object form
      std::string action = policy["action"].is_string()
                               ? policy["action"].as_string()
                               : policy["action"]["type"].as_string();
      if (pattern.empty()) continue;
      try {
        compiled.push_back({std::regex(pattern), pattern, action});
      } catch (const std::regex_error&) {
        // unreachable for new experiments (validated at create); restored
        // pre-validation snapshots must not take down log ingestion
      }
    }
    cit = log_policy_cache_.emplace(exp.id, std::move(compiled)).first;
  }
  for (const auto& policy : cit->second) {
    bool matched = false;
    std::string matched_line;
    for (const auto& line : logs.elements()) {
      if (std::regex_search(line.as_string(), policy.re)) {
        matched = true;
        matched_line = line.as_string();
        break;
      }
    }
    if (!matched) continue;
    const std::string& action = policy.action;
    Json rec = Json::object();
    rec.set("time", now_sec()).set("trial_id", trial.id)
        .set("pattern", policy.pattern).set("action", action)
        .set("line", matched_line);
    append_jsonl("exp-" + std::to_string(exp.id) + "-logpattern.jsonl", rec);
    if (action == "cancel_retries") {
      // ≈ logpattern CancelRetries: this failure class is not transient
      trial.no_retries = true;
      dirty_ = true;
    } else if (action == "exclude_node") {
      // ≈ logpattern ExcludeNode → BlockedNodes (trial.go:381): the
      // experiment's future legs avoid the nodes this leg ran on
      const std::string key = "exp-" + std::to_string(exp.id);
      for (const auto& [aid, n] : alloc.reservations) {
        auto ait = agents_.find(aid);
        if (ait != agents_.end()) {
          ait->second.blocked_by.insert(key);
          dirty_ = true;
        }
      }
    }
  }
}

void Master::gc_checkpoints_locked(Experiment& exp) {
  const Json& storage = exp.config["checkpoint_storage"];
  if (!storage.is_object()) return;
  int keep_latest = static_cast<int>(storage["save_trial_latest"].as_int(1));
  int keep_best = static_cast<int>(storage["save_trial_best"].as_int(1));
  int keep_exp_best =
      static_cast<int>(storage["save_experiment_best"].as_int(0));
  bool smaller = true;
  if (exp.config["searcher"].is_object()) {
    smaller = exp.config["searcher"]["smaller_is_better"].as_bool(true);
  }

  std::map<int64_t, std::vector<CheckpointRecord*>> by_trial;
  for (auto& c : checkpoints_) {
    if (c.experiment_id == exp.id && !c.deleted) {
      by_trial[c.trial_id].push_back(&c);  // chronological (append order)
    }
  }
  if (by_trial.empty()) return;

  std::set<std::string> keep;
  // never GC a checkpoint the model registry references
  for (const auto& [id, m] : models_) {
    for (const auto& v : m.versions) keep.insert(v.checkpoint_uuid);
  }
  // per-trial metric-sorted checkpoints (stable: earlier checkpoint wins
  // ties, so a stale-metric duplicate never displaces the measured one)
  std::map<int64_t, std::vector<CheckpointRecord*>> best_sorted;
  for (auto& [tid, records] : by_trial) {
    for (int i = static_cast<int>(records.size()) - 1, n = 0;
         i >= 0 && n < keep_latest; --i, ++n) {
      keep.insert(records[i]->uuid);
    }
    std::vector<CheckpointRecord*> with_metric;
    for (auto* c : records) {
      if (c->metadata.has("validation_metric")) with_metric.push_back(c);
    }
    std::stable_sort(
        with_metric.begin(), with_metric.end(),
        [smaller](const CheckpointRecord* a, const CheckpointRecord* b) {
          double ma = a->metadata["validation_metric"].as_number();
          double mb = b->metadata["validation_metric"].as_number();
          return smaller ? ma < mb : ma > mb;
        });
    for (int i = 0; i < keep_best &&
                    i < static_cast<int>(with_metric.size()); ++i) {
      keep.insert(with_metric[i]->uuid);
    }
    best_sorted[tid] = std::move(with_metric);
  }
  if (keep_exp_best > 0) {
    std::vector<const Trial*> ranked;
    for (const auto& [tid, t] : trials_) {
      if (t.experiment_id == exp.id && t.has_metric) ranked.push_back(&t);
    }
    std::sort(ranked.begin(), ranked.end(),
              [smaller](const Trial* a, const Trial* b) {
                return smaller ? a->best_metric < b->best_metric
                               : a->best_metric > b->best_metric;
              });
    for (int i = 0; i < keep_exp_best &&
                    i < static_cast<int>(ranked.size()); ++i) {
      // the checkpoint that ACHIEVED the trial's best metric, not whatever
      // came last (weights drift after the best validation)
      const auto& bs = best_sorted[ranked[i]->id];
      if (!bs.empty()) {
        keep.insert(bs.front()->uuid);
      } else if (!ranked[i]->latest_checkpoint.empty()) {
        keep.insert(ranked[i]->latest_checkpoint);
      }
    }
  }

  std::vector<std::string> doomed;
  for (auto& c : checkpoints_) {
    if (c.experiment_id == exp.id && !c.deleted && !keep.count(c.uuid)) {
      c.deleted = true;
      doomed.push_back(c.uuid);
    }
  }
  if (doomed.empty()) return;
  dirty_ = true;
  spawn_gc_task_locked(exp, doomed);
}

void Master::spawn_gc_task_locked(const Experiment& exp,
                                  const std::vector<std::string>& doomed) {
  const Json& storage = exp.config["checkpoint_storage"];
  if (!storage.is_object() || doomed.empty()) return;
  // zero-slot GC task deletes the files from storage in-container
  // (≈ runCheckpointGCTask → exec/gc_checkpoints.py:97)
  Allocation gc;
  gc.id = "task-gc-" + std::to_string(next_task_id_++);
  gc.task_type = "command";
  gc.trial_id = 0;
  gc.name = "checkpoint-gc-exp-" + std::to_string(exp.id);
  gc.state = RunState::Queued;
  gc.slots = 0;
  gc.priority = 99;  // background
  // run in the experiment's pool — a "default"-pool task can never
  // schedule on a cluster whose agents all sit in another pool
  if (exp.config["resources"].is_object() &&
      !exp.config["resources"]["resource_pool"].as_string().empty()) {
    gc.resource_pool = exp.config["resources"]["resource_pool"].as_string();
  }
  gc.queued_at = now_sec();
  gc.last_activity = gc.queued_at;
  gc.token = crypto::random_token();
  Json argv = Json::array();
  argv.push_back("python");
  argv.push_back("-m");
  argv.push_back("determined_clone_tpu.exec.gc_checkpoints");
  gc.spec.set("argv", argv);
  Json env = Json::object();
  env.set("DCT_GC_STORAGE", storage.dump());
  std::string csv;
  for (const auto& u : doomed) {
    if (!csv.empty()) csv += ",";
    csv += u;
  }
  env.set("DCT_GC_UUIDS", csv);
  gc.spec.set("env", env);
  allocations_[gc.id] = std::move(gc);
}

void Master::finish_experiment(Experiment& exp, RunState state,
                               const std::string& error) {
  exp.state = state;
  exp.ended_at = now_sec();
  exp.error = error;
  log_event(state == RunState::Errored ? "error" : "info",
            "experiment " + std::to_string(exp.id) + " finished: " +
                std::string(to_string(state)) +
                (error.empty() ? "" : " (" + error + ")"));
  fire_webhooks(exp);  // async, detached (≈ webhooks/shipper.go)
  gc_checkpoints_locked(exp);  // storage-policy GC (≈ checkpoint_gc.go:27)
  // a finished experiment's node blocklist is dead state — drop it so
  // agents don't accumulate exp-N keys (and snapshots don't grow) forever
  const std::string block_key = "exp-" + std::to_string(exp.id);
  for (auto& [aid, agent] : agents_) agent.blocked_by.erase(block_key);
  log_policy_cache_.erase(exp.id);
  // cancel queued allocations of this experiment's trials
  for (auto& [id, alloc] : allocations_) {
    if (alloc.trial_id == 0) continue;
    auto tit = trials_.find(alloc.trial_id);
    if (tit == trials_.end() || tit->second.experiment_id != exp.id) continue;
    if (alloc.state == RunState::Queued) alloc.state = RunState::Canceled;
    if (alloc.state == RunState::Running && !alloc.preempt_requested) {
      alloc.preempt_requested = true;
      ++sched_.preemptions_total;
      sched_event_locked("preempt", alloc, now_sec(), now_sec());
    }
  }
  dirty_ = true;
}

void Master::on_task_done(const std::string& alloc_id, int exit_code,
                          const std::string& error) {
  auto ait = allocations_.find(alloc_id);
  if (ait == allocations_.end()) return;
  Allocation& alloc = ait->second;
  log_event(exit_code == 0 ? "info" : "error",
            "task " + alloc_id + " exited rc=" + std::to_string(exit_code) +
                (error.empty() ? "" : ": " + error));
  // any exit (clean, failed, canceled) invalidates the gang's barrier
  // payloads — a restarted incarnation must never rendezvous against a
  // dead incarnation's addresses
  allgather_.erase(alloc_id);
  // wake log followers so they report end_of_stream promptly instead of
  // sleeping out their follow window against a finished allocation
  logs_cv_.notify_all();
  if (alloc.state == RunState::Completed || alloc.state == RunState::Errored) {
    return;  // idempotent: exits may arrive twice (task_event + heartbeat)
  }
  if (alloc.state == RunState::Canceled) {
    // killed/idle-reaped: record the exit, close out the trial as CANCELED
    // (not an error), and never run restart logic — idempotently
    if (alloc.ended_at == 0) {
      alloc.ended_at = now_sec();
      ++sched_.completed_total;
      if (alloc.task_type == "serving") ++sched_.serving_completed_total;
      sched_event_locked("end", alloc, alloc.ended_at, alloc.ended_at);
      dirty_ = true;
    }
    if (alloc.exit_code == 0 && exit_code != 0) {
      alloc.exit_code = exit_code;
      dirty_ = true;
    }
    if (alloc.trial_id && trials_.count(alloc.trial_id)) {
      Trial& t = trials_[alloc.trial_id];
      if (t.state == RunState::Paused) {
        // the allocation was canceled BY a pause: the trial stays parked
        // (activate re-queues it); only a real cancel closes it out
      } else if (t.state != RunState::Completed &&
                 t.state != RunState::Errored &&
                 t.state != RunState::Canceled) {
        t.state = RunState::Canceled;
        t.ended_at = now_sec();
        dirty_ = true;
      }
    }
    return;
  }
  bool failed = exit_code != 0;
  alloc.exit_code = exit_code;
  alloc.state = failed ? RunState::Errored : RunState::Completed;
  alloc.ended_at = now_sec();
  ++sched_.completed_total;
  if (alloc.task_type == "serving") ++sched_.serving_completed_total;
  sched_event_locked("end", alloc, alloc.ended_at, alloc.ended_at);
  dirty_ = true;
  if (alloc.trial_id == 0) return;
  auto tit = trials_.find(alloc.trial_id);
  if (tit == trials_.end()) return;
  Trial& trial = tit->second;
  Experiment& exp = experiments_[trial.experiment_id];

  if (trial.state == RunState::Completed ||
      trial.state == RunState::Errored ||
      trial.state == RunState::Canceled) {
    // settled (incl. killed via /trials/:id/kill while its harness was
    // still draining): no restart logic may resurrect it
    return;
  }
  if (failed && exp.state == RunState::Paused) {
    // the pause's preempt killed a harness that had not yet installed its
    // SIGTERM handler (startup window): that is the pause taking effect,
    // not a trial failure — park it; activate re-queues from the latest
    // checkpoint and no restart is charged
    trial.state = RunState::Paused;
    return;
  }
  if (failed) {
    // trial restart logic (≈ trial.go:531 handleAllocationExit);
    // no_retries set by a cancel_retries log policy makes the failure
    // non-retryable (≈ trial.go:184 classification)
    const Json& cfg = exp.config;
    int max_restarts = static_cast<int>(cfg["max_restarts"].as_int(5));
    trial.restarts += 1;
    if (!trial.no_retries && trial.restarts <= max_restarts &&
        exp.state == RunState::Running) {
      queue_trial_leg(trial);  // resumes from latest_checkpoint
    } else {
      trial.state = RunState::Errored;
      trial.ended_at = now_sec();
      trial.error = error.empty() ? ("exit code " + std::to_string(exit_code))
                                  : error;
      if (exp.state == RunState::Running) {
        apply_search_ops(
            exp, method_for(exp)->on_trial_exited_early(trial.request_id));
      }
    }
  } else {
    // clean exit (the terminal-state early return above already settled
    // completed/errored/killed trials)
    if (trial.units_done >= trial.target_units) {
      // the searcher has no outstanding target: the trial parks
      trial.state = RunState::Paused;
    } else if (exp.state == RunState::Paused) {
      // preempted by an experiment pause: the trial parks too (activate
      // re-queues it from latest_checkpoint)
      trial.state = RunState::Paused;
    } else if (exp.state == RunState::Running) {
      // clean exit below target with the experiment live: a preemption
      // victim (priority eviction, or an activate racing the pause's
      // drain). Without a re-queue the trial would strand with no live
      // allocation — resume it from the latest checkpoint, restart-free
      // (nothing failed)
      queue_trial_leg(trial);
    }
  }
}

void Master::tick_locked() {
  double now = now_sec();

  // catch-all for log followers: terminal transitions that bypass
  // on_task_done (direct kills of queued allocations, requeues) reach
  // waiting followers within a tick instead of their full follow window
  logs_cv_.notify_all();

  // expired-session sweep: dead tokens must not accumulate in memory or in
  // every snapshot write
  for (auto it = sessions_.begin(); it != sessions_.end();) {
    if (it->second.expires_at < now) {
      it = sessions_.erase(it);
      dirty_ = true;
    } else {
      ++it;
    }
  }

  // idle watcher: NTSC tasks with an idle_timeout and no recent proxy
  // activity are reaped (≈ master/internal/task/idle/watcher.go)
  for (auto& [id, alloc] : allocations_) {
    if (alloc.trial_id == 0 && alloc.state == RunState::Running &&
        alloc.idle_timeout_sec > 0 &&
        now - std::max(alloc.last_activity, alloc.queued_at) >
            alloc.idle_timeout_sec) {
      alloc.state = RunState::Canceled;  // heartbeat derives the kill
      dirty_ = true;
    }
  }

  // unmanaged-trial watchdog: a client that stops heartbeating (SIGKILL,
  // network gone) must not leave a RUNNING experiment behind forever —
  // the agent-timeout and idle-watcher paths both skip these zero-slot
  // client-driven allocations
  for (auto& [id, alloc] : allocations_) {
    if (alloc.task_type != "unmanaged" || alloc.state != RunState::Running) {
      continue;
    }
    if (now - std::max(alloc.last_activity, alloc.queued_at) >
        config_.unmanaged_timeout_sec) {
      // the client is not coming back and no scheduler can restart it, so
      // bypass on_task_done's restart logic (which would mint a fresh
      // unmanaged allocation that times out again, restarts times over)
      if (alloc.trial_id && trials_.count(alloc.trial_id)) {
        trials_[alloc.trial_id].no_retries = true;
      }
      on_task_done(id, 1, "unmanaged client heartbeat lost");
    }
  }

  // log retention: trim FINISHED tasks' log streams to the configured
  // tail. Running tasks keep everything (a live debug session must not
  // lose its head); a terminal task gets a grace window longer than the
  // 60 s follow cap before its first trim, so a client still draining by
  // positional offset finishes before records shift under it. Each task
  // is swept once per master lifetime — late post-terminal log shipments
  // are negligible and a restart re-sweeps.
  if (config_.log_retention_records > 0 &&
      now - last_retention_sweep_ > config_.log_retention_interval_sec) {
    last_retention_sweep_ = now;
    const double grace = config_.log_retention_grace_sec;
    for (const auto& [id, alloc] : allocations_) {
      bool terminal = alloc.state == RunState::Completed ||
                      alloc.state == RunState::Errored ||
                      alloc.state == RunState::Canceled;
      if (!terminal || retention_done_.count(id)) continue;
      auto seen = retention_terminal_seen_.find(id);
      if (seen == retention_terminal_seen_.end()) {
        retention_terminal_seen_[id] = now;
        continue;
      }
      if (now - seen->second < grace) continue;
      store_->retain_stream(
          "task-" + id + "-logs.jsonl",
          static_cast<size_t>(config_.log_retention_records));
      retention_done_.insert(id);
      retention_terminal_seen_.erase(seen);
    }
  }

  // agent liveness: reconnect-with-amnesia (≈ agent.go:330): a timed-out
  // agent's reservations are released and its allocations requeued
  for (auto& [aid, agent] : agents_) {
    if (!agent.enabled) continue;
    if (agent.last_heartbeat > 0 &&
        now - agent.last_heartbeat > config_.agent_timeout_sec) {
      agent.enabled = false;
      log_event("warn", "agent " + aid + " timed out (heartbeat lost); "
                "requeueing its allocations");
      for (auto& [id, alloc] : allocations_) {
        if (alloc.reservations.count(aid) &&
            (alloc.state == RunState::Running ||
             alloc.state == RunState::Pulling)) {
          alloc.state = RunState::Queued;
          alloc.reservations.clear();
          alloc.rendezvous.clear();
          // re-arm the lifecycle clocks: the same allocation id goes back
          // through scheduled/running, and stale stamps would corrupt the
          // latency quantiles on the next pass
          alloc.scheduled_at = 0;
          alloc.running_at = 0;
          ++sched_.reschedules_total;
          sched_event_locked("requeue", alloc, now, now);
          allgather_.erase(id);  // stale barrier payloads die with the leg
          if (alloc.trial_id) {
            auto tit = trials_.find(alloc.trial_id);
            if (tit != trials_.end()) tit->second.state = RunState::Queued;
          }
        }
      }
      dirty_ = true;
    }
  }

  // resource management: agent gang scheduling or kubernetes pods (rm.h)
  RmContext ctx;
  ctx.now = now;
  ctx.allocations = &allocations_;
  ctx.trials = &trials_;
  ctx.mark_dirty = [this] { dirty_ = true; };
  ctx.on_task_done = [this](const std::string& id, int code,
                            const std::string& err) {
    on_task_done(id, code, err);
  };
  ctx.start_command = [this](const Allocation& alloc, int rank) {
    Json cmd = allocation_start_command(alloc, "");
    cmd.set("rank", rank);
    return cmd;
  };
  ctx.clear_barriers = [this](const std::string& id) {
    allgather_.erase(id);
  };
  ctx.agent_tick = [this](double t) { agent_rm_tick_locked(t); };
  rm_->tick(ctx);
}

void Master::agent_rm_tick_locked(double now) {
  // group by pool and schedule (≈ resource_pool.go:360 schedulerTick)
  std::map<std::string, std::vector<Agent>> pool_agents;
  for (const auto& [aid, agent] : agents_) {
    if (agent.enabled) pool_agents[agent.resource_pool].push_back(agent);
  }
  std::map<std::string, std::map<std::string, int>> pool_free;
  for (const auto& [pool, agents] : pool_agents) {
    for (const auto& a : agents) pool_free[pool][a.id] = a.slots;
  }
  std::map<std::string, std::vector<Allocation>> pool_pending, pool_running;
  std::map<std::string, int> share_usage;
  std::map<std::string, std::string> owner_of;
  for (const auto& [id, alloc] : allocations_) {
    std::string owner = alloc.task_type;
    if (alloc.trial_id) {
      auto tit = trials_.find(alloc.trial_id);
      if (tit != trials_.end()) {
        owner = "exp-" + std::to_string(tit->second.experiment_id);
      }
    }
    owner_of[id] = owner;
    if (alloc.state == RunState::Queued) {
      pool_pending[alloc.resource_pool].push_back(alloc);
    } else if (alloc.state == RunState::Running ||
               alloc.state == RunState::Pulling) {
      pool_running[alloc.resource_pool].push_back(alloc);
      share_usage[owner] += alloc.slots;
      for (const auto& [aid, n] : alloc.reservations) {
        pool_free[alloc.resource_pool][aid] -= n;
      }
    }
  }

  sched_.gang_waiting_by_pool.clear();
  for (auto& [pool, pending] : pool_pending) {
    auto policy_it = config_.pools.find(pool);
    const PoolPolicy& policy = policy_it != config_.pools.end()
                                   ? policy_it->second
                                   : config_.default_pool;
    auto pass_t0 = std::chrono::steady_clock::now();
    auto decision = schedule_pool(
        policy, pool_agents[pool], pool_free[pool], pending,
        pool_running[pool], share_usage, owner_of);
    double pass_s = std::chrono::duration<double>(
                        std::chrono::steady_clock::now() - pass_t0).count();
    ++sched_.decisions_total;
    sched_.considered_total += decision.considered;
    sched_.gangs_admitted_total += decision.gangs_admitted;
    sched_.gang_wait_ticks_total += decision.gang_waiting;
    sched_.gang_waiting_by_pool[pool] = decision.gang_waiting;
    sched_.decision_seconds.observe(pass_s);
    SchedEvent pass_ev;
    pass_ev.name = "decision";
    pass_ev.pool = pool;
    pass_ev.wall_epoch = now;
    pass_ev.dur_us = pass_s * 1e6;
    sched_.push_event(std::move(pass_ev));
    for (const auto& [alloc_id, fit] : decision.assignments) {
      // reservation only; start commands are derived from state at each
      // heartbeat (idempotent re-send — a lost response cannot strand the
      // allocation in Pulling)
      Allocation& alloc = allocations_[alloc_id];
      alloc.reservations = fit;
      alloc.state = RunState::Pulling;
      alloc.world_size = static_cast<int>(fit.size());
      alloc.scheduled_at = now;
      ++sched_.scheduled_total;
      if (alloc.queued_at > 0 && now >= alloc.queued_at) {
        sched_.queue_wait_seconds.observe(now - alloc.queued_at);
      }
      sched_event_locked("schedule", alloc, alloc.queued_at, now);
      if (alloc.trial_id) {
        auto tit = trials_.find(alloc.trial_id);
        if (tit != trials_.end()) tit->second.state = RunState::Pulling;
      }
      dirty_ = true;
    }
    for (const auto& victim : decision.preemptions) {
      Allocation& alloc = allocations_[victim];
      if (!alloc.preempt_requested) {
        alloc.preempt_requested = true;
        ++sched_.preemptions_total;
        sched_event_locked("preempt", alloc, now, now);
        dirty_ = true;
      }
    }
  }

  // TPU-VM autoscaling: feed the provisioner the post-scheduling view of
  // its pool — still-queued slots, free chips, idle agents — and disable
  // agents it terminates so the scheduler stops placing on dying slices
  // (≈ provisioner.go Schedule → scaleDecider.calculate)
  if (provisioner_) {
    const std::string& pool = provisioner_->config().resource_pool;
    ClusterView view;
    view.now = now;
    for (const auto& alloc : pool_pending[pool]) {
      if (alloc.reservations.empty() &&
          allocations_[alloc.id].state == RunState::Queued) {
        view.pending_slots += std::max(alloc.slots, 1);
      }
    }
    std::set<std::string> busy;
    for (const auto& [id, alloc] : allocations_) {
      if (alloc.state == RunState::Running || alloc.state == RunState::Pulling) {
        for (const auto& [aid, n] : alloc.reservations) busy.insert(aid);
      }
    }
    for (const auto& agent : pool_agents[pool]) {
      view.agent_ids.insert(agent.id);
      view.free_slots += std::max(0, pool_free[pool][agent.id]);
      if (!busy.count(agent.id)) view.idle_agent_ids.insert(agent.id);
    }
    ScaleDecision scale = provisioner_->step(view);
    for (const auto& name : scale.terminate) {
      auto it = agents_.find(name);
      if (it != agents_.end()) {
        it->second.enabled = false;
        it->second.draining = true;  // heartbeats must not re-enable it
        dirty_ = true;
      }
    }
  }
}

Json Master::allocation_start_command(const Allocation& alloc,
                                      const std::string& agent_id) {
  Json cmd = Json::object();
  cmd.set("type", "start");
  cmd.set("allocation_id", alloc.id);
  cmd.set("task_type", alloc.task_type);
  cmd.set("slots", alloc.reservations.count(agent_id)
                       ? alloc.reservations.at(agent_id) : 0);
  cmd.set("world_size", alloc.world_size);
  cmd.set("n_slices", alloc.n_slices);
  cmd.set("alloc_token", alloc.token);
  cmd.set("spec", alloc.spec);
  if (!alloc.fleet.empty()) cmd.set("fleet", alloc.fleet);
  if (alloc.trial_id) {
    auto tit = trials_.find(alloc.trial_id);
    if (tit != trials_.end()) {
      const Trial& t = tit->second;
      Json trial = Json::object();
      trial.set("id", t.id).set("experiment_id", t.experiment_id)
          .set("hparams", t.hparams).set("target_units", t.target_units)
          .set("latest_checkpoint", t.latest_checkpoint);
      cmd.set("trial", trial);
      cmd.set("config", experiments_[t.experiment_id].config);
    }
  }
  return cmd;
}

}  // namespace dct
