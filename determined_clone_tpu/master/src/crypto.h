// Credential primitives for the master's auth boundary.
//
// The reference delegates password hashing to bcrypt
// (master/internal/user/postgres_users.go UserByUsername → bcrypt compare)
// and session/allocation tokens to crypto/rand. This master has no external
// deps, so the KDF is PBKDF2-HMAC-SHA256 (FIPS 198/180-4, implemented here)
// with per-user random salt, plus constant-time comparison.
#pragma once

#include <cstdint>
#include <string>

namespace dct {
namespace crypto {

// FIPS 180-4 SHA-256 of `data`; returns 32 raw bytes in `out`.
void sha256(const uint8_t* data, size_t len, uint8_t out[32]);

// FIPS 198 HMAC-SHA256.
void hmac_sha256(const uint8_t* key, size_t key_len, const uint8_t* msg,
                 size_t msg_len, uint8_t out[32]);

// PBKDF2-HMAC-SHA256, single 32-byte block (dkLen = 32).
void pbkdf2_sha256(const std::string& password, const std::string& salt,
                   int iterations, uint8_t out[32]);

std::string to_hex(const uint8_t* data, size_t len);

// Timing-safe equality (compares full length regardless of mismatches).
bool constant_time_eq(const std::string& a, const std::string& b);

// 128-bit token from /dev/urandom, hex-encoded. Tokens are the
// --auth-required boundary, so no seeded PRNG.
std::string random_token();

// Password hashing: "pbkdf2_sha256$<iterations>$<salt_hex>$<dk_hex>".
std::string hash_password(const std::string& username,
                          const std::string& password);

// Verifies against the current format AND the legacy 16-hex-char FNV-1a
// format (pre-KDF snapshots); callers should re-hash on successful legacy
// verification. Constant-time on the digest comparison.
bool verify_password(const std::string& stored, const std::string& username,
                     const std::string& password);

// True when `stored` is not in the current KDF format (needs upgrade).
bool password_needs_rehash(const std::string& stored);

}  // namespace crypto
}  // namespace dct
