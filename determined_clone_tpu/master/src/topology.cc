#include "topology.h"

#include <algorithm>
#include <cmath>
#include <cstdlib>

namespace dct {

SliceShape parse_topology(const std::string& topo, int slots_hint) {
  SliceShape flat;
  flat.rows = 1;
  flat.cols = std::max(1, slots_hint);
  auto dash = topo.rfind('-');
  if (dash == std::string::npos || dash == 0 || dash + 1 >= topo.size()) {
    return flat;
  }
  int n = std::atoi(topo.c_str() + dash + 1);
  if (n <= 0) return flat;
  SliceShape out;
  out.gen = topo.substr(0, dash);
  // standard near-square slice: rows = largest divisor <= sqrt(n)
  // (1->1x1, 4->2x2, 8->2x4, 16->4x4, 32->4x8, 64->8x8)
  int rows = 1;
  for (int r = 1; r * r <= n; ++r) {
    if (n % r == 0) rows = r;
  }
  out.rows = rows;
  out.cols = n / rows;
  return out;
}

bool shape_fits(const SliceShape& req, const SliceShape& have) {
  // generations must MATCH — an unknown/absent generation on either side
  // is not a wildcard, or a "v5e-2" gang would schedule onto a topology-
  // less CPU host and crash at runtime (exact-string equality is handled
  // by the caller before shapes are consulted)
  if (req.gen != have.gen) return false;
  return (req.rows <= have.rows && req.cols <= have.cols) ||
         (req.cols <= have.rows && req.rows <= have.cols);
}

ChipGrid::ChipGrid(SliceShape shape)
    : shape_(shape),
      owner_(static_cast<size_t>(shape.rows) * shape.cols) {}

bool ChipGrid::rect_free(int r0, int c0, int r, int c) const {
  if (r0 + r > shape_.rows || c0 + c > shape_.cols) return false;
  for (int i = r0; i < r0 + r; ++i) {
    for (int j = c0; j < c0 + c; ++j) {
      if (!owner_[i * shape_.cols + j].empty()) return false;
    }
  }
  return true;
}

void ChipGrid::mark(const Rect& rect, const std::string& owner) {
  for (int i = rect.r0; i < rect.r0 + rect.r; ++i) {
    for (int j = rect.c0; j < rect.c0 + rect.c; ++j) {
      owner_[i * shape_.cols + j] = owner;
    }
  }
}

bool ChipGrid::find_rect(int area, Rect* out) const {
  if (area <= 0) {
    *out = Rect{0, 0, 0, 0};
    return true;
  }
  // candidate rectangles of this area, squarest first (|r - c| minimal):
  // a squarer sub-torus has the better bisection for the gang
  std::vector<std::pair<int, int>> shapes;
  for (int r = 1; r <= shape_.rows; ++r) {
    if (area % r == 0 && area / r <= shape_.cols) {
      shapes.emplace_back(r, area / r);
    }
  }
  std::sort(shapes.begin(), shapes.end(), [](auto a, auto b) {
    return std::abs(a.first - a.second) < std::abs(b.first - b.second);
  });
  for (auto [r, c] : shapes) {
    for (int r0 = 0; r0 + r <= shape_.rows; ++r0) {
      for (int c0 = 0; c0 + c <= shape_.cols; ++c0) {
        if (rect_free(r0, c0, r, c)) {
          *out = Rect{r0, c0, r, c};
          return true;
        }
      }
    }
  }
  return false;
}

bool ChipGrid::find_shape(const SliceShape& req, Rect* out) const {
  for (auto [r, c] : {std::pair<int, int>{req.rows, req.cols},
                      std::pair<int, int>{req.cols, req.rows}}) {
    for (int r0 = 0; r0 + r <= shape_.rows; ++r0) {
      for (int c0 = 0; c0 + c <= shape_.cols; ++c0) {
        if (rect_free(r0, c0, r, c)) {
          *out = Rect{r0, c0, r, c};
          return true;
        }
      }
    }
  }
  return false;
}

bool ChipGrid::place(int n, const std::string& owner) {
  Rect rect{};
  if (!find_rect(n, &rect)) return false;
  mark(rect, owner);
  return true;
}
bool ChipGrid::can_place(int n) const {
  Rect rect{};
  return find_rect(n, &rect);
}
bool ChipGrid::place_shape(const SliceShape& req, const std::string& owner) {
  Rect rect{};
  if (!find_shape(req, &rect)) return false;
  mark(rect, owner);
  return true;
}
bool ChipGrid::can_place_shape(const SliceShape& req) const {
  Rect rect{};
  return find_shape(req, &rect);
}

void ChipGrid::force_place(int n, const std::string& owner) {
  for (auto& cell : owner_) {
    if (n <= 0) break;
    if (cell.empty()) {
      cell = owner;
      --n;
    }
  }
}

void ChipGrid::release(const std::string& owner) {
  for (auto& cell : owner_) {
    if (cell == owner) cell.clear();
  }
}

int ChipGrid::free_chips() const {
  int n = 0;
  for (const auto& cell : owner_) {
    if (cell.empty()) ++n;
  }
  return n;
}

}  // namespace dct
