#include "json.h"

#include <cctype>
#include <cmath>
#include <cstdio>
#include <cstring>

namespace dct {
namespace {

struct Parser {
  const char* p;
  const char* end;

  [[noreturn]] void fail(const std::string& msg) {
    throw std::runtime_error("json parse error: " + msg);
  }

  void skip_ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r')) ++p;
  }

  char peek() {
    if (p >= end) fail("unexpected end of input");
    return *p;
  }

  void expect(char c) {
    if (p >= end || *p != c) fail(std::string("expected '") + c + "'");
    ++p;
  }

  bool consume(const char* lit) {
    size_t n = std::strlen(lit);
    if (static_cast<size_t>(end - p) >= n && std::memcmp(p, lit, n) == 0) {
      p += n;
      return true;
    }
    return false;
  }

  Json parse_value() {
    skip_ws();
    char c = peek();
    switch (c) {
      case '{': return parse_object();
      case '[': return parse_array();
      case '"': return Json(parse_string());
      case 't': if (consume("true")) return Json(true); fail("bad literal");
      case 'f': if (consume("false")) return Json(false); fail("bad literal");
      case 'n': if (consume("null")) return Json(nullptr); fail("bad literal");
      default: return parse_number();
    }
  }

  Json parse_object() {
    expect('{');
    JsonObject obj;
    skip_ws();
    if (peek() == '}') { ++p; return Json(std::move(obj)); }
    while (true) {
      skip_ws();
      std::string key = parse_string();
      skip_ws();
      expect(':');
      obj[std::move(key)] = parse_value();
      skip_ws();
      char c = peek();
      if (c == ',') { ++p; continue; }
      if (c == '}') { ++p; break; }
      fail("expected ',' or '}' in object");
    }
    return Json(std::move(obj));
  }

  Json parse_array() {
    expect('[');
    JsonArray arr;
    skip_ws();
    if (peek() == ']') { ++p; return Json(std::move(arr)); }
    while (true) {
      arr.push_back(parse_value());
      skip_ws();
      char c = peek();
      if (c == ',') { ++p; continue; }
      if (c == ']') { ++p; break; }
      fail("expected ',' or ']' in array");
    }
    return Json(std::move(arr));
  }

  std::string parse_string() {
    expect('"');
    std::string out;
    while (true) {
      if (p >= end) fail("unterminated string");
      char c = *p++;
      if (c == '"') break;
      if (c == '\\') {
        if (p >= end) fail("bad escape");
        char e = *p++;
        switch (e) {
          case '"': out += '"'; break;
          case '\\': out += '\\'; break;
          case '/': out += '/'; break;
          case 'b': out += '\b'; break;
          case 'f': out += '\f'; break;
          case 'n': out += '\n'; break;
          case 'r': out += '\r'; break;
          case 't': out += '\t'; break;
          case 'u': {
            if (end - p < 4) fail("bad \\u escape");
            unsigned code = 0;
            for (int i = 0; i < 4; ++i) {
              char h = *p++;
              code <<= 4;
              if (h >= '0' && h <= '9') code |= h - '0';
              else if (h >= 'a' && h <= 'f') code |= h - 'a' + 10;
              else if (h >= 'A' && h <= 'F') code |= h - 'A' + 10;
              else fail("bad \\u escape");
            }
            // surrogate pair → one codepoint
            if (code >= 0xD800 && code <= 0xDBFF && end - p >= 6 &&
                p[0] == '\\' && p[1] == 'u') {
              unsigned lo = 0;
              const char* q = p + 2;
              bool ok = true;
              for (int i = 0; i < 4; ++i) {
                char h = q[i];
                lo <<= 4;
                if (h >= '0' && h <= '9') lo |= h - '0';
                else if (h >= 'a' && h <= 'f') lo |= h - 'a' + 10;
                else if (h >= 'A' && h <= 'F') lo |= h - 'A' + 10;
                else { ok = false; break; }
              }
              if (ok && lo >= 0xDC00 && lo <= 0xDFFF) {
                code = 0x10000 + ((code - 0xD800) << 10) + (lo - 0xDC00);
                p += 6;
              }
            }
            // utf-8 encode
            if (code < 0x80) {
              out += static_cast<char>(code);
            } else if (code < 0x800) {
              out += static_cast<char>(0xC0 | (code >> 6));
              out += static_cast<char>(0x80 | (code & 0x3F));
            } else if (code < 0x10000) {
              out += static_cast<char>(0xE0 | (code >> 12));
              out += static_cast<char>(0x80 | ((code >> 6) & 0x3F));
              out += static_cast<char>(0x80 | (code & 0x3F));
            } else {
              out += static_cast<char>(0xF0 | (code >> 18));
              out += static_cast<char>(0x80 | ((code >> 12) & 0x3F));
              out += static_cast<char>(0x80 | ((code >> 6) & 0x3F));
              out += static_cast<char>(0x80 | (code & 0x3F));
            }
            break;
          }
          default: fail("bad escape");
        }
      } else {
        out += c;
      }
    }
    return out;
  }

  Json parse_number() {
    const char* start = p;
    if (p < end && (*p == '-' || *p == '+')) ++p;
    while (p < end && (std::isdigit(*p) || *p == '.' || *p == 'e' ||
                       *p == 'E' || *p == '-' || *p == '+')) {
      ++p;
    }
    if (p == start) fail("invalid number");
    std::string text(start, p - start);
    try {
      return Json(std::stod(text));
    } catch (...) {
      fail("invalid number '" + text + "'");
    }
  }
};

void write_escaped(std::ostringstream& out, const std::string& s) {
  out << '"';
  for (unsigned char c : s) {
    switch (c) {
      case '"': out << "\\\""; break;
      case '\\': out << "\\\\"; break;
      case '\b': out << "\\b"; break;
      case '\f': out << "\\f"; break;
      case '\n': out << "\\n"; break;
      case '\r': out << "\\r"; break;
      case '\t': out << "\\t"; break;
      default:
        if (c < 0x20) {
          char buf[8];
          std::snprintf(buf, sizeof(buf), "\\u%04x", c);
          out << buf;
        } else {
          out << static_cast<char>(c);
        }
    }
  }
  out << '"';
}

}  // namespace

Json Json::parse(const std::string& text) {
  Parser parser{text.data(), text.data() + text.size()};
  Json v = parser.parse_value();
  parser.skip_ws();
  if (parser.p != parser.end) parser.fail("trailing characters");
  return v;
}

void Json::write(std::ostringstream& out) const {
  switch (type_) {
    case Type::Null: out << "null"; break;
    case Type::Bool: out << (bool_ ? "true" : "false"); break;
    case Type::Number: {
      if (std::isfinite(num_) && num_ == std::floor(num_) &&
          std::fabs(num_) < 9.0e15) {
        out << static_cast<int64_t>(num_);
      } else if (std::isfinite(num_)) {
        char buf[32];
        std::snprintf(buf, sizeof(buf), "%.17g", num_);
        out << buf;
      } else {
        out << "null";  // NaN/Inf are not representable in JSON
      }
      break;
    }
    case Type::String: write_escaped(out, str_); break;
    case Type::Array: {
      out << '[';
      for (size_t i = 0; i < arr_.size(); ++i) {
        if (i) out << ',';
        arr_[i].write(out);
      }
      out << ']';
      break;
    }
    case Type::Object: {
      out << '{';
      bool first = true;
      for (const auto& [k, v] : obj_) {
        if (!first) out << ',';
        first = false;
        write_escaped(out, k);
        out << ':';
        v.write(out);
      }
      out << '}';
      break;
    }
  }
}

}  // namespace dct
