#include "searcher.h"

#include <algorithm>
#include <cmath>
#include <deque>
#include <set>

namespace dct {
namespace {

bool is_hp_leaf(const Json& node) {
  if (!node.is_object()) return true;
  const std::string& t = node["type"].as_string();
  return t == "const" || t == "int" || t == "double" || t == "log" ||
         t == "categorical";
}

Json sample_leaf(const Json& hp, std::mt19937_64& rng) {
  if (!hp.is_object()) return hp;
  const std::string& t = hp["type"].as_string();
  if (t == "const") return hp["val"];
  if (t == "int") {
    int64_t lo = hp["minval"].as_int(), hi = hp["maxval"].as_int();
    std::uniform_int_distribution<int64_t> d(lo, hi);
    return Json(d(rng));
  }
  if (t == "double") {
    std::uniform_real_distribution<double> d(hp["minval"].as_number(),
                                             hp["maxval"].as_number());
    return Json(d(rng));
  }
  if (t == "log") {
    double base = hp.has("base") ? hp["base"].as_number() : 10.0;
    std::uniform_real_distribution<double> d(hp["minval"].as_number(),
                                             hp["maxval"].as_number());
    return Json(std::pow(base, d(rng)));
  }
  if (t == "categorical") {
    const auto& vals = hp["vals"].elements();
    if (vals.empty()) return Json();
    std::uniform_int_distribution<size_t> d(0, vals.size() - 1);
    return vals[d(rng)];
  }
  return hp;  // unknown dict → const
}

std::vector<Json> grid_leaf(const Json& hp) {
  if (!hp.is_object()) return {hp};
  const std::string& t = hp["type"].as_string();
  if (t == "const") return {hp["val"]};
  if (t == "categorical") {
    return {hp["vals"].elements().begin(), hp["vals"].elements().end()};
  }
  if (t == "int") {
    int64_t lo = hp["minval"].as_int(), hi = hp["maxval"].as_int();
    int64_t count = hp.has("count") ? hp["count"].as_int() : (hi - lo + 1);
    count = std::min(count, hi - lo + 1);
    std::vector<Json> out;
    if (count <= 1) return {Json(lo)};
    for (int64_t i = 0; i < count; ++i) {
      double v = lo + static_cast<double>(i) * (hi - lo) / (count - 1);
      out.push_back(Json(static_cast<int64_t>(std::llround(v))));
    }
    return out;
  }
  if (t == "double" || t == "log") {
    if (!hp.has("count")) {
      throw std::runtime_error(t + " hyperparameter needs `count` for grid");
    }
    int64_t count = hp["count"].as_int();
    double lo = hp["minval"].as_number(), hi = hp["maxval"].as_number();
    double base = hp.has("base") ? hp["base"].as_number() : 10.0;
    std::vector<Json> out;
    for (int64_t i = 0; i < count; ++i) {
      double v = count == 1 ? lo : lo + i * (hi - lo) / (count - 1);
      out.push_back(Json(t == "log" ? std::pow(base, v) : v));
    }
    return out;
  }
  return {hp};
}

void grid_walk(const Json& space, std::vector<std::pair<std::string, std::vector<Json>>>& axes,
               const std::string& prefix) {
  for (const auto& [key, node] : space.items()) {
    std::string path = prefix.empty() ? key : prefix + "\x1f" + key;
    if (node.is_object() && !is_hp_leaf(node)) {
      grid_walk(node, axes, path);
    } else {
      axes.emplace_back(path, grid_leaf(node));
    }
  }
}

void set_nested(Json& obj, const std::string& path, const Json& value) {
  size_t sep = path.find('\x1f');
  if (sep == std::string::npos) {
    obj.set(path, value);
    return;
  }
  std::string head = path.substr(0, sep);
  Json child = obj.has(head) ? obj[head] : Json::object();
  set_nested(child, path.substr(sep + 1), value);
  obj.set(head, child);
}

}  // namespace

Json sample_hparams(const Json& space, std::mt19937_64& rng) {
  if (!space.is_object()) return Json::object();
  Json out = Json::object();
  for (const auto& [key, node] : space.items()) {
    if (node.is_object() && !is_hp_leaf(node)) {
      out.set(key, sample_hparams(node, rng));
    } else {
      out.set(key, sample_leaf(node, rng));
    }
  }
  return out;
}

std::vector<Json> grid_hparams(const Json& space) {
  std::vector<std::pair<std::string, std::vector<Json>>> axes;
  if (space.is_object()) grid_walk(space, axes, "");
  std::vector<Json> points;
  size_t total = 1;
  for (auto& [_, vals] : axes) total *= std::max<size_t>(1, vals.size());
  std::vector<size_t> idx(axes.size(), 0);
  for (size_t n = 0; n < total; ++n) {
    Json point = Json::object();
    for (size_t i = 0; i < axes.size(); ++i) {
      if (!axes[i].second.empty()) {
        set_nested(point, axes[i].first, axes[i].second[idx[i]]);
      }
    }
    points.push_back(point);
    for (size_t i = axes.size(); i-- > 0;) {
      if (++idx[i] < axes[i].second.size()) break;
      idx[i] = 0;
    }
  }
  return points;
}

namespace {

int64_t config_max_units(const Json& cfg) {
  const Json& ml = cfg["max_length"];
  if (ml.is_number()) return ml.as_int();
  if (ml.is_object()) {
    for (const char* unit : {"batches", "records", "epochs"}) {
      if (ml.has(unit)) return ml[unit].as_int();
    }
  }
  if (cfg.has("max_time")) return cfg["max_time"].as_int();
  throw std::runtime_error("searcher requires max_length (or max_time)");
}

// ---------------------------------------------------------------------------

class SingleSearchCpp : public SearchMethodCpp {
 public:
  SingleSearchCpp(const Json& cfg, Json space, uint64_t seed)
      : space_(std::move(space)), rng_(seed), max_units_(config_max_units(cfg)) {}

  std::vector<SearchOp> initial_operations() override {
    return {SearchOp::create(sample_hparams(space_, rng_))};
  }
  std::vector<SearchOp> on_trial_created(int64_t rid) override {
    return {SearchOp::validate_after(rid, max_units_)};
  }
  std::vector<SearchOp> on_validation_completed(int64_t rid, double,
                                                int64_t) override {
    done_ = true;
    return {SearchOp::close(rid), SearchOp::shutdown()};
  }
  std::vector<SearchOp> on_trial_exited_early(int64_t) override {
    done_ = true;
    return {SearchOp::shutdown(true)};
  }
  double progress() const override { return done_ ? 1.0 : 0.0; }
  Json snapshot() const override {
    Json j = Json::object();
    j.set("done", done_);
    return j;
  }
  void restore(const Json& snap) override { done_ = snap["done"].as_bool(); }

 private:
  Json space_;
  std::mt19937_64 rng_;
  int64_t max_units_;
  bool done_ = false;
};

// ---------------------------------------------------------------------------

class RandomSearchCpp : public SearchMethodCpp {
 public:
  RandomSearchCpp(const Json& cfg, Json space, uint64_t seed, bool grid)
      : space_(std::move(space)), rng_(seed),
        max_units_(config_max_units(cfg)) {
    if (grid) {
      points_ = grid_hparams(space_);
      int64_t cap = cfg["max_trials"].as_int(0);
      if (cap > 1 && static_cast<int64_t>(points_.size()) > cap) {
        points_.resize(cap);
      }
      max_trials_ = static_cast<int64_t>(points_.size());
    } else {
      max_trials_ = std::max<int64_t>(1, cfg["max_trials"].as_int(1));
    }
    max_concurrent_ = cfg["max_concurrent_trials"].as_int(16);
    if (max_concurrent_ <= 0) max_concurrent_ = max_trials_;
    max_concurrent_ = std::min(max_concurrent_, max_trials_);
  }

  std::vector<SearchOp> initial_operations() override {
    std::vector<SearchOp> ops;
    for (int64_t i = 0; i < max_concurrent_; ++i) ops.push_back(next_create());
    return ops;
  }
  std::vector<SearchOp> on_trial_created(int64_t rid) override {
    return {SearchOp::validate_after(rid, max_units_)};
  }
  std::vector<SearchOp> on_validation_completed(int64_t rid, double,
                                                int64_t) override {
    ++completed_;
    std::vector<SearchOp> ops{SearchOp::close(rid)};
    refill(ops);
    return ops;
  }
  std::vector<SearchOp> on_trial_exited_early(int64_t) override {
    ++completed_;
    std::vector<SearchOp> ops;
    refill(ops);
    return ops;
  }
  double progress() const override {
    return static_cast<double>(completed_) / std::max<int64_t>(1, max_trials_);
  }
  Json snapshot() const override {
    Json j = Json::object();
    j.set("created", created_).set("completed", completed_);
    return j;
  }
  void restore(const Json& snap) override {
    created_ = snap["created"].as_int();
    completed_ = snap["completed"].as_int();
  }

 private:
  SearchOp next_create() {
    Json hp = points_.empty()
                  ? sample_hparams(space_, rng_)
                  : points_[static_cast<size_t>(created_) % points_.size()];
    ++created_;
    return SearchOp::create(std::move(hp));
  }
  void refill(std::vector<SearchOp>& ops) {
    if (created_ < max_trials_) {
      ops.push_back(next_create());
    } else if (completed_ >= max_trials_) {
      ops.push_back(SearchOp::shutdown());
    }
  }

  Json space_;
  std::mt19937_64 rng_;
  int64_t max_units_;
  int64_t max_trials_ = 1;
  int64_t max_concurrent_ = 16;
  int64_t created_ = 0;
  int64_t completed_ = 0;
  std::vector<Json> points_;  // grid mode
};

// ---------------------------------------------------------------------------

class AshaSearchCpp : public SearchMethodCpp {
 public:
  AshaSearchCpp(const Json& cfg, Json space, uint64_t seed,
                std::optional<int> num_rungs_override = std::nullopt,
                std::optional<int64_t> max_trials_override = std::nullopt,
                std::optional<int64_t> max_concurrent_override = std::nullopt)
      : space_(std::move(space)), rng_(seed) {
    max_units_ = config_max_units(cfg);
    divisor_ = std::max<int64_t>(2, cfg["divisor"].as_int(4));
    num_rungs_ = num_rungs_override.value_or(
        static_cast<int>(cfg["num_rungs"].as_int(5)));
    max_trials_ = max_trials_override.value_or(
        std::max<int64_t>(1, cfg["max_trials"].as_int(1)));
    max_concurrent_ = max_concurrent_override.value_or(
        cfg["max_concurrent_trials"].as_int(16));
    max_concurrent_ = std::max<int64_t>(
        1, std::min(max_concurrent_, max_trials_));
    smaller_is_better_ = cfg.has("smaller_is_better")
                             ? cfg["smaller_is_better"].as_bool(true)
                             : true;
    stop_once_ = cfg["stop_once"].as_bool(false);

    rung_targets_.resize(num_rungs_);
    for (int r = 0; r < num_rungs_; ++r) {
      double denom = std::pow(static_cast<double>(divisor_),
                              num_rungs_ - 1 - r);
      rung_targets_[r] = std::max<int64_t>(
          1, static_cast<int64_t>(std::llround(max_units_ / denom)));
    }
    for (int r = 1; r < num_rungs_; ++r) {
      if (rung_targets_[r] <= rung_targets_[r - 1]) {
        rung_targets_[r] = rung_targets_[r - 1] + 1;
      }
    }
    rung_targets_[num_rungs_ - 1] =
        std::max(rung_targets_[num_rungs_ - 1], max_units_);
    rungs_.resize(num_rungs_);
    promoted_.resize(num_rungs_);
  }

  std::vector<SearchOp> initial_operations() override {
    std::vector<SearchOp> ops;
    for (int64_t i = 0; i < std::min(max_concurrent_, max_trials_); ++i) {
      ops.push_back(create_trial());
    }
    return ops;
  }

  std::vector<SearchOp> on_trial_created(int64_t rid) override {
    ++started_;
    trial_rung_[rid] = 0;
    return {SearchOp::validate_after(rid, rung_targets_[0])};
  }

  std::vector<SearchOp> on_validation_completed(int64_t rid, double metric,
                                                int64_t units) override {
    int r = rung_of(units);
    trial_rung_[rid] = r;
    rungs_[r].push_back({signed_metric(metric), rid});
    std::vector<SearchOp> ops;

    if (r == num_rungs_ - 1) {
      closed_.insert(rid);
      ops.push_back(SearchOp::close(rid));
      if (created_ < max_trials_) ops.push_back(create_trial());
    } else if (stop_once_) {
      auto records = sorted_rung(r);
      size_t rank = 0;
      for (; rank < records.size(); ++rank) {
        if (records[rank].second == rid) break;
      }
      size_t keep = std::max<size_t>(1, records.size() / divisor_);
      if (rank < keep) {
        trial_rung_[rid] = r + 1;
        ops.push_back(SearchOp::validate_after(rid, rung_targets_[r + 1]));
      } else {
        closed_.insert(rid);
        ops.push_back(SearchOp::close(rid));
        if (created_ < max_trials_) ops.push_back(create_trial());
      }
    } else {
      auto promotions = promote(r);
      bool self_promoted = false;
      for (const auto& op : promotions) {
        if (op.request_id == rid) self_promoted = true;
        ops.push_back(op);
      }
      if (created_ < max_trials_ && !self_promoted) {
        ops.push_back(create_trial());
      }
    }
    finish_if_done(ops);
    return ops;
  }

  std::vector<SearchOp> on_trial_exited_early(int64_t rid) override {
    closed_.insert(rid);
    std::vector<SearchOp> ops;
    if (created_ < max_trials_) ops.push_back(create_trial());
    finish_if_done(ops);
    return ops;
  }

  double progress() const override {
    return done_ ? 1.0
                 : std::min(0.99, static_cast<double>(closed_.size()) /
                                      std::max<int64_t>(1, max_trials_));
  }

  Json snapshot() const override {
    Json rungs = Json::array();
    for (const auto& rung : rungs_) {
      Json rj = Json::array();
      for (const auto& [m, rid] : rung) {
        Json rec = Json::array();
        rec.push_back(m);
        rec.push_back(rid);
        rj.push_back(rec);
      }
      rungs.push_back(rj);
    }
    Json promoted = Json::array();
    for (const auto& p : promoted_) {
      Json pj = Json::array();
      for (int64_t rid : p) pj.push_back(rid);
      promoted.push_back(pj);
    }
    Json trial_rung = Json::object();
    for (const auto& [rid, r] : trial_rung_) {
      trial_rung.set(std::to_string(rid), r);
    }
    Json closed = Json::array();
    for (int64_t rid : closed_) closed.push_back(rid);
    Json j = Json::object();
    j.set("created", created_).set("started", started_)
        .set("rungs", rungs).set("promoted", promoted)
        .set("trial_rung", trial_rung).set("closed", closed)
        .set("done", done_);
    return j;
  }

  void restore(const Json& snap) override {
    created_ = snap["created"].as_int();
    started_ = snap["started"].as_int();
    done_ = snap["done"].as_bool();
    rungs_.assign(num_rungs_, {});
    const auto& rungs = snap["rungs"].elements();
    for (size_t r = 0; r < rungs.size() && r < rungs_.size(); ++r) {
      for (const auto& rec : rungs[r].elements()) {
        rungs_[r].push_back(
            {rec.elements()[0].as_number(), rec.elements()[1].as_int()});
      }
    }
    promoted_.assign(num_rungs_, {});
    const auto& promoted = snap["promoted"].elements();
    for (size_t r = 0; r < promoted.size() && r < promoted_.size(); ++r) {
      for (const auto& rid : promoted[r].elements()) {
        promoted_[r].insert(rid.as_int());
      }
    }
    trial_rung_.clear();
    for (const auto& [rid, r] : snap["trial_rung"].items()) {
      trial_rung_[std::stoll(rid)] = static_cast<int>(r.as_int());
    }
    closed_.clear();
    for (const auto& rid : snap["closed"].elements()) {
      closed_.insert(rid.as_int());
    }
  }

  const std::vector<int64_t>& rung_targets() const { return rung_targets_; }

 private:
  double signed_metric(double m) const {
    return smaller_is_better_ ? m : -m;
  }
  int rung_of(int64_t units) const {
    for (int r = 0; r < num_rungs_; ++r) {
      if (units <= rung_targets_[r]) return r;
    }
    return num_rungs_ - 1;
  }
  SearchOp create_trial() {
    ++created_;
    return SearchOp::create(sample_hparams(space_, rng_));
  }
  std::vector<std::pair<double, int64_t>> sorted_rung(int r) const {
    auto records = rungs_[r];
    std::sort(records.begin(), records.end());
    return records;
  }
  std::vector<SearchOp> promote(int r) {
    std::vector<SearchOp> ops;
    if (r >= num_rungs_ - 1) return ops;
    auto records = sorted_rung(r);
    size_t allowed = records.size() / divisor_;
    while (promoted_[r].size() < allowed) {
      std::optional<int64_t> candidate;
      for (const auto& [m, rid] : records) {
        if (!promoted_[r].count(rid) && !closed_.count(rid)) {
          candidate = rid;
          break;
        }
      }
      if (!candidate) break;
      promoted_[r].insert(*candidate);
      trial_rung_[*candidate] = r + 1;
      ops.push_back(SearchOp::validate_after(*candidate, rung_targets_[r + 1]));
    }
    return ops;
  }
  void finish_if_done(std::vector<SearchOp>& ops) {
    if (done_ || created_ < max_trials_ || started_ < created_) return;
    std::vector<int64_t> live;
    for (const auto& [rid, r] : trial_rung_) {
      if (closed_.count(rid)) continue;
      bool reported = false;
      for (const auto& [m, rec_rid] : rungs_[r]) {
        if (rec_rid == rid) { reported = true; break; }
      }
      if (!reported) return;  // still pending → not done
      live.push_back(rid);
    }
    std::sort(live.begin(), live.end());
    for (int64_t rid : live) {
      closed_.insert(rid);
      ops.push_back(SearchOp::close(rid));
    }
    ops.push_back(SearchOp::shutdown());
    done_ = true;
  }

  Json space_;
  std::mt19937_64 rng_;
  int64_t max_units_;
  int64_t divisor_;
  int num_rungs_;
  int64_t max_trials_;
  int64_t max_concurrent_;
  bool smaller_is_better_;
  bool stop_once_;
  std::vector<int64_t> rung_targets_;
  std::vector<std::vector<std::pair<double, int64_t>>> rungs_;
  std::vector<std::set<int64_t>> promoted_;
  std::map<int64_t, int> trial_rung_;
  std::set<int64_t> closed_;
  int64_t created_ = 0;
  int64_t started_ = 0;
  bool done_ = false;
};

// ---------------------------------------------------------------------------

class AdaptiveAshaCpp : public SearchMethodCpp {
 public:
  AdaptiveAshaCpp(const Json& cfg, Json space, uint64_t seed) {
    int num_rungs = static_cast<int>(cfg["num_rungs"].as_int(5));
    std::string mode = cfg["mode"].as_string().empty()
                           ? "standard" : cfg["mode"].as_string();
    std::vector<int> rung_counts;
    if (cfg["bracket_rungs"].is_array()) {
      for (const auto& r : cfg["bracket_rungs"].elements()) {
        rung_counts.push_back(static_cast<int>(r.as_int()));
      }
    } else if (mode == "aggressive") {
      rung_counts = {num_rungs};
    } else if (mode == "conservative") {
      for (int r = num_rungs; r >= 1; --r) rung_counts.push_back(r);
    } else {
      for (int r = num_rungs; r >= std::max(1, num_rungs - 2); --r) {
        rung_counts.push_back(r);
      }
    }
    int64_t max_trials = std::max<int64_t>(1, cfg["max_trials"].as_int(1));
    int64_t n = static_cast<int64_t>(rung_counts.size());
    int64_t base = max_trials / n, rem = max_trials % n;
    int64_t conc = cfg["max_concurrent_trials"].as_int(16);
    conc = std::max<int64_t>(n, conc);
    int64_t conc_base = conc / n, conc_rem = conc % n;
    for (int64_t i = 0; i < n; ++i) {
      int64_t trials = base + (i < rem ? 1 : 0);
      if (trials == 0) continue;
      int64_t c = conc_base + (i < conc_rem ? 1 : 0);
      brackets_.push_back(std::make_unique<AshaSearchCpp>(
          cfg, space, seed + static_cast<uint64_t>(i),
          rung_counts[static_cast<size_t>(i)], trials,
          std::min(c, trials)));
    }
  }

  std::vector<SearchOp> initial_operations() override {
    std::vector<SearchOp> ops;
    for (size_t i = 0; i < brackets_.size(); ++i) {
      route(i, brackets_[i]->initial_operations(), ops);
    }
    return ops;
  }
  std::vector<SearchOp> on_trial_created(int64_t rid) override {
    if (pending_.empty()) {
      throw std::runtime_error("adaptive asha: unexpected trial_created");
    }
    size_t i = pending_.front();
    pending_.pop_front();
    owner_[rid] = i;
    std::vector<SearchOp> ops;
    route(i, brackets_[i]->on_trial_created(rid), ops);
    return ops;
  }
  std::vector<SearchOp> on_validation_completed(int64_t rid, double metric,
                                                int64_t units) override {
    size_t i = owner_.at(rid);
    std::vector<SearchOp> ops;
    route(i, brackets_[i]->on_validation_completed(rid, metric, units), ops);
    return ops;
  }
  std::vector<SearchOp> on_trial_exited_early(int64_t rid) override {
    size_t i = owner_.at(rid);
    std::vector<SearchOp> ops;
    route(i, brackets_[i]->on_trial_exited_early(rid), ops);
    return ops;
  }
  double progress() const override {
    if (brackets_.empty()) return 1.0;
    double sum = 0;
    for (const auto& b : brackets_) sum += b->progress();
    return sum / static_cast<double>(brackets_.size());
  }
  Json snapshot() const override {
    Json bj = Json::array();
    for (const auto& b : brackets_) bj.push_back(b->snapshot());
    Json owner = Json::object();
    for (const auto& [rid, i] : owner_) {
      owner.set(std::to_string(rid), static_cast<int64_t>(i));
    }
    Json pending = Json::array();
    for (size_t i : pending_) pending.push_back(static_cast<int64_t>(i));
    Json shut = Json::array();
    for (size_t i : shut_) shut.push_back(static_cast<int64_t>(i));
    Json j = Json::object();
    j.set("brackets", bj).set("owner", owner).set("pending", pending)
        .set("shut", shut);
    return j;
  }
  void restore(const Json& snap) override {
    const auto& bj = snap["brackets"].elements();
    for (size_t i = 0; i < brackets_.size() && i < bj.size(); ++i) {
      brackets_[i]->restore(bj[i]);
    }
    owner_.clear();
    for (const auto& [rid, i] : snap["owner"].items()) {
      owner_[std::stoll(rid)] = static_cast<size_t>(i.as_int());
    }
    pending_.clear();
    for (const auto& i : snap["pending"].elements()) {
      pending_.push_back(static_cast<size_t>(i.as_int()));
    }
    shut_.clear();
    for (const auto& i : snap["shut"].elements()) {
      shut_.insert(static_cast<size_t>(i.as_int()));
    }
  }

 private:
  void route(size_t bracket, std::vector<SearchOp> in,
             std::vector<SearchOp>& out) {
    for (auto& op : in) {
      if (op.kind == SearchOp::Kind::Create) {
        pending_.push_back(bracket);
        out.push_back(std::move(op));
      } else if (op.kind == SearchOp::Kind::Shutdown) {
        shut_.insert(bracket);
        if (shut_.size() == brackets_.size()) out.push_back(std::move(op));
      } else {
        out.push_back(std::move(op));
      }
    }
  }

  std::vector<std::unique_ptr<AshaSearchCpp>> brackets_;
  std::map<int64_t, size_t> owner_;
  std::deque<size_t> pending_;
  std::set<size_t> shut_;
};

}  // namespace

// ---------------------------------------------------------------------------
// custom search: event queue for an external search method
// ---------------------------------------------------------------------------

void CustomSearchCpp::record(const std::string& type, Json data) {
  data.set("id", next_event_id_++);
  data.set("type", type);
  events_.push_back(std::move(data));
}

std::vector<SearchOp> CustomSearchCpp::initial_operations() {
  record("initial_operations", Json::object());
  return {};
}

std::vector<SearchOp> CustomSearchCpp::on_trial_created(int64_t rid) {
  Json d = Json::object();
  d.set("request_id", rid);
  record("trial_created", std::move(d));
  return {};
}

std::vector<SearchOp> CustomSearchCpp::on_validation_completed(
    int64_t rid, double metric, int64_t units) {
  Json d = Json::object();
  d.set("request_id", rid).set("metric", metric).set("units", units);
  record("validation_completed", std::move(d));
  return {};
}

std::vector<SearchOp> CustomSearchCpp::on_trial_exited_early(int64_t rid) {
  Json d = Json::object();
  d.set("request_id", rid);
  record("trial_exited_early", std::move(d));
  return {};
}

std::vector<SearchOp> CustomSearchCpp::on_trial_closed(int64_t rid) {
  Json d = Json::object();
  d.set("request_id", rid);
  record("trial_closed", std::move(d));
  return {};
}

void CustomSearchCpp::trim_events(int64_t up_to) {
  events_.erase(
      std::remove_if(events_.begin(), events_.end(),
                     [&](const Json& e) { return e["id"].as_int() <= up_to; }),
      events_.end());
}

Json CustomSearchCpp::events_after(int64_t since) const {
  Json out = Json::array();
  for (const auto& e : events_) {
    if (e["id"].as_int() > since) out.push_back(e);
  }
  return out;
}

Json CustomSearchCpp::snapshot() const {
  Json j = Json::object();
  Json evs = Json::array();
  for (const auto& e : events_) evs.push_back(e);
  j.set("events", evs).set("next_event_id", next_event_id_)
      .set("progress", progress_);
  return j;
}

void CustomSearchCpp::restore(const Json& snap) {
  events_.clear();
  for (const auto& e : snap["events"].elements()) events_.push_back(e);
  next_event_id_ = snap["next_event_id"].as_int(1);
  progress_ = snap["progress"].as_number(0.0);
}

std::unique_ptr<SearchMethodCpp> build_search_method(
    const Json& cfg, const Json& space, uint64_t seed) {
  const std::string& name =
      cfg["name"].as_string().empty() ? "single" : cfg["name"].as_string();
  if (name == "single") {
    return std::make_unique<SingleSearchCpp>(cfg, space, seed);
  }
  if (name == "random") {
    return std::make_unique<RandomSearchCpp>(cfg, space, seed, false);
  }
  if (name == "grid") {
    return std::make_unique<RandomSearchCpp>(cfg, space, seed, true);
  }
  if (name == "asha") {
    return std::make_unique<AshaSearchCpp>(cfg, space, seed);
  }
  if (name == "adaptive_asha") {
    return std::make_unique<AdaptiveAshaCpp>(cfg, space, seed);
  }
  if (name == "custom") {
    return std::make_unique<CustomSearchCpp>();
  }
  throw std::runtime_error("unknown searcher name '" + name + "'");
}

}  // namespace dct
