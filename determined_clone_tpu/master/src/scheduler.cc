#include "scheduler.h"

#include <algorithm>

namespace dct {
namespace {

// Shape-aware (SURVEY §7): a requested topology is satisfied by any agent
// whose slice CONTAINS it — "v5e-4" fits inside a v5e-8 slice as a 2x2
// sub-torus. Generations must match when both are named; plain string
// equality (the reference's semantics) falls out as a special case.
bool topology_ok(const Allocation& alloc, const Agent& agent) {
  if (alloc.topology.empty() || alloc.topology == agent.topology) {
    return true;
  }
  return shape_fits(parse_topology(alloc.topology, alloc.slots),
                    parse_topology(agent.topology, agent.slots));
}

bool agent_usable(const Allocation& alloc, const Agent& agent,
                  const std::string& experiment_key) {
  if (!agent.enabled) return false;
  if (!topology_ok(alloc, agent)) return false;
  if (!experiment_key.empty() && agent.blocked_by.count(experiment_key)) {
    return false;  // log-pattern node blocklisting (logpattern → trial.go:381)
  }
  return true;
}

}  // namespace

std::map<std::string, ChipGrid> build_chip_grids(
    const std::vector<Agent>& agents,
    const std::vector<Allocation>& running) {
  std::map<std::string, ChipGrid> grids;
  for (const auto& a : agents) {
    SliceShape shape = parse_topology(a.topology, a.slots);
    if (shape.chips() != a.slots) {
      // advertised slots disagree with the topology string (artificial
      // slots in tests, misconfig): trust slots, flat-contiguous grid
      shape = SliceShape{};
      shape.rows = 1;
      shape.cols = std::max(1, a.slots);
    }
    grids.emplace(a.id, ChipGrid(shape));
  }
  // deterministic replay: same inputs -> same placements across ticks
  std::vector<const Allocation*> ordered;
  for (const auto& r : running) ordered.push_back(&r);
  std::sort(ordered.begin(), ordered.end(),
            [](const Allocation* a, const Allocation* b) {
              return a->queued_at != b->queued_at
                         ? a->queued_at < b->queued_at
                         : a->id < b->id;
            });
  for (const Allocation* r : ordered) {
    for (const auto& [aid, n] : r->reservations) {
      auto it = grids.find(aid);
      if (it == grids.end() || n <= 0) continue;
      if (!it->second.place(n, r->id)) {
        // drifted state (e.g. restored pre-topology reservations that no
        // longer tile): stay count-consistent rather than lose capacity
        it->second.force_place(n, r->id);
      }
    }
  }
  return grids;
}

std::optional<std::map<std::string, int>> find_fit(
    const Allocation& alloc, const std::vector<Agent>& agents,
    const std::map<std::string, int>& free_slots,
    const std::string& experiment_key,
    const std::map<std::string, ChipGrid>* grids) {
  if (alloc.slots == 0) {
    // zero-slot (cpu-only aux task): place on the least-loaded usable agent
    const Agent* best = nullptr;
    int best_free = -1;
    for (const auto& a : agents) {
      if (!agent_usable(alloc, a, experiment_key)) continue;
      auto it = free_slots.find(a.id);
      int free = it == free_slots.end() ? 0 : it->second;
      if (free > best_free) { best = &a; best_free = free; }
    }
    if (!best) return std::nullopt;
    return std::map<std::string, int>{{best->id, 0}};
  }

  // 0.5) multislice slice-group (SURVEY §7.7 — beyond the reference):
  // reserve n_slices WHOLE idle agents, one slice each, as one gang.
  // `topology` names the PER-SLICE shape; an agent qualifies when its own
  // advertised topology matches (or, with no shape given, when it holds
  // exactly slots/n_slices chips). Rank order == sorted agent id ==
  // slice_id, which the rendezvous payload hands to the harness so
  // exec/trial.py can build the ICI×DCN mesh.
  if (alloc.n_slices > 1) {
    int per_slice = alloc.slots / alloc.n_slices;
    if (per_slice * alloc.n_slices != alloc.slots || per_slice <= 0) {
      return std::nullopt;  // mis-sized request can never fit
    }
    std::vector<const Agent*> idle;
    for (const auto& a : agents) {
      // agent_usable's topology_ok gives the same SEMANTIC shape matching
      // as the single-slice path ("2x4" satisfies a v5e-8 agent); the
      // exact-size check below pins one whole slice per agent
      if (!agent_usable(alloc, a, experiment_key)) continue;
      auto it = free_slots.find(a.id);
      bool whole_free = it != free_slots.end() && it->second == a.slots;
      if (!whole_free || a.slots != per_slice) continue;
      idle.push_back(&a);
    }
    if (static_cast<int>(idle.size()) < alloc.n_slices) return std::nullopt;
    std::sort(idle.begin(), idle.end(),
              [](const Agent* x, const Agent* y) { return x->id < y->id; });
    std::map<std::string, int> gang;
    for (int i = 0; i < alloc.n_slices; ++i) gang[idle[i]->id] = idle[i]->slots;
    return gang;
  }

  // 1) best single-agent fit: smallest free-slot surplus (bin packing),
  //    exact-capacity agents preferred, AND — with grids — a contiguous
  //    free rectangle must exist: n free chips scattered across the torus
  //    do not make an n-chip gang (fragmentation-aware fitting, SURVEY §7)
  const Agent* best = nullptr;
  int best_surplus = 1 << 30;
  SliceShape req_shape = parse_topology(alloc.topology, alloc.slots);
  for (const auto& a : agents) {
    if (!agent_usable(alloc, a, experiment_key)) continue;
    auto it = free_slots.find(a.id);
    int free = it == free_slots.end() ? 0 : it->second;
    if (free < alloc.slots) continue;
    if (grids) {
      auto git = grids->find(a.id);
      if (git != grids->end()) {
        bool ok = alloc.topology.empty()
                      ? git->second.can_place(alloc.slots)
                      : git->second.can_place_shape(req_shape);
        if (!ok) continue;
      }
    }
    int surplus = free - alloc.slots;
    // prefer exact whole-agent fits, then minimal surplus
    if (surplus < best_surplus) { best = &a; best_surplus = surplus; }
  }
  if (best) return std::map<std::string, int>{{best->id, alloc.slots}};

  // 2) multi-agent gang: whole idle agents only (each contributes its full
  //    slice; the harness lays dp/fsdp across agents, tp/sp within).
  std::vector<const Agent*> idle;
  for (const auto& a : agents) {
    if (!agent_usable(alloc, a, experiment_key)) continue;
    auto it = free_slots.find(a.id);
    if (it != free_slots.end() && it->second == a.slots && a.slots > 0) {
      idle.push_back(&a);
    }
  }
  // deterministic order, largest slices first to minimize gang width
  std::sort(idle.begin(), idle.end(), [](const Agent* x, const Agent* y) {
    return x->slots != y->slots ? x->slots > y->slots : x->id < y->id;
  });
  std::map<std::string, int> gang;
  int needed = alloc.slots;
  for (const Agent* a : idle) {
    if (needed <= 0) break;
    if (a->slots > needed) continue;  // whole agents only; skip oversized
    gang[a->id] = a->slots;
    needed -= a->slots;
  }
  if (needed == 0 && !gang.empty()) return gang;
  return std::nullopt;
}

SchedulerDecision schedule_pool(
    const PoolPolicy& policy,
    const std::vector<Agent>& agents,
    std::map<std::string, int> free_slots,
    std::vector<Allocation> pending,
    const std::vector<Allocation>& running,
    const std::map<std::string, int>& share_usage,
    const std::map<std::string, std::string>& owner_of_alloc) {
  SchedulerDecision decision;

  auto owner_key = [&](const Allocation& a) -> std::string {
    auto it = owner_of_alloc.find(a.id);
    return it == owner_of_alloc.end() ? a.task_type : it->second;
  };

  if (policy.type == "fifo") {
    std::sort(pending.begin(), pending.end(),
              [](const Allocation& a, const Allocation& b) {
                return a.queued_at != b.queued_at ? a.queued_at < b.queued_at
                                                  : a.id < b.id;
              });
  } else if (policy.type == "round_robin") {
    // interleave owners: first job of each owner, then second of each, ...
    // (≈ round_robin.go: rotate among groups in arrival order)
    std::sort(pending.begin(), pending.end(),
              [](const Allocation& a, const Allocation& b) {
                return a.queued_at != b.queued_at ? a.queued_at < b.queued_at
                                                  : a.id < b.id;
              });
    std::map<std::string, int> seen;   // owner -> jobs already taken
    std::vector<std::pair<std::pair<int, double>, Allocation>> keyed;
    for (auto& a : pending) {
      int round = seen[owner_key(a)]++;
      keyed.push_back({{round, a.queued_at}, std::move(a)});
    }
    std::sort(keyed.begin(), keyed.end(),
              [](const auto& x, const auto& y) {
                if (x.first.first != y.first.first) {
                  return x.first.first < y.first.first;
                }
                if (x.first.second != y.first.second) {
                  return x.first.second < y.first.second;
                }
                return x.second.id < y.second.id;
              });
    pending.clear();
    for (auto& [key, a] : keyed) pending.push_back(std::move(a));
  } else if (policy.type == "fair_share") {
    // owners with fewer held slots go first (≈ fair_share.go:51)
    std::map<std::string, int> usage = share_usage;
    std::stable_sort(pending.begin(), pending.end(),
                     [&](const Allocation& a, const Allocation& b) {
                       int ua = usage.count(owner_key(a)) ? usage.at(owner_key(a)) : 0;
                       int ub = usage.count(owner_key(b)) ? usage.at(owner_key(b)) : 0;
                       return ua != ub ? ua < ub : a.queued_at < b.queued_at;
                     });
  } else {  // priority: lower number = higher priority (≈ priority.go:30)
    std::sort(pending.begin(), pending.end(),
              [](const Allocation& a, const Allocation& b) {
                if (a.priority != b.priority) return a.priority < b.priority;
                return a.queued_at != b.queued_at ? a.queued_at < b.queued_at
                                                  : a.id < b.id;
              });
  }

  // chip grids: running reservations placed as rectangles, so sub-slice
  // fits below are contiguity-aware (topology.h)
  auto grids = build_chip_grids(agents, running);
  auto grid_place = [&](std::map<std::string, ChipGrid>& g,
                        const Allocation& alloc, const std::string& aid,
                        int n) {
    auto git = g.find(aid);
    if (git == g.end() || n <= 0) return;
    // place THIS AGENT's contribution (n), as the requested shape only
    // when the whole gang lands on this one agent — a multi-agent member
    // contributes n chips, not the full request shape
    bool ok = (!alloc.topology.empty() && n == alloc.slots)
                  ? git->second.place_shape(
                        parse_topology(alloc.topology, alloc.slots),
                        alloc.id)
                  : git->second.place(n, alloc.id);
    if (!ok) git->second.force_place(n, alloc.id);
  };

  std::map<std::string, int> usage = share_usage;
  for (auto& alloc : pending) {
    std::string key = owner_key(alloc);
    ++decision.considered;
    auto fit = find_fit(alloc, agents, free_slots, key, &grids);
    if (fit) {
      for (const auto& [aid, n] : *fit) {
        free_slots[aid] -= n;
        grid_place(grids, alloc, aid, n);
      }
      usage[key] += alloc.slots;
      if (fit->size() > 1 || alloc.n_slices > 1) ++decision.gangs_admitted;
      decision.assignments[alloc.id] = *fit;
      continue;
    }
    if (alloc.slots > 0) ++decision.gang_waiting;
    if (policy.type == "priority" && policy.preemption_enabled) {
      // can preempting strictly-lower-priority gangs free enough capacity?
      // (≈ priority.go:199 — victims chosen newest-first)
      std::vector<const Allocation*> victims;
      for (const auto& r : running) {
        if (r.priority > alloc.priority) victims.push_back(&r);
      }
      std::sort(victims.begin(), victims.end(),
                [](const Allocation* a, const Allocation* b) {
                  return a->queued_at > b->queued_at;
                });
      auto trial_free = free_slots;
      auto trial_grids = grids;
      std::vector<std::string> chosen;
      bool fits_after = false;
      for (const auto* v : victims) {
        for (const auto& [aid, n] : v->reservations) trial_free[aid] += n;
        for (auto& [aid, grid] : trial_grids) grid.release(v->id);
        chosen.push_back(v->id);
        fits_after =
            find_fit(alloc, agents, trial_free, key, &trial_grids).has_value();
        if (fits_after) break;
      }
      if (!chosen.empty() && fits_after) {
        // request preemption now; the allocation schedules on a later tick
        // once the victims have checkpointed and released
        for (const auto& id : chosen) decision.preemptions.push_back(id);
      }
    }
    // gang semantics: an unfittable high-priority job does NOT let smaller
    // lower-priority jobs jump it in priority mode... except it does in the
    // reference's backfill-free world too; we keep strict ordering only for
    // fifo. priority/fair_share continue to try later entries (backfill).
    if (policy.type == "fifo") break;
  }
  return decision;
}

}  // namespace dct
