#include "http.h"

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <cstring>
#include <memory>
#include <sstream>

namespace dct {
namespace {

int hex_val(char c) {
  if (c >= '0' && c <= '9') return c - '0';
  if (c >= 'a' && c <= 'f') return c - 'a' + 10;
  if (c >= 'A' && c <= 'F') return c - 'A' + 10;
  return -1;
}

std::string url_decode(const std::string& s) {
  std::string out;
  for (size_t i = 0; i < s.size(); ++i) {
    int hi, lo;
    if (s[i] == '%' && i + 2 < s.size() && (hi = hex_val(s[i + 1])) >= 0 &&
        (lo = hex_val(s[i + 2])) >= 0) {
      out += static_cast<char>(hi * 16 + lo);
      i += 2;
    } else if (s[i] == '+') {
      out += ' ';
    } else {
      out += s[i];
    }
  }
  return out;
}

bool read_line(int fd, std::string& line, std::string& buffer) {
  while (true) {
    auto pos = buffer.find("\r\n");
    if (pos != std::string::npos) {
      line = buffer.substr(0, pos);
      buffer.erase(0, pos + 2);
      return true;
    }
    char chunk[4096];
    ssize_t n = ::recv(fd, chunk, sizeof(chunk), 0);
    if (n <= 0) return false;
    buffer.append(chunk, n);
    if (buffer.size() > 64 * 1024 * 1024) return false;  // header bomb
  }
}

bool read_exact(int fd, size_t len, std::string& out, std::string& buffer) {
  while (buffer.size() < len) {
    char chunk[65536];
    ssize_t n = ::recv(fd, chunk, sizeof(chunk), 0);
    if (n <= 0) return false;
    buffer.append(chunk, n);
  }
  out = buffer.substr(0, len);
  buffer.erase(0, len);
  return true;
}

bool send_all(int fd, const std::string& data) {
  size_t sent = 0;
  while (sent < data.size()) {
    ssize_t n = ::send(fd, data.data() + sent, data.size() - sent, MSG_NOSIGNAL);
    if (n <= 0) return false;
    sent += n;
  }
  return true;
}

const char* status_text(int code) {
  switch (code) {
    case 200: return "OK";
    case 201: return "Created";
    case 204: return "No Content";
    case 302: return "Found";
    case 400: return "Bad Request";
    case 401: return "Unauthorized";
    case 403: return "Forbidden";
    case 404: return "Not Found";
    case 405: return "Method Not Allowed";
    case 409: return "Conflict";
    case 500: return "Internal Server Error";
    default: return "Unknown";
  }
}

}  // namespace

void HttpServer::start(int port) {
  listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
  if (listen_fd_ < 0) throw std::runtime_error("socket() failed");
  int one = 1;
  ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0) {
    throw std::runtime_error("bind() failed on port " + std::to_string(port));
  }
  socklen_t len = sizeof(addr);
  ::getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr), &len);
  port_ = ntohs(addr.sin_port);
  if (::listen(listen_fd_, 128) < 0) throw std::runtime_error("listen() failed");
  running_ = true;
  accept_thread_ = std::thread([this] { accept_loop(); });
}

void HttpServer::stop() {
  if (!running_.exchange(false)) return;
  ::shutdown(listen_fd_, SHUT_RDWR);
  ::close(listen_fd_);
  {
    // wake worker threads blocked in recv() on live connections
    std::lock_guard<std::mutex> lock(conn_mu_);
    for (int fd : conn_fds_) ::shutdown(fd, SHUT_RDWR);
  }
  if (accept_thread_.joinable()) accept_thread_.join();
  for (auto& w : workers_) {
    if (w.thread.joinable()) w.thread.join();
  }
  workers_.clear();
}

void HttpServer::accept_loop() {
  while (running_) {
    int fd = ::accept(listen_fd_, nullptr, nullptr);
    if (fd < 0) {
      if (!running_) break;
      continue;
    }
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    // idle keep-alive connections must not block shutdown: bounded recv
    timeval tv{120, 0};
    ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
    {
      std::lock_guard<std::mutex> lock(conn_mu_);
      conn_fds_.insert(fd);
    }
    // reap finished connection threads before spawning the next: a soak's
    // connection churn must not accumulate ten thousand dead std::threads
    // (only the accept thread touches workers_, so no lock needed).
    // Two passes — join first, then erase with a side-effect-free
    // predicate ([alg.req] forbids remove_if predicates that mutate).
    for (auto& w : workers_) {
      if (w.done->load() && w.thread.joinable()) w.thread.join();
    }
    workers_.erase(
        std::remove_if(workers_.begin(), workers_.end(),
                       [](const Worker& w) {
                         return w.done->load() && !w.thread.joinable();
                       }),
        workers_.end());
    auto done = std::make_shared<std::atomic<bool>>(false);
    Worker w;
    w.done = done;
    w.thread = std::thread([this, fd, done] {
      serve_connection(fd);
      {
        std::lock_guard<std::mutex> lock(conn_mu_);
        conn_fds_.erase(fd);
      }
      done->store(true);
    });
    workers_.push_back(std::move(w));
  }
}

void HttpServer::serve_connection(int fd) {
  std::string buffer;
  while (running_) {
    std::string request_line;
    if (!read_line(fd, request_line, buffer)) break;
    if (request_line.empty()) continue;

    HttpRequest req;
    {
      std::istringstream rl(request_line);
      std::string target, version;
      rl >> req.method >> target >> version;
      auto qpos = target.find('?');
      if (qpos != std::string::npos) {
        std::string qs = target.substr(qpos + 1);
        target = target.substr(0, qpos);
        std::istringstream qstream(qs);
        std::string pair;
        while (std::getline(qstream, pair, '&')) {
          auto eq = pair.find('=');
          if (eq != std::string::npos) {
            req.query[url_decode(pair.substr(0, eq))] =
                url_decode(pair.substr(eq + 1));
          }
        }
      }
      req.path = url_decode(target);
    }
    {
      std::istringstream pstream(req.path);
      std::string part;
      while (std::getline(pstream, part, '/')) {
        if (!part.empty()) req.path_parts.push_back(part);
      }
    }

    bool keep_alive = true;
    while (true) {
      std::string header;
      if (!read_line(fd, header, buffer)) { keep_alive = false; break; }
      if (header.empty()) break;
      auto colon = header.find(':');
      if (colon == std::string::npos) continue;
      std::string key = header.substr(0, colon);
      for (auto& c : key) c = static_cast<char>(::tolower(c));
      std::string value = header.substr(colon + 1);
      while (!value.empty() && value.front() == ' ') value.erase(0, 1);
      req.headers[key] = value;
    }
    if (!keep_alive) break;

    auto cl = req.headers.find("content-length");
    if (cl != req.headers.end()) {
      size_t len = 0;
      try {
        len = std::stoul(cl->second);
      } catch (const std::exception&) {
        break;  // malformed Content-Length: drop the connection
      }
      if (len > 256 * 1024 * 1024) break;  // oversized body
      if (!read_exact(fd, len, req.body, buffer)) break;
    }
    auto conn = req.headers.find("connection");
    if (conn != req.headers.end() && conn->second == "close") keep_alive = false;

    HttpResponse resp;
    try {
      resp = handler_(req);
    } catch (const std::exception& e) {
      resp = HttpResponse::json(
          500, std::string("{\"error\":\"") + e.what() + "\"}");
    }

    if (resp.hijack) {
      // connection takeover (WebSocket/TCP proxying): hand over the raw
      // socket plus any bytes a pipelining client already sent. Lift the
      // keep-alive recv timeout — an idle notebook kernel socket is not
      // a dead connection (stop() still unblocks via shutdown()).
      timeval no_tv{0, 0};
      ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &no_tv, sizeof(no_tv));
      resp.hijack(fd, std::move(buffer));
      break;
    }

    std::ostringstream out;
    out << "HTTP/1.1 " << resp.status << ' ' << status_text(resp.status)
        << "\r\nContent-Type: " << resp.content_type
        << "\r\nContent-Length: " << resp.body.size()
        << "\r\nConnection: " << (keep_alive ? "keep-alive" : "close");
    for (const auto& [name, value] : resp.headers) {
      out << "\r\n" << name << ": " << value;
    }
    out << "\r\n\r\n" << resp.body;
    if (!send_all(fd, out.str())) break;
    if (!keep_alive) break;
  }
  ::close(fd);
}

bool split_host_port(const std::string& s, std::string* host, int* port) {
  auto colon = s.rfind(':');
  if (colon == std::string::npos || colon == 0 || colon + 1 >= s.size()) {
    return false;
  }
  const std::string port_str = s.substr(colon + 1);
  if (port_str.find_first_not_of("0123456789") != std::string::npos) {
    return false;
  }
  long p = std::strtol(port_str.c_str(), nullptr, 10);
  if (p < 1 || p > 65535) return false;
  *host = s.substr(0, colon);
  *port = static_cast<int>(p);
  return true;
}

bool send_all_fd(int fd, const std::string& data) { return send_all(fd, data); }

int tcp_connect(const std::string& host, int port, int timeout_sec) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  timeval tv{timeout_sec, 0};
  ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  ::setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1) {
    // not an IPv4 literal: resolve the hostname (SSO issuers, webhook
    // targets, and k8s service names are rarely raw addresses)
    addrinfo hints{};
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    addrinfo* res = nullptr;
    if (::getaddrinfo(host.c_str(), nullptr, &hints, &res) != 0 || !res) {
      ::close(fd);
      return -1;
    }
    addr.sin_addr = reinterpret_cast<sockaddr_in*>(res->ai_addr)->sin_addr;
    ::freeaddrinfo(res);
  }
  if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0) {
    ::close(fd);
    return -1;
  }
  return fd;
}

void relay_bidirectional(int client_fd, int upstream_fd) {
  auto pump = [](int from, int to) {
    char buf[16384];
    while (true) {
      ssize_t n = ::recv(from, buf, sizeof(buf), 0);
      if (n <= 0) break;
      ssize_t off = 0;
      while (off < n) {
        ssize_t w = ::send(to, buf + off, static_cast<size_t>(n - off),
                           MSG_NOSIGNAL);
        if (w <= 0) return;
        off += w;
      }
    }
    // half-close so the peer's pump sees EOF and drains cleanly
    ::shutdown(to, SHUT_WR);
    ::shutdown(from, SHUT_RD);
  };
  std::thread down([&] { pump(upstream_fd, client_fd); });
  pump(client_fd, upstream_fd);
  down.join();
}

std::optional<HttpClientResponse> http_request(
    const std::string& host, int port, const std::string& method,
    const std::string& path, const std::string& body, int timeout_sec,
    const std::map<std::string, std::string>& extra_headers) {
  int fd = tcp_connect(host, port, timeout_sec);
  if (fd < 0) return std::nullopt;
  std::ostringstream out;
  out << method << ' ' << path << " HTTP/1.1\r\nHost: " << host
      << "\r\nContent-Type: application/json\r\nContent-Length: "
      << body.size() << "\r\nConnection: close";
  for (const auto& [k, v] : extra_headers) out << "\r\n" << k << ": " << v;
  out << "\r\n\r\n" << body;
  if (!send_all(fd, out.str())) { ::close(fd); return std::nullopt; }

  std::string data;
  char chunk[65536];
  while (true) {
    ssize_t n = ::recv(fd, chunk, sizeof(chunk), 0);
    if (n <= 0) break;
    data.append(chunk, n);
  }
  ::close(fd);

  auto header_end = data.find("\r\n\r\n");
  if (header_end == std::string::npos) return std::nullopt;
  HttpClientResponse resp;
  {
    std::istringstream rl(data.substr(0, data.find("\r\n")));
    std::string version;
    rl >> version >> resp.status;
  }
  // response headers: only content-type matters to callers (proxy pass-thru)
  {
    std::istringstream headers(data.substr(0, header_end));
    std::string line;
    std::getline(headers, line);  // status line
    while (std::getline(headers, line)) {
      if (!line.empty() && line.back() == '\r') line.pop_back();
      auto colon = line.find(':');
      if (colon == std::string::npos) continue;
      std::string key = line.substr(0, colon);
      for (auto& c : key) {
        c = static_cast<char>(std::tolower(static_cast<unsigned char>(c)));
      }
      if (key == "content-type") {
        auto start = line.find_first_not_of(" \t", colon + 1);
        if (start != std::string::npos) resp.content_type = line.substr(start);
      }
    }
  }
  resp.body = data.substr(header_end + 4);
  return resp;
}

}  // namespace dct
