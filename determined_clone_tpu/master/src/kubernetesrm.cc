#include "kubernetesrm.h"

#include <sys/stat.h>
#include <sys/wait.h>

#include <algorithm>
#include <cctype>
#include <chrono>
#include <cstdio>
#include <fstream>
#include <iostream>
#include <map>
#include <sstream>

namespace dct {
namespace {

// (≈ agent.cc task_env: the DCT_* environment one task sees; here it is
// rendered into the pod container env so the in-pod harness can do
// rendezvous/metrics/logs against the master exactly like the agent path)
Json pod_env(const Json& cmd, const std::string& alloc_id,
             const KubeRmConfig& cfg, int rank) {
  std::map<std::string, std::string> env;
  env["DCT_MASTER_HOST"] = cfg.master_host;
  env["DCT_MASTER_PORT"] = std::to_string(cfg.master_port);
  env["DCT_ALLOCATION_ID"] = alloc_id;
  env["DCT_ALLOC_TOKEN"] = cmd["alloc_token"].as_string();
  env["DCT_AGENT_ID"] = "k8s";
  env["DCT_SLOTS"] = std::to_string(cmd["slots"].as_int());
  env["DCT_RANK"] = std::to_string(rank);
  env["DCT_WORLD_SIZE"] = std::to_string(cmd["world_size"].as_int());
  env["DCT_TASK_TYPE"] = cmd["task_type"].as_string();
  if (cmd["trial"].is_object()) {
    env["DCT_TRIAL_ID"] = std::to_string(cmd["trial"]["id"].as_int());
    env["DCT_EXPERIMENT_ID"] =
        std::to_string(cmd["trial"]["experiment_id"].as_int());
    env["DCT_HPARAMS"] = cmd["trial"]["hparams"].dump();
    env["DCT_TARGET_UNITS"] =
        std::to_string(cmd["trial"]["target_units"].as_int());
    env["DCT_LATEST_CHECKPOINT"] =
        cmd["trial"]["latest_checkpoint"].as_string();
    env["DCT_EXPERIMENT_CONFIG"] = cmd["config"].dump();
  }
  if (cmd["spec"]["env"].is_object()) {
    for (const auto& [k, v] : cmd["spec"]["env"].items()) {
      env[k] = v.as_string();
    }
  }
  Json arr = Json::array();
  for (const auto& [k, v] : env) {
    Json e = Json::object();
    e.set("name", k).set("value", v);
    arr.push_back(e);
  }
  return arr;
}

// (≈ agent.cc task_argv) NTSC argv, or the trial harness module
Json pod_command(const Json& cmd) {
  Json out = Json::array();
  const Json& argv = cmd["spec"]["argv"];
  if (argv.is_array() && argv.size() > 0) return argv;
  const std::string entrypoint = cmd["spec"]["entrypoint"].as_string();
  if (!entrypoint.empty()) {
    out.push_back("python");
    out.push_back("-m");
    out.push_back("determined_clone_tpu.exec.trial");
    out.push_back(entrypoint);
  }
  return out;
}

// pod names must be DNS-1123: lowercase alphanumerics and '-'
std::string sanitize(const std::string& s) {
  std::string out;
  for (char c : s) {
    if (std::isalnum(static_cast<unsigned char>(c))) {
      out += static_cast<char>(std::tolower(static_cast<unsigned char>(c)));
    } else {
      out += '-';
    }
  }
  return out;
}

std::string pod_name(const std::string& alloc_id, int rank) {
  return "dct-" + sanitize(alloc_id) + "-" + std::to_string(rank);
}

bool terminal(const Allocation& a) {
  return a.state == RunState::Completed || a.state == RunState::Errored ||
         a.state == RunState::Canceled;
}

// one gang member's chip share: hosts carry `slots_per_pod` chips, the
// last pod takes the remainder, zero-slot tasks reserve nothing. Shared by
// the submit and reattach paths so a master restart never changes the
// recorded split.
int member_pod_slots(int total_slots, int slots_per_pod, int rank) {
  int slots = std::max(total_slots, 0);
  if (slots == 0) return 0;
  int per_pod = std::min(std::max(1, slots_per_pod), slots);
  return std::max(0, std::min(per_pod, slots - rank * per_pod));
}

int gang_world(int total_slots, int slots_per_pod) {
  int slots = std::max(total_slots, 0);
  if (slots == 0) return 1;
  int per_pod = std::min(std::max(1, slots_per_pod), slots);
  return (slots + per_pod - 1) / per_pod;
}

struct RunResult {
  int rc = -1;
  std::string out;
};

RunResult run_capture(const std::string& cmd) {
  RunResult r;
  FILE* pipe = ::popen(cmd.c_str(), "r");
  if (!pipe) return r;
  char buf[4096];
  size_t n;
  while ((n = ::fread(buf, 1, sizeof(buf), pipe)) > 0) r.out.append(buf, n);
  int status = ::pclose(pipe);
  r.rc = WIFEXITED(status) ? WEXITSTATUS(status) : -1;
  return r;
}

// feed `input` to the command's stdin (kubectl apply -f -): no temp file,
// so no predictable-path /tmp hazard and no cross-master clobbering
int run_with_stdin(const std::string& cmd, const std::string& input) {
  FILE* pipe = ::popen(cmd.c_str(), "w");
  if (!pipe) return -1;
  ::fwrite(input.data(), 1, input.size(), pipe);
  int status = ::pclose(pipe);
  return WIFEXITED(status) ? WEXITSTATUS(status) : -1;
}

}  // namespace

// ---------------------------------------------------------------------------
// DryRunKubectl: pods.json in state_dir is the "cluster"
// ---------------------------------------------------------------------------

DryRunKubectl::DryRunKubectl(std::string state_dir) {
  ::mkdir(state_dir.c_str(), 0755);
  path_ = state_dir + "/pods.json";
}

Json DryRunKubectl::load() {
  std::ifstream in(path_);
  if (!in) return Json::array();
  std::stringstream ss;
  ss << in.rdbuf();
  try {
    Json pods = Json::parse(ss.str());
    return pods.is_array() ? pods : Json::array();
  } catch (const std::exception&) {
    return Json::array();
  }
}

void DryRunKubectl::store(const Json& pods) {
  std::string tmp = path_ + ".tmp";
  {
    std::ofstream out(tmp);
    out << pods.dump();
  }
  ::rename(tmp.c_str(), path_.c_str());
}

bool DryRunKubectl::apply(const Json& manifest) {
  Json pods = load();
  const std::string name = manifest["metadata"]["name"].as_string();
  for (const auto& p : pods.elements()) {
    if (p["name"].as_string() == name) return true;  // apply is idempotent
  }
  Json entry = Json::object();
  entry.set("name", name)
      .set("alloc", manifest["metadata"]["labels"]["dct-alloc"].as_string())
      .set("rank",
           static_cast<int64_t>(std::stoll(
               manifest["metadata"]["labels"]["dct-rank"].as_string())))
      .set("phase", "Pending")
      .set("ip", "")
      .set("exit_code", static_cast<int64_t>(0))
      .set("manifest", manifest);
  pods.push_back(entry);
  store(pods);
  return true;
}

std::vector<KubePodStatus> DryRunKubectl::list_pods() {
  std::vector<KubePodStatus> out;
  const Json pods = load();  // named: elements() refs its internals
  for (const auto& p : pods.elements()) {
    KubePodStatus s;
    s.name = p["name"].as_string();
    s.alloc_id = p["alloc"].as_string();
    s.rank = static_cast<int>(p["rank"].as_int());
    s.phase = p["phase"].as_string();
    s.ip = p["ip"].as_string();
    s.exit_code = static_cast<int>(p["exit_code"].as_int());
    out.push_back(std::move(s));
  }
  return out;
}

bool DryRunKubectl::delete_alloc(const std::string& alloc_id) {
  Json pods = load();
  Json keep = Json::array();
  for (const auto& p : pods.elements()) {
    if (p["alloc"].as_string() != alloc_id) keep.push_back(p);
  }
  store(keep);
  return true;
}

// ---------------------------------------------------------------------------
// LiveKubectl: real kubectl subprocesses
// ---------------------------------------------------------------------------

bool LiveKubectl::apply(const Json& manifest) {
  int rc = run_with_stdin(
      "kubectl -n " + ns_ + " apply -f - >/dev/null 2>&1", manifest.dump());
  if (rc != 0) {
    std::cerr << "[kubernetesrm] kubectl apply exited " << rc << " for pod "
              << manifest["metadata"]["name"].as_string() << std::endl;
    return false;
  }
  return true;
}

std::vector<KubePodStatus> LiveKubectl::list_pods() {
  std::vector<KubePodStatus> out;
  RunResult r = run_capture("kubectl -n " + ns_ +
                            " get pods -l dct-managed=true -o json 2>/dev/null");
  if (r.rc != 0 || r.out.empty()) return out;
  Json doc;
  try {
    doc = Json::parse(r.out);
  } catch (const std::exception&) {
    return out;
  }
  for (const auto& item : doc["items"].elements()) {
    KubePodStatus s;
    s.name = item["metadata"]["name"].as_string();
    s.alloc_id = item["metadata"]["labels"]["dct-alloc"].as_string();
    try {
      s.rank = static_cast<int>(
          std::stoll(item["metadata"]["labels"]["dct-rank"].as_string()));
    } catch (const std::exception&) {
    }
    s.phase = item["status"]["phase"].as_string();
    s.ip = item["status"]["podIP"].as_string();
    for (const auto& c : item["status"]["containerStatuses"].elements()) {
      if (c["state"]["terminated"].is_object()) {
        s.exit_code =
            static_cast<int>(c["state"]["terminated"]["exitCode"].as_int());
      }
    }
    out.push_back(std::move(s));
  }
  return out;
}

bool LiveKubectl::delete_alloc(const std::string& alloc_id) {
  RunResult r = run_capture("kubectl -n " + ns_ + " delete pods -l dct-alloc=" +
                            sanitize(alloc_id) +
                            " --ignore-not-found --wait=false 2>&1");
  return r.rc == 0;
}

// ---------------------------------------------------------------------------
// AsyncKubectl
// ---------------------------------------------------------------------------

AsyncKubectl::AsyncKubectl(std::unique_ptr<KubectlRunner> inner,
                           double poll_interval_sec)
    : inner_(std::move(inner)), interval_(poll_interval_sec) {
  worker_ = std::thread([this] { loop(); });
}

AsyncKubectl::~AsyncKubectl() {
  {
    std::lock_guard<std::mutex> lock(mu_);
    stop_ = true;
  }
  cv_.notify_all();
  if (worker_.joinable()) worker_.join();
}

void AsyncKubectl::loop() {
  std::unique_lock<std::mutex> lock(mu_);
  while (!stop_) {
    // drain queued apply/delete ops (off the lock: they block on kubectl)
    while (!queue_.empty()) {
      auto op = std::move(queue_.front());
      queue_.erase(queue_.begin());
      lock.unlock();
      op();
      lock.lock();
      if (stop_) return;
    }
    lock.unlock();
    auto pods = inner_->list_pods();
    lock.lock();
    if (stop_) return;
    // ops enqueued while we were polling may already have echoed pods the
    // poll predates; only replace the snapshot when the queue is quiet
    if (queue_.empty()) {
      snapshot_ = std::move(pods);
      have_snapshot_ = true;
    }
    cv_.wait_for(lock, std::chrono::duration<double>(interval_),
                 [this] { return stop_ || !queue_.empty(); });
  }
}

bool AsyncKubectl::apply(const Json& manifest) {
  KubePodStatus echo;
  echo.name = manifest["metadata"]["name"].as_string();
  echo.alloc_id = manifest["metadata"]["labels"]["dct-alloc"].as_string();
  try {
    echo.rank = static_cast<int>(
        std::stoll(manifest["metadata"]["labels"]["dct-rank"].as_string()));
  } catch (const std::exception&) {
  }
  echo.phase = "Pending";
  std::lock_guard<std::mutex> lock(mu_);
  bool known = false;
  for (const auto& p : snapshot_) known = known || p.name == echo.name;
  if (!known) snapshot_.push_back(std::move(echo));
  queue_.push_back([this, manifest] { inner_->apply(manifest); });
  cv_.notify_all();
  return true;
}

std::vector<KubePodStatus> AsyncKubectl::list_pods() {
  std::lock_guard<std::mutex> lock(mu_);
  return snapshot_;
}

bool AsyncKubectl::delete_alloc(const std::string& alloc_id) {
  std::lock_guard<std::mutex> lock(mu_);
  snapshot_.erase(std::remove_if(snapshot_.begin(), snapshot_.end(),
                                 [&](const KubePodStatus& p) {
                                   return p.alloc_id == alloc_id;
                                 }),
                  snapshot_.end());
  queue_.push_back([this, alloc_id] { inner_->delete_alloc(alloc_id); });
  cv_.notify_all();
  return true;
}

bool AsyncKubectl::ready() {
  std::lock_guard<std::mutex> lock(mu_);
  return have_snapshot_;
}

// ---------------------------------------------------------------------------
// KubernetesRM
// ---------------------------------------------------------------------------

KubernetesRM::KubernetesRM(KubeRmConfig config,
                           std::unique_ptr<KubectlRunner> runner)
    : config_(std::move(config)), runner_(std::move(runner)) {}

Json KubernetesRM::pod_manifest(const Allocation& alloc, const Json& start_cmd,
                                int rank, int world, int pod_slots) const {
  Json labels = Json::object();
  labels.set("dct-managed", "true")
      .set("dct-alloc", sanitize(alloc.id))
      .set("dct-rank", std::to_string(rank));

  Json container = Json::object();
  container.set("name", "task")
      .set("image", config_.image)
      .set("command", pod_command(start_cmd))
      .set("env", pod_env(start_cmd, alloc.id, config_, rank));
  if (pod_slots > 0) {
    Json limits = Json::object();
    limits.set("google.com/tpu", std::to_string(pod_slots));
    Json resources = Json::object();
    resources.set("limits", limits);
    container.set("resources", resources);
  }

  Json spec = Json::object();
  Json containers = Json::array();
  containers.push_back(container);
  spec.set("restartPolicy", "Never").set("containers", containers);
  if (pod_slots > 0) {
    // GKE TPU node-pool selectors: the k8s scheduler (not us) picks nodes,
    // but it must pick within the right slice topology
    Json sel = Json::object();
    sel.set("cloud.google.com/gke-tpu-accelerator", config_.accelerator);
    if (!alloc.topology.empty()) {
      sel.set("cloud.google.com/gke-tpu-topology", alloc.topology);
    }
    spec.set("nodeSelector", sel);
  }

  Json meta = Json::object();
  meta.set("name", pod_name(alloc.id, rank))
      .set("namespace", config_.ns)
      .set("labels", labels);

  Json pod = Json::object();
  pod.set("apiVersion", "v1").set("kind", "Pod").set("metadata", meta)
      .set("spec", spec);
  (void)world;
  return pod;
}

void KubernetesRM::tick(RmContext& ctx) {
  if (!runner_->ready()) return;  // async runner: no cluster view yet
  auto pods = runner_->list_pods();
  std::map<std::string, std::vector<const KubePodStatus*>> by_alloc;
  for (const auto& p : pods) by_alloc[p.alloc_id].push_back(&p);

  for (auto& [alloc_id, alloc] : *ctx.allocations) {
    if (alloc.task_type == "unmanaged") continue;  // client-run, no pods
    auto mine_it = by_alloc.find(sanitize(alloc_id));
    const std::vector<const KubePodStatus*>* mine =
        mine_it == by_alloc.end() ? nullptr : &mine_it->second;

    if (terminal(alloc)) {
      if (mine) runner_->delete_alloc(sanitize(alloc_id));
      continue;
    }

    if (alloc.state == RunState::Queued) {
      if (mine && !mine->empty()) {
        // reattach after master restart (≈ ReattachAllocationPods,
        // pods.go:266): the pods are already there — re-adopt them, with
        // the same per-pod split the submit path used
        alloc.reservations.clear();
        for (const auto* p : *mine) {
          alloc.reservations[p->name] =
              member_pod_slots(alloc.slots, config_.slots_per_pod, p->rank);
        }
        alloc.world_size = static_cast<int>(mine->size());
        alloc.state = RunState::Pulling;
        if (alloc.trial_id && ctx.trials->count(alloc.trial_id)) {
          (*ctx.trials)[alloc.trial_id].state = RunState::Pulling;
        }
        ctx.mark_dirty();
      } else {
        // submit: one pod per TPU host; the last pod takes the remainder
        int world = gang_world(alloc.slots, config_.slots_per_pod);
        alloc.world_size = world;
        bool ok = true;
        for (int rank = 0; rank < world && ok; ++rank) {
          int pod_slots =
              member_pod_slots(alloc.slots, config_.slots_per_pod, rank);
          Json cmd = ctx.start_command(alloc, rank);
          cmd.set("slots", pod_slots);  // per-member share, not the gang total
          Json manifest = pod_manifest(alloc, cmd, rank, world, pod_slots);
          ok = runner_->apply(manifest);
          if (ok) alloc.reservations[pod_name(alloc.id, rank)] = pod_slots;
        }
        if (ok) {
          alloc.state = RunState::Pulling;
          if (alloc.trial_id && ctx.trials->count(alloc.trial_id)) {
            (*ctx.trials)[alloc.trial_id].state = RunState::Pulling;
          }
          ctx.mark_dirty();
        } else {
          // partial submit: tear down and retry next tick
          runner_->delete_alloc(sanitize(alloc.id));
          alloc.reservations.clear();
          alloc.world_size = 0;
        }
      }
      continue;
    }

    if (alloc.state == RunState::Pulling || alloc.state == RunState::Running) {
      if (!mine || mine->empty()) {
        // pods vanished (node reclaimed, kubectl delete out-of-band):
        // requeue; trial max_restarts accounting happens via on_task_done
        // only on real exits, so a reclaim is a silent retry like the
        // agent-amnesia path
        alloc.state = RunState::Queued;
        alloc.reservations.clear();
        alloc.rendezvous.clear();
        if (ctx.clear_barriers) ctx.clear_barriers(alloc_id);
        if (alloc.trial_id && ctx.trials->count(alloc.trial_id)) {
          (*ctx.trials)[alloc.trial_id].state = RunState::Queued;
        }
        ctx.mark_dirty();
        continue;
      }
      int running = 0, succeeded = 0;
      const KubePodStatus* failed = nullptr;
      for (const auto* p : *mine) {
        if (p->phase == "Running") ++running;
        if (p->phase == "Succeeded") ++succeeded;
        if (p->phase == "Failed" && !failed) failed = p;
      }
      int world = std::max(1, alloc.world_size);
      if (failed) {
        ctx.on_task_done(alloc_id,
                         failed->exit_code ? failed->exit_code : 1,
                         "pod " + failed->name + " failed");
        runner_->delete_alloc(sanitize(alloc_id));
      } else if (succeeded >= world) {
        ctx.on_task_done(alloc_id, 0, "");
        runner_->delete_alloc(sanitize(alloc_id));
      } else if (alloc.state == RunState::Pulling && running >= world) {
        alloc.state = RunState::Running;
        if (alloc.trial_id && ctx.trials->count(alloc.trial_id)) {
          (*ctx.trials)[alloc.trial_id].state = RunState::Running;
        }
        ctx.mark_dirty();
      }
    }
  }
}

}  // namespace dct
