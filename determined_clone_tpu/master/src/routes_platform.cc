// Platform-breadth routes: auth/users, workspaces/projects, model registry,
// config templates, webhooks.
//
// ≈ the reference's master/internal/api_{user,workspace,project,model,
// template,webhook}.go handlers over their service packages, collapsed onto
// the Master's single state map the way routes.cc does for experiments.
#include <cctype>
#include <fstream>
#include <iostream>
#include <random>
#include <thread>

#include "crypto.h"
#include "master.h"

namespace dct {
namespace {

Json perr(const std::string& msg) {
  Json j = Json::object();
  j.set("error", msg);
  return j;
}
HttpResponse pok(const Json& j) { return HttpResponse::json(200, j.dump()); }
HttpResponse pcreated(const Json& j) {
  return HttpResponse::json(201, j.dump());
}
HttpResponse pbad(const std::string& msg) {
  return HttpResponse::json(400, perr(msg).dump());
}
HttpResponse pnotfound(const std::string& msg) {
  return HttpResponse::json(404, perr(msg).dump());
}
HttpResponse punauthorized(const std::string& msg) {
  return HttpResponse::json(401, perr(msg).dump());
}
HttpResponse pforbidden(const std::string& msg) {
  return HttpResponse::json(403, perr(msg).dump());
}

// PBKDF2-HMAC-SHA256 with per-user random salt (crypto.cc); verification
// accepts legacy FNV entries from pre-KDF snapshots and login re-hashes them
using crypto::hash_password;

std::string new_token() { return crypto::random_token(); }

// deep-merge: template config is the base, experiment config overrides
// (≈ master/internal/templates merge semantics via schemas.Merge)
Json merge_configs(const Json& base, const Json& over) {
  if (!base.is_object() || !over.is_object()) return over;
  Json out = base;
  for (const auto& [k, v] : over.items()) {
    out.set(k, merge_configs(base[k], v));
  }
  return out;
}

}  // namespace

// strips the "Bearer " scheme; empty string when no auth header is present
std::string bearer_token(const HttpRequest& req) {
  auto it = req.headers.find("authorization");
  if (it == req.headers.end()) return "";
  std::string token = it->second;
  const std::string bearer = "Bearer ";
  if (token.rfind(bearer, 0) == 0) token = token.substr(bearer.size());
  return token;
}

User* Master::current_user(const HttpRequest& req) {
  std::string token = bearer_token(req);
  if (token.empty()) return nullptr;
  auto sit = sessions_.find(token);
  if (sit == sessions_.end()) return nullptr;
  if (sit->second.expires_at < now_sec()) {
    sessions_.erase(sit);
    return nullptr;
  }
  auto uit = users_.find(sit->second.user_id);
  if (uit == users_.end() || !uit->second.active) return nullptr;
  return &uit->second;
}

bool Master::alloc_authed(const HttpRequest& req) {
  const std::string token = bearer_token(req);
  if (token.empty()) return false;
  // scan is O(allocations); constant-time per compare. Tokens of terminal
  // allocations stay valid until GC'd — matches the reference's allocation
  // sessions living as long as the allocation row.
  for (const auto& [id, alloc] : allocations_) {
    if (!alloc.token.empty() &&
        crypto::constant_time_eq(token, alloc.token)) {
      return true;
    }
  }
  return false;
}

int Master::rbac_rank(const User* u, int64_t workspace_id) {
  if (!u) return 0;
  if (u->admin) return role_rank("ClusterAdmin");
  int best = 0;
  for (const auto& [id, a] : role_assignments_) {
    bool principal = a.user_id != 0 && a.user_id == u->id;
    if (!principal && a.group_id != 0) {
      auto git = groups_.find(a.group_id);
      principal = git != groups_.end() && git->second.has_user(u->id);
    }
    if (!principal) continue;
    // global assignments apply at every scope; workspace assignments only
    // at their workspace (≈ rbac scope resolution in the reference)
    if (a.workspace_id != 0 && a.workspace_id != workspace_id) continue;
    best = std::max(best, role_rank(a.role));
  }
  return best;
}

bool Master::rbac_allows(const HttpRequest& req, int min_rank,
                         int64_t workspace_id) {
  if (!config_.rbac_enabled || !config_.auth_required) return true;
  return rbac_rank(current_user(req), workspace_id) >= min_rank;
}

bool Master::cluster_admin_ok(const HttpRequest& req) {
  if (!config_.auth_required) return true;
  User* caller = current_user(req);
  if (!caller) return false;
  if (caller->admin) return true;
  // role-granted ClusterAdmin only counts while RBAC is enabled — with
  // --rbac removed, persisted assignments must be inert (rbac/me reports
  // enforced:false), not a backdoor to the admin surface
  return config_.rbac_enabled &&
         rbac_rank(caller, 0) >= role_rank("ClusterAdmin");
}

int64_t Master::workspace_id_by_name(const std::string& name) {
  for (const auto& [id, w] : workspaces_) {
    if (w.name == name) return id;
  }
  return 0;
}

void Master::bootstrap_users_locked() {
  // ≈ the reference's bootstrap users (admin + determined, empty passwords)
  if (!users_.empty()) return;
  for (const char* name : {"admin", "determined"}) {
    User u;
    u.id = next_user_id_++;
    u.username = name;
    u.admin = std::string(name) == "admin";
    u.password_hash = hash_password(name, "");
    users_[u.id] = u;
  }
  ensure_workspace("Uncategorized", "admin").immutable = true;
}

Workspace& Master::ensure_workspace(const std::string& name,
                                    const std::string& owner) {
  for (auto& [id, w] : workspaces_) {
    if (w.name == name) return w;
  }
  Workspace w;
  w.id = next_workspace_id_++;
  w.name = name;
  w.owner = owner;
  int64_t id = w.id;
  workspaces_[id] = w;
  ensure_project("Uncategorized", id, owner);
  dirty_ = true;
  return workspaces_[id];
}

void Master::ensure_project(const std::string& name, int64_t workspace_id,
                            const std::string& owner) {
  for (auto& [id, p] : projects_) {
    if (p.workspace_id == workspace_id && p.name == name) return;
  }
  Project p;
  p.id = next_project_id_++;
  p.name = name;
  p.workspace_id = workspace_id;
  p.owner = owner;
  projects_[p.id] = p;
  dirty_ = true;
}

void Master::post_webhook(const Webhook& hook, const Json& payload) {
  // parse http://host[:port][/path]
  std::string url = hook.url;
  const std::string scheme = "http://";
  if (url.rfind(scheme, 0) == 0) url = url.substr(scheme.size());
  std::string hostport = url, path = "/";
  auto slash = url.find('/');
  if (slash != std::string::npos) {
    hostport = url.substr(0, slash);
    path = url.substr(slash);
  }
  std::string host = hostport;
  int port = 80;
  auto colon = hostport.rfind(':');
  if (colon != std::string::npos) {
    host = hostport.substr(0, colon);
    try {
      port = std::stoi(hostport.substr(colon + 1));
    } catch (const std::exception&) {
      return;  // unparseable port: skip rather than POST to port 0
    }
  }
  std::string body = payload.dump();
  // fire-and-forget off the master lock (≈ shipper's async queue)
  std::thread([host, port, path, body] {
    http_request(host, port, "POST", path, body, 10);
  }).detach();
}

void Master::fire_webhooks(const Experiment& exp) {
  const std::string state = to_string(exp.state);
  for (const auto& [id, hook] : webhooks_) {
    bool match = hook.triggers.empty() && hook.log_pattern.empty();
    for (const auto& t : hook.triggers) {
      if (t == state) match = true;
    }
    if (!match) continue;
    Json payload = Json::object();
    if (hook.webhook_type == "slack") {
      // ≈ webhooks/shipper.go slack formatting
      payload.set("text", "experiment " + std::to_string(exp.id) + " (" +
                              exp.name + ") is " + state);
    } else {
      payload.set("event", "experiment_state_change");
      payload.set("experiment_id", exp.id);
      payload.set("experiment_name", exp.name);
      payload.set("state", state);
      payload.set("workspace", exp.workspace);
    }
    post_webhook(hook, payload);
  }
}

std::optional<HttpResponse> Master::route_platform(const HttpRequest& req) {
  const auto& parts = req.path_parts;
  const std::string& root = parts.size() > 2 ? parts[2] : "";

  // ---- auth --------------------------------------------------------------
  if (root == "auth") {
    if (parts.size() == 4 && parts[3] == "login" && req.method == "POST") {
      Json body = Json::parse(req.body);
      const std::string& username = body["username"].as_string();
      const std::string& password = body["password"].as_string();
      for (auto& [id, u] : users_) {
        if (u.username == username) {
          if (!u.active) return punauthorized("user deactivated");
          if (!crypto::verify_password(u.password_hash, username, password)) {
            return punauthorized("invalid credentials");
          }
          if (crypto::password_needs_rehash(u.password_hash)) {
            // transparent upgrade of legacy FNV entries from old snapshots
            u.password_hash = hash_password(username, password);
          }
          SessionToken tok;
          tok.token = new_token();
          tok.user_id = id;
          tok.expires_at = now_sec() + config_.session_ttl_sec;
          sessions_[tok.token] = tok;
          dirty_ = true;
          Json j = Json::object();
          j.set("token", tok.token).set("user", u.to_json());
          return pok(j);
        }
      }
      return punauthorized("invalid credentials");
    }
    if (parts.size() == 4 && parts[3] == "logout" && req.method == "POST") {
      std::string token = bearer_token(req);
      if (!token.empty() && sessions_.erase(token)) dirty_ = true;
      return pok(Json::object());
    }
    if (parts.size() == 4 && parts[3] == "me" && req.method == "GET") {
      User* u = current_user(req);
      if (!u) return punauthorized("not logged in");
      Json j = Json::object();
      j.set("user", u->to_json());
      return pok(j);
    }
    // ---- SSO (OIDC-shaped; ≈ the reference's OIDC plugin hooks) ----------
    if (parts.size() >= 5 && parts[3] == "sso") {
      if (config_.sso_issuer_host.empty()) {
        return pbad("sso is not configured (--sso-issuer)");
      }
      const double now = now_sec();
      for (auto it = sso_states_.begin(); it != sso_states_.end();) {
        it = it->second < now ? sso_states_.erase(it) : std::next(it);
      }
      if (parts[4] == "login" && req.method == "GET") {
        // mint a state nonce and bounce the browser to the IdP. The
        // redirect_uri must be ABSOLUTE (a browser resolves a relative
        // Location against the IdP's origin, not ours) and must come from
        // configuration — never the request's Host header (see below).
        std::string state = crypto::random_token();
        // bound outstanding states: anonymous login spam must not grow
        // master memory — evict the nearest-expiry entries beyond the cap
        constexpr size_t kMaxStates = 1024;
        while (sso_states_.size() >= kMaxStates) {
          auto oldest = sso_states_.begin();
          for (auto it = sso_states_.begin(); it != sso_states_.end(); ++it) {
            if (it->second < oldest->second) oldest = it;
          }
          sso_states_.erase(oldest);
        }
        sso_states_[state] = now + 600;
        // The callback host must NOT come from the request's Host header:
        // a forged Host would point the issuer redirect (and thus the
        // authorization code) at an attacker-controlled callback. Use the
        // configured external host; without one, trust Host only when it
        // names this master's loopback, and otherwise fail LOUDLY — a
        // silent loopback fallback would send a remote user's browser to
        // its own machine with nothing in the logs naming the fix.
        std::string loopback =
            "127.0.0.1:" + std::to_string(config_.port);
        std::string self_host = config_.sso_external_host;
        if (self_host.empty()) {
          auto host_it = req.headers.find("host");
          std::string h =
              host_it != req.headers.end() ? host_it->second : "";
          if (h == loopback ||
              h == "localhost:" + std::to_string(config_.port)) {
            self_host = h;
          } else {
            std::cerr << "[master] sso login via untrusted host '" << h
                      << "': set --sso-external-host (or sso.external_host)"
                      << std::endl;
            return pbad(
                "sso requires --sso-external-host when the master is not "
                "reached via loopback (got Host: " + h + ")");
          }
        }
        std::string redirect =
            "http://" + config_.sso_issuer_host + ":" +
            std::to_string(config_.sso_issuer_port) +
            "/authorize?client_id=" + config_.sso_client_id +
            "&state=" + state + "&redirect_uri=http%3A%2F%2F" + self_host +
            "%2Fapi%2Fv1%2Fauth%2Fsso%2Fcallback";
        HttpResponse resp;
        resp.status = 302;
        resp.headers["Location"] = redirect;
        resp.body = "";
        return resp;
      }
      // (the callback is dispatched from handle() before the state lock —
      // its token exchange must not block the master; sso_callback_route)
      return pnotfound("unknown sso route");
    }
    return pnotfound("unknown auth route");
  }

  // ---- users (≈ api_user.go) ---------------------------------------------
  if (root == "users") {
    if (parts.size() == 3 && req.method == "GET") {
      Json arr = Json::array();
      for (const auto& [id, u] : users_) arr.push_back(u.to_json());
      Json j = Json::object();
      j.set("users", arr);
      return pok(j);
    }
    if (parts.size() == 3 && req.method == "POST") {
      if (!cluster_admin_ok(req)) return pforbidden("admin required");
      Json body = Json::parse(req.body);
      const std::string& username = body["username"].as_string();
      if (username.empty()) return pbad("username required");
      for (const auto& [id, u] : users_) {
        if (u.username == username) return pbad("username taken");
      }
      User u;
      u.id = next_user_id_++;
      u.username = username;
      u.admin = body["admin"].as_bool();
      u.display_name = body["display_name"].as_string();
      u.password_hash = hash_password(username, body["password"].as_string());
      users_[u.id] = u;
      dirty_ = true;
      Json j = Json::object();
      j.set("user", users_[u.id].to_json());
      return pcreated(j);
    }
    // per-user UI/CLI settings (≈ GetUserSetting / PostUserSetting /
    // ResetUserSetting, api_user.go): a key→value bag scoped to the
    // calling session (user 0 when auth is off)
    if (parts.size() == 4 && parts[3] == "settings") {
      User* caller = current_user(req);
      int64_t uid = caller ? caller->id : 0;
      if (req.method == "GET") {
        Json j = Json::object();
        auto sit = user_settings_.find(uid);
        j.set("settings",
              sit != user_settings_.end() ? sit->second : Json::object());
        return pok(j);
      }
      if (req.method == "POST") {
        Json body = Json::parse(req.body);
        const std::string& key = body["key"].as_string();
        if (key.empty()) return pbad("setting key required");
        Json& bag = user_settings_[uid];
        if (!bag.is_object()) bag = Json::object();
        bag.set(key, body["value"]);
        dirty_ = true;
        Json j = Json::object();
        j.set("settings", bag);
        return pok(j);
      }
      if (req.method == "DELETE") {
        user_settings_.erase(uid);
        dirty_ = true;
        return pok(Json::object());
      }
    }
    if (parts.size() >= 4) {
      int64_t uid = 0;
      try {
        uid = std::stoll(parts[3]);
      } catch (const std::exception&) {
        return pbad("bad user id");
      }
      auto it = users_.find(uid);
      if (it == users_.end()) return pnotfound("no user " + parts[3]);
      User& u = it->second;
      if (parts.size() == 4 && req.method == "GET") {
        Json j = Json::object();
        j.set("user", u.to_json());
        return pok(j);
      }
      if (parts.size() == 4 && req.method == "PATCH") {
        // ≈ PatchUser: display name self-service; admin flag admin-only
        User* caller = current_user(req);
        bool self = caller && caller->id == uid;
        if (config_.auth_required && !self && !cluster_admin_ok(req)) {
          return pforbidden("admin or self required");
        }
        Json body = Json::parse(req.body);
        if (body["display_name"].is_string()) {
          u.display_name = body["display_name"].as_string();
        }
        if (body.has("admin")) {
          if (config_.auth_required && !cluster_admin_ok(req)) {
            return pforbidden("admin required to change the admin flag");
          }
          u.admin = body["admin"].as_bool();
        }
        dirty_ = true;
        Json j = Json::object();
        j.set("user", u.to_json());
        return pok(j);
      }
      if (parts.size() == 5 && req.method == "POST") {
        User* caller = current_user(req);
        bool self = caller && caller->id == uid;
        if (config_.auth_required && !self && !cluster_admin_ok(req)) {
          return pforbidden("admin or self required");
        }
        if (parts[4] == "password") {
          Json body = Json::parse(req.body);
          u.password_hash =
              hash_password(u.username, body["password"].as_string());
          dirty_ = true;
          return pok(Json::object());
        }
        if (parts[4] == "activate" || parts[4] == "deactivate") {
          if (!cluster_admin_ok(req)) return pforbidden("admin required");
          u.active = parts[4] == "activate";
          dirty_ = true;
          Json j = Json::object();
          j.set("user", u.to_json());
          return pok(j);
        }
      }
    }
    return pnotfound("unknown users route");
  }

  // ---- workspaces + projects (≈ api_workspace.go / api_project.go) -------
  if (root == "workspaces") {
    if (parts.size() == 3 && req.method == "GET") {
      Json arr = Json::array();
      for (const auto& [id, w] : workspaces_) arr.push_back(w.to_json());
      Json j = Json::object();
      j.set("workspaces", arr);
      return pok(j);
    }
    if (parts.size() == 3 && req.method == "POST") {
      if (!rbac_allows(req, role_rank("Editor"))) {
        return pforbidden("Editor role required to create workspaces");
      }
      Json body = Json::parse(req.body);
      const std::string& name = body["name"].as_string();
      if (name.empty()) return pbad("workspace name required");
      for (const auto& [id, w] : workspaces_) {
        if (w.name == name) return pbad("workspace name taken");
      }
      User* caller = current_user(req);
      Workspace& w = ensure_workspace(name,
                                      caller ? caller->username : "admin");
      Json j = Json::object();
      j.set("workspace", w.to_json());
      return pcreated(j);
    }
    if (parts.size() >= 4) {
      int64_t wid = 0;
      try {
        wid = std::stoll(parts[3]);
      } catch (const std::exception&) {
        return pbad("bad workspace id");
      }
      auto it = workspaces_.find(wid);
      if (it == workspaces_.end()) return pnotfound("no workspace " + parts[3]);
      Workspace& w = it->second;
      if (parts.size() == 4 && req.method == "GET") {
        Json projs = Json::array();
        for (const auto& [pid, p] : projects_) {
          if (p.workspace_id == wid) projs.push_back(p.to_json());
        }
        Json exps = Json::array();
        for (const auto& [eid, e] : experiments_) {
          if (e.workspace == w.name) exps.push_back(e.to_json());
        }
        Json j = Json::object();
        j.set("workspace", w.to_json()).set("projects", projs)
            .set("experiments", exps);
        return pok(j);
      }
      if (parts.size() == 4 && req.method == "DELETE") {
        if (!rbac_allows(req, role_rank("WorkspaceAdmin"), wid)) {
          return pforbidden("WorkspaceAdmin role required");
        }
        if (w.immutable) return pbad("workspace is immutable");
        for (const auto& [eid, e] : experiments_) {
          if (e.workspace == w.name) {
            return pbad("workspace has experiments");
          }
        }
        for (auto pit = projects_.begin(); pit != projects_.end();) {
          if (pit->second.workspace_id == wid) {
            pit = projects_.erase(pit);
          } else {
            ++pit;
          }
        }
        // workspace-scoped role assignments die with the workspace — same
        // no-dangling-grant invariant as group deletion below
        for (auto ait = role_assignments_.begin();
             ait != role_assignments_.end();) {
          if (ait->second.workspace_id == wid) {
            ait = role_assignments_.erase(ait);
          } else {
            ++ait;
          }
        }
        workspaces_.erase(it);
        dirty_ = true;
        return pok(Json::object());
      }
      if (parts.size() == 5 && req.method == "POST" &&
          (parts[4] == "archive" || parts[4] == "unarchive")) {
        if (!rbac_allows(req, role_rank("WorkspaceAdmin"), wid)) {
          return pforbidden("WorkspaceAdmin role required");
        }
        if (w.immutable) return pbad("workspace is immutable");
        w.archived = parts[4] == "archive";
        dirty_ = true;
        Json j = Json::object();
        j.set("workspace", w.to_json());
        return pok(j);
      }
      if (parts.size() == 5 && parts[4] == "projects") {
        if (req.method == "GET") {
          Json projs = Json::array();
          for (const auto& [pid, p] : projects_) {
            if (p.workspace_id == wid) projs.push_back(p.to_json());
          }
          Json j = Json::object();
          j.set("projects", projs);
          return pok(j);
        }
        if (req.method == "POST") {
          if (!rbac_allows(req, role_rank("Editor"), wid)) {
            return pforbidden("Editor role required in this workspace");
          }
          Json body = Json::parse(req.body);
          const std::string& name = body["name"].as_string();
          if (name.empty()) return pbad("project name required");
          for (const auto& [pid, p] : projects_) {
            if (p.workspace_id == wid && p.name == name) {
              return pbad("project name taken in workspace");
            }
          }
          User* caller = current_user(req);
          Project p;
          p.id = next_project_id_++;
          p.name = name;
          p.workspace_id = wid;
          p.owner = caller ? caller->username : "admin";
          p.description = body["description"].as_string();
          projects_[p.id] = p;
          dirty_ = true;
          Json j = Json::object();
          j.set("project", projects_[p.id].to_json());
          return pcreated(j);
        }
      }
    }
    return pnotfound("unknown workspaces route");
  }

  // ---- project depth (≈ api_project.go: Get/Patch/Delete/Archive/Move) ---
  if (root == "projects" && parts.size() >= 4) {
    int64_t pid = 0;
    try {
      pid = std::stoll(parts[3]);
    } catch (const std::exception&) {
      return pbad("project id must be an integer");
    }
    auto it = projects_.find(pid);
    if (it == projects_.end()) {
      return pnotfound("no project " + parts[3]);
    }
    Project& p = it->second;
    // experiments reference (workspace name, project name) pairs — always
    // match both, since project names may repeat across workspaces
    auto wit_own = workspaces_.find(p.workspace_id);
    const std::string own_ws =
        wit_own != workspaces_.end() ? wit_own->second.name : "";
    auto in_project = [&](const Experiment& e) {
      return e.project == p.name && e.workspace == own_ws;
    };
    if (parts.size() == 4 && req.method == "GET") {
      Json exps = Json::array();
      for (const auto& [eid, e] : experiments_) {
        if (in_project(e)) exps.push_back(e.to_json());
      }
      Json j = Json::object();
      j.set("project", p.to_json()).set("experiments", exps);
      return pok(j);
    }
    if (parts.size() == 4 && req.method == "PATCH") {
      if (!rbac_allows(req, role_rank("Editor"), p.workspace_id)) {
        return pforbidden("Editor role required in this workspace");
      }
      Json body = Json::parse(req.body);
      if (body["name"].is_string() && !body["name"].as_string().empty()) {
        const std::string& next = body["name"].as_string();
        for (const auto& [oid, other] : projects_) {
          if (oid != pid && other.workspace_id == p.workspace_id &&
              other.name == next) {
            return pbad("project name taken in workspace");
          }
        }
        // experiments reference projects by name: rename them along
        for (auto& [eid, e] : experiments_) {
          if (in_project(e)) e.project = next;
        }
        p.name = next;
      }
      if (body["description"].is_string()) {
        p.description = body["description"].as_string();
      }
      dirty_ = true;
      Json j = Json::object();
      j.set("project", p.to_json());
      return pok(j);
    }
    if (parts.size() == 4 && req.method == "DELETE") {
      if (!rbac_allows(req, role_rank("WorkspaceAdmin"), p.workspace_id)) {
        return pforbidden("WorkspaceAdmin role required");
      }
      for (const auto& [eid, e] : experiments_) {
        if (in_project(e)) {
          return pbad("project still holds experiments; move them first");
        }
      }
      projects_.erase(it);
      dirty_ = true;
      return pok(Json::object());
    }
    if (parts.size() == 5 && req.method == "POST" &&
        (parts[4] == "archive" || parts[4] == "unarchive")) {
      if (!rbac_allows(req, role_rank("Editor"), p.workspace_id)) {
        return pforbidden("Editor role required in this workspace");
      }
      p.archived = parts[4] == "archive";
      dirty_ = true;
      Json j = Json::object();
      j.set("project", p.to_json());
      return pok(j);
    }
    if (parts.size() == 5 && parts[4] == "move" && req.method == "POST") {
      Json body = Json::parse(req.body);
      int64_t dest = body["workspace_id"].as_int(-1);
      auto wit = workspaces_.find(dest);
      if (wit == workspaces_.end()) {
        return pbad("destination workspace_id required");
      }
      // moving between workspaces needs rights on BOTH scopes
      if (!rbac_allows(req, role_rank("Editor"), p.workspace_id) ||
          !rbac_allows(req, role_rank("Editor"), dest)) {
        return pforbidden("Editor role required in both workspaces");
      }
      for (const auto& [oid, other] : projects_) {
        if (oid != pid && other.workspace_id == dest &&
            other.name == p.name) {
          return pbad("project name taken in destination workspace");
        }
      }
      // experiments track workspace by name: follow the project
      for (auto& [eid, e] : experiments_) {
        if (in_project(e)) e.workspace = wit->second.name;
      }
      p.workspace_id = dest;
      dirty_ = true;
      Json j = Json::object();
      j.set("project", p.to_json());
      return pok(j);
    }
    return pnotfound("unknown projects route");
  }

  // ---- model registry (≈ api_model.go) -----------------------------------
  if (root == "models") {
    auto find_model = [&](const std::string& key) -> RegisteredModel* {
      try {
        size_t pos = 0;
        int64_t mid = std::stoll(key, &pos);
        if (pos == key.size()) {  // whole key numeric, not "2fast"
          auto it = models_.find(mid);
          if (it != models_.end()) return &it->second;
        }
      } catch (const std::exception&) {
      }
      for (auto& [id, m] : models_) {
        if (m.name == key) return &m;
      }
      return nullptr;
    };
    if (parts.size() == 3 && req.method == "GET") {
      auto name_filter = req.query.find("name");
      Json arr = Json::array();
      for (const auto& [id, m] : models_) {
        if (name_filter != req.query.end() &&
            m.name.find(name_filter->second) == std::string::npos) {
          continue;
        }
        arr.push_back(m.to_json());
      }
      Json j = Json::object();
      j.set("models", arr);
      return pok(j);
    }
    if (parts.size() == 3 && req.method == "POST") {
      Json body = Json::parse(req.body);
      const std::string& name = body["name"].as_string();
      if (name.empty()) return pbad("model name required");
      {
        std::string ws = body["workspace"].as_string();
        if (ws.empty()) ws = "Uncategorized";
        if (!rbac_allows(req, role_rank("Editor"), workspace_id_by_name(ws))) {
          return pforbidden("Editor role required in workspace " + ws);
        }
      }
      for (const auto& [id, m] : models_) {
        if (m.name == name) return pbad("model name taken");
      }
      User* caller = current_user(req);
      RegisteredModel m;
      m.id = next_model_id_++;
      m.name = name;
      m.description = body["description"].as_string();
      m.metadata = body["metadata"];
      m.labels = body["labels"];
      if (!body["workspace"].as_string().empty()) {
        m.workspace = body["workspace"].as_string();
      }
      m.owner = caller ? caller->username : "admin";
      m.created_at = now_sec();
      models_[m.id] = m;
      dirty_ = true;
      Json j = Json::object();
      j.set("model", models_[m.id].to_json());
      return pcreated(j);
    }
    if (parts.size() >= 4) {
      RegisteredModel* m = find_model(parts[3]);
      if (!m) return pnotfound("no model " + parts[3]);
      // model mutations: Editor at the model's workspace; deletes are
      // WorkspaceAdmin (destructive, like the reference's delete perms)
      if (req.method != "GET") {
        int min_rank = req.method == "DELETE" ? role_rank("WorkspaceAdmin")
                                              : role_rank("Editor");
        if (!rbac_allows(req, min_rank, workspace_id_by_name(m->workspace))) {
          return pforbidden("insufficient role in workspace " + m->workspace);
        }
      }
      if (parts.size() == 4 && req.method == "GET") {
        Json j = Json::object();
        j.set("model", m->to_json());
        return pok(j);
      }
      if (parts.size() == 4 && req.method == "PATCH") {
        Json body = Json::parse(req.body);
        if (body.has("description")) {
          m->description = body["description"].as_string();
        }
        if (body.has("metadata")) m->metadata = body["metadata"];
        if (body.has("labels")) m->labels = body["labels"];
        dirty_ = true;
        Json j = Json::object();
        j.set("model", m->to_json());
        return pok(j);
      }
      if (parts.size() == 4 && req.method == "DELETE") {
        models_.erase(m->id);
        dirty_ = true;
        return pok(Json::object());
      }
      if (parts.size() == 5 && parts[4] == "archive" && req.method == "POST") {
        m->archived = true;
        dirty_ = true;
        return pok(Json::object());
      }
      if (parts.size() == 5 && parts[4] == "unarchive" &&
          req.method == "POST") {
        m->archived = false;
        dirty_ = true;
        return pok(Json::object());
      }
      if (parts.size() == 5 && parts[4] == "versions") {
        if (req.method == "GET") {
          Json arr = Json::array();
          for (const auto& v : m->versions) arr.push_back(v.to_json());
          Json j = Json::object();
          j.set("versions", arr);
          return pok(j);
        }
        if (req.method == "POST") {
          Json body = Json::parse(req.body);
          const std::string& uuid = body["checkpoint_uuid"].as_string();
          if (uuid.empty()) return pbad("checkpoint_uuid required");
          bool known = false;
          for (const auto& c : checkpoints_) {
            if (c.uuid == uuid && !c.deleted) known = true;
          }
          if (!known) return pbad("unknown checkpoint " + uuid);
          ModelVersion v;
          v.version = m->next_version++;
          v.checkpoint_uuid = uuid;
          // "version_name" is the proto field (the model's own name fills
          // the path slot); bare "name" stays accepted for raw callers
          v.name = !body["version_name"].as_string().empty()
                       ? body["version_name"].as_string()
                       : body["name"].as_string();
          v.comment = body["comment"].as_string();
          v.created_at = now_sec();
          m->versions.push_back(v);
          dirty_ = true;
          Json j = Json::object();
          j.set("version", m->versions.back().to_json());
          return pcreated(j);
        }
      }
      if (parts.size() == 6 && parts[4] == "versions" &&
          req.method == "DELETE") {
        int64_t ver = 0;
        try {
          ver = std::stoll(parts[5]);
        } catch (const std::exception&) {
          return pbad("bad version");
        }
        for (auto vit = m->versions.begin(); vit != m->versions.end(); ++vit) {
          if (vit->version == ver) {
            m->versions.erase(vit);
            dirty_ = true;
            return pok(Json::object());
          }
        }
        return pnotfound("no version");
      }
    }
    return pnotfound("unknown models route");
  }

  // ---- templates (≈ master/internal/templates) ---------------------------
  if (root == "templates") {
    if (parts.size() == 3 && req.method == "GET") {
      Json arr = Json::array();
      for (const auto& [name, cfg] : templates_) {
        Json t = Json::object();
        t.set("name", name).set("config", cfg);
        arr.push_back(t);
      }
      Json j = Json::object();
      j.set("templates", arr);
      return pok(j);
    }
    if (parts.size() == 3 && req.method == "POST") {
      if (!rbac_allows(req, role_rank("WorkspaceAdmin"))) {
        return pforbidden("WorkspaceAdmin role required");
      }
      Json body = Json::parse(req.body);
      const std::string& name = body["name"].as_string();
      if (name.empty()) return pbad("template name required");
      if (!body["config"].is_object()) return pbad("template config required");
      templates_[name] = body["config"];
      dirty_ = true;
      Json t = Json::object();
      t.set("name", name).set("config", templates_[name]);
      return pcreated(t);
    }
    if (parts.size() == 4) {
      auto it = templates_.find(parts[3]);
      if (it == templates_.end()) return pnotfound("no template " + parts[3]);
      if (req.method == "GET") {
        Json t = Json::object();
        t.set("name", it->first).set("config", it->second);
        return pok(t);
      }
      if (req.method == "DELETE") {
        if (!rbac_allows(req, role_rank("WorkspaceAdmin"))) {
          return pforbidden("WorkspaceAdmin role required");
        }
        templates_.erase(it);
        dirty_ = true;
        return pok(Json::object());
      }
    }
    return pnotfound("unknown templates route");
  }

  // ---- webhooks (≈ api_webhook.go) ---------------------------------------
  if (root == "webhooks") {
    if (parts.size() == 3 && req.method == "GET") {
      Json arr = Json::array();
      for (const auto& [id, w] : webhooks_) arr.push_back(w.to_json());
      Json j = Json::object();
      j.set("webhooks", arr);
      return pok(j);
    }
    if (parts.size() == 3 && req.method == "POST") {
      if (!rbac_allows(req, role_rank("WorkspaceAdmin"))) {
        return pforbidden("WorkspaceAdmin role required");
      }
      Json body = Json::parse(req.body);
      const std::string& url = body["url"].as_string();
      if (url.empty()) return pbad("webhook url required");
      Webhook w;
      w.id = next_webhook_id_++;
      w.url = url;
      if (!body["webhook_type"].as_string().empty()) {
        w.webhook_type = body["webhook_type"].as_string();
      }
      for (const auto& t : body["triggers"].elements()) {
        w.triggers.push_back(t.as_string());
      }
      w.log_pattern = body["log_pattern"].as_string();
      if (!w.log_pattern.empty()) {
        try {
          std::regex re(w.log_pattern);
        } catch (const std::regex_error& e) {
          return pbad("invalid log_pattern '" + w.log_pattern +
                      "': " + e.what());
        }
      }
      webhooks_[w.id] = w;
      dirty_ = true;
      Json j = Json::object();
      j.set("webhook", webhooks_[w.id].to_json());
      return pcreated(j);
    }
    if (parts.size() == 4 && req.method == "DELETE") {
      if (!rbac_allows(req, role_rank("WorkspaceAdmin"))) {
        return pforbidden("WorkspaceAdmin role required");
      }
      int64_t wid = 0;
      try {
        wid = std::stoll(parts[3]);
      } catch (const std::exception&) {
        return pbad("bad webhook id");
      }
      if (!webhooks_.erase(wid)) return pnotfound("no webhook " + parts[3]);
      webhook_pattern_cache_.erase(wid);
      dirty_ = true;
      return pok(Json::object());
    }
    return pnotfound("unknown webhooks route");
  }

  // ---- user groups (≈ master/internal/usergroup) -------------------------
  if (root == "groups") {
    // group management is a cluster-admin surface, like user management
    auto admin_gate = [&]() -> std::optional<HttpResponse> {
      if (cluster_admin_ok(req)) return std::nullopt;
      return pforbidden("cluster admin required");
    };
    if (parts.size() == 3 && req.method == "GET") {
      Json arr = Json::array();
      for (const auto& [id, g] : groups_) arr.push_back(g.to_json());
      Json j = Json::object();
      j.set("groups", arr);
      return pok(j);
    }
    if (parts.size() == 3 && req.method == "POST") {
      if (auto resp = admin_gate()) return *resp;
      Json body = Json::parse(req.body);
      const std::string& name = body["name"].as_string();
      if (name.empty()) return pbad("group name required");
      for (const auto& [id, g] : groups_) {
        if (g.name == name) return pbad("group name taken");
      }
      Group g;
      g.id = next_group_id_++;
      g.name = name;
      for (const auto& u : body["user_ids"].elements()) {
        int64_t uid = u.as_int();
        if (!users_.count(uid)) return pbad("no user " + std::to_string(uid));
        if (!g.has_user(uid)) g.user_ids.push_back(uid);
      }
      groups_[g.id] = g;
      dirty_ = true;
      Json j = Json::object();
      j.set("group", groups_[g.id].to_json());
      return pcreated(j);
    }
    if (parts.size() >= 4) {
      int64_t gid = 0;
      try {
        gid = std::stoll(parts[3]);
      } catch (const std::exception&) {
        return pbad("bad group id");
      }
      auto it = groups_.find(gid);
      if (it == groups_.end()) return pnotfound("no group " + parts[3]);
      Group& g = it->second;
      if (parts.size() == 4 && req.method == "GET") {
        Json j = Json::object();
        j.set("group", g.to_json());
        return pok(j);
      }
      if (parts.size() == 4 && req.method == "DELETE") {
        if (auto resp = admin_gate()) return *resp;
        // assignments referencing the group die with it — a dangling
        // group_id would silently grant nothing but still list as a grant
        for (auto ait = role_assignments_.begin();
             ait != role_assignments_.end();) {
          if (ait->second.group_id == gid) {
            ait = role_assignments_.erase(ait);
          } else {
            ++ait;
          }
        }
        groups_.erase(it);
        dirty_ = true;
        return pok(Json::object());
      }
      if (parts.size() == 5 && parts[4] == "members" && req.method == "POST") {
        if (auto resp = admin_gate()) return *resp;
        Json body = Json::parse(req.body);
        // validate every id BEFORE mutating — a 400 must leave no side
        // effects (same invariant as experiment submission, routes.cc)
        for (const auto& u : body["add"].elements()) {
          int64_t uid = u.as_int();
          if (!users_.count(uid)) {
            return pbad("no user " + std::to_string(uid));
          }
        }
        for (const auto& u : body["add"].elements()) {
          int64_t uid = u.as_int();
          if (!g.has_user(uid)) g.user_ids.push_back(uid);
        }
        for (const auto& u : body["remove"].elements()) {
          int64_t uid = u.as_int();
          g.user_ids.erase(
              std::remove(g.user_ids.begin(), g.user_ids.end(), uid),
              g.user_ids.end());
        }
        dirty_ = true;
        Json j = Json::object();
        j.set("group", g.to_json());
        return pok(j);
      }
    }
    return pnotfound("unknown groups route");
  }

  // ---- rbac (≈ master/internal/rbac: static roles + scoped assignments) --
  if (root == "rbac") {
    const std::string& sub = parts.size() > 3 ? parts[3] : "";
    if (sub == "roles" && req.method == "GET") {
      Json arr = Json::array();
      for (const char* name :
           {"Viewer", "Editor", "WorkspaceAdmin", "ClusterAdmin"}) {
        Json r = Json::object();
        r.set("name", std::string(name))
            .set("rank", static_cast<int64_t>(role_rank(name)));
        arr.push_back(r);
      }
      Json j = Json::object();
      j.set("roles", arr);
      return pok(j);
    }
    if (sub == "me" && req.method == "GET") {
      User* caller = current_user(req);
      if (!caller) return punauthorized("not logged in");
      int64_t ws = 0;
      auto q = req.query.find("workspace_id");
      if (q != req.query.end()) {
        try {
          ws = std::stoll(q->second);
        } catch (const std::exception&) {
          return pbad("bad workspace_id");
        }
      }
      int rank = rbac_rank(caller, ws);
      const char* role = rank >= 4   ? "ClusterAdmin"
                         : rank == 3 ? "WorkspaceAdmin"
                         : rank == 2 ? "Editor"
                         : rank == 1 ? "Viewer"
                                     : "";
      Json j = Json::object();
      j.set("rank", static_cast<int64_t>(rank)).set("role", std::string(role))
          .set("workspace_id", ws)
          .set("enforced", config_.rbac_enabled && config_.auth_required);
      return pok(j);
    }
    if (sub == "assignments") {
      if (parts.size() == 4 && req.method == "GET") {
        Json arr = Json::array();
        for (const auto& [id, a] : role_assignments_) {
          arr.push_back(a.to_json());
        }
        Json j = Json::object();
        j.set("assignments", arr);
        return pok(j);
      }
      // assignment mutations: cluster-admin only
      if (!cluster_admin_ok(req)) return pforbidden("cluster admin required");
      if (parts.size() == 4 && req.method == "POST") {
        Json body = Json::parse(req.body);
        RoleAssignment a;
        a.role = body["role"].as_string();
        if (role_rank(a.role) == 0) {
          return pbad("unknown role '" + a.role +
                      "' (Viewer|Editor|WorkspaceAdmin|ClusterAdmin)");
        }
        a.user_id = body["user_id"].as_int();
        a.group_id = body["group_id"].as_int();
        if ((a.user_id == 0) == (a.group_id == 0)) {
          return pbad("exactly one of user_id / group_id required");
        }
        if (a.user_id && !users_.count(a.user_id)) {
          return pbad("no user " + std::to_string(a.user_id));
        }
        if (a.group_id && !groups_.count(a.group_id)) {
          return pbad("no group " + std::to_string(a.group_id));
        }
        a.workspace_id = body["workspace_id"].as_int();
        if (a.workspace_id != 0 && !workspaces_.count(a.workspace_id)) {
          return pbad("no workspace " + std::to_string(a.workspace_id));
        }
        if (a.role == "ClusterAdmin" && a.workspace_id != 0) {
          return pbad("ClusterAdmin is global-scope only");
        }
        for (const auto& [id, existing] : role_assignments_) {
          if (existing.role == a.role && existing.user_id == a.user_id &&
              existing.group_id == a.group_id &&
              existing.workspace_id == a.workspace_id) {
            // a duplicate would make revocation misleading: deleting one of
            // two identical rows leaves the grant silently active
            return pbad("assignment already exists (id " +
                        std::to_string(id) + ")");
          }
        }
        a.id = next_assignment_id_++;
        role_assignments_[a.id] = a;
        dirty_ = true;
        Json j = Json::object();
        j.set("assignment", role_assignments_[a.id].to_json());
        return pcreated(j);
      }
      if (parts.size() == 5 && req.method == "DELETE") {
        int64_t aid = 0;
        try {
          aid = std::stoll(parts[4]);
        } catch (const std::exception&) {
          return pbad("bad assignment id");
        }
        if (!role_assignments_.erase(aid)) {
          return pnotfound("no assignment " + parts[4]);
        }
        dirty_ = true;
        return pok(Json::object());
      }
    }
    return pnotfound("unknown rbac route");
  }

  return std::nullopt;
}

HttpResponse Master::sso_callback_route(const HttpRequest& req) {
  // phase 1 (locked): validate config, consume the state nonce
  {
    std::lock_guard<std::mutex> lock(mu_);
    if (config_.sso_issuer_host.empty()) {
      return pbad("sso is not configured (--sso-issuer)");
    }
    auto state_it = req.query.find("state");
    auto code_it = req.query.find("code");
    if (state_it == req.query.end() || code_it == req.query.end()) {
      return pbad("missing state/code");
    }
    const double now = now_sec();
    for (auto it = sso_states_.begin(); it != sso_states_.end();) {
      it = it->second < now ? sso_states_.erase(it) : std::next(it);
    }
    if (!sso_states_.erase(state_it->second)) {
      return punauthorized("unknown or expired sso state");
    }
  }
  // phase 2 (UNLOCKED): exchange the code at the issuer's token endpoint —
  // a blocking outbound request that must never stall the master
  Json body = Json::object();
  body.set("grant_type", "authorization_code")
      .set("code", req.query.at("code"))
      .set("client_id", config_.sso_client_id)
      .set("client_secret", config_.sso_client_secret);
  auto resp = http_request(config_.sso_issuer_host, config_.sso_issuer_port,
                           "POST", "/token", body.dump(), 15);
  if (!resp || resp->status != 200) {
    return punauthorized("sso token exchange failed");
  }
  Json identity;
  try {
    identity = Json::parse(resp->body);
  } catch (const std::exception&) {
    return punauthorized("sso issuer returned malformed identity");
  }
  std::string username = identity["username"].as_string();
  if (username.empty()) username = identity["email"].as_string();
  if (username.empty()) {
    return punauthorized("sso identity has no username/email");
  }
  // phase 3 (locked): find-or-provision the user, mint the session
  std::lock_guard<std::mutex> lock(mu_);
  User* user = nullptr;
  for (auto& [id, u] : users_) {
    if (u.username == username) user = &u;
  }
  if (user && !user->active) return punauthorized("user deactivated");
  if (!user) {
    // never admin; roles come from rbac
    User u;
    u.id = next_user_id_++;
    u.username = username;
    u.display_name = identity["name"].as_string();
    // no password entry: SSO users authenticate via the issuer only
    u.password_hash = "sso";
    users_[u.id] = u;
    user = &users_[u.id];
  }
  SessionToken tok;
  tok.token = new_token();
  tok.user_id = user->id;
  tok.expires_at = now_sec() + config_.session_ttl_sec;
  sessions_[tok.token] = tok;
  dirty_ = true;
  // hand the token to the SPA via the URL fragment (never sent to the
  // server, read once by app.js and moved to localStorage)
  HttpResponse out;
  out.status = 302;
  out.headers["Location"] = "/#sso_token=" + tok.token;
  out.body = "";
  return out;
}

Json Master::resolve_template(const Json& config) {
  if (!config["template"].is_string() ||
      config["template"].as_string().empty()) {
    return config;
  }
  auto it = templates_.find(config["template"].as_string());
  if (it == templates_.end()) {
    throw std::runtime_error("unknown template " +
                             config["template"].as_string());
  }
  return merge_configs(it->second, config);
}

}  // namespace dct
