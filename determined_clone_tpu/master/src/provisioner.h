// GCP TPU-VM provisioner: autoscaling the agent fleet from queue depth.
//
// ≈ the reference's agentrm provisioner (master/internal/rm/agentrm/
// provisioner/provisioner.go:44 + scaledecider/), re-targeted from GCE GPU
// instances to TPU-VM slices: one instance = one ICI slice (e.g. v5litepod-8
// = 8 chips in a 2x4 torus), so the scale unit is a whole slice, launched
// and deleted via `gcloud compute tpus tpu-vm create|delete`. A dry-run
// client records the commands instead of shelling out (the test seam and
// the no-credentials default).
#pragma once

#include <map>
#include <memory>
#include <set>
#include <string>
#include <vector>

#include "json.h"

namespace dct {

struct ProvisionerConfig {
  bool enabled = false;
  std::string zone = "us-central2-b";
  std::string project;                 // "" = gcloud's configured default
  std::string accelerator_type = "v5litepod-8";
  std::string runtime_version = "tpu-ubuntu2204-base";
  std::string resource_pool = "default";
  int slots_per_instance = 8;          // chips per slice
  int min_instances = 0;
  int max_instances = 4;
  double startup_grace_sec = 600;      // launch → agent-registered budget
  double idle_timeout_sec = 300;       // idle agent age before terminate
  double cooldown_sec = 15;            // min seconds between scale actions
  bool dry_run = true;                 // record commands, don't exec gcloud
};

// What the master sees this tick for the provisioner's pool.
struct ClusterView {
  int pending_slots = 0;               // slots of queued, unplaced allocations
  int free_slots = 0;                  // free chips on enabled agents
  std::set<std::string> agent_ids;     // enabled agents in the pool
  std::set<std::string> idle_agent_ids;  // subset with zero reservations
  double now = 0;
};

struct ScaleDecision {
  std::vector<std::string> launch;     // new instance names
  std::vector<std::string> terminate;  // agent/instance names to delete
};

// Cloud seam: real gcloud or a recorder.
class CloudClient {
 public:
  virtual ~CloudClient() = default;
  virtual void launch(const std::string& name,
                      const ProvisionerConfig& cfg) = 0;
  virtual void terminate(const std::string& name,
                         const ProvisionerConfig& cfg) = 0;
};

// Shells out to gcloud on a detached thread (launch takes minutes; the
// master tick must not block on it).
class GcloudTpuVmClient : public CloudClient {
 public:
  void launch(const std::string& name, const ProvisionerConfig& cfg) override;
  void terminate(const std::string& name,
                 const ProvisionerConfig& cfg) override;
};

// Dry-run / test client: records the equivalent command lines.
class RecordingClient : public CloudClient {
 public:
  void launch(const std::string& name, const ProvisionerConfig& cfg) override;
  void terminate(const std::string& name,
                 const ProvisionerConfig& cfg) override;
  std::vector<std::string> commands;
};

class Provisioner {
 public:
  Provisioner(ProvisionerConfig cfg, std::unique_ptr<CloudClient> client);

  // One scale pass: track idleness/startup, decide, execute. Called from
  // the master tick under its lock (execution is non-blocking).
  ScaleDecision step(const ClusterView& view);

  // Pure decision logic (unit-testable without a client):
  // `starting` = instances launched but not yet registered as agents;
  // `idle_candidates` = agents idle longer than idle_timeout_sec.
  static ScaleDecision decide(const ProvisionerConfig& cfg,
                              const ClusterView& view, int starting,
                              const std::vector<std::string>& idle_candidates);

  Json status() const;  // instances starting, idle ages, recent actions

  const ProvisionerConfig& config() const { return cfg_; }

 private:
  void act(const std::string& entry);

  ProvisionerConfig cfg_;
  std::unique_ptr<CloudClient> client_;
  std::map<std::string, double> starting_;    // instance -> launch time
  std::set<std::string> registered_;          // launched AND seen as an agent
  std::map<std::string, double> idle_since_;  // agent -> first idle sighting
  double last_action_ = 0;
  std::vector<std::string> actions_;          // bounded recent-action log
};

}  // namespace dct
