// The master: API server + experiment orchestration + scheduling + registry.
//
// C++ equivalent of the reference control plane (master/internal/core.go:879
// Master.Run): REST API (≈ the grpc-gateway surface), experiment → searcher →
// trial → allocation orchestration (experiment.go, trial.go, task/), gang
// scheduler over agents (rm/agentrm), persistence via atomic JSON snapshot +
// per-trial JSONL metric/log files (in place of Postgres).
#pragma once

#include <atomic>
#include <deque>
#include <map>
#include <memory>
#include <condition_variable>
#include <mutex>
#include <regex>
#include <set>
#include <string>
#include <thread>
#include <vector>

#include "http.h"
#include "json.h"
#include "kubernetesrm.h"
#include "model.h"
#include "platform.h"
#include "provisioner.h"
#include "rm.h"
#include "sched_telemetry.h"
#include "scheduler.h"
#include "searcher.h"
#include "store.h"

namespace dct {

struct MasterConfig {
  int port = 8080;
  std::string data_dir = "master_data";
  PoolPolicy default_pool;
  // per-resource-pool scheduler overrides (≈ the reference's per-pool
  // configs, rm/agentrm/resource_pool.go); pools not listed here use
  // default_pool
  std::map<std::string, PoolPolicy> pools;
  double agent_timeout_sec = 60;   // heartbeat "amnesia" window
  // unmanaged trials: errored when the client's heartbeats stop this long
  double unmanaged_timeout_sec = 300;
  double tick_interval_sec = 0.5;  // ≈ resource_pool.go:62 schedulerTick
  // when true, user-facing routes (experiments/tasks/registry/...) require a
  // Bearer token from /api/v1/auth/login; the agent + data planes stay open
  // (the reference gives those their own allocation tokens)
  bool auth_required = false;
  // role-based access control (≈ master/internal/rbac, an opt-in feature in
  // the reference too): when true (and auth_required), mutating routes check
  // the caller's resolved role at the target workspace's scope
  bool rbac_enabled = false;
  double session_ttl_sec = 7 * 24 * 3600;
  // SSO via an OIDC-shaped identity provider (≈ the reference's
  // OIDC/SAML plugin hooks): the master redirects to
  // <issuer>/authorize and exchanges the callback code at
  // <issuer>/token for the identity; users auto-provision on first
  // login. Empty host disables.
  std::string sso_issuer_host;
  int sso_issuer_port = 0;
  std::string sso_client_id = "dct";
  std::string sso_client_secret;
  // externally visible host:port the IdP should send the browser back to;
  // when empty the callback host falls back to loopback rather than the
  // request's Host header (a forged Host must not steer the authorization
  // code to an attacker-controlled callback)
  std::string sso_external_host;
  // static WebUI assets directory ("" disables); served at / and /ui/*
  std::string webui_dir = "webui";
  // TPU-VM autoscaling (provisioner.h); disabled unless enabled=true
  ProvisionerConfig provisioner;
  // persistence backend: "auto" (sqlite when libsqlite3 loads, else files),
  // "sqlite", or "files" (store.h)
  std::string db = "auto";
  // log retention: keep only the newest N records of each FINISHED task's
  // log stream (0 = keep everything). Applied periodically by the tick
  // thread (≈ the reference's retention policies, master/internal/logs).
  int64_t log_retention_records = 0;
  double log_retention_interval_sec = 60;
  // must exceed the 60 s follow cap so draining clients finish first
  double log_retention_grace_sec = 120;
  // thread budget for log-follow long-polls: each held follower pins one
  // connection thread (bounded 60 s); beyond this many concurrent
  // followers the route degrades to an immediate (non-held) response and
  // the client simply re-polls — tailing stays correct, just chattier
  int max_log_followers = 64;
  // resource manager: "agent" (gang scheduler over dct-agents) or
  // "kubernetes" (allocations become TPU pods; ≈ rm/setup.go:17-28)
  std::string rm = "agent";
  KubeRmConfig kube;
};

class Master {
 public:
  explicit Master(MasterConfig config);
  ~Master();

  void start();           // boot: restore snapshot, start HTTP + tick loop
  void stop();
  int port() const { return server_->port(); }

  // exposed for unit tests
  HttpResponse handle(const HttpRequest& req);

 private:
  // -- orchestration (holding lock) --
  void apply_search_ops(Experiment& exp, std::vector<SearchOp> ops);
  SearchMethodCpp* method_for(Experiment& exp);
  void queue_trial_leg(Trial& trial);
  void finish_experiment(Experiment& exp, RunState state,
                         const std::string& error = "");
  void on_task_done(const std::string& alloc_id, int exit_code,
                    const std::string& error);
  void tick_locked();
  // the agentrm scheduling pass (schedule_pool + provisioner), extracted so
  // the RM seam can swap it out for kubernetesrm (rm.h)
  void agent_rm_tick_locked(double now);
  Json allocation_start_command(const Allocation& alloc,
                                const std::string& agent_id);

  // -- persistence --
  void save_snapshot_locked();
  void load_snapshot();
  void append_jsonl(const std::string& file, const Json& record);
  // one stream open for the whole batch (profiler flushes are 100 samples)
  void append_jsonl_many(const std::string& file,
                         const std::vector<const Json*>& records);
  std::vector<Json> read_jsonl(const std::string& file, size_t limit,
                               size_t offset = 0);
  // last `limit` records — live-monitoring reads want the newest data
  std::vector<Json> read_jsonl_tail(const std::string& file, size_t limit);

  // -- routes --
  HttpResponse route(const HttpRequest& req);
  // /proxy/:allocID/* — reverse proxy to a running task's registered
  // address (≈ master/internal/proxy/proxy.go). Forwards OUTSIDE the
  // master lock; only the address lookup locks.
  HttpResponse proxy_route(const HttpRequest& req);
  // GET /metrics — Prometheus text exposition of cluster state gauges
  HttpResponse metrics_route();
  // GET /api/v1/cluster/scheduler[/events] — control-plane telemetry
  // summary + master-lane event dump (routes.cc; caller holds mu_)
  Json sched_summary_locked();
  Json sched_events_locked();
  // GET /api/v1/experiments/:id/trace — trial span samples + synthesized
  // master-lane lifecycle spans for `dct trace export` (caller holds mu_)
  HttpResponse experiment_trace_locked(int64_t exp_id);
  // record a master-lane lifecycle event (caller holds mu_); start/end are
  // epoch seconds (end <= start records an instant)
  void sched_event_locked(const char* name, const Allocation& alloc,
                          double start, double end);
  // GET /debug/requests | /debug/stats — request tracing (≈ the
  // reference's otel spans + prom middleware, core.go:1014,1189)
  HttpResponse debug_route(const HttpRequest& req);
  void record_span(const HttpRequest& req, int status, double dur_ms);
  // GET / and /ui/* — WebUI static assets (webui/, served by the master the
  // way the reference master serves the built React bundle)
  HttpResponse static_route(const HttpRequest& req);
  // platform-breadth routes: auth/users, workspaces/projects, model
  // registry, templates, webhooks (routes_platform.cc). Returns nullopt when
  // the path is not one of its roots.
  std::optional<HttpResponse> route_platform(const HttpRequest& req);
  // GET /api/v1/auth/sso/callback — dispatched from handle() BEFORE the
  // state lock: the IdP token exchange is a blocking outbound request and
  // must never run under mu_ (locks only around state reads/writes)
  HttpResponse sso_callback_route(const HttpRequest& req);
  // GET /api/v1/allocations/:id/logs?follow=N — long-poll follow mode
  // (≈ the reference's streaming TrialLogs with follow, api.proto:781).
  // Dispatched from handle() BEFORE the state lock: it sleeps on
  // logs_cv_ between reads and must not pin route()'s lock_guard.
  HttpResponse logs_follow_route(const HttpRequest& req);
  // generic + typed NTSC task surface (tasks/notebooks/shells/commands/
  // tensorboards roots share it; forced_type pins the type, "" = generic)
  HttpResponse tasks_route(const HttpRequest& req,
                           const std::string& forced_type,
                           const char* singular, const char* plural);
  // serving fleets: /api/v1/serving/fleets[...] — replica gang
  // allocations of task_type "serving" (docs/serving.md). Caller holds
  // mu_ (dispatched from route()).
  HttpResponse serving_route(const HttpRequest& req);
  // enqueue one serving replica allocation for the fleet (holding mu_)
  Allocation& queue_serving_replica_locked(ServingFleetRec& fleet);
  // cancel the highest-seq live replicas down to `target` (holding mu_)
  void shrink_serving_fleet_locked(ServingFleetRec& fleet, int target);
  Json serving_fleet_json_locked(const ServingFleetRec& fleet);

  // -- platform helpers (routes_platform.cc) --
  User* current_user(const HttpRequest& req);   // nullptr if no valid token
  // caller's max role rank at a workspace scope (global assignments count
  // everywhere; workspace assignments only at that workspace). The admin
  // flag is ClusterAdmin. 0 = no role.
  int rbac_rank(const User* u, int64_t workspace_id);
  // RBAC gate: true when enforcement is off, or the caller's rank at the
  // scope is >= min_rank (use role_rank("Editor") etc.)
  bool rbac_allows(const HttpRequest& req, int min_rank,
                   int64_t workspace_id = 0);
  // the cluster-admin surface (user/group/role management): legacy admin
  // flag OR role-granted ClusterAdmin; always passes when auth is off
  bool cluster_admin_ok(const HttpRequest& req);
  int64_t workspace_id_by_name(const std::string& name);  // 0 if unknown
  // true when the request bears a live allocation's token (the data-plane
  // analogue of a user session; ≈ the reference's allocation session tokens,
  // master/internal/task/allocation_service.go)
  bool alloc_authed(const HttpRequest& req);
  void bootstrap_users_locked();
  Workspace& ensure_workspace(const std::string& name,
                              const std::string& owner);
  void ensure_project(const std::string& name, int64_t workspace_id,
                      const std::string& owner);
  // fires matching webhooks for a terminal experiment (detached threads)
  void fire_webhooks(const Experiment& exp);
  // POST a payload to one webhook's URL (detached thread, off the lock)
  void post_webhook(const Webhook& hook, const Json& payload);
  // merges a named template under the config (throws on unknown template)
  Json resolve_template(const Json& config);
  // log-pattern policies on a shipped log batch (routes.cc):
  // cancel_retries / exclude_node (≈ master/internal/logpattern)
  void apply_log_policies(const Allocation& alloc, const Json& logs);
  // checkpoint GC per storage policy at experiment end; marks records
  // deleted and spawns a zero-slot GC task (≈ checkpoint_gc.go:27)
  void gc_checkpoints_locked(Experiment& exp);
  // enqueue the zero-slot storage-GC task for a doomed checkpoint list
  void spawn_gc_task_locked(const Experiment& exp,
                            const std::vector<std::string>& doomed);

  MasterConfig config_;
  std::unique_ptr<HttpServer> server_;
  std::thread tick_thread_;
  std::atomic<bool> running_{false};
  std::unique_ptr<Provisioner> provisioner_;  // null unless enabled
  std::unique_ptr<ResourceManager> rm_;       // agent | kubernetes
  std::unique_ptr<Store> store_;  // created in the ctor (routes need it
                                  // even when start() is never called)

  std::mutex mu_;
  // pinged on every store append (and terminal task transitions) so log
  // followers wake instantly instead of sleeping out their poll window.
  // stream_versions_ lets a woken follower skip the store read unless ITS
  // stream changed — metrics/profiler appends would otherwise fan out
  // into O(appends x followers) reads under mu_.
  std::condition_variable logs_cv_;
  std::map<std::string, uint64_t> stream_versions_;
  // master's own event log (≈ the reference's master logs API,
  // api_master.go GetMasterLogs): bounded in-memory ring; seq numbers stay
  // absolute across drops so client cursors survive trimming
  std::deque<Json> event_log_;
  uint64_t event_log_head_seq_ = 0;  // seq of event_log_.front()
  void log_event(const std::string& level, const std::string& msg);
  double last_retention_sweep_ = 0;
  // retention bookkeeping: when each terminal allocation was first seen
  // (grace timer) and which have already been trimmed (once per lifetime)
  std::map<std::string, double> retention_terminal_seen_;
  std::set<std::string> retention_done_;
  std::atomic<int> active_followers_{0};
  // upstream sockets of live WebSocket/TCP relays: stop() must shut them
  // down or relay pump threads blocked in recv() would hang shutdown
  std::mutex relay_mu_;
  std::set<int> relay_fds_;
  int64_t next_experiment_id_ = 1;
  int64_t next_trial_id_ = 1;
  int64_t next_task_id_ = 1;
  std::map<int64_t, Experiment> experiments_;
  std::map<int64_t, Trial> trials_;
  // control-plane scheduler telemetry (guarded by mu_, like the state it
  // observes; metrics_route and the cluster routes read it under mu_ too)
  SchedTelemetry sched_;
  std::map<std::string, Allocation> allocations_;
  // serving fleets by name (replicas live in allocations_)
  std::map<std::string, ServingFleetRec> fleets_;
  std::map<std::string, Agent> agents_;
  std::vector<CheckpointRecord> checkpoints_;
  // live searcher methods (rebuilt from snapshots on restore)
  std::map<int64_t, std::unique_ptr<SearchMethodCpp>> methods_;
  // experiment request_id -> global trial id
  std::map<int64_t, std::map<int64_t, int64_t>> request_to_trial_;
  // -- platform breadth (platform.h) --
  int64_t next_user_id_ = 1;
  int64_t next_workspace_id_ = 1;
  int64_t next_project_id_ = 1;
  int64_t next_model_id_ = 1;
  int64_t next_webhook_id_ = 1;
  int64_t next_group_id_ = 1;
  int64_t next_assignment_id_ = 1;
  std::map<int64_t, User> users_;
  std::map<int64_t, Json> user_settings_;  // per-user UI/CLI settings bag
  std::map<std::string, SessionToken> sessions_;
  std::map<int64_t, Workspace> workspaces_;
  std::map<int64_t, Project> projects_;
  std::map<int64_t, RegisteredModel> models_;
  std::map<std::string, Json> templates_;
  std::map<int64_t, Webhook> webhooks_;
  std::map<int64_t, Group> groups_;
  std::map<int64_t, RoleAssignment> role_assignments_;
  // outstanding SSO login attempts: state nonce -> expiry (transient)
  std::map<std::string, double> sso_states_;
  // -- request tracing (own mutex: never contends the state lock) --
  struct RouteStats {
    int64_t count = 0;
    int64_t errors = 0;  // status >= 500
    double total_ms = 0;
    double max_ms = 0;
    std::vector<double> samples;  // ring, capped (p95 source)
    size_t next_sample = 0;
  };
  struct Span {
    double at = 0;
    double dur_ms = 0;
    int status = 0;
    std::string method, path, route;
  };
  std::mutex trace_mu_;
  std::deque<Span> recent_spans_;              // newest last, capped
  std::map<std::string, RouteStats> route_stats_;
  // master-mediated allgather barriers (≈ master/internal/task/allgather):
  // alloc id -> round -> rank -> payload. Transient (not persisted).
  std::map<std::string, std::map<int64_t, std::map<int, Json>>> allgather_;

  // compiled log-pattern policies per experiment (lazy; not persisted)
  struct CompiledLogPolicy {
    std::regex re;
    std::string pattern;
    std::string action;
  };
  std::map<int64_t, std::vector<CompiledLogPolicy>> log_policy_cache_;
  // compiled log_pattern regexes per webhook id (lazy; not persisted)
  std::map<int64_t, std::regex> webhook_pattern_cache_;
  bool dirty_ = false;
};

double now_sec();

// strips the "Bearer " scheme from the Authorization header; empty string
// when absent (routes_platform.cc)
std::string bearer_token(const HttpRequest& req);

}  // namespace dct
