// Gang scheduler over TPU agents.
//
// ≈ the reference agentrm (master/internal/rm/agentrm): resource pools with
// pluggable policies — fifo, priority (with preemption), fair-share — and
// all-or-nothing gang fitting. The fitting is slice-topology-aware where the
// reference's is count-based (fitting.go:71): a gang either takes whole
// agents (each agent's chips are one ICI domain) or a chip subset of a
// single agent; it never splits across partial agents, because cross-agent
// partial gangs would put gradient collectives on DCN between arbitrary
// chip subsets.
#pragma once

#include <map>
#include <optional>
#include <string>
#include <vector>

#include "model.h"
#include "topology.h"

namespace dct {

struct SchedulerDecision {
  // allocation id -> (agent id -> slots)
  std::map<std::string, std::map<std::string, int>> assignments;
  // allocation ids to preempt (priority policy)
  std::vector<std::string> preemptions;
  // pass statistics (control-plane telemetry, docs/observability.md):
  int considered = 0;     // pending allocations examined this pass
  int gang_waiting = 0;   // examined slot-requesting allocs with no fit —
                          // still waiting on capacity/gang assembly
  int gangs_admitted = 0; // assignments spanning >1 agent or >1 slice
};

struct PoolPolicy {
  // fifo | priority | fair_share | round_robin
  std::string type = "priority";
  bool preemption_enabled = true;
};

// agents: all agents of the pool (enabled, with free slot counts precomputed
// by the caller from running reservations).
// pending: allocations waiting, running: allocations holding reservations.
// share_usage: owner key (experiment id / task type) -> slots currently held
// (fair-share input).
SchedulerDecision schedule_pool(
    const PoolPolicy& policy,
    const std::vector<Agent>& agents,
    std::map<std::string, int> free_slots,  // agent id -> free chips
    std::vector<Allocation> pending,        // copy: gets sorted
    const std::vector<Allocation>& running,
    const std::map<std::string, int>& share_usage,
    const std::map<std::string, std::string>& owner_of_alloc);

// Gang fit for one allocation. Returns agent->slots or nullopt.
// `grids` (optional): per-agent chip grids with the running reservations
// placed — single-agent sub-slice fits then require a contiguous free
// rectangle (topology.h), not just a free count. Null = count-based only.
std::optional<std::map<std::string, int>> find_fit(
    const Allocation& alloc, const std::vector<Agent>& agents,
    const std::map<std::string, int>& free_slots,
    const std::string& experiment_key,
    const std::map<std::string, ChipGrid>* grids = nullptr);

// Per-agent chip grids with every running allocation's reservation placed
// (deterministic replay in queued_at order; rectangle placement with a
// count-based fallback for drifted state).
std::map<std::string, ChipGrid> build_chip_grids(
    const std::vector<Agent>& agents,
    const std::vector<Allocation>& running);

}  // namespace dct
