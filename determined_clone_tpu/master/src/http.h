// Small HTTP/1.1 server + client.
//
// Serves the master's REST API (the role grpc-gateway + echo play in the
// reference, master/internal/core.go) and carries the agent↔master protocol
// (HTTP long-poll where the reference uses a websocket,
// agent/internal/agent.go:268 — same reconnect semantics, simpler wire).
// Thread-per-connection with keep-alive: the API's perf gate (p95 < 1 s at
// 25 VUs, performance/src/api_performance_tests.ts) needs nothing fancier.
#pragma once

#include <atomic>
#include <functional>
#include <map>
#include <memory>
#include <mutex>
#include <optional>
#include <set>
#include <string>
#include <thread>
#include <vector>

namespace dct {

struct HttpRequest {
  std::string method;
  std::string path;                      // without query string
  std::map<std::string, std::string> query;
  std::map<std::string, std::string> headers;  // lower-cased keys
  std::string body;
  std::vector<std::string> path_parts;   // split on '/'
};

struct HttpResponse {
  int status = 200;
  std::string content_type = "application/json";
  std::string body;
  // extra response headers (e.g. Location on a 302 redirect)
  std::map<std::string, std::string> headers;
  // Connection takeover (WebSocket/raw-TCP proxying): when set, the
  // server writes NOTHING — the hook receives the raw client fd plus any
  // bytes already buffered past the request head (early frames from a
  // pipelining client) and owns the socket until it returns, after which
  // the connection is closed. Runs on the connection's dedicated thread.
  std::function<void(int fd, std::string buffered)> hijack;

  static HttpResponse json(int status, const std::string& body) {
    HttpResponse r;
    r.status = status;
    r.body = body;
    return r;
  }
};

using HttpHandler = std::function<HttpResponse(const HttpRequest&)>;

class HttpServer {
 public:
  explicit HttpServer(HttpHandler handler) : handler_(std::move(handler)) {}
  ~HttpServer() { stop(); }

  // Binds and starts the accept loop on a background thread.
  // port 0 → ephemeral; port() returns the bound port.
  void start(int port);
  void stop();
  int port() const { return port_; }

 private:
  void accept_loop();
  void serve_connection(int fd);

  HttpHandler handler_;
  std::atomic<bool> running_{false};
  int listen_fd_ = -1;
  int port_ = 0;
  std::thread accept_thread_;
  // one thread per live connection; finished entries (done flag set by the
  // thread itself) are reaped on the next accept, so a long-lived master
  // under connection churn holds O(live connections) threads, not
  // O(total connections ever)
  struct Worker {
    std::thread thread;
    std::shared_ptr<std::atomic<bool>> done;
  };
  std::vector<Worker> workers_;
  std::mutex conn_mu_;
  std::set<int> conn_fds_;
};

// Blocking HTTP client (agent→master, harness→master, CLI smoke tests).
struct HttpClientResponse {
  int status = 0;
  std::string body;
  std::string content_type = "application/json";  // from the response headers
};

// "host:port" -> (host, port). False when the colon or a valid (1-65535)
// numeric port is missing — shared by every config surface that takes an
// address so validation cannot drift.
bool split_host_port(const std::string& s, std::string* host, int* port);

// Returns nullopt on connect/transport error. `extra_headers` are appended
// to the request (e.g. the proxy path's x-alloc-token injection).
std::optional<HttpClientResponse> http_request(
    const std::string& host, int port, const std::string& method,
    const std::string& path, const std::string& body = "",
    int timeout_sec = 70,
    const std::map<std::string, std::string>& extra_headers = {});

// Blocking full-buffer send; false on error (EPIPE etc.).
bool send_all_fd(int fd, const std::string& data);

// Connected TCP socket to host:port (IPv4 literal or resolved hostname)
// with send/recv timeouts set, or -1. The building block http_request and
// the proxy's upgrade path share.
int tcp_connect(const std::string& host, int port, int timeout_sec);

// Pump bytes both ways between two connected sockets until either side
// closes (WebSocket/TCP proxying). Spawns one helper thread for the
// upstream->client direction and pumps client->upstream on the calling
// thread; returns once both directions are drained. Closes NEITHER fd —
// callers own their sockets.
void relay_bidirectional(int client_fd, int upstream_fd);

}  // namespace dct
