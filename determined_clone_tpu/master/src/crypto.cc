#include "crypto.h"

#include <cstdio>
#include <cstring>
#include <fstream>
#include <random>
#include <vector>

namespace dct {
namespace crypto {
namespace {

// ---- SHA-256 (FIPS 180-4) --------------------------------------------------

constexpr uint32_t kK[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

inline uint32_t rotr(uint32_t x, int n) { return (x >> n) | (x << (32 - n)); }

struct Sha256Ctx {
  uint32_t h[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                   0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};
  uint8_t buf[64];
  size_t buf_len = 0;
  uint64_t total = 0;

  void block(const uint8_t* p) {
    uint32_t w[64];
    for (int i = 0; i < 16; ++i) {
      w[i] = (uint32_t(p[4 * i]) << 24) | (uint32_t(p[4 * i + 1]) << 16) |
             (uint32_t(p[4 * i + 2]) << 8) | uint32_t(p[4 * i + 3]);
    }
    for (int i = 16; i < 64; ++i) {
      uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
      uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
      w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint32_t a = h[0], b = h[1], c = h[2], d = h[3], e = h[4], f = h[5],
             g = h[6], hh = h[7];
    for (int i = 0; i < 64; ++i) {
      uint32_t s1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
      uint32_t ch = (e & f) ^ (~e & g);
      uint32_t t1 = hh + s1 + ch + kK[i] + w[i];
      uint32_t s0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
      uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
      uint32_t t2 = s0 + maj;
      hh = g; g = f; f = e; e = d + t1;
      d = c; c = b; b = a; a = t1 + t2;
    }
    h[0] += a; h[1] += b; h[2] += c; h[3] += d;
    h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
  }

  void update(const uint8_t* data, size_t len) {
    total += len;
    while (len > 0) {
      size_t take = std::min(len, sizeof(buf) - buf_len);
      std::memcpy(buf + buf_len, data, take);
      buf_len += take;
      data += take;
      len -= take;
      if (buf_len == 64) {
        block(buf);
        buf_len = 0;
      }
    }
  }

  void final(uint8_t out[32]) {
    uint64_t bits = total * 8;
    uint8_t pad = 0x80;
    update(&pad, 1);
    uint8_t zero = 0;
    while (buf_len != 56) update(&zero, 1);
    uint8_t len_be[8];
    for (int i = 0; i < 8; ++i) {
      len_be[i] = static_cast<uint8_t>(bits >> (56 - 8 * i));
    }
    update(len_be, 8);
    for (int i = 0; i < 8; ++i) {
      out[4 * i] = static_cast<uint8_t>(h[i] >> 24);
      out[4 * i + 1] = static_cast<uint8_t>(h[i] >> 16);
      out[4 * i + 2] = static_cast<uint8_t>(h[i] >> 8);
      out[4 * i + 3] = static_cast<uint8_t>(h[i]);
    }
  }
};

constexpr int kIterations = 10000;
constexpr const char* kScheme = "pbkdf2_sha256";

// legacy FNV-1a 64 hash (pre-KDF snapshots persisted these; verify-only)
std::string legacy_fnv_hash(const std::string& username,
                            const std::string& password) {
  const std::string salted = username + "\x1f" + password + "\x1f" + "dct-salt";
  uint64_t h = 1469598103934665603ull;
  for (unsigned char c : salted) {
    h ^= c;
    h *= 1099511628211ull;
  }
  char buf[17];
  std::snprintf(buf, sizeof(buf), "%016llx",
                static_cast<unsigned long long>(h));
  return buf;
}

}  // namespace

void sha256(const uint8_t* data, size_t len, uint8_t out[32]) {
  Sha256Ctx ctx;
  ctx.update(data, len);
  ctx.final(out);
}

void hmac_sha256(const uint8_t* key, size_t key_len, const uint8_t* msg,
                 size_t msg_len, uint8_t out[32]) {
  uint8_t k[64] = {0};
  if (key_len > 64) {
    sha256(key, key_len, k);  // leaves bytes 32..63 zero
  } else {
    std::memcpy(k, key, key_len);
  }
  uint8_t ipad[64], opad[64];
  for (int i = 0; i < 64; ++i) {
    ipad[i] = k[i] ^ 0x36;
    opad[i] = k[i] ^ 0x5c;
  }
  Sha256Ctx inner;
  inner.update(ipad, 64);
  inner.update(msg, msg_len);
  uint8_t inner_digest[32];
  inner.final(inner_digest);
  Sha256Ctx outer;
  outer.update(opad, 64);
  outer.update(inner_digest, 32);
  outer.final(out);
}

void pbkdf2_sha256(const std::string& password, const std::string& salt,
                   int iterations, uint8_t out[32]) {
  // dkLen = hLen = 32 → exactly one block (INT(i) = 1)
  std::vector<uint8_t> msg(salt.begin(), salt.end());
  msg.push_back(0);
  msg.push_back(0);
  msg.push_back(0);
  msg.push_back(1);
  uint8_t u[32];
  hmac_sha256(reinterpret_cast<const uint8_t*>(password.data()),
              password.size(), msg.data(), msg.size(), u);
  uint8_t t[32];
  std::memcpy(t, u, 32);
  for (int i = 1; i < iterations; ++i) {
    hmac_sha256(reinterpret_cast<const uint8_t*>(password.data()),
                password.size(), u, 32, u);
    for (int j = 0; j < 32; ++j) t[j] ^= u[j];
  }
  std::memcpy(out, t, 32);
}

std::string to_hex(const uint8_t* data, size_t len) {
  static const char* hex = "0123456789abcdef";
  std::string out;
  out.reserve(len * 2);
  for (size_t i = 0; i < len; ++i) {
    out += hex[data[i] >> 4];
    out += hex[data[i] & 0xF];
  }
  return out;
}

bool constant_time_eq(const std::string& a, const std::string& b) {
  // length leak is fine (formats are public); content must not leak
  unsigned char diff = a.size() == b.size() ? 0 : 1;
  const size_t n = std::min(a.size(), b.size());
  for (size_t i = 0; i < n; ++i) {
    diff |= static_cast<unsigned char>(a[i]) ^ static_cast<unsigned char>(b[i]);
  }
  return diff == 0;
}

std::string random_token() {
  unsigned char raw[16];
  std::ifstream urandom("/dev/urandom", std::ios::binary);
  if (urandom.good()) {
    urandom.read(reinterpret_cast<char*>(raw), sizeof(raw));
  }
  if (!urandom.good()) {
    std::random_device rd;  // fallback: one fresh word per byte-pair
    for (size_t i = 0; i < sizeof(raw); i += 2) {
      unsigned int v = rd();
      raw[i] = static_cast<unsigned char>(v & 0xFF);
      raw[i + 1] = static_cast<unsigned char>((v >> 8) & 0xFF);
    }
  }
  return to_hex(raw, sizeof(raw));
}

std::string hash_password(const std::string& username,
                          const std::string& password) {
  std::string salt_hex = random_token();  // 128-bit per-user random salt
  uint8_t dk[32];
  pbkdf2_sha256(username + "\x1f" + password, salt_hex, kIterations, dk);
  return std::string(kScheme) + "$" + std::to_string(kIterations) + "$" +
         salt_hex + "$" + to_hex(dk, 32);
}

bool password_needs_rehash(const std::string& stored) {
  return stored.rfind(std::string(kScheme) + "$", 0) != 0;
}

bool verify_password(const std::string& stored, const std::string& username,
                     const std::string& password) {
  if (password_needs_rehash(stored)) {
    // legacy FNV-1a entries from pre-KDF snapshots
    return constant_time_eq(stored, legacy_fnv_hash(username, password));
  }
  // pbkdf2_sha256$<iterations>$<salt_hex>$<dk_hex>
  size_t p1 = stored.find('$');
  size_t p2 = stored.find('$', p1 + 1);
  size_t p3 = stored.find('$', p2 + 1);
  if (p2 == std::string::npos || p3 == std::string::npos) return false;
  int iterations = 0;
  try {
    iterations = std::stoi(stored.substr(p1 + 1, p2 - p1 - 1));
  } catch (const std::exception&) {
    return false;
  }
  if (iterations <= 0 || iterations > 10000000) return false;
  const std::string salt_hex = stored.substr(p2 + 1, p3 - p2 - 1);
  const std::string dk_hex = stored.substr(p3 + 1);
  uint8_t dk[32];
  pbkdf2_sha256(username + "\x1f" + password, salt_hex, iterations, dk);
  return constant_time_eq(dk_hex, to_hex(dk, 32));
}

}  // namespace crypto
}  // namespace dct
