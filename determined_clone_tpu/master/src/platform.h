// Platform-breadth domain model: users/sessions, workspaces/projects,
// model registry, config templates, webhooks.
//
// ≈ the reference's master/internal/{user,workspace,project,model,templates,
// webhooks} DB models, collapsed into snapshot-persisted structs the same
// way model.h does for experiments/trials.
#pragma once

#include <algorithm>
#include <cstdint>
#include <map>
#include <string>
#include <vector>

#include "json.h"

namespace dct {

// ≈ master/internal/user (sessions live in sessions_ on the Master)
struct User {
  int64_t id = 0;
  std::string username;
  std::string password_hash;  // pbkdf2_sha256$... (crypto.cc; like det's default
                              // empty-password bootstrap users)
  bool admin = false;
  bool active = true;
  std::string display_name;

  Json to_json(bool redact = true) const {
    Json j = Json::object();
    j.set("id", id).set("username", username).set("admin", admin)
        .set("active", active).set("display_name", display_name);
    if (!redact) j.set("password_hash", password_hash);
    return j;
  }
  static User from_json(const Json& j) {
    User u;
    u.id = j["id"].as_int();
    u.username = j["username"].as_string();
    u.password_hash = j["password_hash"].as_string();
    u.admin = j["admin"].as_bool();
    u.active = j["active"].as_bool(true);
    u.display_name = j["display_name"].as_string();
    return u;
  }
};

struct SessionToken {
  std::string token;
  int64_t user_id = 0;
  double expires_at = 0;

  Json to_json() const {
    Json j = Json::object();
    j.set("token", token).set("user_id", user_id)
        .set("expires_at", expires_at);
    return j;
  }
  static SessionToken from_json(const Json& j) {
    SessionToken s;
    s.token = j["token"].as_string();
    s.user_id = j["user_id"].as_int();
    s.expires_at = j["expires_at"].as_number();
    return s;
  }
};

// ≈ master/internal/workspace
struct Workspace {
  int64_t id = 0;
  std::string name;
  std::string owner = "admin";
  bool archived = false;
  bool immutable = false;  // the bootstrap "Uncategorized" workspace

  Json to_json() const {
    Json j = Json::object();
    j.set("id", id).set("name", name).set("owner", owner)
        .set("archived", archived).set("immutable", immutable);
    return j;
  }
  static Workspace from_json(const Json& j) {
    Workspace w;
    w.id = j["id"].as_int();
    w.name = j["name"].as_string();
    w.owner = j["owner"].as_string();
    w.archived = j["archived"].as_bool();
    w.immutable = j["immutable"].as_bool();
    return w;
  }
};

// ≈ master/internal/project
struct Project {
  int64_t id = 0;
  std::string name;
  int64_t workspace_id = 0;
  std::string owner = "admin";
  std::string description;
  bool archived = false;

  Json to_json() const {
    Json j = Json::object();
    j.set("id", id).set("name", name).set("workspace_id", workspace_id)
        .set("owner", owner).set("description", description)
        .set("archived", archived);
    return j;
  }
  static Project from_json(const Json& j) {
    Project p;
    p.id = j["id"].as_int();
    p.name = j["name"].as_string();
    p.workspace_id = j["workspace_id"].as_int();
    p.owner = j["owner"].as_string();
    p.description = j["description"].as_string();
    p.archived = j["archived"].as_bool();
    return p;
  }
};

// ≈ master/internal/model (registry, not an ML model)
struct ModelVersion {
  int64_t version = 0;
  std::string checkpoint_uuid;
  std::string name;
  std::string comment;
  double created_at = 0;

  Json to_json() const {
    Json j = Json::object();
    j.set("version", version).set("checkpoint_uuid", checkpoint_uuid)
        .set("name", name).set("comment", comment)
        .set("created_at", created_at);
    return j;
  }
  static ModelVersion from_json(const Json& j) {
    ModelVersion v;
    v.version = j["version"].as_int();
    v.checkpoint_uuid = j["checkpoint_uuid"].as_string();
    v.name = j["name"].as_string();
    v.comment = j["comment"].as_string();
    v.created_at = j["created_at"].as_number();
    return v;
  }
};

struct RegisteredModel {
  int64_t id = 0;
  std::string name;
  std::string description;
  Json metadata;
  Json labels;  // array of strings
  std::string workspace = "Uncategorized";
  std::string owner = "admin";
  bool archived = false;
  double created_at = 0;
  std::vector<ModelVersion> versions;
  // monotonic: a deleted latest version's number is never reused (a consumer
  // that recorded "model m vN" must never resolve to a different checkpoint)
  int64_t next_version = 1;

  Json to_json() const {
    Json vs = Json::array();
    for (const auto& v : versions) vs.push_back(v.to_json());
    Json j = Json::object();
    j.set("id", id).set("name", name).set("description", description)
        .set("metadata", metadata).set("labels", labels)
        .set("workspace", workspace).set("owner", owner)
        .set("archived", archived).set("created_at", created_at)
        .set("versions", vs).set("next_version", next_version);
    return j;
  }
  static RegisteredModel from_json(const Json& j) {
    RegisteredModel m;
    m.id = j["id"].as_int();
    m.name = j["name"].as_string();
    m.description = j["description"].as_string();
    m.metadata = j["metadata"];
    m.labels = j["labels"];
    m.workspace = j["workspace"].as_string();
    m.owner = j["owner"].as_string();
    m.archived = j["archived"].as_bool();
    m.created_at = j["created_at"].as_number();
    for (const auto& v : j["versions"].elements()) {
      m.versions.push_back(ModelVersion::from_json(v));
    }
    m.next_version = j["next_version"].as_int(1);
    for (const auto& v : m.versions) {  // old snapshots: derive counter
      m.next_version = std::max(m.next_version, v.version + 1);
    }
    return m;
  }
};

// ≈ master/internal/usergroup: named sets of users, assignable to roles
struct Group {
  int64_t id = 0;
  std::string name;
  std::vector<int64_t> user_ids;

  bool has_user(int64_t uid) const {
    return std::find(user_ids.begin(), user_ids.end(), uid) != user_ids.end();
  }
  Json to_json() const {
    Json members = Json::array();
    for (int64_t uid : user_ids) members.push_back(uid);
    Json j = Json::object();
    j.set("id", id).set("name", name).set("user_ids", members);
    return j;
  }
  static Group from_json(const Json& j) {
    Group g;
    g.id = j["id"].as_int();
    g.name = j["name"].as_string();
    for (const auto& u : j["user_ids"].elements()) {
      g.user_ids.push_back(u.as_int());
    }
    return g;
  }
};

// ≈ master/internal/rbac: a role granted to a user OR a group, at global
// scope (workspace_id == 0) or scoped to one workspace. Roles form a strict
// hierarchy — rank order Viewer < Editor < WorkspaceAdmin < ClusterAdmin —
// which covers the reference's pre-canned role set (rbac/static roles)
// without per-permission grants.
struct RoleAssignment {
  int64_t id = 0;
  std::string role;         // Viewer | Editor | WorkspaceAdmin | ClusterAdmin
  int64_t user_id = 0;      // exactly one of user_id / group_id is non-zero
  int64_t group_id = 0;
  int64_t workspace_id = 0;  // 0 = global scope

  Json to_json() const {
    Json j = Json::object();
    j.set("id", id).set("role", role).set("user_id", user_id)
        .set("group_id", group_id).set("workspace_id", workspace_id);
    return j;
  }
  static RoleAssignment from_json(const Json& j) {
    RoleAssignment a;
    a.id = j["id"].as_int();
    a.role = j["role"].as_string();
    a.user_id = j["user_id"].as_int();
    a.group_id = j["group_id"].as_int();
    a.workspace_id = j["workspace_id"].as_int();
    return a;
  }
};

// role name -> hierarchy rank; 0 for unknown roles
inline int role_rank(const std::string& role) {
  if (role == "Viewer") return 1;
  if (role == "Editor") return 2;
  if (role == "WorkspaceAdmin") return 3;
  if (role == "ClusterAdmin") return 4;
  return 0;
}

// ≈ master/internal/webhooks (shipper.go): fire on experiment state change
struct Webhook {
  int64_t id = 0;
  std::string url;             // http://host:port/path
  std::string webhook_type = "default";  // default | slack
  // triggers: experiment states that fire it (e.g. COMPLETED, ERRORED)
  std::vector<std::string> triggers;
  // non-empty: also fires when a task-log line matches this regex
  // (≈ the reference's TRIGGER_TYPE_TASK_LOG webhooks)
  std::string log_pattern;

  Json to_json() const {
    Json ts = Json::array();
    for (const auto& t : triggers) ts.push_back(t);
    Json j = Json::object();
    j.set("id", id).set("url", url).set("webhook_type", webhook_type)
        .set("triggers", ts).set("log_pattern", log_pattern);
    return j;
  }
  static Webhook from_json(const Json& j) {
    Webhook w;
    w.id = j["id"].as_int();
    w.url = j["url"].as_string();
    w.webhook_type = j["webhook_type"].as_string();
    for (const auto& t : j["triggers"].elements()) {
      w.triggers.push_back(t.as_string());
    }
    w.log_pattern = j["log_pattern"].as_string();
    return w;
  }
};

}  // namespace dct
