#include "provisioner.h"

#include <sys/wait.h>
#include <unistd.h>

#include <algorithm>
#include <iostream>
#include <thread>

#include "crypto.h"

namespace dct {
namespace {

std::vector<std::string> gcloud_argv(const std::string& verb,
                                     const std::string& name,
                                     const ProvisionerConfig& cfg) {
  std::vector<std::string> argv = {
      "gcloud", "compute", "tpus", "tpu-vm", verb, name,
      "--zone", cfg.zone, "--quiet",
  };
  if (verb == "create") {
    argv.push_back("--accelerator-type");
    argv.push_back(cfg.accelerator_type);
    argv.push_back("--version");
    argv.push_back(cfg.runtime_version);
  }
  if (!cfg.project.empty()) {
    argv.push_back("--project");
    argv.push_back(cfg.project);
  }
  return argv;
}

std::string join(const std::vector<std::string>& parts) {
  std::string out;
  for (const auto& p : parts) {
    if (!out.empty()) out += " ";
    out += p;
  }
  return out;
}

void exec_detached(std::vector<std::string> argv) {
  // fork/exec on a detached thread: `gcloud tpus tpu-vm create` blocks for
  // minutes and the caller is the master tick
  std::thread([argv = std::move(argv)]() {
    pid_t pid = ::fork();
    if (pid == 0) {
      std::vector<char*> cargv;
      for (const auto& a : argv) cargv.push_back(const_cast<char*>(a.c_str()));
      cargv.push_back(nullptr);
      ::execvp(cargv[0], cargv.data());
      std::_Exit(127);
    }
    if (pid < 0) {
      std::cerr << "[provisioner] fork failed for: " << join(argv)
                << std::endl;
      return;
    }
    int status = 0;
    ::waitpid(pid, &status, 0);
    if (WIFEXITED(status) && WEXITSTATUS(status) != 0) {
      std::cerr << "[provisioner] command exited " << WEXITSTATUS(status)
                << ": " << join(argv) << std::endl;
    } else if (WIFSIGNALED(status)) {
      std::cerr << "[provisioner] command killed by signal "
                << WTERMSIG(status) << ": " << join(argv) << std::endl;
    }
  }).detach();
}

}  // namespace

void GcloudTpuVmClient::launch(const std::string& name,
                               const ProvisionerConfig& cfg) {
  exec_detached(gcloud_argv("create", name, cfg));
}

void GcloudTpuVmClient::terminate(const std::string& name,
                                  const ProvisionerConfig& cfg) {
  exec_detached(gcloud_argv("delete", name, cfg));
}

void RecordingClient::launch(const std::string& name,
                             const ProvisionerConfig& cfg) {
  commands.push_back(join(gcloud_argv("create", name, cfg)));
  if (commands.size() > 200) commands.erase(commands.begin());
}

void RecordingClient::terminate(const std::string& name,
                                const ProvisionerConfig& cfg) {
  commands.push_back(join(gcloud_argv("delete", name, cfg)));
  if (commands.size() > 200) commands.erase(commands.begin());
}

Provisioner::Provisioner(ProvisionerConfig cfg,
                         std::unique_ptr<CloudClient> client)
    : cfg_(std::move(cfg)), client_(std::move(client)) {
  // a zero/negative slice size would divide by zero in decide() — clamp
  // (reachable via an unvalidated --provision-slots flag)
  if (cfg_.slots_per_instance < 1) cfg_.slots_per_instance = 1;
}

void Provisioner::act(const std::string& entry) {
  actions_.push_back(entry);
  if (actions_.size() > 100) actions_.erase(actions_.begin());
}

ScaleDecision Provisioner::decide(
    const ProvisionerConfig& cfg, const ClusterView& view, int starting,
    const std::vector<std::string>& idle_candidates) {
  ScaleDecision out;
  const int instances = static_cast<int>(view.agent_ids.size()) + starting;

  // scale up: slots the queue needs beyond current + in-flight capacity
  // (≈ scaledecider calculateInstanceStates: desired from pending slots)
  const int deficit =
      view.pending_slots - view.free_slots - starting * cfg.slots_per_instance;
  if (deficit > 0) {
    int want = (deficit + cfg.slots_per_instance - 1) / cfg.slots_per_instance;
    want = std::min(want, cfg.max_instances - instances);
    for (int i = 0; i < want; ++i) out.launch.push_back("");  // named by step()
    return out;  // never terminate while the queue is starved
  }

  // floor: keep min_instances warm even with an empty queue
  int removable = instances - cfg.min_instances;
  for (const auto& name : idle_candidates) {
    if (removable <= 0) break;
    out.terminate.push_back(name);
    --removable;
  }
  // below the floor (e.g. after manual deletes): top back up
  if (instances < cfg.min_instances) {
    for (int i = instances; i < cfg.min_instances; ++i) out.launch.push_back("");
  }
  return out;
}

ScaleDecision Provisioner::step(const ClusterView& view) {
  // startup tracking: an instance stops being "starting" when its agent
  // registers; a grace-budget expiry is a presumed-failed launch — issue a
  // best-effort delete so a slow create that eventually succeeds cannot
  // leak a slice that nothing tracks
  for (auto it = starting_.begin(); it != starting_.end();) {
    if (view.agent_ids.count(it->first)) {
      registered_.insert(it->first);
      it = starting_.erase(it);
    } else if (view.now - it->second > cfg_.startup_grace_sec) {
      client_->terminate(it->first, cfg_);
      act("cleanup " + it->first + " (startup grace expired)");
      it = starting_.erase(it);
    } else {
      ++it;
    }
  }
  // reconciliation: an instance we launched whose agent has vanished
  // (heartbeat timeout disabled it, or the VM died) must be deleted, or
  // the slice bills forever with no owner
  for (auto it = registered_.begin(); it != registered_.end();) {
    if (!view.agent_ids.count(*it)) {
      client_->terminate(*it, cfg_);
      act("reclaim " + *it + " (agent gone)");
      it = registered_.erase(it);
    } else {
      ++it;
    }
  }
  // idle tracking: first-seen-idle timestamps; busy agents reset
  for (auto it = idle_since_.begin(); it != idle_since_.end();) {
    if (!view.idle_agent_ids.count(it->first)) {
      it = idle_since_.erase(it);
    } else {
      ++it;
    }
  }
  for (const auto& id : view.idle_agent_ids) {
    idle_since_.emplace(id, view.now);
  }

  if (view.now - last_action_ < cfg_.cooldown_sec) return {};

  std::vector<std::string> idle_candidates;
  for (const auto& [id, since] : idle_since_) {
    // only instances this provisioner launched are ours to delete —
    // statically provisioned agents in the same pool are the operator's
    if (registered_.count(id) &&
        view.now - since >= cfg_.idle_timeout_sec) {
      idle_candidates.push_back(id);
    }
  }
  std::sort(idle_candidates.begin(), idle_candidates.end());

  ScaleDecision decision =
      decide(cfg_, view, static_cast<int>(starting_.size()), idle_candidates);
  for (auto& name : decision.launch) {
    // random suffix: names must not collide with instances from a previous
    // master incarnation that still exist in the cloud
    name = "dct-tpu-" + cfg_.accelerator_type + "-" +
           crypto::random_token().substr(0, 8);
    starting_[name] = view.now;
    client_->launch(name, cfg_);
    act("launch " + name);
  }
  for (const auto& name : decision.terminate) {
    idle_since_.erase(name);
    registered_.erase(name);
    client_->terminate(name, cfg_);
    act("terminate " + name);
  }
  if (!decision.launch.empty() || !decision.terminate.empty()) {
    last_action_ = view.now;
  }
  return decision;
}

Json Provisioner::status() const {
  Json starting = Json::array();
  for (const auto& [name, t] : starting_) {
    Json j = Json::object();
    j.set("name", name).set("launched_at", t);
    starting.push_back(j);
  }
  Json actions = Json::array();
  for (const auto& a : actions_) actions.push_back(a);
  Json j = Json::object();
  j.set("enabled", cfg_.enabled).set("dry_run", cfg_.dry_run)
      .set("accelerator_type", cfg_.accelerator_type)
      .set("zone", cfg_.zone)
      .set("slots_per_instance", cfg_.slots_per_instance)
      .set("min_instances", cfg_.min_instances)
      .set("max_instances", cfg_.max_instances)
      .set("starting", starting)
      .set("recent_actions", actions);
  if (auto* rec = dynamic_cast<RecordingClient*>(client_.get())) {
    Json cmds = Json::array();
    for (const auto& c : rec->commands) cmds.push_back(c);
    j.set("commands", cmds);  // dry-run: the gcloud lines that would run
  }
  return j;
}

}  // namespace dct
