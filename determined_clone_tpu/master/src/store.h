// Persistence seam: snapshot document + append-only record streams.
//
// ≈ the reference's master/internal/db (Postgres + 144 migrations) scaled
// to this master's needs: one whole-state snapshot (crash recovery) and
// per-entity append streams (metrics, task logs, profiler samples) with
// indexed reads. Two backends:
//   files  — snapshot.json + per-stream .jsonl appends (the original mode;
//            reads rescan the file)
//   sqlite — libsqlite3 loaded at runtime via dlopen (no -dev package in
//            the image): WAL journal, (stream, seq) primary key, O(log n)
//            offset/tail reads. The BASELINE.md p95 < 1 s API gate at 25
//            concurrent readers needs this once history grows.
#pragma once

#include <memory>
#include <string>
#include <vector>

#include "json.h"

namespace dct {

class Store {
 public:
  virtual ~Store() = default;

  virtual void save_snapshot(const std::string& json) = 0;
  virtual std::string load_snapshot() = 0;  // "" = no snapshot yet

  virtual void append(const std::string& stream, const Json& rec) = 0;
  virtual void append_many(const std::string& stream,
                           const std::vector<const Json*>& recs) = 0;
  // offset/limit page, oldest first (the poll-stream cursor counts
  // returned records)
  virtual std::vector<Json> read(const std::string& stream, size_t limit,
                                 size_t offset) = 0;
  // newest `limit` records, oldest first
  virtual std::vector<Json> read_tail(const std::string& stream,
                                      size_t limit) = 0;

  // -- typed trial metrics ---------------------------------------------
  // Relational on sqlite (metrics rows + a materialized per-(group, name)
  // summary, ≈ the reference's postgres_trial.go metric tables +
  // calculate-full-trial-summary-metrics.sql); stream-backed with scan
  // aggregation on the files backend.
  virtual void append_metric(int64_t trial_id, const Json& rec) = 0;
  virtual std::vector<Json> read_metrics(int64_t trial_id, size_t limit,
                                         size_t offset) = 0;
  // {"summary": [{group, name, count, min, max, mean, last, last_step}]}
  // — the flat-cost read the experiment/trial pages aggregate from
  virtual Json metric_summary(int64_t trial_id) = 0;

  // log retention: drop all but the newest keep_last records of a stream
  virtual void retain_stream(const std::string& stream, size_t keep_last) = 0;

  // backend schema version (files backend: 0; sqlite: migration stamp)
  virtual int schema_version() = 0;

  virtual const char* kind() const = 0;
};

std::unique_ptr<Store> make_file_store(const std::string& data_dir);
// nullptr when libsqlite3 cannot be loaded. Falls back to a legacy
// snapshot.json for the initial load (migration from the files backend).
std::unique_ptr<Store> make_sqlite_store(const std::string& data_dir);

}  // namespace dct
