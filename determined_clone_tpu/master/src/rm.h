// Pluggable resource-manager seam.
//
// ≈ the reference's rm.ResourceManager interface (master/internal/rm/
// resource_manager_iface.go:12) and rm.New's agentrm-vs-kubernetesrm
// selection (master/internal/rm/setup.go:17-28). The master owns all
// cluster state under one lock; an RM is a strategy object invoked from
// the master tick with a narrow context of references + callbacks, so
// each RM stays testable without threading master internals through it.
#pragma once

#include <functional>
#include <map>
#include <memory>
#include <string>

#include "json.h"
#include "model.h"

namespace dct {

struct RmContext {
  double now = 0;
  std::map<std::string, Allocation>* allocations = nullptr;
  std::map<int64_t, Trial>* trials = nullptr;
  std::function<void()> mark_dirty;
  // terminal-state idempotent task-exit handler (master.cc on_task_done)
  std::function<void(const std::string& alloc_id, int exit_code,
                     const std::string& error)> on_task_done;
  // full start command for one allocation member (allocation_start_command
  // + rank) — the same payload an agent heartbeat would deliver
  std::function<Json(const Allocation&, int rank)> start_command;
  // invalidate an allocation's master-mediated barrier state (allgather
  // rounds) when the RM requeues a leg — a restarted incarnation must not
  // see a dead incarnation's payloads
  std::function<void(const std::string& alloc_id)> clear_barriers;
  // the whole agent-scheduling tick (schedule_pool + provisioner); only
  // AgentRM calls it
  std::function<void(double now)> agent_tick;
};

class ResourceManager {
 public:
  virtual ~ResourceManager() = default;
  virtual std::string name() const = 0;
  // called every master tick, under the master lock
  virtual void tick(RmContext& ctx) = 0;
};

// The default RM: gang scheduling over registered dct-agents
// (scheduler.cc + topology.cc + provisioner.cc stay the implementation).
class AgentRM : public ResourceManager {
 public:
  std::string name() const override { return "agent"; }
  void tick(RmContext& ctx) override { ctx.agent_tick(ctx.now); }
};

}  // namespace dct
