// TPU slice topology: shapes and contiguous sub-slice reservation.
//
// SURVEY §7 names topology-aware gang fitting a hard part of the
// TPU-native design; the reference's fitting is flat slot counts
// (master/internal/rm/agentrm/fitting.go:71). Here an agent's slice is a
// 2-D ICI torus (v5e-8 = 2x4, v5e-16 = 4x4, ...) and a sub-slice
// reservation must be a contiguous rectangle — a gang scattered over
// non-adjacent chips would put its collectives on degraded paths. The
// consequence the scheduler must honor: n free chips do NOT imply an
// n-chip gang fits (fragmentation), and non-rectangular counts (e.g. 5 on
// a 2x4) never fit a sub-slice.
#pragma once

#include <string>
#include <vector>

namespace dct {

struct SliceShape {
  std::string gen;  // "v5e", "v4", ... ("" = unknown/flat)
  int rows = 1;
  int cols = 1;
  int chips() const { return rows * cols; }
};

// "v5e-8" -> {v5e, 2, 4}. Chip counts map to the standard near-square
// slice shapes (8 -> 2x4, 16 -> 4x4, 32 -> 4x8). Unparseable topologies
// (e.g. "cpu", "") become a flat 1 x slots_hint row — every reservation
// contiguous, the pre-topology behavior.
SliceShape parse_topology(const std::string& topo, int slots_hint = 1);

// True when a slice of shape `req` fits inside an agent slice `have`:
// generations must match exactly (unknown is NOT a wildcard) and the
// rectangle must fit in either orientation.
bool shape_fits(const SliceShape& req, const SliceShape& have);

// One agent's chip grid with rectangle reservations.
class ChipGrid {
 public:
  explicit ChipGrid(SliceShape shape);

  // Reserve n chips as one contiguous free rectangle (squarest candidate
  // first — better bisection for the gang's collectives). False when no
  // free rectangle of area n exists, even if n chips are free.
  bool place(int n, const std::string& owner);
  bool can_place(int n) const;
  // Reserve a specific sub-slice shape (topology-requesting gangs).
  bool place_shape(const SliceShape& req, const std::string& owner);
  bool can_place_shape(const SliceShape& req) const;
  // Count-based fallback for replaying persisted reservations that no
  // longer fit a rectangle (state drift): marks the first n free cells.
  void force_place(int n, const std::string& owner);
  void release(const std::string& owner);

  int free_chips() const;
  const SliceShape& shape() const { return shape_; }

 private:
  struct Rect {
    int r0, c0, r, c;
  };
  bool rect_free(int r0, int c0, int r, int c) const;
  void mark(const Rect& rect, const std::string& owner);
  // const searches; place() marks the found rectangle
  bool find_rect(int area, Rect* out) const;
  bool find_shape(const SliceShape& req, Rect* out) const;

  SliceShape shape_;
  std::vector<std::string> owner_;  // rows*cols cells; "" = free
};

}  // namespace dct
