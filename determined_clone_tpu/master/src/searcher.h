// Hyperparameter search inside the master.
//
// C++ home of the search engine (reference: Go master/pkg/searcher — per the
// native-component checklist, SURVEY.md §2.9 it belongs in the master, not
// the Python harness). Protocol identical to the Python engine
// (determined_clone_tpu/searcher/base.py): methods emit Create /
// ValidateAfter / Close / Shutdown operations; state snapshots to JSON.
// Methods: single, random, grid, ASHA (promote + stopping variants),
// adaptive ASHA (bracket tournament).
#pragma once

#include <memory>
#include <optional>
#include <random>
#include <vector>

#include "json.h"

namespace dct {

struct SearchOp {
  enum class Kind { Create, ValidateAfter, Close, Shutdown } kind;
  int64_t request_id = -1;   // Create: -1 = engine assigns
  Json hparams;              // Create
  int64_t units = 0;         // ValidateAfter: cumulative target
  bool failure = false;      // Shutdown

  static SearchOp create(Json hparams) {
    return {Kind::Create, -1, std::move(hparams), 0, false};
  }
  static SearchOp validate_after(int64_t rid, int64_t units) {
    return {Kind::ValidateAfter, rid, Json(), units, false};
  }
  static SearchOp close(int64_t rid) {
    return {Kind::Close, rid, Json(), 0, false};
  }
  static SearchOp shutdown(bool failure = false) {
    return {Kind::Shutdown, -1, Json(), 0, failure};
  }
};

// Samples one assignment from an hparam-space JSON
// (same union as config/hyperparameters.py: const/int/double/log/categorical,
// nested objects; bare values are consts).
Json sample_hparams(const Json& space, std::mt19937_64& rng);
// Full cartesian grid (throws std::runtime_error if a double/log hparam
// lacks "count").
std::vector<Json> grid_hparams(const Json& space);

class SearchMethodCpp {
 public:
  virtual ~SearchMethodCpp() = default;
  virtual std::vector<SearchOp> initial_operations() = 0;
  virtual std::vector<SearchOp> on_trial_created(int64_t rid) = 0;
  virtual std::vector<SearchOp> on_validation_completed(
      int64_t rid, double metric, int64_t units) = 0;
  virtual std::vector<SearchOp> on_trial_exited_early(int64_t rid) = 0;
  virtual double progress() const = 0;
  virtual Json snapshot() const = 0;
  virtual void restore(const Json& snap) = 0;
};

// Factory from the searcher config JSON (name/metric/max_trials/max_length/
// divisor/num_rungs/mode/...). Throws std::runtime_error on unknown name.
std::unique_ptr<SearchMethodCpp> build_search_method(
    const Json& searcher_config, const Json& hparam_space, uint64_t seed);

}  // namespace dct
