// Hyperparameter search inside the master.
//
// C++ home of the search engine (reference: Go master/pkg/searcher — per the
// native-component checklist, SURVEY.md §2.9 it belongs in the master, not
// the Python harness). Protocol identical to the Python engine
// (determined_clone_tpu/searcher/base.py): methods emit Create /
// ValidateAfter / Close / Shutdown operations; state snapshots to JSON.
// Methods: single, random, grid, ASHA (promote + stopping variants),
// adaptive ASHA (bracket tournament).
#pragma once

#include <memory>
#include <optional>
#include <random>
#include <vector>

#include "json.h"

namespace dct {

struct SearchOp {
  enum class Kind { Create, ValidateAfter, Close, Shutdown } kind;
  int64_t request_id = -1;   // Create: -1 = engine assigns
  Json hparams;              // Create
  int64_t units = 0;         // ValidateAfter: cumulative target
  bool failure = false;      // Shutdown → Errored
  bool cancel = false;       // Shutdown → Canceled (failure wins)

  static SearchOp create(Json hparams) {
    return {Kind::Create, -1, std::move(hparams), 0, false, false};
  }
  static SearchOp validate_after(int64_t rid, int64_t units) {
    return {Kind::ValidateAfter, rid, Json(), units, false, false};
  }
  static SearchOp close(int64_t rid) {
    return {Kind::Close, rid, Json(), 0, false, false};
  }
  static SearchOp shutdown(bool failure = false, bool cancel = false) {
    return {Kind::Shutdown, -1, Json(), 0, failure, cancel};
  }
};

// Samples one assignment from an hparam-space JSON
// (same union as config/hyperparameters.py: const/int/double/log/categorical,
// nested objects; bare values are consts).
Json sample_hparams(const Json& space, std::mt19937_64& rng);
// Full cartesian grid (throws std::runtime_error if a double/log hparam
// lacks "count").
std::vector<Json> grid_hparams(const Json& space);

class SearchMethodCpp {
 public:
  virtual ~SearchMethodCpp() = default;
  virtual std::vector<SearchOp> initial_operations() = 0;
  virtual std::vector<SearchOp> on_trial_created(int64_t rid) = 0;
  virtual std::vector<SearchOp> on_validation_completed(
      int64_t rid, double metric, int64_t units) = 0;
  virtual std::vector<SearchOp> on_trial_exited_early(int64_t rid) = 0;
  // a trial reached Completed via a Close op (custom search records it;
  // built-ins drive closes themselves, so the default is a no-op)
  virtual std::vector<SearchOp> on_trial_closed(int64_t) { return {}; }
  virtual double progress() const = 0;
  virtual Json snapshot() const = 0;
  virtual void restore(const Json& snap) = 0;
};

// Custom search (≈ master/pkg/searcher/custom_search.go:15-23): the method
// lives OUTSIDE the master — a user process running a Python SearchMethod —
// and talks to the experiment through an event queue. Each lifecycle
// callback appends an event (and returns no operations); the remote runner
// polls GET /api/v1/experiments/<id>/searcher/events and posts operations
// back via POST .../searcher/operations, which the orchestrator applies
// exactly like built-in method output.
class CustomSearchCpp : public SearchMethodCpp {
 public:
  std::vector<SearchOp> initial_operations() override;
  std::vector<SearchOp> on_trial_created(int64_t rid) override;
  std::vector<SearchOp> on_validation_completed(int64_t rid, double metric,
                                                int64_t units) override;
  std::vector<SearchOp> on_trial_exited_early(int64_t rid) override;
  std::vector<SearchOp> on_trial_closed(int64_t rid) override;
  double progress() const override { return progress_; }
  Json snapshot() const override;
  void restore(const Json& snap) override;

  // events with id > since, oldest first (the runner's poll cursor)
  Json events_after(int64_t since) const;
  void set_progress(double p) { progress_ = p; }
  // drop events with id <= up_to. Opt-in (the runner must persist its own
  // state to still resume): bounds the log/snapshot for long searches.
  void trim_events(int64_t up_to);

 private:
  void record(const std::string& type, Json data);
  std::vector<Json> events_;   // each: {"id", "type", ...payload}
  int64_t next_event_id_ = 1;
  double progress_ = 0.0;
};

// Factory from the searcher config JSON (name/metric/max_trials/max_length/
// divisor/num_rungs/mode/...). Throws std::runtime_error on unknown name.
std::unique_ptr<SearchMethodCpp> build_search_method(
    const Json& searcher_config, const Json& hparam_space, uint64_t seed);

}  // namespace dct
