// Kubernetes resource manager: allocations become TPU pods.
//
// ≈ the reference kubernetesrm (master/internal/rm/kubernetesrm/pods.go:240
// StartTaskPod / ReattachAllocationPods, spec.go pod-spec build,
// informer.go state tracking), redesigned for GKE TPU node pools: each gang
// member is one pod requesting `google.com/tpu` chips with the GKE TPU
// nodeSelectors, scheduling itself is delegated to the k8s scheduler, and
// pod phases drive allocation state. The kubectl interaction sits behind a
// seam (like the provisioner's gcloud seam): a dry-run runner backed by a
// JSON state file for tests, and a live runner that shells out to kubectl.
#pragma once

#include <condition_variable>
#include <functional>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "rm.h"

namespace dct {

struct KubePodStatus {
  std::string name;
  std::string alloc_id;   // dct-alloc label
  int rank = 0;           // dct-rank label
  std::string phase;      // Pending | Running | Succeeded | Failed
  std::string ip;
  int exit_code = 0;
};

// The kubectl seam. Only three verbs are needed: apply a pod manifest,
// list managed pods, delete an allocation's pods.
class KubectlRunner {
 public:
  virtual ~KubectlRunner() = default;
  virtual bool apply(const Json& manifest) = 0;
  virtual std::vector<KubePodStatus> list_pods() = 0;
  virtual bool delete_alloc(const std::string& alloc_id) = 0;
  // false until the runner has a usable view of the cluster (async runner:
  // first poll not yet completed); the RM skips its tick meanwhile
  virtual bool ready() { return true; }
};

struct KubeRmConfig {
  std::string ns = "default";
  std::string image = "determined-clone-tpu:latest";
  // address pods use to reach the master (a Service name on a real
  // cluster; 127.0.0.1 in tests)
  std::string master_host = "dct-master";
  int master_port = 8080;
  int slots_per_pod = 8;  // chips per TPU-VM host (v5e-8 host)
  std::string accelerator = "tpu-v5-lite-podslice";  // GKE accelerator label
  // dry-run: pod state lives in <state_dir>/pods.json; tests play kubelet
  // by editing phases. Empty state_dir + dry_run=false = real kubectl.
  bool dry_run = true;
  std::string state_dir = "kube_state";
};

// Dry-run runner: manifests and phases persist in <state_dir>/pods.json.
class DryRunKubectl : public KubectlRunner {
 public:
  explicit DryRunKubectl(std::string state_dir);
  bool apply(const Json& manifest) override;
  std::vector<KubePodStatus> list_pods() override;
  bool delete_alloc(const std::string& alloc_id) override;

 private:
  Json load();
  void store(const Json& pods);
  std::string path_;
};

// Live runner: shells out to kubectl (apply -f -, get -o json, delete -l).
// BLOCKING — wrap in AsyncKubectl so subprocess latency never runs under
// the master lock.
class LiveKubectl : public KubectlRunner {
 public:
  explicit LiveKubectl(std::string ns) : ns_(std::move(ns)) {}
  bool apply(const Json& manifest) override;
  std::vector<KubePodStatus> list_pods() override;
  bool delete_alloc(const std::string& alloc_id) override;

 private:
  std::string ns_;
};

// Decouples the master tick from kubectl latency (≈ the reference's
// request_queue.go worker pool + informer cache): apply/delete enqueue onto
// a worker thread, list_pods returns the poller's latest snapshot. Applied
// pods are echoed into the snapshot immediately so the RM never sees its
// own submission as "pods vanished".
class AsyncKubectl : public KubectlRunner {
 public:
  explicit AsyncKubectl(std::unique_ptr<KubectlRunner> inner,
                        double poll_interval_sec = 1.0);
  ~AsyncKubectl() override;
  bool apply(const Json& manifest) override;
  std::vector<KubePodStatus> list_pods() override;
  bool delete_alloc(const std::string& alloc_id) override;
  bool ready() override;

 private:
  void loop();
  std::unique_ptr<KubectlRunner> inner_;
  double interval_;
  std::mutex mu_;
  std::condition_variable cv_;
  bool stop_ = false;
  bool have_snapshot_ = false;
  std::vector<std::function<void()>> queue_;  // runs on the worker thread
  std::vector<KubePodStatus> snapshot_;
  std::thread worker_;
};

class KubernetesRM : public ResourceManager {
 public:
  KubernetesRM(KubeRmConfig config, std::unique_ptr<KubectlRunner> runner);
  std::string name() const override { return "kubernetes"; }
  void tick(RmContext& ctx) override;

  // exposed for unit tests
  Json pod_manifest(const Allocation& alloc, const Json& start_cmd, int rank,
                    int world, int pod_slots) const;

 private:
  KubeRmConfig config_;
  std::unique_ptr<KubectlRunner> runner_;
};

}  // namespace dct
