// REST API routes — the master's public surface.
//
// Covers the workhorse subset of the reference's 217-RPC service
// (proto/src/determined/api/v1/api.proto:79): experiments, trials, metrics,
// searcher ops, checkpoints, agents, allocations (rendezvous/preemption),
// task logs, job queue, master info.
#include <sys/socket.h>
#include <sys/stat.h>
#include <unistd.h>

#include <algorithm>
#include <cctype>
#include <chrono>
#include <cmath>
#include <fstream>
#include <regex>
#include <set>
#include <sstream>

#include "crypto.h"
#include "master.h"

namespace dct {
namespace {

Json error_json(const std::string& msg) {
  Json j = Json::object();
  j.set("error", msg);
  return j;
}

HttpResponse ok_json(const Json& j) { return HttpResponse::json(200, j.dump()); }
HttpResponse bad_request(const std::string& msg) {
  return HttpResponse::json(400, error_json(msg).dump());
}
HttpResponse not_found(const std::string& msg) {
  return HttpResponse::json(404, error_json(msg).dump());
}

// parses a non-negative integer query param; false = malformed (caller 400s)
bool parse_size(const std::map<std::string, std::string>& query,
                const char* key, size_t* out) {
  auto it = query.find(key);
  if (it == query.end()) return true;  // absent: keep caller default
  try {
    long long v = std::stoll(it->second);
    if (v < 0) return false;
    *out = static_cast<size_t>(v);
  } catch (const std::exception&) {
    return false;
  }
  return true;
}

std::string url_encode(const std::string& s) {
  static const char* hex = "0123456789ABCDEF";
  std::string out;
  for (unsigned char c : s) {
    if (std::isalnum(c) || c == '-' || c == '_' || c == '.' || c == '~') {
      out += static_cast<char>(c);
    } else {
      out += '%';
      out += hex[c >> 4];
      out += hex[c & 0xF];
    }
  }
  return out;
}

// -- Prometheus exposition helpers, format-compatible with the Python
// registry (telemetry/metrics.py): parse_prometheus_text must round-trip
// this output byte-for-byte in meaning, so escaping and number rendering
// mirror _escape_label_value / _escape_help / _fmt exactly.

std::string prom_escape_label(const std::string& v) {
  std::string out;
  out.reserve(v.size());
  for (char c : v) {
    if (c == '\\') out += "\\\\";
    else if (c == '"') out += "\\\"";
    else if (c == '\n') out += "\\n";
    else out += c;
  }
  return out;
}

std::string prom_escape_help(const std::string& v) {
  std::string out;
  out.reserve(v.size());
  for (char c : v) {
    if (c == '\\') out += "\\\\";
    else if (c == '\n') out += "\\n";
    else out += c;
  }
  return out;
}

// _fmt: NaN -> "NaN"; integral magnitudes under 1e15 print as integers;
// everything else prints as the shortest decimal that round-trips
std::string prom_fmt(double v) {
  if (std::isnan(v)) return "NaN";
  if (std::isfinite(v) && std::fabs(v) < 1e15 && v == std::floor(v)) {
    return std::to_string(static_cast<long long>(v));
  }
  for (int prec = 1; prec <= 17; ++prec) {
    std::ostringstream s;
    s.precision(prec);
    s << v;
    try {
      if (std::stod(s.str()) == v) return s.str();
    } catch (const std::exception&) {
      break;
    }
  }
  std::ostringstream s;
  s.precision(17);
  s << v;
  return s.str();
}

// one summary family (quantile children + _sum/_count), matching
// Histogram.sample_lines' layout and quantile set
void prom_summary(std::ostringstream& out, const std::string& name,
                  const std::string& help, const SchedReservoir& r) {
  out << "# HELP " << name << " " << prom_escape_help(help) << "\n"
      << "# TYPE " << name << " summary\n";
  const double qs[] = {0.5, 0.95, 0.99};
  const char* qlabels[] = {"0.5", "0.95", "0.99"};
  for (int i = 0; i < 3; ++i) {
    out << name << "{quantile=\"" << qlabels[i] << "\"} "
        << prom_fmt(r.percentile(qs[i])) << "\n";
  }
  out << name << "_sum " << prom_fmt(r.sum()) << "\n";
  out << name << "_count " << r.count() << "\n";
}

}  // namespace

HttpResponse Master::handle(const HttpRequest& req) {
  // every request is traced (≈ otel middleware around the echo server,
  // core.go:1014): duration + status recorded under trace_mu_, never the
  // state lock
  auto t0 = std::chrono::steady_clock::now();
  HttpResponse resp;
  try {
    if (req.path_parts.size() >= 2 && req.path_parts[0] == "proxy") {
      resp = proxy_route(req);
    } else if (req.path_parts.size() == 1 && req.path_parts[0] == "metrics" &&
               req.method == "GET") {
      resp = metrics_route();
    } else if (!req.path_parts.empty() && req.path_parts[0] == "debug" &&
               req.method == "GET") {
      // operator surface: spans carry request paths (experiment/trial
      // ids), so it sits behind the session gate like the API roots
      bool authed = true;
      if (config_.auth_required) {
        std::lock_guard<std::mutex> lock(mu_);
        authed = current_user(req) != nullptr;
      }
      resp = authed ? debug_route(req)
                    : HttpResponse::json(
                          401, error_json("authentication required").dump());
    } else if (req.method == "GET" && !config_.webui_dir.empty() &&
               (req.path == "/" ||
                (!req.path_parts.empty() && req.path_parts[0] == "ui"))) {
      resp = static_route(req);
    } else if (req.method == "GET" &&
               req.path == "/api/v1/auth/sso/callback") {
      // the IdP token exchange blocks on an outbound request — it manages
      // its own locking instead of running under route()'s state lock
      resp = sso_callback_route(req);
    } else if (req.method == "GET" && req.path_parts.size() == 5 &&
               req.path_parts[0] == "api" && req.path_parts[1] == "v1" &&
               req.path_parts[2] == "allocations" &&
               req.path_parts[4] == "logs" && req.query.count("follow")) {
      // follow mode long-polls on logs_cv_; it manages its own locking
      // (the connection has a dedicated thread, so waiting here is safe)
      resp = logs_follow_route(req);
    } else {
      resp = route(req);
    }
  } catch (const std::exception& e) {
    resp = HttpResponse::json(500, error_json(e.what()).dump());
  }
  double dur_ms = std::chrono::duration<double, std::milli>(
                      std::chrono::steady_clock::now() - t0).count();
  record_span(req, resp.status, dur_ms);
  return resp;
}

namespace {

// normalize a path into a route key: id-ish segments become ':id' so
// /api/v1/experiments/17 and /23 aggregate together
std::string route_key(const HttpRequest& req) {
  std::string out = req.method;
  for (const auto& part : req.path_parts) {
    bool id_like = !part.empty() &&
                   part.find_first_not_of("0123456789") == std::string::npos;
    // allocation/task ids: "trial-3.0", "task-command-7", "unmanaged-9.1"
    id_like = id_like || part.find('.') != std::string::npos ||
              (part.find('-') != std::string::npos &&
               part.find_first_of("0123456789") != std::string::npos);
    out += "/" + (id_like ? std::string(":id") : part);
  }
  return out;
}

}  // namespace

void Master::record_span(const HttpRequest& req, int status, double dur_ms) {
  constexpr size_t kRecentCap = 256, kSampleCap = 512, kRouteCap = 256;
  std::lock_guard<std::mutex> lock(trace_mu_);
  Span span;
  span.at = now_sec();
  span.dur_ms = dur_ms;
  span.status = status;
  span.method = req.method;
  span.path = req.path;
  span.route = route_key(req);
  // bound the per-route table: unauthenticated scanners probing arbitrary
  // paths must not grow master memory one RouteStats per unique path
  if (!route_stats_.count(span.route) && route_stats_.size() >= kRouteCap) {
    span.route = "OTHER";
  }
  recent_spans_.push_back(std::move(span));
  if (recent_spans_.size() > kRecentCap) recent_spans_.pop_front();
  RouteStats& stats = route_stats_[recent_spans_.back().route];
  stats.count++;
  if (status >= 500) stats.errors++;
  stats.total_ms += dur_ms;
  stats.max_ms = std::max(stats.max_ms, dur_ms);
  if (stats.samples.size() < kSampleCap) {
    stats.samples.push_back(dur_ms);
  } else {
    stats.samples[stats.next_sample] = dur_ms;
    stats.next_sample = (stats.next_sample + 1) % kSampleCap;
  }
}

HttpResponse Master::debug_route(const HttpRequest& req) {
  const std::string& what = req.path_parts.size() > 1 ? req.path_parts[1] : "";
  std::lock_guard<std::mutex> lock(trace_mu_);
  if (what == "requests") {
    Json arr = Json::array();
    for (const auto& s : recent_spans_) {
      Json j = Json::object();
      j.set("at", s.at).set("duration_ms", s.dur_ms)
          .set("status", static_cast<int64_t>(s.status))
          .set("method", s.method).set("path", s.path)
          .set("route", s.route);
      arr.push_back(j);
    }
    Json out = Json::object();
    out.set("requests", arr);
    return ok_json(out);
  }
  if (what == "stats") {
    Json arr = Json::array();
    for (const auto& [route, stats] : route_stats_) {
      std::vector<double> sorted = stats.samples;
      std::sort(sorted.begin(), sorted.end());
      double p95 = sorted.empty()
                       ? 0
                       : sorted[static_cast<size_t>(
                             (sorted.size() - 1) * 0.95)];
      Json j = Json::object();
      j.set("route", route).set("count", stats.count)
          .set("errors", stats.errors)
          .set("mean_ms", stats.count ? stats.total_ms / stats.count : 0)
          .set("p95_ms", p95).set("max_ms", stats.max_ms);
      arr.push_back(j);
    }
    Json out = Json::object();
    out.set("routes", arr);
    return ok_json(out);
  }
  return not_found("unknown debug route (requests|stats)");
}

// Prometheus text exposition (≈ the reference's /prom/det-state-metrics
// endpoints, master/internal/core.go:1203 + internal/prom/)
HttpResponse Master::metrics_route() {
  std::lock_guard<std::mutex> lock(mu_);
  std::map<std::string, int> exp_states, trial_states, alloc_states;
  for (const auto& [id, e] : experiments_) exp_states[to_string(e.state)]++;
  for (const auto& [id, t] : trials_) trial_states[to_string(t.state)]++;
  int queue_depth = 0, slots_total = 0, slots_used = 0, agents_alive = 0;
  for (const auto& [id, a] : allocations_) {
    alloc_states[to_string(a.state)]++;
    if (a.state == RunState::Queued) queue_depth++;
    if (a.state == RunState::Running || a.state == RunState::Pulling) {
      for (const auto& [aid, n] : a.reservations) slots_used += n;
    }
  }
  for (const auto& [id, a] : agents_) {
    if (a.enabled) {
      agents_alive++;
      slots_total += a.slots;
    }
  }
  std::ostringstream out;
  auto gauge = [&](const std::string& name, const std::string& help) {
    out << "# HELP " << name << " " << help << "\n"
        << "# TYPE " << name << " gauge\n";
  };
  gauge("dct_experiments", "experiments by state");
  for (const auto& [state, n] : exp_states) {
    out << "dct_experiments{state=\"" << state << "\"} " << n << "\n";
  }
  gauge("dct_trials", "trials by state");
  for (const auto& [state, n] : trial_states) {
    out << "dct_trials{state=\"" << state << "\"} " << n << "\n";
  }
  gauge("dct_allocations", "allocations by state");
  for (const auto& [state, n] : alloc_states) {
    out << "dct_allocations{state=\"" << state << "\"} " << n << "\n";
  }
  gauge("dct_agents_alive", "enabled agents");
  out << "dct_agents_alive " << agents_alive << "\n";
  gauge("dct_slots_total", "slots on enabled agents");
  out << "dct_slots_total " << slots_total << "\n";
  gauge("dct_slots_used", "slots reserved by live allocations");
  out << "dct_slots_used " << slots_used << "\n";
  gauge("dct_queue_depth", "queued allocations");
  out << "dct_queue_depth " << queue_depth << "\n";

  // -- control-plane scheduler families (docs/observability.md): lifecycle
  // counters, decision-loop stats, and latency summaries in the Python
  // registry's exact exposition format --
  auto counter = [&](const std::string& name, const std::string& help,
                     int64_t v) {
    out << "# HELP " << name << " " << prom_escape_help(help) << "\n"
        << "# TYPE " << name << " counter\n"
        << name << " " << v << "\n";
  };
  counter("dct_master_sched_submitted_total",
          "allocations entering the queue", sched_.submitted_total);
  counter("dct_master_sched_scheduled_total",
          "allocations granted reservations", sched_.scheduled_total);
  counter("dct_master_sched_running_total",
          "allocations confirmed running by the harness",
          sched_.running_total);
  counter("dct_master_sched_completed_total",
          "allocations reaching a terminal state", sched_.completed_total);
  counter("dct_master_sched_preemptions_total",
          "preempt requests issued", sched_.preemptions_total);
  counter("dct_master_sched_reschedules_total",
          "requeues and operator queue reshuffles",
          sched_.reschedules_total);
  counter("dct_master_sched_queue_moves_total",
          "job-queue move-ahead/behind operations",
          sched_.queue_moves_total);
  counter("dct_master_sched_priority_changes_total",
          "job-queue reprioritize operations",
          sched_.priority_changes_total);
  counter("dct_master_sched_decisions_total",
          "scheduling passes (schedule_pool calls)",
          sched_.decisions_total);
  counter("dct_master_sched_considered_total",
          "pending allocations examined across passes",
          sched_.considered_total);
  counter("dct_master_sched_gangs_admitted_total",
          "multi-agent/multislice gang admissions",
          sched_.gangs_admitted_total);
  counter("dct_master_sched_gang_wait_ticks_total",
          "allocation-passes spent waiting for a gang fit",
          sched_.gang_wait_ticks_total);
  // -- serving-fleet families (docs/serving.md): replica gang lifecycle
  // counters plus live/desired gauges labeled by fleet --
  counter("dct_master_sched_serving_submitted_total",
          "serving replica allocations created",
          sched_.serving_submitted_total);
  counter("dct_master_sched_serving_running_total",
          "serving replicas confirmed running",
          sched_.serving_running_total);
  counter("dct_master_sched_serving_completed_total",
          "serving replicas reaching a terminal state",
          sched_.serving_completed_total);
  std::map<std::string, int> fleet_live;
  for (const auto& [id, a] : allocations_) {
    if (a.task_type == "serving" &&
        (a.state == RunState::Running || a.state == RunState::Pulling)) {
      fleet_live[a.fleet]++;
    }
  }
  gauge("dct_master_sched_serving_replicas",
        "live serving replicas by fleet");
  for (const auto& [fleet, n] : fleet_live) {
    out << "dct_master_sched_serving_replicas{fleet=\""
        << prom_escape_label(fleet) << "\"} " << n << "\n";
  }
  gauge("dct_master_sched_serving_replicas_desired",
        "desired serving replicas by fleet");
  for (const auto& [name, fleet] : fleets_) {
    out << "dct_master_sched_serving_replicas_desired{fleet=\""
        << prom_escape_label(name) << "\"} " << fleet.desired << "\n";
  }
  // per-pool queue depth + gang-wait gauges; pool names are user input, so
  // label values go through the Python-compatible escaper
  std::map<std::string, int> pool_depth;
  for (const auto& [id, a] : allocations_) {
    if (a.state == RunState::Queued) pool_depth[a.resource_pool]++;
  }
  gauge("dct_master_sched_queue_depth", "queued allocations by pool");
  for (const auto& [pool, n] : pool_depth) {
    out << "dct_master_sched_queue_depth{pool=\"" << prom_escape_label(pool)
        << "\"} " << n << "\n";
  }
  gauge("dct_master_sched_gang_waiting",
        "slot-requesting allocations with no fit on the last pass, by pool");
  for (const auto& [pool, n] : sched_.gang_waiting_by_pool) {
    out << "dct_master_sched_gang_waiting{pool=\"" << prom_escape_label(pool)
        << "\"} " << n << "\n";
  }
  prom_summary(out, "dct_master_sched_decision_seconds",
               "wall time of one schedule_pool pass",
               sched_.decision_seconds);
  prom_summary(out, "dct_master_sched_queue_wait_seconds",
               "queued to scheduled latency", sched_.queue_wait_seconds);
  prom_summary(out, "dct_master_sched_submit_to_running_seconds",
               "submitted to running latency",
               sched_.submit_to_running_seconds);
  HttpResponse resp;
  resp.status = 200;
  resp.content_type = "text/plain; version=0.0.4";
  resp.body = out.str();
  return resp;
}

namespace {

// JSON view of one latency reservoir (quantiles omitted while empty so
// consumers can distinguish "no data" from "zero latency")
Json sched_latency_json(const SchedReservoir& r) {
  Json j = Json::object();
  j.set("count", r.count());
  j.set("sum", r.sum());
  if (r.count() > 0) {
    j.set("p50", r.percentile(0.5));
    j.set("p95", r.percentile(0.95));
    j.set("p99", r.percentile(0.99));
  }
  return j;
}

// one master-lane span record in the shape Telemetry.publish ships trial
// spans (chrome_trace.py stitches on process/wall_epoch/ts_us/dur_us)
Json master_span_json(const std::string& name, double start_epoch,
                      double dur_us, const std::string& tname) {
  Json rec = Json::object();
  rec.set("group", "span").set("process", "master").set("name", name)
      .set("wall_epoch", start_epoch).set("ts_us", 0.0)
      .set("dur_us", dur_us).set("tid", static_cast<int64_t>(1))
      .set("tname", tname);
  return rec;
}

}  // namespace

// GET /api/v1/cluster/scheduler — the JSON twin of the dct_master_sched_*
// Prometheus families (caller holds mu_)
Json Master::sched_summary_locked() {
  Json counters = Json::object();
  counters.set("submitted", sched_.submitted_total)
      .set("scheduled", sched_.scheduled_total)
      .set("running", sched_.running_total)
      .set("completed", sched_.completed_total)
      .set("preemptions", sched_.preemptions_total)
      .set("reschedules", sched_.reschedules_total)
      .set("queue_moves", sched_.queue_moves_total)
      .set("priority_changes", sched_.priority_changes_total)
      .set("decisions", sched_.decisions_total)
      .set("considered", sched_.considered_total)
      .set("gangs_admitted", sched_.gangs_admitted_total)
      .set("gang_wait_ticks", sched_.gang_wait_ticks_total)
      .set("serving_submitted", sched_.serving_submitted_total)
      .set("serving_running", sched_.serving_running_total)
      .set("serving_completed", sched_.serving_completed_total);
  Json depth_by_pool = Json::object();
  int64_t queue_depth = 0;
  std::map<std::string, int64_t> pool_depth;
  for (const auto& [id, a] : allocations_) {
    if (a.state == RunState::Queued) {
      ++pool_depth[a.resource_pool];
      ++queue_depth;
    }
  }
  for (const auto& [pool, n] : pool_depth) depth_by_pool.set(pool, n);
  Json gang_by_pool = Json::object();
  int64_t gang_waiting = 0;
  for (const auto& [pool, n] : sched_.gang_waiting_by_pool) {
    gang_by_pool.set(pool, n);
    gang_waiting += n;
  }
  int64_t serving_live = 0;
  for (const auto& [id, a] : allocations_) {
    if (a.task_type == "serving" &&
        (a.state == RunState::Running || a.state == RunState::Pulling)) {
      ++serving_live;
    }
  }
  int64_t serving_desired = 0;
  for (const auto& [name, f] : fleets_) serving_desired += f.desired;
  Json gauges = Json::object();
  gauges.set("queue_depth", queue_depth)
      .set("queue_depth_by_pool", depth_by_pool)
      .set("gang_waiting", gang_waiting)
      .set("gang_waiting_by_pool", gang_by_pool)
      .set("serving_replicas_running", serving_live)
      .set("serving_replicas_desired", serving_desired);
  Json latency = Json::object();
  latency.set("decision_seconds", sched_latency_json(sched_.decision_seconds))
      .set("queue_wait_seconds",
           sched_latency_json(sched_.queue_wait_seconds))
      .set("submit_to_running_seconds",
           sched_latency_json(sched_.submit_to_running_seconds));
  Json j = Json::object();
  j.set("counters", counters).set("gauges", gauges).set("latency", latency)
      .set("events_dropped", sched_.events_dropped)
      .set("time", now_sec());
  return j;
}

// GET /api/v1/cluster/scheduler/events — the bounded master-lane event
// ring as Chrome-trace-ready span samples (caller holds mu_)
Json Master::sched_events_locked() {
  Json samples = Json::array();
  for (const auto& ev : sched_.events) {
    Json rec = master_span_json(ev.name, ev.wall_epoch, ev.dur_us,
                                "scheduler");
    if (ev.trial_id) rec.set("trial_id", ev.trial_id);
    Json args = Json::object();
    if (!ev.alloc_id.empty()) args.set("allocation_id", ev.alloc_id);
    if (ev.experiment_id) args.set("experiment_id", ev.experiment_id);
    if (!ev.pool.empty()) args.set("pool", ev.pool);
    rec.set("args", args);
    samples.push_back(rec);
  }
  Json j = Json::object();
  j.set("samples", samples).set("dropped", sched_.events_dropped);
  return j;
}

// GET /api/v1/experiments/:id/trace — every trial's shipped span samples
// plus a synthesized master lane (submit→schedule→run per allocation,
// anchored on the lifecycle timestamps so ring eviction cannot lose an
// old experiment's lane). Caller holds mu_.
HttpResponse Master::experiment_trace_locked(int64_t exp_id) {
  Json samples = Json::array();
  double now = now_sec();
  for (const auto& [tid, trial] : trials_) {
    if (trial.experiment_id != exp_id) continue;
    // the trial lane: span-group profiler samples the harness shipped
    std::string trace_id;
    for (const auto& rec : read_jsonl_tail(
             "trial-" + std::to_string(tid) + "-profiler.jsonl", 5000)) {
      if (rec["group"].as_string() != "span") continue;
      Json out = rec;
      if (!out.has("trial_id")) out.set("trial_id", tid);
      if (trace_id.empty()) trace_id = rec["trace_id"].as_string();
      samples.push_back(out);
    }
    // the master lane: one submit→schedule→run triplet per allocation leg,
    // carrying the trial's trace_id (the DCT_TRACE_ID contract) so the
    // stitched trace ties both lanes to one identity
    for (const auto& [aid, alloc] : allocations_) {
      if (alloc.trial_id != tid) continue;
      double submitted = alloc.submitted_at > 0 ? alloc.submitted_at
                                                : alloc.queued_at;
      double scheduled = alloc.scheduled_at;
      double running = alloc.running_at;
      double ended = alloc.ended_at > 0 ? alloc.ended_at : now;
      struct Leg { const char* name; double start, end; };
      const Leg legs[] = {
          {"submit", submitted, scheduled > 0 ? scheduled : ended},
          {"schedule", scheduled, running > 0 ? running : ended},
          {"run", running, ended},
      };
      for (const auto& leg : legs) {
        if (leg.start <= 0) continue;
        double dur_us = leg.end > leg.start ? (leg.end - leg.start) * 1e6 : 0;
        Json rec = master_span_json(leg.name, leg.start, dur_us, "scheduler");
        rec.set("trial_id", tid);
        if (!trace_id.empty()) rec.set("trace_id", trace_id);
        Json args = Json::object();
        args.set("allocation_id", alloc.id)
            .set("experiment_id", exp_id)
            .set("pool", alloc.resource_pool);
        rec.set("args", args);
        samples.push_back(rec);
      }
    }
  }
  Json j = Json::object();
  j.set("samples", samples);
  return ok_json(j);
}

// WebUI static assets. The reference master embeds and serves the built
// React bundle (master/internal/core.go webui routes); here the master
// serves the dependency-free vanilla bundle from webui/ on disk.
HttpResponse Master::static_route(const HttpRequest& req) {
  std::string rel = "index.html";
  if (req.path != "/") {
    // "/ui/<file...>" — rebuild from decoded parts, skipping the "ui" root
    rel.clear();
    for (size_t i = 1; i < req.path_parts.size(); ++i) {
      if (!rel.empty()) rel += "/";
      rel += req.path_parts[i];
    }
  }
  // traversal guard: no "..", no absolute, no empty
  if (rel.empty() || rel[0] == '/' || rel.find("..") != std::string::npos) {
    return not_found("no asset " + req.path);
  }
  const std::string full = config_.webui_dir + "/" + rel;
  struct stat st {};
  if (::stat(full.c_str(), &st) != 0 || !S_ISREG(st.st_mode)) {
    return not_found("no asset " + req.path);  // directories are not assets
  }
  std::ifstream in(full, std::ios::binary);
  if (!in.good()) return not_found("no asset " + req.path);
  std::stringstream buf;
  buf << in.rdbuf();
  HttpResponse resp;
  resp.status = 200;
  resp.body = buf.str();
  auto dot = rel.rfind('.');
  const std::string ext = dot == std::string::npos ? "" : rel.substr(dot);
  if (ext == ".html") resp.content_type = "text/html; charset=utf-8";
  else if (ext == ".js") resp.content_type = "text/javascript";
  else if (ext == ".css") resp.content_type = "text/css";
  else if (ext == ".svg") resp.content_type = "image/svg+xml";
  else if (ext == ".json") resp.content_type = "application/json";
  else if (ext == ".png") resp.content_type = "image/png";
  else resp.content_type = "application/octet-stream";
  return resp;
}

// GET /api/v1/allocations/:id/logs?follow=N&offset=M — hold the request
// open until new records land past the cursor, the follow window expires,
// or the allocation reaches a terminal state (end_of_stream tells the
// client to stop re-polling). The reference streams TrialLogs over gRPC
// with a follow flag (api.proto:781); this is the long-poll equivalent,
// indexed by the store's record cursor rather than a tail rescan.
HttpResponse Master::logs_follow_route(const HttpRequest& req) {
  const std::string& alloc_id = req.path_parts[3];
  size_t limit = 1000, offset = 0, follow_s = 30;
  if (!parse_size(req.query, "limit", &limit) ||
      !parse_size(req.query, "offset", &offset) ||
      !parse_size(req.query, "follow", &follow_s)) {
    return bad_request("limit/offset/follow must be non-negative integers");
  }
  follow_s = std::min<size_t>(follow_s, 60);  // bound the held connection
  // thread budget (config max_log_followers): past the cap, degrade to an
  // immediate response instead of holding the connection thread — the
  // client's next poll retries, so tailing stays correct under a stampede
  // of WebUI tabs while the master keeps threads for everyone else
  struct FollowerSlot {
    std::atomic<int>& count;
    bool held;
    explicit FollowerSlot(std::atomic<int>& c, int cap) : count(c) {
      held = count.fetch_add(1) < cap;
      if (!held) count.fetch_sub(1);
    }
    ~FollowerSlot() {
      if (held) count.fetch_sub(1);
    }
  } slot(active_followers_, config_.max_log_followers);
  if (!slot.held) follow_s = 0;
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::seconds(follow_s);
  const std::string stream = "task-" + alloc_id + "-logs.jsonl";

  std::unique_lock<std::mutex> lock(mu_);
  {
    auto it = allocations_.find(alloc_id);
    if (it == allocations_.end()) {
      return not_found("no allocation " + alloc_id);
    }
    // same gate as the allocations block in route(): the stream carries
    // user log output, so a token or session is required under auth
    bool alloc_member =
        !it->second.token.empty() &&
        crypto::constant_time_eq(bearer_token(req), it->second.token);
    if (config_.auth_required && !alloc_member && !current_user(req)) {
      return HttpResponse::json(
          401, error_json("allocation token or session required").dump());
    }
  }
  uint64_t seen_version = 0;
  bool first = true;
  while (true) {
    // only touch the store when THIS stream changed (metrics/profiler
    // appends to other streams wake us too — skip the read then)
    std::vector<Json> recs;
    auto vit = stream_versions_.find(stream);
    uint64_t version = vit == stream_versions_.end() ? 0 : vit->second;
    if (first || version != seen_version) {
      recs = store_->read(stream, limit, offset);
      seen_version = version;
      first = false;
    }
    auto it = allocations_.find(alloc_id);  // may be reaped mid-follow
    bool terminal = it == allocations_.end() ||
                    it->second.state == RunState::Completed ||
                    it->second.state == RunState::Errored ||
                    it->second.state == RunState::Canceled;
    if (!recs.empty() || terminal || !running_ ||
        std::chrono::steady_clock::now() >= deadline) {
      Json arr = Json::array();
      for (auto& rec : recs) arr.push_back(rec);
      Json j = Json::object();
      j.set("logs", arr)
          .set("next_offset", static_cast<int64_t>(offset + recs.size()))
          // terminal with records still pending is NOT the end: the
          // client drains first and hears end_of_stream on its next call
          .set("end_of_stream", terminal && recs.empty());
      return ok_json(j);
    }
    logs_cv_.wait_until(lock, deadline);
  }
}

HttpResponse Master::proxy_route(const HttpRequest& req) {
  const std::string& alloc_id = req.path_parts[1];
  std::string address;
  std::string alloc_token;
  {
    std::lock_guard<std::mutex> lock(mu_);
    auto it = allocations_.find(alloc_id);
    if (it == allocations_.end()) return not_found("no allocation " + alloc_id);
    // the proxy fronts task servers whose /exec runs arbitrary argv — it is
    // part of the user-facing surface and must sit behind the same auth gate
    // as the API (a user session, or the allocation's own token for
    // task-to-task traffic)
    // empty tokens never match — a restored pre-token allocation must not
    // turn the empty Authorization header into a grant
    bool alloc_token_ok =
        !it->second.token.empty() &&
        crypto::constant_time_eq(bearer_token(req), it->second.token);
    if (config_.auth_required && !current_user(req) && !alloc_token_ok) {
      return HttpResponse::json(
          401, error_json("authentication required").dump());
    }
    if (it->second.proxy_address.empty()) {
      return HttpResponse::json(
          502, error_json("task has not registered a proxy address").dump());
    }
    address = it->second.proxy_address;
    alloc_token = it->second.token;
    it->second.last_activity = now_sec();
    dirty_ = true;  // persists activity across master restarts (idle watcher)
  }
  std::string host = address;
  int port = 80;
  auto colon = address.rfind(':');
  if (colon != std::string::npos) {
    host = address.substr(0, colon);
    try {
      port = std::stoi(address.substr(colon + 1));
    } catch (const std::exception&) {
      return HttpResponse::json(
          502, error_json("invalid proxy address " + address).dump());
    }
  }
  // re-encode: path_parts/query were url-decoded by the server (http.cc)
  std::string path;
  for (size_t i = 2; i < req.path_parts.size(); ++i) {
    path += "/" + url_encode(req.path_parts[i]);
  }
  if (path.empty()) path = "/";
  if (!req.query.empty()) {
    std::string qs;
    for (const auto& [k, v] : req.query) {
      qs += (qs.empty() ? "?" : "&") + url_encode(k) + "=" + url_encode(v);
    }
    path += qs;
  }
  // WebSocket (or any Connection: Upgrade) request: splice the two
  // sockets instead of request/response buffering. Real jupyter under
  // DCT_NOTEBOOK_REAL=1 needs this for kernel channels; interactive
  // shells get a live stream instead of request/response /exec.
  // (≈ master/internal/proxy/ws.go, tcp.go — same hijack-and-pump idea.)
  auto conn_hdr = req.headers.find("connection");
  auto upgrade_hdr = req.headers.find("upgrade");
  bool wants_upgrade = false;
  if (conn_hdr != req.headers.end() && upgrade_hdr != req.headers.end()) {
    std::string c = conn_hdr->second;
    for (auto& ch : c) ch = static_cast<char>(::tolower(ch));
    wants_upgrade = c.find("upgrade") != std::string::npos;
  }
  if (wants_upgrade) {
    int up_fd = tcp_connect(host, port, 10);
    if (up_fd < 0) {
      return HttpResponse::json(
          502, error_json("task at " + address + " unreachable").dump());
    }
    // replay the request head upstream: original headers minus hop/auth
    // ones (Host is rewritten; the session cookie/bearer must not reach
    // untrusted task code), plus the x-alloc-token the task server
    // expects from master-fronted traffic
    std::ostringstream head;
    head << req.method << ' ' << path << " HTTP/1.1\r\nHost: " << host
         << ':' << port;
    for (const auto& [k, v] : req.headers) {
      // x-alloc-token: the master injects the genuine one below — a
      // client-supplied copy would land first and win header parsing
      if (k == "host" || k == "authorization" || k == "cookie" ||
          k == "content-length" || k == "x-alloc-token") {
        continue;
      }
      head << "\r\n" << k << ": " << v;
    }
    head << "\r\nx-alloc-token: " << alloc_token << "\r\n\r\n" << req.body;
    if (!send_all_fd(up_fd, head.str())) {
      ::close(up_fd);
      return HttpResponse::json(
          502, error_json("task at " + address + " dropped the upgrade")
                   .dump());
    }
    HttpResponse out;
    out.hijack = [this, up_fd](int client_fd, std::string buffered) {
      // frames the client sent before the takeover must reach upstream —
      // fully: a partial send would desync the spliced WS framing
      if (!buffered.empty() && !send_all_fd(up_fd, buffered)) {
        ::close(up_fd);
        return;
      }
      // kernel sockets idle (recv) and stall (send backpressure) for
      // long stretches; neither is a dead connection
      timeval no_tv{0, 0};
      ::setsockopt(up_fd, SOL_SOCKET, SO_RCVTIMEO, &no_tv, sizeof(no_tv));
      ::setsockopt(up_fd, SOL_SOCKET, SO_SNDTIMEO, &no_tv, sizeof(no_tv));
      {
        std::lock_guard<std::mutex> rlock(relay_mu_);
        relay_fds_.insert(up_fd);  // stop() shuts these down
      }
      if (!running_) {
        // raced stop()'s sweep of relay_fds_: shut down ourselves or the
        // pump below blocks a worker join forever
        ::shutdown(up_fd, SHUT_RDWR);
      }
      relay_bidirectional(client_fd, up_fd);
      {
        std::lock_guard<std::mutex> rlock(relay_mu_);
        relay_fds_.erase(up_fd);
      }
      ::close(up_fd);
    };
    return out;
  }

  // inject the allocation token so the task server can reject traffic that
  // did not come through the master's authenticated proxy
  auto resp = http_request(host, port, req.method, path, req.body, 30,
                           {{"x-alloc-token", alloc_token}});
  if (!resp) {
    return HttpResponse::json(
        502, error_json("task at " + address + " unreachable").dump());
  }
  // pass the upstream response through untouched: content-type matters for
  // proxied HTML/JS (real jupyter under DCT_NOTEBOOK_REAL=1)
  HttpResponse out;
  out.status = resp->status;
  out.content_type = resp->content_type;
  out.body = resp->body;
  return out;
}

// Generic NTSC task surface, shared by /api/v1/tasks and the typed roots
// (/api/v1/{notebooks,shells,commands,tensorboards} — ≈ the reference's
// typed LaunchNotebook/LaunchShell/LaunchTensorboard families,
// api_notebook.go etc.). `forced_type` pins the task type ("" = generic:
// type from the body / query); `singular`/`plural` name the response keys.
HttpResponse Master::tasks_route(const HttpRequest& req,
                                 const std::string& forced_type,
                                 const char* singular, const char* plural) {
  const auto& parts = req.path_parts;
    if (parts.size() == 3 && req.method == "POST") {
      // rbac: NTSC tasks consume cluster slots like experiments do
      if (!rbac_allows(req, role_rank("Editor"))) {
        return HttpResponse::json(
            403, error_json("Editor role required to create tasks").dump());
      }
      Json body = Json::parse(req.body);
      std::string type = body["type"].as_string();
      if (!forced_type.empty()) type = forced_type;
      if (type.empty()) type = "command";
      if (type != "command" && type != "notebook" && type != "shell" &&
          type != "tensorboard") {
        return bad_request("unknown task type " + type);
      }
      Allocation alloc;
      alloc.id = "task-" + type + "-" + std::to_string(next_task_id_++);
      alloc.task_type = type;
      alloc.trial_id = 0;
      alloc.name = body["name"].as_string().empty() ? alloc.id
                                                    : body["name"].as_string();
      // owner is the authenticated caller — a client-supplied owner would
      // make the owner-may-kill gate below spoofable. The body field is
      // honored only when there is no session (auth off / internal use).
      if (User* caller = current_user(req)) {
        alloc.owner = caller->username;
      } else if (!body["owner"].as_string().empty()) {
        alloc.owner = body["owner"].as_string();
      }
      alloc.state = RunState::Queued;
      alloc.slots = static_cast<int>(body["slots"].as_int(0));
      alloc.priority = static_cast<int>(body["priority"].as_int(42));
      alloc.resource_pool = body["resource_pool"].as_string().empty()
                                ? "default"
                                : body["resource_pool"].as_string();
      alloc.idle_timeout_sec = body["idle_timeout"].as_number(0);
      alloc.queued_at = now_sec();
      alloc.last_activity = alloc.queued_at;
      alloc.token = crypto::random_token();
      // the agent execs spec.argv directly; built-in task types run the
      // generic harness task server (determined_clone_tpu/exec/task.py)
      Json argv = Json::array();
      if (type == "command") {
        if (!body["cmd"].is_array() || body["cmd"].size() == 0) {
          return bad_request("command task requires cmd argv array");
        }
        for (const auto& e : body["cmd"].elements()) {
          if (!e.is_string() || e.as_string().empty()) {
            return bad_request("cmd argv elements must be non-empty strings");
          }
        }
        argv = body["cmd"];
      } else {
        argv.push_back("python");
        argv.push_back("-m");
        argv.push_back("determined_clone_tpu.exec.task");
        argv.push_back(type);
        if (type == "tensorboard" && body["experiment_ids"].is_array()) {
          std::string ids;
          for (const auto& e : body["experiment_ids"].elements()) {
            if (!ids.empty()) ids += ",";
            ids += std::to_string(e.as_int());
          }
          argv.push_back("--experiment-ids");
          argv.push_back(ids);
        }
      }
      alloc.spec.set("argv", argv);
      if (body["env"].is_object()) alloc.spec.set("env", body["env"]);
      std::string id = alloc.id;
      allocations_[id] = std::move(alloc);
      dirty_ = true;
      Json j = Json::object();
      j.set(singular, allocations_[id].to_json());
      return HttpResponse::json(201, j.dump());
    }
    if (parts.size() == 3 && req.method == "GET") {
      auto type_filter = req.query.find("type");
      Json arr = Json::array();
      for (const auto& [id, a] : allocations_) {
        if (a.trial_id != 0 || a.task_type == "trial") continue;
        if (!forced_type.empty() && a.task_type != forced_type) continue;
        if (type_filter != req.query.end() &&
            a.task_type != type_filter->second) {
          continue;
        }
        arr.push_back(a.to_json());
      }
      Json j = Json::object();
      j.set(plural, arr);
      return ok_json(j);
    }
    if (parts.size() >= 4) {
      auto it = allocations_.find(parts[3]);
      if (it == allocations_.end() || it->second.task_type == "trial" ||
          (!forced_type.empty() && it->second.task_type != forced_type)) {
        return not_found("no task " + parts[3]);
      }
      Allocation& alloc = it->second;
      if (parts.size() == 4 && req.method == "GET") {
        Json j = Json::object();
        j.set(singular, alloc.to_json());
        return ok_json(j);
      }
      if (parts.size() == 5 && parts[4] == "kill" && req.method == "POST") {
        // rbac: global Editor, or the task's owner killing their own task
        User* caller = current_user(req);
        bool own = caller && caller->username == alloc.owner;
        if (!own && !rbac_allows(req, role_rank("Editor"))) {
          return HttpResponse::json(
              403, error_json("Editor role (or task ownership) required")
                       .dump());
        }
        if (alloc.state == RunState::Queued || alloc.state == RunState::Pulling ||
            alloc.state == RunState::Running) {
          alloc.state = RunState::Canceled;  // heartbeat derives the kill
          dirty_ = true;
        }
        Json j = Json::object();
        j.set(singular, alloc.to_json());
        return ok_json(j);
      }
    }
  return not_found("no such route");
}

// ---- serving fleets ------------------------------------------------------
// /api/v1/serving/fleets — N `serving` replica allocations gang-scheduled
// against a resource pool (docs/serving.md). The replicas ride the exact
// allocation lifecycle trials and NTSC tasks use: the scheduler grants
// reservations, the fleet's agent receives idempotent start/kill commands
// over its heartbeat, and scale-down kills are drain-protected on the
// agent side (the fleet finishes in-flight decodes before reporting
// exited, which is when the slots are reclaimed).

Json Master::serving_fleet_json_locked(const ServingFleetRec& fleet) {
  Json replicas = Json::array();
  int running = 0, queued = 0;
  for (const auto& [id, a] : allocations_) {
    if (a.task_type != "serving" || a.fleet != fleet.name) continue;
    replicas.push_back(a.to_json());
    if (a.state == RunState::Running || a.state == RunState::Pulling) {
      ++running;
    } else if (a.state == RunState::Queued) {
      ++queued;
    }
  }
  Json j = fleet.to_json();
  j.set("replicas", replicas)
      .set("running", static_cast<int64_t>(running))
      .set("queued", static_cast<int64_t>(queued));
  return j;
}

Allocation& Master::queue_serving_replica_locked(ServingFleetRec& fleet) {
  Allocation alloc;
  alloc.id = "serving-" + fleet.name + "-" + std::to_string(fleet.next_seq++);
  alloc.task_type = "serving";
  alloc.fleet = fleet.name;
  alloc.trial_id = 0;
  alloc.name = alloc.id;
  alloc.owner = fleet.owner;
  alloc.state = RunState::Queued;
  alloc.slots = fleet.slots_per_replica;
  alloc.priority = fleet.priority;
  alloc.resource_pool = fleet.resource_pool;
  alloc.queued_at = now_sec();
  alloc.submitted_at = alloc.queued_at;
  alloc.last_activity = alloc.queued_at;
  alloc.token = crypto::random_token();
  // the argv a real (exec-style) agent would run; the in-process fleet
  // agent (serving/fleet.py MasterLink) spawns the replica directly
  Json argv = Json::array();
  argv.push_back("python");
  argv.push_back("-m");
  argv.push_back("determined_clone_tpu.serving.fleet");
  argv.push_back("--fleet");
  argv.push_back(fleet.name);
  alloc.spec.set("argv", argv);
  alloc.spec.set("fleet", fleet.name);
  ++sched_.submitted_total;
  ++sched_.serving_submitted_total;
  sched_event_locked("submit", alloc, alloc.submitted_at, alloc.queued_at);
  std::string id = alloc.id;
  allocations_[id] = std::move(alloc);
  dirty_ = true;
  return allocations_[id];
}

void Master::shrink_serving_fleet_locked(ServingFleetRec& fleet,
                                         int target) {
  // live replicas, newest last (creation order == queued_at, id tiebreak):
  // scale-down cancels from the top of the sequence so the longest-lived
  // replicas keep serving
  std::vector<Allocation*> live;
  for (auto& [id, a] : allocations_) {
    if (a.task_type != "serving" || a.fleet != fleet.name) continue;
    if (a.state == RunState::Completed || a.state == RunState::Errored ||
        a.state == RunState::Canceled) {
      continue;
    }
    live.push_back(&a);
  }
  std::sort(live.begin(), live.end(),
            [](const Allocation* x, const Allocation* y) {
              if (x->queued_at != y->queued_at) {
                return x->queued_at < y->queued_at;
              }
              return x->id < y->id;
            });
  while (static_cast<int>(live.size()) > target) {
    Allocation* a = live.back();
    live.pop_back();
    if (a->state == RunState::Queued && a->reservations.empty()) {
      // never scheduled: terminal immediately, no agent involved
      a->state = RunState::Canceled;
      a->ended_at = now_sec();
      ++sched_.completed_total;
      ++sched_.serving_completed_total;
      sched_event_locked("end", *a, a->ended_at, a->ended_at);
    } else {
      // running replica: Canceled makes the next heartbeat derive a kill;
      // the fleet agent drains (admission stopped, in-flight decodes
      // finish, blocks released) and THEN reports exited — on_task_done
      // is when the slots actually free (drain-protected reclaim)
      a->state = RunState::Canceled;
    }
    dirty_ = true;
  }
}

HttpResponse Master::serving_route(const HttpRequest& req) {
  const auto& parts = req.path_parts;  // {"api","v1","serving","fleets",..}
  if (parts.size() < 4 || parts[3] != "fleets") {
    return not_found("no such route");
  }
  if (parts.size() == 4 && req.method == "POST") {
    // rbac: fleets consume cluster slots like experiments do
    if (!rbac_allows(req, role_rank("Editor"))) {
      return HttpResponse::json(
          403, error_json("Editor role required to create fleets").dump());
    }
    Json body = Json::parse(req.body);
    const std::string name = body["name"].as_string();
    if (name.empty()) return bad_request("fleet name required");
    for (char c : name) {
      bool ok = (c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z') ||
                (c >= '0' && c <= '9') || c == '-' || c == '_';
      // the name is embedded in allocation ids and prometheus labels
      if (!ok) return bad_request("fleet name must be [A-Za-z0-9_-]");
    }
    if (fleets_.count(name)) {
      return HttpResponse::json(
          409, error_json("fleet " + name + " already exists").dump());
    }
    ServingFleetRec fleet;
    fleet.name = name;
    if (!body["resource_pool"].as_string().empty()) {
      fleet.resource_pool = body["resource_pool"].as_string();
    }
    fleet.slots_per_replica =
        std::max(0, static_cast<int>(body["slots_per_replica"].as_int(1)));
    fleet.priority = static_cast<int>(body["priority"].as_int(42));
    fleet.desired = std::max(0, static_cast<int>(body["replicas"].as_int(1)));
    if (User* caller = current_user(req)) fleet.owner = caller->username;
    fleet.created_at = now_sec();
    ServingFleetRec& rec = fleets_[name] = fleet;
    for (int i = 0; i < rec.desired; ++i) queue_serving_replica_locked(rec);
    log_event("info", "serving fleet created: " + name + " (" +
                          std::to_string(rec.desired) + " replicas x " +
                          std::to_string(rec.slots_per_replica) +
                          " slots in " + rec.resource_pool + ")");
    Json j = Json::object();
    j.set("fleet", serving_fleet_json_locked(rec));
    return HttpResponse::json(201, j.dump());
  }
  if (parts.size() == 4 && req.method == "GET") {
    Json arr = Json::array();
    for (const auto& [name, fleet] : fleets_) {
      arr.push_back(serving_fleet_json_locked(fleet));
    }
    Json j = Json::object();
    j.set("fleets", arr);
    return ok_json(j);
  }
  if (parts.size() >= 5) {
    auto it = fleets_.find(parts[4]);
    if (it == fleets_.end()) return not_found("no fleet " + parts[4]);
    ServingFleetRec& fleet = it->second;
    if (parts.size() == 5 && req.method == "GET") {
      Json j = Json::object();
      j.set("fleet", serving_fleet_json_locked(fleet));
      return ok_json(j);
    }
    if (parts.size() == 6 && parts[5] == "scale" && req.method == "POST") {
      if (!rbac_allows(req, role_rank("Editor"))) {
        return HttpResponse::json(
            403, error_json("Editor role required to scale fleets").dump());
      }
      Json body = Json::parse(req.body);
      int target =
          std::max(0, static_cast<int>(body["replicas"].as_int(-1)));
      if (body["replicas"].as_int(-1) < 0) {
        return bad_request("scale requires replicas >= 0");
      }
      int live = 0;
      for (const auto& [id, a] : allocations_) {
        if (a.task_type == "serving" && a.fleet == fleet.name &&
            a.state != RunState::Completed &&
            a.state != RunState::Errored &&
            a.state != RunState::Canceled) {
          ++live;
        }
      }
      if (target > live) {
        for (int i = live; i < target; ++i) {
          queue_serving_replica_locked(fleet);
        }
      } else if (target < live) {
        shrink_serving_fleet_locked(fleet, target);
      }
      fleet.desired = target;
      dirty_ = true;
      log_event("info", "serving fleet " + fleet.name + " scaled " +
                            std::to_string(live) + " -> " +
                            std::to_string(target));
      Json j = Json::object();
      j.set("fleet", serving_fleet_json_locked(fleet));
      return ok_json(j);
    }
    if (parts.size() == 6 && parts[5] == "kill" && req.method == "POST") {
      User* caller = current_user(req);
      bool own = caller && caller->username == fleet.owner;
      if (!own && !rbac_allows(req, role_rank("Editor"))) {
        return HttpResponse::json(
            403,
            error_json("Editor role (or fleet ownership) required").dump());
      }
      shrink_serving_fleet_locked(fleet, 0);
      fleet.desired = 0;
      dirty_ = true;
      Json j = Json::object();
      j.set("fleet", serving_fleet_json_locked(fleet));
      return ok_json(j);
    }
  }
  return not_found("no such route");
}

HttpResponse Master::route(const HttpRequest& req) {
  const auto& parts = req.path_parts;  // e.g. {"api","v1","experiments","3"}
  if (parts.size() < 2 || parts[0] != "api" || parts[1] != "v1") {
    return not_found("unknown path " + req.path);
  }
  std::lock_guard<std::mutex> lock(mu_);
  const std::string& root = parts.size() > 2 ? parts[2] : "";

  // auth enforcement (when enabled): user-facing roots require a session
  // token. A live allocation token (the data-plane credential handed to
  // every task via DCT_ALLOC_TOKEN) grants READ-ONLY access to experiments
  // and users — the in-cluster needs (TensorBoard metric fetch, agent
  // context download) — and nothing else: task containers run untrusted
  // user code, so the token must not reach mutating routes (≈ the
  // reference's allocation-scoped session tokens, which are similarly
  // limited). (/api/v1/auth/login mints sessions and stays open.)
  static const std::set<std::string> kAuthRoots = {
      "experiments", "tasks",  "users",    "workspaces", "models",
      "templates",   "webhooks", "job-queue", "provisioner", "groups",
      "rbac", "notebooks", "shells", "commands", "tensorboards",
      "projects", "checkpoints", "cluster", "serving"};
  if (config_.auth_required && kAuthRoots.count(root)) {
    bool alloc_readonly = req.method == "GET" &&
                          (root == "experiments" || root == "users") &&
                          alloc_authed(req);
    if (!current_user(req) && !alloc_readonly) {
      return HttpResponse::json(
          401, error_json("authentication required").dump());
    }
  }

  {
    auto platform = route_platform(req);
    if (platform) return *platform;
  }

  // ---- master info -------------------------------------------------------
  if (root == "master" && parts.size() == 3 && req.method == "GET") {
    Json j = Json::object();
    Json store = Json::object();
    store.set("kind", store_->kind())
        .set("schema_version", static_cast<int64_t>(store_->schema_version()));
    j.set("version", "0.1.0").set("cluster_name", "dct")
        .set("agents", static_cast<int64_t>(agents_.size()))
        .set("experiments", static_cast<int64_t>(experiments_.size()))
        .set("store", store);
    return ok_json(j);
  }
  // master's own event log (≈ GetMasterLogs, api_master.go): bounded ring
  // of lifecycle events; absolute seq cursor survives ring trimming.
  // Session-gated under auth — unlike /master (sanitized info), the event
  // log carries agent/experiment/task detail.
  if (root == "master" && parts.size() == 4 && parts[3] == "logs" &&
      req.method == "GET") {
    if (config_.auth_required && !current_user(req)) {
      return HttpResponse::json(
          401, error_json("authentication required").dump());
    }
    size_t limit = 1000, offset = 0;
    if (!parse_size(req.query, "limit", &limit) ||
        !parse_size(req.query, "offset", &offset)) {
      return bad_request("limit/offset must be non-negative integers");
    }
    Json arr = Json::array();
    uint64_t seq = event_log_head_seq_;
    size_t start = offset > seq ? offset - seq : 0;
    for (size_t i = start; i < event_log_.size() && arr.size() < limit;
         ++i) {
      Json rec = event_log_[i];
      rec.set("seq", static_cast<int64_t>(seq + i));
      arr.push_back(rec);
    }
    uint64_t consumed = seq + start + arr.size();
    Json j = Json::object();
    j.set("logs", arr)
        .set("next_offset", static_cast<int64_t>(
                                std::max<uint64_t>(offset, consumed)));
    return ok_json(j);
  }
  // active config, secrets omitted (≈ GetMasterConfig api_master.go);
  // operator surface: admin-gated under auth
  if (root == "master" && parts.size() == 4 && parts[3] == "config" &&
      req.method == "GET") {
    if (config_.auth_required) {
      // 401 for no/expired session (client should re-login), 403 only for
      // an authenticated non-admin — the same split as the API roots
      if (!current_user(req)) {
        return HttpResponse::json(
            401, error_json("authentication required").dump());
      }
      if (!cluster_admin_ok(req)) {
        return HttpResponse::json(
            403, error_json("admin required").dump());
      }
    }
    Json pools = Json::object();
    for (const auto& [name, policy] : config_.pools) {
      Json p = Json::object();
      p.set("scheduler", policy.type)
          .set("preemption", policy.preemption_enabled);
      pools.set(name, p);
    }
    Json j = Json::object();
    j.set("port", static_cast<int64_t>(config_.port))
        .set("data_dir", config_.data_dir)
        .set("scheduler", config_.default_pool.type)
        .set("preemption", config_.default_pool.preemption_enabled)
        .set("pools", pools)
        .set("auth_required", config_.auth_required)
        .set("rbac", config_.rbac_enabled)
        .set("rm", config_.rm)
        .set("db", store_->kind())
        .set("agent_timeout_sec", config_.agent_timeout_sec)
        .set("unmanaged_timeout_sec", config_.unmanaged_timeout_sec)
        .set("webui_dir", config_.webui_dir)
        .set("sso_issuer",
             config_.sso_issuer_host.empty()
                 ? ""
                 : config_.sso_issuer_host + ":" +
                       std::to_string(config_.sso_issuer_port));
    return ok_json(j);
  }

  // ---- experiments -------------------------------------------------------
  if (root == "experiments") {
    if (parts.size() == 3 && req.method == "POST") {
      Json body = Json::parse(req.body);
      if (!body["config"].is_object()) return bad_request("missing config object");
      Json config;
      try {
        // template merge (≈ master/internal/templates; template is base)
        config = resolve_template(body["config"]);
      } catch (const std::exception& e) {
        return bad_request(e.what());
      }
      {
        // rbac: experiment creation needs Editor at the target workspace
        std::string ws = config["workspace"].as_string();
        if (ws.empty()) ws = "Uncategorized";
        if (!rbac_allows(req, role_rank("Editor"), workspace_id_by_name(ws))) {
          return HttpResponse::json(
              403,
              error_json("Editor role required in workspace " + ws).dump());
        }
      }
      // validate log-pattern regexes up front — a typo'd pattern must be a
      // 400 at submission, not a silent no-op policy at runtime
      if (config["log_policies"].is_array()) {
        for (const auto& policy : config["log_policies"].elements()) {
          const std::string& pattern = policy["pattern"].as_string();
          if (pattern.empty()) {
            return bad_request("log policy requires a non-empty pattern");
          }
          try {
            std::regex re(pattern);
          } catch (const std::regex_error& e) {
            return bad_request("invalid log policy pattern '" + pattern +
                               "': " + e.what());
          }
        }
      }
      // topology requests must agree with the slot count, or capacity
      // gating (slots) and chip-grid bookkeeping (shape) silently diverge
      if (config["resources"].is_object() &&
          !config["resources"]["topology"].as_string().empty()) {
        const std::string& topo = config["resources"]["topology"].as_string();
        int slots = static_cast<int>(
            config["resources"]["slots_per_trial"].as_int(1));
        SliceShape shape = parse_topology(topo, slots);
        if (shape.gen.empty()) {
          return bad_request("unrecognized topology '" + topo +
                             "' (expected e.g. v5e-8)");
        }
        if (shape.chips() != slots) {
          return bad_request(
              "topology " + topo + " is " + std::to_string(shape.chips()) +
              " chips but slots_per_trial is " + std::to_string(slots));
        }
      }
      // validate the context upload BEFORE any state mutates — a 400 must
      // truly leave no side effects (no trials, allocations, workspaces)
      if (body["context"].is_array() && body["context"].size() > 0) {
        size_t total = 0;
        for (const auto& f : body["context"].elements()) {
          const std::string& fpath = f["path"].as_string();
          if (fpath.empty() || fpath[0] == '/' ||
              fpath.find("..") != std::string::npos) {
            return bad_request("context paths must be relative, no '..'");
          }
          total += f["content_b64"].as_string().size();
        }
        if (total > 8u << 20) {
          return bad_request("context directory too large (8MB b64 cap)");
        }
      }
      Experiment exp;
      exp.id = next_experiment_id_++;
      exp.name = config["name"].as_string().empty() ? "unnamed"
                                                    : config["name"].as_string();
      exp.config = config;
      exp.state = RunState::Running;
      exp.created_at = now_sec();
      if (User* caller = current_user(req)) exp.owner = caller->username;
      if (config["workspace"].is_string() && !config["workspace"].as_string().empty())
        exp.workspace = config["workspace"].as_string();
      if (config["project"].is_string() && !config["project"].as_string().empty())
        exp.project = config["project"].as_string();
      int64_t id = exp.id;
      experiments_[id] = std::move(exp);
      Experiment& stored = experiments_[id];
      try {
        apply_search_ops(stored, method_for(stored)->initial_operations());
      } catch (const std::exception& e) {
        experiments_.erase(id);
        methods_.erase(id);
        return bad_request(std::string("invalid experiment config: ") + e.what());
      }
      // register workspace/project only once the config validated — a 400
      // must leave no side effects
      Workspace& ws = ensure_workspace(stored.workspace, stored.owner);
      ensure_project(stored.project, ws.id, stored.owner);
      // model-def context directory (≈ read_v1_context's base64 file list,
      // cli/experiment.py:242): stored on disk, served to agents on demand
      // (validated above, before any state mutated)
      if (body["context"].is_array() && body["context"].size() > 0) {
        Json ctx = Json::object();
        ctx.set("context", body["context"]);
        std::ofstream out(config_.data_dir + "/exp-" + std::to_string(id) +
                          "-context.json");
        out << ctx.dump();
      }
      dirty_ = true;
      Json j = Json::object();
      j.set("experiment", experiments_[id].to_json());
      if (config["unmanaged"].as_bool(false)) {
        // hand the unmanaged client its trial ids + data-plane tokens
        Json arr = Json::array();
        for (const auto& [tid, t] : trials_) {
          if (t.experiment_id != id) continue;
          for (const auto& [aid, alloc] : allocations_) {
            if (alloc.trial_id != tid || alloc.task_type != "unmanaged") {
              continue;
            }
            Json u = Json::object();
            u.set("trial_id", tid).set("allocation_id", aid)
                .set("token", alloc.token)
                .set("target_units", t.target_units);
            arr.push_back(u);
          }
        }
        j.set("unmanaged", arr);
      }
      return HttpResponse::json(201, j.dump());
    }
    if (parts.size() == 3 && req.method == "GET") {
      Json arr = Json::array();
      for (const auto& [id, e] : experiments_) arr.push_back(e.to_json());
      Json j = Json::object();
      j.set("experiments", arr);
      return ok_json(j);
    }
    if (parts.size() >= 4) {
      int64_t id = std::stoll(parts[3]);
      auto it = experiments_.find(id);
      if (it == experiments_.end()) return not_found("no experiment " + parts[3]);
      Experiment& exp = it->second;
      if (parts.size() == 4 && req.method == "GET") {
        Json j = Json::object();
        j.set("experiment", exp.to_json());
        Json trials = Json::array();
        for (const auto& [tid, t] : trials_) {
          if (t.experiment_id == id) trials.push_back(t.to_json());
        }
        j.set("trials", trials);
        auto mit = methods_.find(id);
        if (mit != methods_.end()) j.set("progress", mit->second->progress());
        return ok_json(j);
      }
      if (parts.size() == 5 && parts[4] == "kill" && req.method == "POST") {
        // rbac: Editor at the workspace, or the submitter killing their own
        // experiment (a revoked Editor must still be able to stop the work
        // they started — same escape hatch as task kill)
        User* caller = current_user(req);
        bool own = caller && caller->username == exp.owner;
        if (!own && !rbac_allows(req, role_rank("Editor"),
                                 workspace_id_by_name(exp.workspace))) {
          return HttpResponse::json(
              403, error_json("Editor role required in workspace " +
                              exp.workspace).dump());
        }
        if (exp.state == RunState::Running || exp.state == RunState::Queued ||
            exp.state == RunState::Paused) {
          finish_experiment(exp, RunState::Canceled);
        }
        Json j = Json::object();
        j.set("experiment", exp.to_json());
        return ok_json(j);
      }
      if (parts.size() == 5 && parts[4] == "checkpoints" && req.method == "GET") {
        Json arr = Json::array();
        for (const auto& c : checkpoints_) {
          if (c.experiment_id == id && !c.deleted) arr.push_back(c.to_json());
        }
        Json j = Json::object();
        j.set("checkpoints", arr);
        return ok_json(j);
      }
      // pause/activate (≈ PauseExperiment/ActivateExperiment): pause
      // preempts running trials (they checkpoint and release their chips)
      // and parks the experiment; activate re-queues the unfinished trials,
      // which resume from their latest checkpoints
      if (parts.size() == 5 &&
          (parts[4] == "pause" || parts[4] == "activate" ||
           parts[4] == "archive" || parts[4] == "unarchive") &&
          req.method == "POST") {
        User* caller = current_user(req);
        bool own = caller && caller->username == exp.owner;
        if (!own && !rbac_allows(req, role_rank("Editor"),
                                 workspace_id_by_name(exp.workspace))) {
          return HttpResponse::json(
              403, error_json("Editor role required in workspace " +
                              exp.workspace).dump());
        }
        const std::string& action = parts[4];
        if (action == "pause") {
          if (exp.state != RunState::Running) {
            return bad_request("only a running experiment can pause");
          }
          exp.state = RunState::Paused;
          for (auto& [aid, alloc] : allocations_) {
            if (alloc.trial_id == 0) continue;
            auto tit = trials_.find(alloc.trial_id);
            if (tit == trials_.end() ||
                tit->second.experiment_id != exp.id) {
              continue;
            }
            if (alloc.state == RunState::Queued ||
                alloc.state == RunState::Pulling) {
              // not running yet (Pulling may have raced a start command:
              // the heartbeat's terminal-state kill sweep covers that) —
              // cancel outright; activate re-queues a fresh leg
              alloc.state = RunState::Canceled;
              alloc.reservations.clear();
              tit->second.state = RunState::Paused;
            } else if (alloc.state == RunState::Running) {
              if (!alloc.preempt_requested) {
                alloc.preempt_requested = true;  // graceful: ckpt then exit
                ++sched_.preemptions_total;
                sched_event_locked("preempt", alloc, now_sec(), now_sec());
              }
            }
          }
          dirty_ = true;
        } else if (action == "activate") {
          if (exp.state != RunState::Paused) {
            return bad_request("only a paused experiment can activate");
          }
          exp.state = RunState::Running;
          // un-preempt allocations still draining from the pause: the
          // harness may not have polled the flag yet and can just keep
          // training (if it already exited, the clean-exit path re-queues)
          for (auto& [aid, alloc] : allocations_) {
            if (alloc.trial_id == 0 || !alloc.preempt_requested) continue;
            auto tit = trials_.find(alloc.trial_id);
            if (tit != trials_.end() &&
                tit->second.experiment_id == exp.id &&
                (alloc.state == RunState::Running ||
                 alloc.state == RunState::Pulling)) {
              alloc.preempt_requested = false;
            }
          }
          for (auto& [tid, trial] : trials_) {
            if (trial.experiment_id != exp.id) continue;
            bool terminal = trial.state == RunState::Completed ||
                            trial.state == RunState::Errored ||
                            trial.state == RunState::Canceled;
            if (terminal || trial.units_done >= trial.target_units) {
              continue;
            }
            queue_trial_leg(trial);  // resumes from latest_checkpoint
          }
          dirty_ = true;
        } else if (action == "archive" || action == "unarchive") {
          bool terminal = exp.state == RunState::Completed ||
                          exp.state == RunState::Errored ||
                          exp.state == RunState::Canceled;
          if (!terminal) {
            return bad_request("only a finished experiment can be archived");
          }
          exp.archived = action == "archive";
          dirty_ = true;
        }
        Json j = Json::object();
        j.set("experiment", exp.to_json());
        return ok_json(j);
      }
      // patch (≈ PatchExperiment): display metadata only — name,
      // description, labels; lifecycle stays with the action routes
      if (parts.size() == 4 && req.method == "PATCH") {
        if (!rbac_allows(req, role_rank("Editor"),
                         workspace_id_by_name(exp.workspace))) {
          return HttpResponse::json(
              403, error_json("Editor role required").dump());
        }
        Json body = Json::parse(req.body);
        if (body["name"].is_string() && !body["name"].as_string().empty()) {
          exp.name = body["name"].as_string();
        }
        if (body["description"].is_string()) {
          exp.description = body["description"].as_string();
        }
        if (body["labels"].is_array()) {
          exp.labels.clear();
          for (const auto& l : body["labels"].elements()) {
            if (l.is_string()) exp.labels.push_back(l.as_string());
          }
        }
        dirty_ = true;
        Json j = Json::object();
        j.set("experiment", exp.to_json());
        return ok_json(j);
      }
      // move to another project (≈ MoveExperiment, api_experiment.go)
      if (parts.size() == 5 && parts[4] == "move" && req.method == "POST") {
        Json body = Json::parse(req.body);
        int64_t pid = body["project_id"].as_int(-1);
        auto pit = projects_.find(pid);
        if (pit == projects_.end()) {
          return bad_request("destination project_id required");
        }
        auto wit = workspaces_.find(pit->second.workspace_id);
        if (wit == workspaces_.end()) {
          return bad_request("destination project has no workspace");
        }
        // rights on both the source and destination workspace scopes
        if (!rbac_allows(req, role_rank("Editor"),
                         workspace_id_by_name(exp.workspace)) ||
            !rbac_allows(req, role_rank("Editor"),
                         pit->second.workspace_id)) {
          return HttpResponse::json(
              403,
              error_json("Editor role required in both workspaces").dump());
        }
        exp.project = pit->second.name;
        exp.workspace = wit->second.name;
        dirty_ = true;
        Json j = Json::object();
        j.set("experiment", exp.to_json());
        return ok_json(j);
      }
      // searcher progress (≈ GetExperimentProgress / the searcher-progress
      // reads in api_experiment.go): fraction of target units done across
      // live trials
      if (parts.size() == 5 && parts[4] == "progress" &&
          req.method == "GET") {
        double done = 0, target = 0;
        for (const auto& [tid, t] : trials_) {
          if (t.experiment_id != id) continue;
          target += static_cast<double>(std::max<int64_t>(t.target_units, 0));
          done += static_cast<double>(
              std::min<int64_t>(t.units_done, t.target_units));
        }
        Json j = Json::object();
        bool terminal = exp.state == RunState::Completed;
        j.set("progress", terminal ? 1.0
                                   : (target > 0 ? done / target : 0.0))
            .set("units_done", done).set("units_target", target)
            .set("state", std::string(to_string(exp.state)));
        return ok_json(j);
      }
      // delete (≈ DeleteExperiment): terminal only; every checkpoint is
      // GC'd from storage and all records drop out of the master
      if (parts.size() == 4 && req.method == "DELETE") {
        if (!rbac_allows(req, role_rank("WorkspaceAdmin"),
                         workspace_id_by_name(exp.workspace))) {
          return HttpResponse::json(
              403, error_json("WorkspaceAdmin role required").dump());
        }
        bool terminal = exp.state == RunState::Completed ||
                        exp.state == RunState::Errored ||
                        exp.state == RunState::Canceled;
        if (!terminal) {
          return bad_request("kill the experiment before deleting it");
        }
        std::vector<std::string> doomed;
        for (auto& c : checkpoints_) {
          if (c.experiment_id == id && !c.deleted) {
            c.deleted = true;
            doomed.push_back(c.uuid);
          }
        }
        spawn_gc_task_locked(exp, doomed);
        checkpoints_.erase(
            std::remove_if(checkpoints_.begin(), checkpoints_.end(),
                           [&](const CheckpointRecord& c) {
                             return c.experiment_id == id;
                           }),
            checkpoints_.end());
        for (auto tit = trials_.begin(); tit != trials_.end();) {
          if (tit->second.experiment_id == id) {
            tit = trials_.erase(tit);
          } else {
            ++tit;
          }
        }
        for (auto ait = allocations_.begin(); ait != allocations_.end();) {
          if (ait->second.trial_id != 0 &&
              !trials_.count(ait->second.trial_id)) {
            allgather_.erase(ait->first);
            ait = allocations_.erase(ait);
          } else {
            ++ait;
          }
        }
        methods_.erase(id);
        request_to_trial_.erase(id);
        log_policy_cache_.erase(id);
        experiments_.erase(id);
        dirty_ = true;
        return ok_json(Json::object());
      }
      // custom-search event queue (≈ master/pkg/searcher/custom_search.go
      // events + api_experiment.go GetSearcherEvents/PostSearcherOperations)
      if (parts.size() == 6 && parts[4] == "searcher") {
        // rbac: the search runner mutates search state (creates/stops
        // trials), so it needs Editor at the experiment's workspace — or to
        // be the experiment's owner (the usual case for a remote runner)
        if (req.method == "POST") {
          User* caller = current_user(req);
          bool own = caller && caller->username == exp.owner;
          if (!own && !rbac_allows(req, role_rank("Editor"),
                                   workspace_id_by_name(exp.workspace))) {
            return HttpResponse::json(
                403, error_json("Editor role required in workspace " +
                                exp.workspace).dump());
          }
        }
        auto* custom = dynamic_cast<CustomSearchCpp*>(method_for(exp));
        if (parts[5] == "events" && req.method == "GET") {
          if (!custom) {
            return bad_request("experiment searcher is not custom");
          }
          size_t since_sz = 0;
          if (!parse_size(req.query, "since", &since_sz)) {
            return bad_request("since must be a non-negative integer");
          }
          int64_t since = static_cast<int64_t>(since_sz);
          Json j = Json::object();
          j.set("events", custom->events_after(since));
          j.set("state", to_string(exp.state));
          j.set("progress", custom->progress());
          return ok_json(j);
        }
        if (parts[5] == "operations" && req.method == "POST") {
          if (!custom) {
            return bad_request("experiment searcher is not custom");
          }
          Json body = Json::parse(req.body);
          // parse/validate ALL ops before mutating anything — a 400 must
          // truly leave no side effects (progress included)
          std::vector<SearchOp> ops;
          for (const auto& o : body["ops"].elements()) {
            const std::string& type = o["type"].as_string();
            if (type == "create") {
              SearchOp op = SearchOp::create(o["hparams"]);
              if (o.has("request_id")) op.request_id = o["request_id"].as_int();
              ops.push_back(std::move(op));
            } else if (type == "validate_after") {
              ops.push_back(SearchOp::validate_after(
                  o["request_id"].as_int(), o["units"].as_int()));
            } else if (type == "close") {
              ops.push_back(SearchOp::close(o["request_id"].as_int()));
            } else if (type == "shutdown") {
              ops.push_back(SearchOp::shutdown(o["failure"].as_bool(),
                                               o["cancel"].as_bool()));
            } else {
              return bad_request("unknown searcher op type '" + type + "'");
            }
          }
          if (body["progress"].is_number()) {
            custom->set_progress(body["progress"].as_number());
          }
          if (body["ack_through"].is_number()) {
            // opt-in log trim: the runner persists its own state and no
            // longer needs events <= ack_through for replay
            custom->trim_events(body["ack_through"].as_int());
          }
          if (exp.state == RunState::Running && !ops.empty()) {
            apply_search_ops(exp, std::move(ops));
          } else {
            exp.searcher_snapshot = method_for(exp)->snapshot();
            dirty_ = true;  // persist progress updates even with no ops
          }
          Json j = Json::object();
          j.set("state", to_string(exp.state));
          return ok_json(j);
        }
      }
      // stitched-trace source: trial span samples + synthesized master-lane
      // lifecycle spans (`dct trace export --experiment N`)
      if (parts.size() == 5 && parts[4] == "trace" && req.method == "GET") {
        return experiment_trace_locked(id);
      }
      // context-dir download by agents (≈ prep_container.py:29)
      if (parts.size() == 5 && parts[4] == "context" && req.method == "GET") {
        std::ifstream in(config_.data_dir + "/exp-" + std::to_string(id) +
                         "-context.json");
        if (!in.good()) {
          Json j = Json::object();
          j.set("context", Json::array());
          return ok_json(j);
        }
        std::stringstream buf;
        buf << in.rdbuf();
        return HttpResponse::json(200, buf.str());
      }
    }
  }

  // ---- trials ------------------------------------------------------------
  if (root == "trials" && parts.size() >= 4) {
    int64_t id = std::stoll(parts[3]);
    auto it = trials_.find(id);
    if (it == trials_.end()) return not_found("no trial " + parts[3]);
    Trial& trial = it->second;
    Experiment& exp = experiments_[trial.experiment_id];
    // 'trials' is not in kAuthRoots because its data plane is driven by
    // alloc-token holders, so the gate lives here: under --auth-required a
    // mutation (metrics/checkpoints/searcher ops can steer or stop a
    // search) needs a session or THIS trial's allocation token; reads open
    // to any live alloc token (TensorBoard fetches sibling-trial metrics)
    // or a session. Control mutations (kill) additionally demand a
    // session below.
    if (config_.auth_required) {
      bool session_ok = current_user(req) != nullptr;
      bool allowed = session_ok;
      if (!allowed && req.method == "GET") {
        allowed = alloc_authed(req);
      } else if (!allowed) {
        const std::string tok = bearer_token(req);
        for (const auto& [aid, a] : allocations_) {
          if (a.trial_id == id && !a.token.empty() &&
              crypto::constant_time_eq(tok, a.token)) {
            allowed = true;
            break;
          }
        }
      }
      if (!allowed) {
        return HttpResponse::json(
            401, error_json("session or allocation token required").dump());
      }
    }

    if (parts.size() == 4 && req.method == "GET") {
      Json j = Json::object();
      j.set("trial", trial.to_json());
      // the newest allocation leg (log stream target; managed legs are
      // trial-<id>.<leg>, unmanaged ones unmanaged-<id>.<leg> — clients
      // should not reconstruct the naming)
      std::string latest;
      double latest_at = -1;
      for (const auto& [aid, alloc] : allocations_) {
        if (alloc.trial_id == id && alloc.queued_at > latest_at) {
          latest = aid;
          latest_at = alloc.queued_at;
        }
      }
      j.set("latest_allocation", latest);
      return ok_json(j);
    }
    // kill one trial without touching its experiment (≈ KillTrial): the
    // searcher is told the trial exited early so HP search can continue
    if (parts.size() == 5 && parts[4] == "kill" && req.method == "POST") {
      User* caller = current_user(req);
      // 'trials' is not in kAuthRoots (its data-plane POSTs are driven by
      // alloc-token holders), so this control-plane mutation must demand a
      // session itself: with RBAC off, rbac_allows() passes unconditionally
      // and an anonymous kill would fall through.
      if (config_.auth_required && !caller) {
        return HttpResponse::json(
            401, error_json("authentication required").dump());
      }
      bool own = caller && caller->username == exp.owner;
      if (!own && !rbac_allows(req, role_rank("Editor"),
                               workspace_id_by_name(exp.workspace))) {
        return HttpResponse::json(
            403, error_json("Editor role required in workspace " +
                            exp.workspace).dump());
      }
      bool terminal = trial.state == RunState::Completed ||
                      trial.state == RunState::Errored ||
                      trial.state == RunState::Canceled;
      if (!terminal) {
        for (auto& [aid, alloc] : allocations_) {
          if (alloc.trial_id != id) continue;
          if (alloc.state == RunState::Queued ||
              alloc.state == RunState::Pulling) {
            alloc.state = RunState::Canceled;
            alloc.reservations.clear();
          } else if (alloc.state == RunState::Running) {
            // graceful: the harness checkpoints and exits; the Canceled
            // trial state below keeps on_task_done from re-queuing
            alloc.preempt_requested = true;
          }
        }
        trial.state = RunState::Canceled;
        trial.ended_at = now_sec();
        // the searcher must hear about the exit even mid-pause, or a
        // random/ASHA search can never reach its trial count and the
        // experiment stalls RUNNING forever after activate
        if (exp.state == RunState::Running ||
            exp.state == RunState::Paused) {
          auto ops = method_for(exp)->on_trial_exited_early(trial.request_id);
          for (auto& op : ops) {
            if (op.kind == SearchOp::Kind::Shutdown && op.failure) {
              // the searcher is giving up because its trial died — but the
              // cause was a USER cancel, not a failure: the experiment
              // ends CANCELED (like experiment kill), not ERRORED
              op.failure = false;
              op.cancel = true;
            }
          }
          apply_search_ops(exp, std::move(ops));
        }
        dirty_ = true;
      }
      Json j = Json::object();
      j.set("trial", trial.to_json());
      return ok_json(j);
    }
    // unmanaged-trial heartbeat: liveness + client-driven completion
    // (≈ harness/determined/core/_heartbeat.py:15 + unmanaged experiment
    // close semantics; the response carries the preempt flag so the client
    // needs no separate long-poll)
    if (parts.size() == 5 && parts[4] == "heartbeat" && req.method == "POST") {
      Allocation* ua = nullptr;
      for (auto& [aid, a] : allocations_) {
        if (a.trial_id == id && a.task_type == "unmanaged" &&
            a.state == RunState::Running) {
          ua = &a;
        }
      }
      if (!ua) return bad_request("trial has no live unmanaged allocation");
      // the client authenticates with the allocation's data-plane token
      // (dct core._unmanaged ships it from the create-experiment response);
      // a user session with Editor rights may also drive the trial
      bool token_ok =
          crypto::constant_time_eq(bearer_token(req), ua->token);
      if (config_.auth_required && !token_ok &&
          !(current_user(req) &&
            rbac_allows(req, role_rank("Editor"),
                        workspace_id_by_name(exp.workspace)))) {
        return HttpResponse::json(
            401, error_json("allocation token or Editor session required")
                     .dump());
      }
      ua->last_activity = now_sec();
      Json body = req.body.empty() ? Json::object() : Json::parse(req.body);
      const std::string& state = body["state"].as_string();
      Json j = Json::object();
      j.set("preempt", ua->preempt_requested);
      if (state == "COMPLETED" || state == "ERRORED") {
        bool failed = state == "ERRORED";
        ua->state = failed ? RunState::Errored : RunState::Completed;
        ua->exit_code = failed ? 1 : 0;
        trial.state = ua->state;
        trial.ended_at = now_sec();
        if (failed && body["error"].is_string()) {
          trial.error = body["error"].as_string();
        }
        // the experiment's final state reflects EVERY trial, not just the
        // last reporter: one errored trial makes the experiment errored
        bool all_done = true, any_errored = false;
        for (const auto& [tid, t] : trials_) {
          if (t.experiment_id != exp.id) continue;
          all_done = all_done && (t.state == RunState::Completed ||
                                  t.state == RunState::Errored ||
                                  t.state == RunState::Canceled);
          any_errored = any_errored || t.state == RunState::Errored;
        }
        if (all_done && exp.state == RunState::Running) {
          finish_experiment(exp, any_errored ? RunState::Errored
                                             : RunState::Completed);
        }
        dirty_ = true;
      }
      return ok_json(j);
    }
    // report metrics (≈ ReportTrialMetrics api_trials.go:1330) — typed
    // store path: relational rows + incrementally materialized summary
    // (store.h append_metric; ≈ postgres_trial.go + the reference's
    // calculate-full-trial-summary-metrics.sql)
    if (parts.size() == 5 && parts[4] == "metrics") {
      if (req.method == "POST") {
        Json body = Json::parse(req.body);
        body.set("time", now_sec());
        store_->append_metric(id, body);
        if (body["group"].as_string() == "training" &&
            body.has("steps_completed")) {
          // monotonic: a restarted leg resuming from an older checkpoint
          // must not move searcher progress backwards
          trial.units_done =
              std::max(trial.units_done, body["steps_completed"].as_int());
          dirty_ = true;
        }
        return ok_json(Json::object());
      }
      if (req.method == "GET") {
        size_t limit = 1000, offset = 0;
        if (!parse_size(req.query, "limit", &limit) ||
            !parse_size(req.query, "offset", &offset)) {
          return bad_request("limit/offset must be non-negative integers");
        }
        Json arr = Json::array();
        for (auto& rec : store_->read_metrics(id, limit, offset)) {
          arr.push_back(rec);
        }
        Json j = Json::object();
        j.set("metrics", arr);
        return ok_json(j);
      }
    }
    // materialized per-trial metric summary: flat-cost aggregates for the
    // experiment/trial pages (no history scan per refresh)
    if (parts.size() == 6 && parts[4] == "metrics" &&
        parts[5] == "summary" && req.method == "GET") {
      return ok_json(store_->metric_summary(id));
    }
    // workload history (≈ GetTrialWorkloads, api_trials.go): the
    // training/validation record sequence as workload entries
    if (parts.size() == 5 && parts[4] == "workloads" &&
        req.method == "GET") {
      size_t limit = 1000, offset = 0;
      if (!parse_size(req.query, "limit", &limit) ||
          !parse_size(req.query, "offset", &offset)) {
        return bad_request("limit/offset must be non-negative integers");
      }
      Json arr = Json::array();
      for (auto& rec : store_->read_metrics(id, limit, offset)) {
        Json w = Json::object();
        w.set("kind", rec["group"].as_string())
            .set("steps_completed", rec["steps_completed"].as_int(0))
            .set("time", rec["time"].as_number(0))
            .set("metrics", rec["metrics"]);
        arr.push_back(w);
      }
      Json j = Json::object();
      j.set("workloads", arr);
      return ok_json(j);
    }
    // profiler series discovery (≈ GetTrialProfilerAvailableSeries): the
    // distinct metric names the profiler stream carries, so a chart UI
    // can enumerate before fetching samples
    if (parts.size() == 6 && parts[4] == "profiler" &&
        parts[5] == "series" && req.method == "GET") {
      // samples are flat {"time", "group", <metric>: number, ...} dicts
      // (profiler.py sample_once); a series is "<group>/<metric>"
      std::set<std::string> names;
      for (auto& rec : read_jsonl_tail(
               "trial-" + std::to_string(id) + "-profiler.jsonl", 2000)) {
        if (!rec.is_object()) continue;
        std::string group = rec["group"].as_string();
        if (group.empty()) group = "system";
        for (const auto& [k, v] : rec.items()) {
          if (k == "time" || !v.is_number()) continue;
          names.insert(group + "/" + k);
        }
      }
      Json arr = Json::array();
      for (const auto& n : names) arr.push_back(n);
      Json j = Json::object();
      j.set("series", arr);
      return ok_json(j);
    }
    // profiler samples (≈ master profiler API, common/api/profiler.py)
    if (parts.size() == 5 && parts[4] == "profiler") {
      if (req.method == "POST") {
        Json body = Json::parse(req.body);
        std::vector<const Json*> batch;
        for (const auto& sample : body["samples"].elements()) {
          batch.push_back(&sample);
        }
        append_jsonl_many("trial-" + std::to_string(id) + "-profiler.jsonl",
                          batch);
        return ok_json(Json::object());
      }
      if (req.method == "GET") {
        size_t limit = 1000;
        if (!parse_size(req.query, "limit", &limit)) {
          return bad_request("limit must be a non-negative integer");
        }
        Json arr = Json::array();
        // tail: live monitoring wants the NEWEST samples, and without it
        // anything past the first `limit` records would be unreachable
        for (auto& rec : read_jsonl_tail(
                 "trial-" + std::to_string(id) + "-profiler.jsonl", limit)) {
          arr.push_back(rec);
        }
        Json j = Json::object();
        j.set("samples", arr);
        return ok_json(j);
      }
    }
    // searcher operation poll + completion (≈ SearcherContext +
    // CompleteTrialSearcherValidation api_trials.go:1248)
    if (parts.size() == 6 && parts[4] == "searcher") {
      if (parts[5] == "operation" && req.method == "GET") {
        Json j = Json::object();
        bool closed = trial.state == RunState::Completed ||
                      trial.state == RunState::Errored ||
                      exp.state != RunState::Running;
        j.set("closed", closed);
        j.set("target_units", trial.target_units);
        j.set("units_done", trial.units_done);
        j.set("has_work", !closed && trial.units_done < trial.target_units);
        return ok_json(j);
      }
      if (parts[5] == "completed_op" && req.method == "POST") {
        if (trial.state == RunState::Canceled) {
          // a killed trial's draining harness may still report its last
          // op — the searcher was already told it exited early; accepting
          // this would double-account (and could spawn successor trials)
          Json j = Json::object();
          j.set("trial", trial.to_json());
          return ok_json(j);
        }
        Json body = Json::parse(req.body);
        double metric = body["metric"].as_number();
        int64_t units = body["units"].as_int(trial.target_units);
        trial.units_done = std::max(trial.units_done, units);
        bool smaller = true;
        if (exp.config["searcher"].has("smaller_is_better")) {
          smaller = exp.config["searcher"]["smaller_is_better"].as_bool(true);
        }
        if (!trial.has_metric ||
            (smaller ? metric < trial.best_metric
                     : metric > trial.best_metric)) {
          trial.best_metric = metric;
          trial.has_metric = true;
        }
        if (exp.state == RunState::Running) {
          apply_search_ops(exp, method_for(exp)->on_validation_completed(
                                    trial.request_id, metric, units));
        }
        Json j = Json::object();
        j.set("trial", trial.to_json());
        return ok_json(j);
      }
    }
    // checkpoint report (≈ core/_checkpoint.py:687 chief report)
    if (parts.size() == 5 && parts[4] == "checkpoints" && req.method == "GET") {
      Json arr = Json::array();
      for (const auto& c : checkpoints_) {
        if (c.trial_id == id && !c.deleted) arr.push_back(c.to_json());
      }
      Json j = Json::object();
      j.set("checkpoints", arr);
      return ok_json(j);
    }
    if (parts.size() == 5 && parts[4] == "checkpoints" && req.method == "POST") {
      Json body = Json::parse(req.body);
      CheckpointRecord rec;
      rec.uuid = body["uuid"].as_string();
      rec.trial_id = id;
      rec.experiment_id = trial.experiment_id;
      rec.metadata = body["metadata"];
      rec.resources = body["resources"];
      rec.reported_at = now_sec();
      if (rec.uuid.empty()) return bad_request("checkpoint uuid required");
      checkpoints_.push_back(rec);
      trial.latest_checkpoint = rec.uuid;
      dirty_ = true;
      return ok_json(rec.to_json());
    }
  }

  // ---- checkpoints -------------------------------------------------------
  if (root == "checkpoints" && parts.size() == 4 && req.method == "GET") {
    for (const auto& c : checkpoints_) {
      if (c.uuid == parts[3] && !c.deleted) return ok_json(c.to_json());
    }
    return not_found("no checkpoint " + parts[3]);
  }
  // checkpoint mutation (≈ PatchCheckpoints / DeleteCheckpoints,
  // api_checkpoint.go): metadata merge, and bulk delete that enqueues the
  // zero-slot storage-GC task per owning experiment
  if (root == "checkpoints" && parts.size() == 4 && req.method == "PATCH") {
    if (!rbac_allows(req, role_rank("Editor"))) {
      return HttpResponse::json(
          403, error_json("Editor role required").dump());
    }
    Json body = Json::parse(req.body);
    for (auto& c : checkpoints_) {
      if (c.uuid != parts[3] || c.deleted) continue;
      if (body["metadata"].is_object()) {
        for (const auto& [k, v] : body["metadata"].items()) {
          c.metadata.set(k, v);
        }
      }
      dirty_ = true;
      return ok_json(c.to_json());
    }
    return not_found("no checkpoint " + parts[3]);
  }
  if (root == "checkpoints" && parts.size() == 4 && parts[3] == "delete" &&
      req.method == "POST") {
    if (!rbac_allows(req, role_rank("Editor"))) {
      return HttpResponse::json(
          403, error_json("Editor role required").dump());
    }
    Json body = Json::parse(req.body);
    if (!body["uuids"].is_array()) {
      return bad_request("uuids array required");
    }
    std::set<std::string> wanted;
    for (const auto& u : body["uuids"].elements()) {
      wanted.insert(u.as_string());
    }
    // group doomed checkpoints by experiment so each GC task runs with
    // that experiment's checkpoint_storage config
    std::map<int64_t, std::vector<std::string>> doomed_by_exp;
    int64_t deleted = 0;
    for (auto& c : checkpoints_) {
      if (!wanted.count(c.uuid) || c.deleted) continue;
      c.deleted = true;
      ++deleted;
      doomed_by_exp[c.experiment_id].push_back(c.uuid);
      // a trial whose latest checkpoint was deleted must not resume from it
      for (auto& [tid, t] : trials_) {
        if (t.latest_checkpoint == c.uuid) t.latest_checkpoint.clear();
      }
    }
    for (const auto& [eid, doomed] : doomed_by_exp) {
      auto eit = experiments_.find(eid);
      if (eit != experiments_.end()) {
        spawn_gc_task_locked(eit->second, doomed);
      }
    }
    if (deleted) dirty_ = true;
    Json j = Json::object();
    j.set("deleted", deleted);
    return ok_json(j);
  }

  // ---- NTSC tasks: notebooks/shells/commands/tensorboards ----------------
  // (≈ master/internal/command/command_service.go + api_{notebook,shell,
  //  tensorboard,command}.go, collapsed onto the shared allocation path)
  if (root == "tasks") {
    return tasks_route(req, "", "task", "tasks");
  }
  // typed NTSC roots: aliases over the same machinery with the type pinned
  if (root == "notebooks") {
    return tasks_route(req, "notebook", "notebook", "notebooks");
  }
  if (root == "shells") {
    return tasks_route(req, "shell", "shell", "shells");
  }
  if (root == "commands") {
    return tasks_route(req, "command", "command", "commands");
  }
  if (root == "tensorboards") {
    return tasks_route(req, "tensorboard", "tensorboard", "tensorboards");
  }
  // ---- serving fleets: replica gang allocations (docs/serving.md) --------
  if (root == "serving") {
    return serving_route(req);
  }

  // ---- agents ------------------------------------------------------------
  // ---- resource pools (≈ GetResourcePools, api_resourcepools.go):
  //      configured policies + live slot/agent occupancy per pool --------
  if (root == "resource-pools" && parts.size() == 3 &&
      req.method == "GET") {
    auto pool_json = [&](const std::string& name, const PoolPolicy& p) {
      int agents = 0, slots = 0, used = 0;
      for (const auto& [aid, a] : agents_) {
        if (a.resource_pool != name || !a.enabled) continue;
        ++agents;
        slots += a.slots;
      }
      for (const auto& [aid, alloc] : allocations_) {
        if (alloc.state != RunState::Running &&
            alloc.state != RunState::Pulling) {
          continue;
        }
        // attribute used slots to the agent actually holding them, and
        // only when that agent counts toward totals — otherwise a drained
        // agent's allocations would report >100% pool occupancy
        for (const auto& [raid, n] : alloc.reservations) {
          auto agent_it = agents_.find(raid);
          if (agent_it != agents_.end() && agent_it->second.enabled &&
              agent_it->second.resource_pool == name) {
            used += n;
          }
        }
      }
      Json j = Json::object();
      j.set("name", name)
          .set("scheduler", p.type)
          .set("preemption", p.preemption_enabled)
          .set("agents", static_cast<int64_t>(agents))
          .set("slots_total", static_cast<int64_t>(slots))
          .set("slots_used", static_cast<int64_t>(used))
          .set("is_default", name == "default");
      return j;
    };
    Json arr = Json::array();
    std::set<std::string> seen;
    for (const auto& [name, p] : config_.pools) {
      arr.push_back(pool_json(name, p));
      seen.insert(name);
    }
    // pools that exist only because an agent registered into them run
    // under the default policy — list them too, or occupancy is invisible
    for (const auto& [aid, a] : agents_) {
      if (seen.insert(a.resource_pool).second) {
        arr.push_back(pool_json(a.resource_pool, config_.default_pool));
      }
    }
    if (seen.insert("default").second) {
      arr.push_back(pool_json("default", config_.default_pool));
    }
    Json j = Json::object();
    j.set("resource_pools", arr);
    return ok_json(j);
  }

  if (root == "agents") {
    if (parts.size() == 3 && req.method == "GET") {
      Json arr = Json::array();
      for (const auto& [id, a] : agents_) arr.push_back(a.to_json());
      Json j = Json::object();
      j.set("agents", arr);
      return ok_json(j);
    }
    if (parts.size() == 4 && req.method == "GET") {
      auto ait = agents_.find(parts[3]);
      if (ait == agents_.end()) return not_found("no agent " + parts[3]);
      Json j = Json::object();
      j.set("agent", ait->second.to_json());
      return ok_json(j);
    }
    // operator drain controls (≈ the reference's agent enable/disable,
    // api_agent.go): disable stops NEW fits (scheduler skips !enabled);
    // running allocations drain naturally. draining must be set too — the
    // heartbeat handler re-enables any non-draining live agent, which
    // would silently undo the admin's disable seconds later.
    if (parts.size() == 5 && req.method == "POST" &&
        (parts[4] == "enable" || parts[4] == "disable")) {
      if (!cluster_admin_ok(req)) {
        return HttpResponse::json(
            403, error_json("cluster admin required").dump());
      }
      auto ait = agents_.find(parts[3]);
      if (ait == agents_.end()) return not_found("no agent " + parts[3]);
      bool enable = parts[4] == "enable";
      ait->second.enabled = enable;
      ait->second.admin_disabled = !enable;  // survives re-registration
      dirty_ = true;
      Json j = Json::object();
      j.set("agent", ait->second.to_json());
      return ok_json(j);
    }
    if (parts.size() == 4 && parts[3] == "register" && req.method == "POST") {
      Json body = Json::parse(req.body);
      const std::string& aid = body["id"].as_string();
      if (aid.empty()) return bad_request("agent id required");
      Agent& agent = agents_[aid];
      bool reconnect = !agent.id.empty();
      agent.id = aid;
      agent.slots = static_cast<int>(body["slots"].as_int());
      agent.topology = body["topology"].as_string();
      agent.address = body["address"].as_string();
      if (!body["resource_pool"].as_string().empty()) {
        agent.resource_pool = body["resource_pool"].as_string();
      }
      // a fresh registration is a live node again — unless an operator
      // disabled it: that drain must survive agent restarts
      agent.enabled = !agent.admin_disabled;
      agent.draining = false;
      agent.last_heartbeat = now_sec();
      log_event("info", std::string(reconnect ? "agent reconnected: "
                                              : "agent registered: ") +
                            aid + " (" + std::to_string(agent.slots) +
                            " slots, " + agent.topology + ")");
      dirty_ = true;
      Json j = Json::object();
      j.set("agent", agent.to_json());
      j.set("reconnect", reconnect);
      return ok_json(j);
    }
    if (parts.size() == 5 && parts[4] == "heartbeat" && req.method == "POST") {
      const std::string& aid = parts[3];
      auto it = agents_.find(aid);
      if (it == agents_.end()) return not_found("unregistered agent " + aid);
      it->second.last_heartbeat = now_sec();
      // a draining agent (provisioner-terminated, VM deletion in flight)
      // or an admin-disabled one must not flip back to schedulable on
      // its heartbeats
      if (!it->second.draining && !it->second.admin_disabled) {
        it->second.enabled = true;
      }
      Json body = req.body.empty() ? Json::object() : Json::parse(req.body);
      // exit reports ride the heartbeat at-least-once (agent retries until
      // a heartbeat succeeds); on_task_done is terminal-state idempotent.
      // Processed BEFORE command derivation so a just-exited task can't be
      // re-issued a start below.
      for (const auto& e : body["exited"].elements()) {
        on_task_done(e["allocation_id"].as_string(),
                     static_cast<int>(e["exit_code"].as_int()),
                     e["error"].as_string());
      }
      std::set<std::string> reported;
      for (const auto& r : body["running"].elements()) {
        reported.insert(r.as_string());
      }
      // Commands are DERIVED from state each heartbeat (idempotent): a lost
      // response re-sends on the next beat; duplicate starts are no-ops on
      // the agent. This doubles as master-restart reattach (manager.go:76).
      Json commands = Json::array();
      for (auto& [alloc_id, alloc] : allocations_) {
        bool mine = alloc.reservations.count(aid) > 0;
        bool terminal = alloc.state == RunState::Completed ||
                        alloc.state == RunState::Errored ||
                        alloc.state == RunState::Canceled;
        bool live = alloc.state == RunState::Pulling ||
                    alloc.state == RunState::Running;
        // start derives from "reserved and not yet running here" — NOT from
        // the Pulling state alone: in a gang, the first member's `running`
        // event flips the allocation to Running before slower members'
        // heartbeats, which must still receive their start command
        if (mine && live && !alloc.preempt_requested &&
            !reported.count(alloc_id)) {
          Json cmd = allocation_start_command(alloc, aid);
          int rank = 0;
          for (const auto& [agent_id, n] : alloc.reservations) {
            if (agent_id == aid) break;
            ++rank;
          }
          cmd.set("rank", rank);
          commands.push_back(cmd);
        } else if (mine && alloc.state == RunState::Running &&
                   alloc.preempt_requested && reported.count(alloc_id)) {
          Json cmd = Json::object();
          cmd.set("type", "preempt");
          cmd.set("allocation_id", alloc_id);
          commands.push_back(cmd);
        } else if (!mine && reported.count(alloc_id) &&
                   alloc.state == RunState::Queued &&
                   alloc.reservations.empty()) {
          // post-restart adoption: the agent still runs a task the restored
          // master requeued — take it back instead of double-scheduling
          alloc.reservations[aid] = alloc.slots;
          alloc.state = RunState::Running;
          if (alloc.world_size == 0) alloc.world_size = 1;
          if (alloc.running_at == 0) {
            double now = now_sec();
            alloc.scheduled_at = alloc.scheduled_at ? alloc.scheduled_at : now;
            alloc.running_at = now;
            ++sched_.running_total;
            if (alloc.task_type == "serving") {
              ++sched_.serving_running_total;
            }
            double sub = alloc.submitted_at > 0 ? alloc.submitted_at
                                                : alloc.queued_at;
            if (sub > 0 && now >= sub) {
              sched_.submit_to_running_seconds.observe(now - sub);
            }
            sched_event_locked("running", alloc, alloc.scheduled_at, now);
          }
          if (alloc.trial_id && trials_.count(alloc.trial_id)) {
            trials_[alloc.trial_id].state = RunState::Running;
          }
          dirty_ = true;
        } else if (reported.count(alloc_id) && terminal) {
          Json cmd = Json::object();
          cmd.set("type", "kill");
          cmd.set("allocation_id", alloc_id);
          commands.push_back(cmd);
        }
      }
      // tasks the agent reports that the master has no record of: zombies
      for (const auto& rid : reported) {
        if (!allocations_.count(rid)) {
          Json cmd = Json::object();
          cmd.set("type", "kill");
          cmd.set("allocation_id", rid);
          commands.push_back(cmd);
        }
      }
      Json j = Json::object();
      j.set("commands", commands);
      return ok_json(j);
    }
    if (parts.size() == 5 && parts[4] == "task_event" && req.method == "POST") {
      Json body = Json::parse(req.body);
      const std::string& alloc_id = body["allocation_id"].as_string();
      const std::string& event = body["event"].as_string();
      auto ait = allocations_.find(alloc_id);
      if (ait == allocations_.end()) return not_found("no allocation " + alloc_id);
      if (event == "running") {
        Allocation& alloc = ait->second;
        alloc.state = RunState::Running;
        if (alloc.running_at == 0) {
          // first running report only: gang members each send one, and the
          // latency sample belongs to the first (the gang is live then)
          double now = now_sec();
          alloc.running_at = now;
          ++sched_.running_total;
          if (alloc.task_type == "serving") ++sched_.serving_running_total;
          double sub = alloc.submitted_at > 0 ? alloc.submitted_at
                                              : alloc.queued_at;
          if (sub > 0 && now >= sub) {
            sched_.submit_to_running_seconds.observe(now - sub);
          }
          sched_event_locked("running", alloc,
                             alloc.scheduled_at > 0 ? alloc.scheduled_at : now,
                             now);
        }
        if (alloc.trial_id) {
          trials_[alloc.trial_id].state = RunState::Running;
        }
        dirty_ = true;
      } else if (event == "exited") {
        on_task_done(alloc_id, static_cast<int>(body["exit_code"].as_int()),
                     body["error"].as_string());
      }
      return ok_json(Json::object());
    }
  }

  // ---- allocations: rendezvous / preemption / logs -----------------------
  if (root == "allocations" && parts.size() >= 5) {
    const std::string& alloc_id = parts[3];
    auto it = allocations_.find(alloc_id);
    if (it == allocations_.end()) return not_found("no allocation " + alloc_id);
    Allocation& alloc = it->second;
    // every allocation route is data-plane: rendezvous/allgather posts
    // steer the gang's addresses, proxy registration re-points user
    // traffic, and log posts feed log-pattern policies (a kill/requeue
    // primitive). Under --auth-required the caller must prove membership
    // with the allocation's token (as the trial heartbeat does) or hold a
    // user session. Empty tokens never match: a restored pre-token
    // allocation must not turn an empty header into a grant.
    bool alloc_member =
        !alloc.token.empty() &&
        crypto::constant_time_eq(bearer_token(req), alloc.token);
    if (config_.auth_required && !alloc_member && !current_user(req)) {
      return HttpResponse::json(
          401, error_json("allocation token or session required").dump());
    }

    // rendezvous (≈ task/rendezvous.go:94: all members register, then all
    // receive the full member list; rank 0's host is the jax coordinator)
    if (parts[4] == "rendezvous") {
      if (req.method == "POST") {
        Json body = Json::parse(req.body);
        int rank = static_cast<int>(body["rank"].as_int());
        int world = std::max(1, alloc.world_size);
        if (rank < 0 || rank >= world) {
          return bad_request("rank " + std::to_string(rank) +
                             " out of range for world size " +
                             std::to_string(world));
        }
        alloc.rendezvous[rank] = body["address"].as_string();
        dirty_ = true;
      }
      bool ready = static_cast<int>(alloc.rendezvous.size()) >=
                   std::max(1, alloc.world_size);
      Json members = Json::array();
      for (const auto& [rank, addr] : alloc.rendezvous) members.push_back(addr);
      Json j = Json::object();
      j.set("ready", ready).set("members", members)
          .set("world_size", alloc.world_size);
      if (alloc.n_slices > 1) {
        // multislice gang: tell the harness which DCN slice each rank's
        // host belongs to (ranks are assigned in sorted-agent order, the
        // same order the scheduler reserved slices in — rank r == slice
        // r * n_slices / world). exec/trial.py uses this to build the
        // ICI×DCN mesh with jax.devices() enumerating slice-major.
        int world = std::max(1, alloc.world_size);
        Json slice_ids = Json::array();
        for (int r = 0; r < world; ++r) {
          slice_ids.push_back(
              static_cast<int64_t>(r) * alloc.n_slices / world);
        }
        j.set("n_slices", alloc.n_slices).set("slice_ids", slice_ids);
      }
      return ok_json(j);
    }
    if (parts[4] == "preempt" && req.method == "GET") {
      Json j = Json::object();
      j.set("preempt", alloc.preempt_requested);
      return ok_json(j);
    }
    // general allgather barrier (≈ master/internal/task/allgather): every
    // member posts {rank, round, data}; once world_size members of a round
    // have posted, all receive the rank-ordered payload list. Used by the
    // harness before its own control network exists (e.g. to share ports).
    if (parts[4] == "allgather" && req.method == "POST") {
      // only a live gang may post: a lingering member of a requeued leg
      // must not repopulate the barrier clear_barriers just wiped (its
      // payload would be a dead incarnation's address)
      if (alloc.state != RunState::Pulling &&
          alloc.state != RunState::Running) {
        return HttpResponse::json(
            409, error_json("allocation is not live (state " +
                            std::string(to_string(alloc.state)) + ")")
                     .dump());
      }
      Json body = Json::parse(req.body);
      int rank = static_cast<int>(body["rank"].as_int());
      int64_t round = body["round"].as_int(0);
      int world = std::max(1, alloc.world_size);
      if (rank < 0 || rank >= world) {
        return bad_request("rank " + std::to_string(rank) +
                           " out of range for world size " +
                           std::to_string(world));
      }
      auto& rounds = allgather_[alloc_id];
      rounds[round][rank] = body["data"];
      // older rounds are complete and fetched once a later round starts
      for (auto it2 = rounds.begin(); it2 != rounds.end();) {
        if (it2->first < round - 1) {
          it2 = rounds.erase(it2);
        } else {
          ++it2;
        }
      }
      const auto& members = rounds[round];
      bool ready = static_cast<int>(members.size()) >= world;
      Json data = Json::array();
      if (ready) {
        for (const auto& [r, payload] : members) data.push_back(payload);
      }
      Json j = Json::object();
      j.set("ready", ready).set("round", round)
          .set("world_size", static_cast<int64_t>(world)).set("data", data);
      return ok_json(j);
    }
    // proxy address registration (≈ prep_container.py:231 proxy regs)
    if (parts[4] == "proxy") {
      if (req.method == "POST") {
        Json body = Json::parse(req.body);
        const std::string& addr = body["address"].as_string();
        // validate now so proxying can't hit a malformed address later
        auto colon = addr.rfind(':');
        bool valid = colon != std::string::npos && colon > 0 &&
                     colon + 1 < addr.size();
        if (valid) {
          for (size_t i = colon + 1; i < addr.size(); ++i) {
            if (!std::isdigit(static_cast<unsigned char>(addr[i]))) {
              valid = false;
              break;
            }
          }
        }
        if (!valid) return bad_request("proxy address must be host:port");
        alloc.proxy_address = addr;
        alloc.last_activity = now_sec();
        dirty_ = true;
      }
      Json j = Json::object();
      j.set("address", alloc.proxy_address);
      return ok_json(j);
    }
    if (parts[4] == "logs") {
      if (req.method == "POST") {
        // batched task logs (≈ postTaskLogs core.go:863 → tasklogger)
        Json body = Json::parse(req.body);
        for (const auto& line : body["logs"].elements()) {
          Json rec = Json::object();
          rec.set("allocation_id", alloc_id).set("time", now_sec())
              .set("log", line);
          append_jsonl("task-" + alloc_id + "-logs.jsonl", rec);
        }
        // log-pattern policies (≈ logpattern.go → trial.go:381)
        apply_log_policies(alloc, body["logs"]);
        return ok_json(Json::object());
      }
      if (req.method == "GET") {
        size_t limit = 1000;
        size_t offset = 0;  // stream cursor (generated bindings page with it)
        if (!parse_size(req.query, "limit", &limit) ||
            !parse_size(req.query, "offset", &offset)) {
          return bad_request("limit/offset must be non-negative integers");
        }
        Json arr = Json::array();
        for (auto& rec : read_jsonl("task-" + alloc_id + "-logs.jsonl", limit,
                                    offset)) {
          arr.push_back(rec);
        }
        Json j = Json::object();
        j.set("logs", arr);
        return ok_json(j);
      }
    }
  }

  // ---- cluster: control-plane scheduler telemetry ------------------------
  if (root == "cluster" && req.method == "GET" && parts.size() >= 4 &&
      parts[3] == "scheduler") {
    if (parts.size() == 4) return ok_json(sched_summary_locked());
    if (parts.size() == 5 && parts[4] == "events") {
      return ok_json(sched_events_locked());
    }
  }

  // ---- provisioner (≈ GET provisioner state for ops visibility) ----------
  if (root == "provisioner" && req.method == "GET") {
    if (!provisioner_) {
      Json j = Json::object();
      j.set("enabled", false);
      return ok_json(j);
    }
    return ok_json(provisioner_->status());
  }

  // ---- job queue (≈ jobservice + RM GetJobQ/MoveJob/SetGroupPriority,
  //      resource_manager_iface.go:47-51) -----------------------------------
  if (root == "job-queue") {
    if (parts.size() == 3 && req.method == "GET") {
      Json arr = Json::array();
      for (const auto& [id, alloc] : allocations_) {
        if (alloc.task_type == "unmanaged") continue;  // no resources held
        if (alloc.state == RunState::Queued ||
            alloc.state == RunState::Pulling ||
            alloc.state == RunState::Running) {
          Json j = alloc.to_json();
          arr.push_back(j);
        }
      }
      Json j = Json::object();
      j.set("queue", arr);
      return ok_json(j);
    }
    if (parts.size() == 5 && req.method == "POST") {
      auto it = allocations_.find(parts[3]);
      if (it == allocations_.end()) {
        return not_found("no allocation " + parts[3]);
      }
      Allocation& alloc = it->second;
      // queue mutations are an operator surface
      if (!rbac_allows(req, role_rank("WorkspaceAdmin"))) {
        return HttpResponse::json(
            403, error_json("WorkspaceAdmin role required").dump());
      }
      if (parts[4] == "priority") {
        Json body = Json::parse(req.body);
        if (!body["priority"].is_number()) {
          return bad_request("priority required");
        }
        alloc.priority = static_cast<int>(body["priority"].as_int());
        // an operator reshuffle is a reschedule of queue order: both the
        // specific and the umbrella counter move (docs/observability.md)
        ++sched_.priority_changes_total;
        ++sched_.reschedules_total;
        sched_event_locked("reprioritize", alloc, now_sec(), now_sec());
        dirty_ = true;
        Json j = Json::object();
        j.set("job", alloc.to_json());
        return ok_json(j);
      }
      if (parts[4] == "move") {
        // move ahead_of/behind an anchor job: queue position IS queued_at,
        // and the new position lands BETWEEN the anchor and its actual
        // queue neighbor (the reference's place-between-neighbors decimal
        // positions, time-valued) — a fixed offset could overshoot jobs
        // submitted close together or collide on repeated moves
        Json body = Json::parse(req.body);
        const std::string& ahead_of = body["ahead_of"].as_string();
        const std::string& behind = body["behind"].as_string();
        if ((ahead_of.empty()) == (behind.empty())) {
          return bad_request("exactly one of ahead_of / behind required");
        }
        const std::string& anchor_id = ahead_of.empty() ? behind : ahead_of;
        auto anchor_it = allocations_.find(anchor_id);
        auto in_queue = [](const Allocation& a) {
          return a.task_type != "unmanaged" &&
                 (a.state == RunState::Queued ||
                  a.state == RunState::Pulling ||
                  a.state == RunState::Running);
        };
        if (anchor_it == allocations_.end() ||
            !in_queue(anchor_it->second) || anchor_id == alloc.id) {
          return bad_request("anchor must be a different job currently in "
                             "the queue");
        }
        if (alloc.state != RunState::Queued) {
          return bad_request("only queued jobs can be moved");
        }
        const Allocation& anchor = anchor_it->second;
        // nearest queue neighbor on the target side of the anchor
        double neighbor = ahead_of.empty() ? anchor.queued_at + 2.0
                                           : anchor.queued_at - 2.0;
        bool have_neighbor = false;
        for (const auto& [oid, other] : allocations_) {
          if (oid == alloc.id || oid == anchor_id || !in_queue(other)) {
            continue;
          }
          if (ahead_of.empty()) {  // behind: first job after the anchor
            if (other.queued_at > anchor.queued_at &&
                (!have_neighbor || other.queued_at < neighbor)) {
              neighbor = other.queued_at;
              have_neighbor = true;
            }
          } else {  // ahead_of: last job before the anchor
            if (other.queued_at < anchor.queued_at &&
                (!have_neighbor || other.queued_at > neighbor)) {
              neighbor = other.queued_at;
              have_neighbor = true;
            }
          }
        }
        alloc.queued_at = (anchor.queued_at + neighbor) / 2.0;
        // in priority mode, ordering is priority-first: adopt the anchor's
        // priority so the move is effective there too
        alloc.priority = anchor.priority;
        ++sched_.queue_moves_total;
        ++sched_.reschedules_total;
        sched_event_locked("move", alloc, now_sec(), now_sec());
        dirty_ = true;
        Json j = Json::object();
        j.set("job", alloc.to_json());
        return ok_json(j);
      }
      return not_found("unknown job-queue action " + parts[4]);
    }
  }

  return not_found("unknown route " + req.method + " " + req.path);
}

}  // namespace dct
