// determined-clone-tpu master binary (≈ master/cmd/determined-master/main.go:9).
#include <algorithm>
#include <chrono>
#include <csignal>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <iostream>
#include <map>
#include <sstream>
#include <thread>

#include "config_file.h"
#include "master.h"

namespace {
// async-signal-safe: the handler only sets a flag; the main thread does the
// actual (mutex/join-heavy) shutdown
volatile std::sig_atomic_t g_stop = 0;
void handle_signal(int) { g_stop = 1; }

using dct::configfile::parse_bool;

// "<scheduler>[:nopreempt]" -> PoolPolicy; returns an error string or "".
// One parser for the CLI flag and the config file so validation can't
// drift between them.
std::string parse_pool_policy(const std::string& value,
                              dct::PoolPolicy* policy) {
  auto colon = value.find(':');
  policy->type = value.substr(0, colon);
  if (policy->type != "fifo" && policy->type != "priority" &&
      policy->type != "fair_share" && policy->type != "round_robin") {
    return "unknown pool scheduler '" + policy->type +
           "' (fifo|priority|fair_share|round_robin)";
  }
  policy->preemption_enabled = true;
  if (colon != std::string::npos) {
    const std::string suffix = value.substr(colon + 1);
    if (suffix != "nopreempt") {
      // a typo'd suffix silently leaving preemption ON would betray the
      // operator's intent — reject it
      return "unknown pool option '" + suffix + "' (only :nopreempt)";
    }
    policy->preemption_enabled = false;
  }
  return "";
}

void apply_config_file(const std::string& path, dct::MasterConfig* config) {
  for (const auto& [key, value] : dct::configfile::parse(path)) {
    if (key == "port") config->port = std::atoi(value.c_str());
    else if (key == "data_dir") config->data_dir = value;
    else if (key == "scheduler") config->default_pool.type = value;
    else if (key.rfind("pool.", 0) == 0) {
      // pool.<name>: <scheduler>[:nopreempt]
      dct::PoolPolicy policy;
      std::string err = parse_pool_policy(value, &policy);
      if (!err.empty()) throw std::runtime_error(err + " in " + path);
      config->pools[key.substr(5)] = policy;
    }
    else if (key == "preemption") {
      config->default_pool.preemption_enabled = parse_bool(value);
    } else if (key == "agent_timeout") {
      config->agent_timeout_sec = std::atof(value.c_str());
    } else if (key == "unmanaged_timeout") {
      config->unmanaged_timeout_sec = std::atof(value.c_str());
    } else if (key == "log_retention_records") {
      config->log_retention_records = std::atoll(value.c_str());
    } else if (key == "log_retention_interval") {
      config->log_retention_interval_sec = std::atof(value.c_str());
    } else if (key == "log_retention_grace") {
      config->log_retention_grace_sec = std::atof(value.c_str());
    } else if (key == "max_log_followers") {
      config->max_log_followers = std::atoi(value.c_str());
    } else if (key == "auth_required") config->auth_required = parse_bool(value);
    else if (key == "rbac") config->rbac_enabled = parse_bool(value);
    else if (key == "sso.issuer") {
      if (!dct::split_host_port(value, &config->sso_issuer_host,
                                &config->sso_issuer_port)) {
        throw std::runtime_error("sso.issuer expects host:port");
      }
    } else if (key == "sso.client_id") config->sso_client_id = value;
    else if (key == "sso.client_secret") config->sso_client_secret = value;
    else if (key == "sso.external_host") config->sso_external_host = value;
    else if (key == "session_ttl") {
      config->session_ttl_sec = std::atof(value.c_str());
    } else if (key == "webui_dir") config->webui_dir = value;
    else if (key == "db") config->db = value;
    else if (key == "rm") config->rm = value;
    else if (key == "kube.namespace") config->kube.ns = value;
    else if (key == "kube.image") config->kube.image = value;
    else if (key == "kube.master_host") config->kube.master_host = value;
    else if (key == "kube.slots_per_pod") {
      config->kube.slots_per_pod = std::max(1, std::atoi(value.c_str()));
    } else if (key == "kube.accelerator") config->kube.accelerator = value;
    else if (key == "kube.live") config->kube.dry_run = !parse_bool(value);
    else if (key == "provisioner.accelerator_type") {
      config->provisioner.enabled = true;
      config->provisioner.accelerator_type = value;
    } else if (key == "provisioner.zone") config->provisioner.zone = value;
    else if (key == "provisioner.project") config->provisioner.project = value;
    else if (key == "provisioner.slots_per_instance") {
      config->provisioner.slots_per_instance =
          std::max(1, std::atoi(value.c_str()));
    } else if (key == "provisioner.min_instances") {
      config->provisioner.min_instances = std::atoi(value.c_str());
    } else if (key == "provisioner.max_instances") {
      config->provisioner.max_instances = std::atoi(value.c_str());
    } else if (key == "provisioner.idle_timeout") {
      config->provisioner.idle_timeout_sec = std::atof(value.c_str());
    } else if (key == "provisioner.cooldown") {
      config->provisioner.cooldown_sec = std::atof(value.c_str());
    } else if (key == "provisioner.live") {
      config->provisioner.dry_run = !parse_bool(value);
    } else {
      throw std::runtime_error("unknown config key '" + key + "' in " + path);
    }
  }
}
}  // namespace

int main(int argc, char** argv) {
  dct::MasterConfig config;
  // config file first, flags override (viper precedence: flags > file)
  for (int i = 1; i < argc; ++i) {
    if (!std::strcmp(argv[i], "--config") && i + 1 < argc) {
      try {
        apply_config_file(argv[i + 1], &config);
      } catch (const std::exception& e) {
        std::cerr << e.what() << "\n";
        return 2;
      }
    }
  }
  for (int i = 1; i < argc; ++i) {
    if (!std::strcmp(argv[i], "--config") && i + 1 < argc) {
      ++i;  // handled above
    } else if (!std::strcmp(argv[i], "--port") && i + 1 < argc) {
      config.port = std::atoi(argv[++i]);
    } else if (!std::strcmp(argv[i], "--data-dir") && i + 1 < argc) {
      config.data_dir = argv[++i];
    } else if (!std::strcmp(argv[i], "--scheduler") && i + 1 < argc) {
      config.default_pool.type = argv[++i];
    } else if (!std::strcmp(argv[i], "--pool") && i + 1 < argc) {
      // per-pool scheduler override: --pool name=fifo[:nopreempt]
      std::string arg = argv[++i];
      auto eq = arg.find('=');
      if (eq == std::string::npos || eq == 0) {
        std::cerr << "--pool expects name=scheduler[:nopreempt]\n";
        return 2;
      }
      dct::PoolPolicy policy;
      std::string err = parse_pool_policy(arg.substr(eq + 1), &policy);
      if (!err.empty()) {
        std::cerr << err << "\n";
        return 2;
      }
      config.pools[arg.substr(0, eq)] = policy;
    } else if (!std::strcmp(argv[i], "--agent-timeout") && i + 1 < argc) {
      config.agent_timeout_sec = std::atof(argv[++i]);
    } else if (!std::strcmp(argv[i], "--unmanaged-timeout") && i + 1 < argc) {
      config.unmanaged_timeout_sec = std::atof(argv[++i]);
    } else if (!std::strcmp(argv[i], "--auth-required")) {
      config.auth_required = true;
    } else if (!std::strcmp(argv[i], "--rbac")) {
      config.rbac_enabled = true;
    } else if (!std::strcmp(argv[i], "--sso-issuer") && i + 1 < argc) {
      // host:port of an OIDC-shaped identity provider
      if (!dct::split_host_port(argv[++i], &config.sso_issuer_host,
                                &config.sso_issuer_port)) {
        std::cerr << "--sso-issuer expects host:port\n";
        return 2;
      }
    } else if (!std::strcmp(argv[i], "--sso-client-id") && i + 1 < argc) {
      config.sso_client_id = argv[++i];
    } else if (!std::strcmp(argv[i], "--sso-client-secret") && i + 1 < argc) {
      config.sso_client_secret = argv[++i];
    } else if (!std::strcmp(argv[i], "--sso-external-host") && i + 1 < argc) {
      // externally visible host:port for the IdP callback redirect
      config.sso_external_host = argv[++i];
    } else if (!std::strcmp(argv[i], "--webui-dir") && i + 1 < argc) {
      config.webui_dir = argv[++i];
    } else if (!std::strcmp(argv[i], "--db") && i + 1 < argc) {
      config.db = argv[++i];
      if (config.db != "auto" && config.db != "sqlite" &&
          config.db != "files") {
        std::cerr << "unknown --db '" << config.db
                  << "' (auto|sqlite|files)\n";
        return 2;
      }
    } else if (!std::strcmp(argv[i], "--provision-accelerator") &&
               i + 1 < argc) {
      config.provisioner.enabled = true;
      config.provisioner.accelerator_type = argv[++i];
    } else if (!std::strcmp(argv[i], "--provision-zone") && i + 1 < argc) {
      config.provisioner.zone = argv[++i];
    } else if (!std::strcmp(argv[i], "--provision-project") && i + 1 < argc) {
      config.provisioner.project = argv[++i];
    } else if (!std::strcmp(argv[i], "--provision-slots") && i + 1 < argc) {
      config.provisioner.slots_per_instance = std::max(1, std::atoi(argv[++i]));
    } else if (!std::strcmp(argv[i], "--provision-min") && i + 1 < argc) {
      config.provisioner.min_instances = std::atoi(argv[++i]);
    } else if (!std::strcmp(argv[i], "--provision-max") && i + 1 < argc) {
      config.provisioner.max_instances = std::atoi(argv[++i]);
    } else if (!std::strcmp(argv[i], "--provision-idle-timeout") &&
               i + 1 < argc) {
      config.provisioner.idle_timeout_sec = std::atof(argv[++i]);
    } else if (!std::strcmp(argv[i], "--provision-cooldown") && i + 1 < argc) {
      config.provisioner.cooldown_sec = std::atof(argv[++i]);
    } else if (!std::strcmp(argv[i], "--provision-live")) {
      config.provisioner.dry_run = false;  // actually exec gcloud
    } else if (!std::strcmp(argv[i], "--rm") && i + 1 < argc) {
      config.rm = argv[++i];
      if (config.rm != "agent" && config.rm != "kubernetes") {
        std::cerr << "unknown --rm '" << config.rm
                  << "' (agent|kubernetes)\n";
        return 2;
      }
    } else if (!std::strcmp(argv[i], "--kube-namespace") && i + 1 < argc) {
      config.kube.ns = argv[++i];
    } else if (!std::strcmp(argv[i], "--kube-image") && i + 1 < argc) {
      config.kube.image = argv[++i];
    } else if (!std::strcmp(argv[i], "--kube-master-host") && i + 1 < argc) {
      config.kube.master_host = argv[++i];
    } else if (!std::strcmp(argv[i], "--kube-slots-per-pod") && i + 1 < argc) {
      config.kube.slots_per_pod = std::max(1, std::atoi(argv[++i]));
    } else if (!std::strcmp(argv[i], "--kube-accelerator") && i + 1 < argc) {
      config.kube.accelerator = argv[++i];
    } else if (!std::strcmp(argv[i], "--kube-live")) {
      config.kube.dry_run = false;  // actually exec kubectl
    } else if (!std::strcmp(argv[i], "--help")) {
      std::cout << "usage: dct-master [--config FILE] [--port N] "
                   "[--data-dir DIR] "
                   "[--scheduler fifo|priority|fair_share|round_robin] "
                   "[--agent-timeout SEC] [--auth-required] [--rbac] "
                   "[--webui-dir DIR] "
                   "[--rm agent|kubernetes [--kube-namespace NS] "
                   "[--kube-image IMG] [--kube-master-host H] "
                   "[--kube-slots-per-pod N] [--kube-accelerator A] "
                   "[--kube-live]] "
                   "[--provision-accelerator TYPE [--provision-zone Z] "
                   "[--provision-project P] [--provision-slots N] "
                   "[--provision-min N] [--provision-max N] "
                   "[--provision-idle-timeout SEC] "
                   "[--provision-cooldown SEC] [--provision-live]]\n";
      return 0;
    }
  }
  if (config.rm == "kubernetes" && config.provisioner.enabled) {
    // the TPU-VM provisioner only runs inside the agent RM's tick; letting
    // the flags pass would silently never autoscale
    std::cerr << "--provision-* flags require --rm agent (kubernetes "
                 "autoscaling belongs to the cluster autoscaler)\n";
    return 2;
  }
  // env overrides (≈ viper env config in the reference)
  if (const char* p = std::getenv("DCT_MASTER_PORT")) config.port = std::atoi(p);
  if (const char* d = std::getenv("DCT_MASTER_DATA_DIR")) config.data_dir = d;

  try {
    // construction can throw too (--db sqlite without libsqlite3)
    dct::Master master(config);
    std::signal(SIGINT, handle_signal);
    std::signal(SIGTERM, handle_signal);
    master.start();
    std::cout << "dct-master listening on port " << master.port()
              << " (data dir: " << config.data_dir << ")" << std::endl;
    while (!g_stop) {
      std::this_thread::sleep_for(std::chrono::milliseconds(200));
    }
    master.stop();  // final snapshot save
  } catch (const std::exception& e) {
    std::cerr << "dct-master failed to start: " << e.what() << std::endl;
    return 1;
  }
  return 0;
}
