// Shared YAML-subset config-file parser for the master and agent binaries
// (≈ viper's yaml config loading, master root.go:69-117 / agent
// options.go:47). One parser, two key-apply tables — the format cannot
// drift between the binaries.
//
// Format: `key: value` lines; one nesting level as an indented section
// under `section:` or as dotted keys (`kube.namespace: x`); '#' comments;
// matching single/double quotes around values are stripped.
#pragma once

#include <fstream>
#include <map>
#include <stdexcept>
#include <string>

namespace dct {
namespace configfile {

inline std::string trim(std::string s) {
  size_t a = s.find_first_not_of(" \t");
  size_t b = s.find_last_not_of(" \t\r");
  return a == std::string::npos ? std::string() : s.substr(a, b - a + 1);
}

inline bool parse_bool(const std::string& v) {
  return v == "true" || v == "1" || v == "yes" || v == "on";
}

// Returns dotted-key -> value; throws std::runtime_error with file:line on
// lines it cannot parse.
inline std::map<std::string, std::string> parse(const std::string& path) {
  std::ifstream in(path);
  if (!in) throw std::runtime_error("cannot open config file " + path);
  std::map<std::string, std::string> out;
  std::string line, section;
  int lineno = 0;
  while (std::getline(in, line)) {
    ++lineno;
    // strip comments OUTSIDE quotes only: `dir: "/data/#shared"` keeps its #
    {
      char quote = 0;
      for (size_t i = 0; i < line.size(); ++i) {
        char c = line[i];
        if (quote) {
          if (c == quote) quote = 0;
        } else if (c == '"' || c == '\'') {
          quote = c;
        } else if (c == '#') {
          line = line.substr(0, i);
          break;
        }
      }
    }
    if (trim(line).empty()) continue;
    bool indented = line[0] == ' ' || line[0] == '\t';
    auto colon = line.find(':');
    if (colon == std::string::npos) {
      throw std::runtime_error(path + ":" + std::to_string(lineno) +
                               ": expected 'key: value'");
    }
    std::string key = trim(line.substr(0, colon));
    std::string value = trim(line.substr(colon + 1));
    bool quoted = value.size() >= 2 &&
                  (value.front() == '"' || value.front() == '\'') &&
                  value.back() == value.front();
    if (quoted) value = value.substr(1, value.size() - 2);
    if (value.empty() && !quoted && !indented) {
      section = key;  // `kube:` opens a section (but `key: ""` is a value)
      continue;
    }
    if (!indented) section.clear();
    out[indented && !section.empty() ? section + "." + key : key] = value;
  }
  return out;
}

}  // namespace configfile
}  // namespace dct
