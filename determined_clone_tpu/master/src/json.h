// Minimal JSON value type + parser/serializer for the master's REST API.
//
// The reference master speaks protobuf/grpc-gateway JSON via generated code
// (proto/..., master/internal/api_*.go); this master is REST/JSON-first with
// a small hand-rolled core instead of a codegen pipeline — one wire format,
// no generator step.
#pragma once

#include <cstdint>
#include <map>
#include <memory>
#include <sstream>
#include <stdexcept>
#include <string>
#include <vector>

namespace dct {

class Json;
using JsonArray = std::vector<Json>;
// std::map keeps key order deterministic (stable serialization for tests
// and content hashing).
using JsonObject = std::map<std::string, Json>;

class Json {
 public:
  enum class Type { Null, Bool, Number, String, Array, Object };

  Json() : type_(Type::Null) {}
  Json(std::nullptr_t) : type_(Type::Null) {}
  Json(bool b) : type_(Type::Bool), bool_(b) {}
  Json(int v) : type_(Type::Number), num_(v) {}
  Json(int64_t v) : type_(Type::Number), num_(static_cast<double>(v)) {}
  Json(double v) : type_(Type::Number), num_(v) {}
  Json(const char* s) : type_(Type::String), str_(s) {}
  Json(std::string s) : type_(Type::String), str_(std::move(s)) {}
  Json(JsonArray a) : type_(Type::Array), arr_(std::move(a)) {}
  Json(JsonObject o) : type_(Type::Object), obj_(std::move(o)) {}

  static Json array() { return Json(JsonArray{}); }
  static Json object() { return Json(JsonObject{}); }

  Type type() const { return type_; }
  bool is_null() const { return type_ == Type::Null; }
  bool is_bool() const { return type_ == Type::Bool; }
  bool is_number() const { return type_ == Type::Number; }
  bool is_string() const { return type_ == Type::String; }
  bool is_array() const { return type_ == Type::Array; }
  bool is_object() const { return type_ == Type::Object; }

  bool as_bool(bool dflt = false) const {
    return is_bool() ? bool_ : dflt;
  }
  double as_number(double dflt = 0) const {
    return is_number() ? num_ : dflt;
  }
  int64_t as_int(int64_t dflt = 0) const {
    return is_number() ? static_cast<int64_t>(num_) : dflt;
  }
  const std::string& as_string() const {
    static const std::string empty;
    return is_string() ? str_ : empty;
  }

  // object access
  const Json& operator[](const std::string& key) const {
    static const Json null_json;
    if (!is_object()) return null_json;
    auto it = obj_.find(key);
    return it == obj_.end() ? null_json : it->second;
  }
  Json& set(const std::string& key, Json value) {
    if (!is_object()) { type_ = Type::Object; obj_.clear(); }
    obj_[key] = std::move(value);
    return *this;
  }
  bool has(const std::string& key) const {
    return is_object() && obj_.count(key) > 0;
  }
  const JsonObject& items() const { return obj_; }

  // array access
  const JsonArray& elements() const { return arr_; }
  void push_back(Json v) {
    if (!is_array()) { type_ = Type::Array; arr_.clear(); }
    arr_.push_back(std::move(v));
  }
  size_t size() const {
    if (is_array()) return arr_.size();
    if (is_object()) return obj_.size();
    return 0;
  }

  std::string dump() const {
    std::ostringstream out;
    write(out);
    return out.str();
  }

  // Throws std::runtime_error on malformed input.
  static Json parse(const std::string& text);

 private:
  void write(std::ostringstream& out) const;
  Type type_;
  bool bool_ = false;
  double num_ = 0;
  std::string str_;
  JsonArray arr_;
  JsonObject obj_;
};

}  // namespace dct
