// Domain model for the master: experiments, trials, agents, allocations.
//
// ≈ the reference's DB row structs + in-memory actors (master/pkg/model,
// master/internal/experiment.go:59, trial.go:61, task/allocation.go:96) —
// collapsed into plain structs with JSON (de)serialization; the Store
// persists them via WAL + snapshot instead of Postgres.
#pragma once

#include <cstdint>
#include <map>
#include <optional>
#include <set>
#include <string>
#include <vector>

#include "json.h"

namespace dct {

// -- lifecycle states (≈ determined experiment/trial/allocation states) -----

enum class RunState {
  Queued, Pulling, Running, Paused, Completed, Errored, Canceled,
};

inline const char* to_string(RunState s) {
  switch (s) {
    case RunState::Queued: return "QUEUED";
    case RunState::Pulling: return "PULLING";
    case RunState::Running: return "RUNNING";
    case RunState::Paused: return "PAUSED";
    case RunState::Completed: return "COMPLETED";
    case RunState::Errored: return "ERRORED";
    case RunState::Canceled: return "CANCELED";
  }
  return "UNKNOWN";
}

inline RunState run_state_from(const std::string& s) {
  if (s == "QUEUED") return RunState::Queued;
  if (s == "PULLING") return RunState::Pulling;
  if (s == "RUNNING") return RunState::Running;
  if (s == "PAUSED") return RunState::Paused;
  if (s == "COMPLETED") return RunState::Completed;
  if (s == "ERRORED") return RunState::Errored;
  if (s == "CANCELED") return RunState::Canceled;
  return RunState::Queued;
}

struct Experiment {
  int64_t id = 0;
  std::string name;
  Json config;             // full experiment config (validated client-side too)
  RunState state = RunState::Queued;
  int64_t next_request_id = 0;  // searcher request ids
  Json searcher_snapshot;       // crash-consistent searcher state
  std::string owner = "admin";
  std::string workspace = "Uncategorized";
  std::string project = "Uncategorized";
  double created_at = 0;
  double ended_at = 0;
  std::string error;
  bool archived = false;
  std::string description;
  std::vector<std::string> labels;

  Json to_json() const {
    Json j = Json::object();
    Json lbls = Json::array();
    for (const auto& l : labels) lbls.push_back(l);
    j.set("id", id).set("name", name).set("config", config)
        .set("state", to_string(state))
        .set("next_request_id", next_request_id)
        .set("searcher_snapshot", searcher_snapshot)
        .set("owner", owner).set("workspace", workspace)
        .set("project", project).set("created_at", created_at)
        .set("ended_at", ended_at).set("error", error)
        .set("archived", archived).set("description", description)
        .set("labels", lbls);
    return j;
  }
  static Experiment from_json(const Json& j) {
    Experiment e;
    e.id = j["id"].as_int();
    e.name = j["name"].as_string();
    e.config = j["config"];
    e.state = run_state_from(j["state"].as_string());
    e.next_request_id = j["next_request_id"].as_int();
    e.searcher_snapshot = j["searcher_snapshot"];
    e.owner = j["owner"].as_string();
    e.workspace = j["workspace"].as_string();
    e.project = j["project"].as_string();
    e.created_at = j["created_at"].as_number();
    e.ended_at = j["ended_at"].as_number();
    e.error = j["error"].as_string();
    e.archived = j["archived"].as_bool(false);
    e.description = j["description"].as_string();
    for (const auto& l : j["labels"].elements()) {
      if (l.is_string()) e.labels.push_back(l.as_string());
    }
    return e;
  }
};

struct Trial {
  int64_t id = 0;            // global trial id
  int64_t experiment_id = 0;
  int64_t request_id = 0;    // searcher request id within the experiment
  Json hparams;
  RunState state = RunState::Queued;
  int64_t target_units = 0;   // current cumulative searcher target
  int64_t units_done = 0;
  int restarts = 0;
  // allocation legs ever queued — names each leg's allocation uniquely.
  // DISTINCT from restarts: a clean preemption (pause, priority eviction)
  // starts a new leg without charging a restart, and id reuse would let
  // the agent's at-least-once duplicate exit report for the old leg kill
  // the new one.
  int legs = 0;
  // log-pattern policy tripped: no more restart legs for this trial
  // (≈ logpattern CancelRetries, master/internal/logpattern/logpattern.go)
  bool no_retries = false;
  std::string latest_checkpoint;
  double best_metric = 0;
  bool has_metric = false;
  double created_at = 0;
  double ended_at = 0;
  std::string error;

  Json to_json() const {
    Json j = Json::object();
    j.set("id", id).set("experiment_id", experiment_id)
        .set("request_id", request_id).set("hparams", hparams)
        .set("state", to_string(state))
        .set("target_units", target_units).set("units_done", units_done)
        .set("restarts", restarts).set("legs", legs)
        .set("no_retries", no_retries)
        .set("latest_checkpoint", latest_checkpoint)
        .set("best_metric", best_metric).set("has_metric", has_metric)
        .set("created_at", created_at).set("ended_at", ended_at)
        .set("error", error);
    return j;
  }
  static Trial from_json(const Json& j) {
    Trial t;
    t.id = j["id"].as_int();
    t.experiment_id = j["experiment_id"].as_int();
    t.request_id = j["request_id"].as_int();
    t.hparams = j["hparams"];
    t.state = run_state_from(j["state"].as_string());
    t.target_units = j["target_units"].as_int();
    t.units_done = j["units_done"].as_int();
    t.restarts = static_cast<int>(j["restarts"].as_int());
    // pre-legs snapshots: seed past restarts so old leg ids never recur
    t.legs = static_cast<int>(j["legs"].as_int(t.restarts + 1));
    t.no_retries = j["no_retries"].as_bool();
    t.latest_checkpoint = j["latest_checkpoint"].as_string();
    t.best_metric = j["best_metric"].as_number();
    t.has_metric = j["has_metric"].as_bool();
    t.created_at = j["created_at"].as_number();
    t.ended_at = j["ended_at"].as_number();
    t.error = j["error"].as_string();
    return t;
  }
};

// A TPU-VM node daemon's registration. Slots are chips; topology names the
// slice shape (e.g. "v5e-8") — the scheduler treats same-topology slots on
// one agent as ICI-contiguous (replaces the reference's flat GPU slot model,
// agent/internal/detect/detect.go).
struct Agent {
  std::string id;
  std::string resource_pool = "default";
  int slots = 0;
  std::string topology;      // e.g. "v5e-8", "cpu"
  std::string address;       // host:port the harness can reach
  double last_heartbeat = 0;
  bool enabled = true;
  // terminated by the provisioner: the VM is being deleted, so heartbeats
  // must NOT re-enable it (a fresh registration clears it)
  bool draining = false;
  // operator drain (POST /agents/:id/disable): unlike draining, this
  // survives agent re-registration — only an explicit enable clears it
  bool admin_disabled = false;
  std::set<std::string> blocked_by;  // experiment ids that blocklisted this node

  Json to_json() const {
    Json blocked = Json::array();
    for (const auto& b : blocked_by) blocked.push_back(b);
    Json j = Json::object();
    j.set("id", id).set("resource_pool", resource_pool).set("slots", slots)
        .set("topology", topology).set("address", address)
        .set("last_heartbeat", last_heartbeat).set("enabled", enabled)
        .set("draining", draining).set("admin_disabled", admin_disabled)
        .set("blocked_by", blocked);
    return j;
  }
  static Agent from_json(const Json& j) {
    Agent a;
    a.id = j["id"].as_string();
    a.resource_pool = j["resource_pool"].as_string();
    a.slots = static_cast<int>(j["slots"].as_int());
    a.topology = j["topology"].as_string();
    a.address = j["address"].as_string();
    a.last_heartbeat = j["last_heartbeat"].as_number();
    a.enabled = j["enabled"].as_bool(true);
    a.draining = j["draining"].as_bool(false);
    a.admin_disabled = j["admin_disabled"].as_bool(false);
    for (const auto& b : j["blocked_by"].elements()) {
      a.blocked_by.insert(b.as_string());
    }
    return a;
  }
};

// One gang run of a trial leg (or an NTSC task): reserved slots on agents,
// rendezvous, preemption flag. ≈ master/internal/task/allocation.go:96.
struct Allocation {
  std::string id;            // "trial-<id>.<attempt>" or "task-<uuid>"
  int64_t trial_id = 0;      // 0 for non-trial tasks
  std::string task_type = "trial";  // trial | command | notebook |
                                    // tensorboard | shell | serving
  // serving replicas: the fleet this replica belongs to ("" otherwise)
  std::string fleet;
  RunState state = RunState::Queued;
  int slots = 0;
  int priority = 42;
  std::string resource_pool = "default";
  std::string topology;      // requested slice shape ("" = any)
  // multislice: gang n_slices whole slices (one agent each) joined over
  // DCN; topology then names the PER-SLICE shape. 1 = single-slice.
  int n_slices = 1;
  double queued_at = 0;
  // lifecycle timestamps (epoch seconds, 0 = not reached): submitted is
  // when the work first entered the master (trial creation / task POST);
  // queued_at doubles as the queue-order key, so operator moves rewrite it
  // while submitted_at stays fixed for latency accounting.
  double submitted_at = 0;
  double scheduled_at = 0;   // reservations granted (Queued -> Pulling)
  double running_at = 0;     // harness reported running
  double ended_at = 0;       // terminal (Completed/Errored/Canceled)
  // agent_id -> slots reserved
  std::map<std::string, int> reservations;
  // rendezvous: rank -> address
  std::map<int, std::string> rendezvous;
  int world_size = 0;        // processes expected (num agents in gang)
  bool preempt_requested = false;
  Json spec;                 // what to run (entrypoint, env, ...)
  // -- NTSC task fields (≈ master/internal/command/command.go) --
  std::string name;          // display name for non-trial tasks
  std::string owner = "admin";
  std::string proxy_address;   // host:port registered by the task
                               // (≈ prep_container.py:231 proxy regs)
  double idle_timeout_sec = 0; // kill idle NTSC tasks (task/idle/watcher.go)
  double last_activity = 0;    // updated on proxy hits
  int exit_code = 0;
  // per-allocation secret: the data-plane credential handed to the task via
  // env and required by the task server / proxy path (≈ the reference's
  // allocation session tokens). Only serialized into the snapshot
  // (with_secrets) — never into API responses.
  std::string token;

  bool scheduled() const { return !reservations.empty(); }

  Json to_json(bool with_secrets = false) const {
    Json res = Json::object();
    for (const auto& [aid, n] : reservations) res.set(aid, n);
    Json rdv = Json::object();
    for (const auto& [rank, addr] : rendezvous) {
      rdv.set(std::to_string(rank), addr);
    }
    Json j = Json::object();
    j.set("id", id).set("trial_id", trial_id).set("task_type", task_type)
        .set("fleet", fleet)
        .set("state", to_string(state)).set("slots", slots)
        .set("priority", priority).set("resource_pool", resource_pool)
        .set("topology", topology).set("n_slices", n_slices)
        .set("queued_at", queued_at)
        .set("submitted_at", submitted_at).set("scheduled_at", scheduled_at)
        .set("running_at", running_at).set("ended_at", ended_at)
        .set("reservations", res).set("rendezvous", rdv)
        .set("world_size", world_size)
        .set("preempt_requested", preempt_requested).set("spec", spec)
        .set("name", name).set("owner", owner)
        .set("proxy_address", proxy_address)
        .set("idle_timeout_sec", idle_timeout_sec)
        .set("last_activity", last_activity).set("exit_code", exit_code);
    if (with_secrets) j.set("token", token);
    return j;
  }
  static Allocation from_json(const Json& j) {
    Allocation a;
    a.id = j["id"].as_string();
    a.trial_id = j["trial_id"].as_int();
    a.task_type = j["task_type"].as_string();
    a.fleet = j["fleet"].as_string();
    a.state = run_state_from(j["state"].as_string());
    a.slots = static_cast<int>(j["slots"].as_int());
    a.priority = static_cast<int>(j["priority"].as_int());
    a.resource_pool = j["resource_pool"].as_string();
    a.topology = j["topology"].as_string();
    a.n_slices = static_cast<int>(j["n_slices"].as_int(1));
    a.queued_at = j["queued_at"].as_number();
    // pre-telemetry snapshots: fall back to the queue time so latency
    // math degrades to zero instead of to 1970-sized values
    a.submitted_at = j["submitted_at"].as_number(a.queued_at);
    a.scheduled_at = j["scheduled_at"].as_number(0);
    a.running_at = j["running_at"].as_number(0);
    a.ended_at = j["ended_at"].as_number(0);
    for (const auto& [aid, n] : j["reservations"].items()) {
      a.reservations[aid] = static_cast<int>(n.as_int());
    }
    for (const auto& [rank, addr] : j["rendezvous"].items()) {
      a.rendezvous[std::stoi(rank)] = addr.as_string();
    }
    a.world_size = static_cast<int>(j["world_size"].as_int());
    a.preempt_requested = j["preempt_requested"].as_bool();
    a.spec = j["spec"];
    a.name = j["name"].as_string();
    a.owner = j["owner"].as_string().empty() ? "admin" : j["owner"].as_string();
    a.proxy_address = j["proxy_address"].as_string();
    a.idle_timeout_sec = j["idle_timeout_sec"].as_number();
    a.last_activity = j["last_activity"].as_number();
    a.exit_code = static_cast<int>(j["exit_code"].as_int());
    a.token = j["token"].as_string();
    return a;
  }
};

// One serving fleet: a named gang of `serving` replica allocations
// scheduled against a resource pool (docs/serving.md). The replicas are
// ordinary Allocations (task_type "serving", fleet = name); this record
// holds the desired size and the id sequence.
struct ServingFleetRec {
  std::string name;
  std::string resource_pool = "default";
  int slots_per_replica = 1;
  int priority = 42;
  int desired = 0;       // replicas the fleet should be running
  int64_t next_seq = 1;  // replica id sequence ("serving-<name>-<seq>")
  std::string owner = "admin";
  double created_at = 0;

  Json to_json() const {
    Json j = Json::object();
    j.set("name", name).set("resource_pool", resource_pool)
        .set("slots_per_replica", slots_per_replica)
        .set("priority", priority).set("desired", desired)
        .set("next_seq", next_seq).set("owner", owner)
        .set("created_at", created_at);
    return j;
  }
  static ServingFleetRec from_json(const Json& j) {
    ServingFleetRec f;
    f.name = j["name"].as_string();
    f.resource_pool = j["resource_pool"].as_string().empty()
                          ? "default"
                          : j["resource_pool"].as_string();
    f.slots_per_replica =
        static_cast<int>(j["slots_per_replica"].as_int(1));
    f.priority = static_cast<int>(j["priority"].as_int(42));
    f.desired = static_cast<int>(j["desired"].as_int(0));
    f.next_seq = j["next_seq"].as_int(1);
    f.owner = j["owner"].as_string().empty() ? "admin"
                                             : j["owner"].as_string();
    f.created_at = j["created_at"].as_number(0);
    return f;
  }
};

struct CheckpointRecord {
  std::string uuid;
  int64_t trial_id = 0;
  int64_t experiment_id = 0;
  Json metadata;
  Json resources;
  double reported_at = 0;
  bool deleted = false;

  Json to_json() const {
    Json j = Json::object();
    j.set("uuid", uuid).set("trial_id", trial_id)
        .set("experiment_id", experiment_id).set("metadata", metadata)
        .set("resources", resources).set("reported_at", reported_at)
        .set("deleted", deleted);
    return j;
  }
  static CheckpointRecord from_json(const Json& j) {
    CheckpointRecord c;
    c.uuid = j["uuid"].as_string();
    c.trial_id = j["trial_id"].as_int();
    c.experiment_id = j["experiment_id"].as_int();
    c.metadata = j["metadata"];
    c.resources = j["resources"];
    c.reported_at = j["reported_at"].as_number();
    c.deleted = j["deleted"].as_bool();
    return c;
  }
};

}  // namespace dct
