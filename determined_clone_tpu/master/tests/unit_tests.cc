// C++ unit tests: json, searcher, scheduler, master API (in-process).
// Run under ASan+UBSan via `make test` (the reference runs Go tests with
// -race; sanitizers are the C++ analogue, SURVEY.md §5.2).
#include <cassert>
#include <cmath>
#include <cstdio>
#include <iostream>
#include <set>

#include <random>

#include "../../agent/src/docker.h"
#include "../src/config_file.h"
#include "../src/crypto.h"
#include "../src/kubernetesrm.h"
#include "../src/topology.h"
#include "../src/json.h"
#include "../src/master.h"
#include "../src/provisioner.h"
#include "../src/scheduler.h"
#include "../src/searcher.h"

using namespace dct;

static int tests_run = 0;
#define CHECK(cond)                                                       \
  do {                                                                    \
    ++tests_run;                                                          \
    if (!(cond)) {                                                        \
      std::cerr << __FILE__ << ":" << __LINE__ << " CHECK failed: " #cond \
                << std::endl;                                             \
      std::abort();                                                       \
    }                                                                     \
  } while (0)

// ---------------------------------------------------------------------------

void test_json() {
  Json j = Json::parse(R"({"a": 1, "b": [true, null, "x\n\"y"], "c": {"d": 2.5}})");
  CHECK(j["a"].as_int() == 1);
  CHECK(j["b"].elements().size() == 3);
  CHECK(j["b"].elements()[0].as_bool());
  CHECK(j["b"].elements()[2].as_string() == "x\n\"y");
  CHECK(std::abs(j["c"]["d"].as_number() - 2.5) < 1e-12);
  // roundtrip
  Json again = Json::parse(j.dump());
  CHECK(again.dump() == j.dump());
  // unicode escapes
  Json u = Json::parse(R"("Aé€")");
  CHECK(u.as_string() == "A\xc3\xa9\xe2\x82\xac");
  // errors
  bool threw = false;
  try { Json::parse("{\"a\": }"); } catch (const std::exception&) { threw = true; }
  CHECK(threw);
  threw = false;
  try { Json::parse("[1,2"); } catch (const std::exception&) { threw = true; }
  CHECK(threw);
  // big ints survive
  Json big = Json::parse("{\"v\": 1234567890123}");
  CHECK(big["v"].as_int() == 1234567890123);
  CHECK(big.dump() == "{\"v\":1234567890123}");
}

// ---------------------------------------------------------------------------

Json searcher_cfg(const char* extra) {
  return Json::parse(std::string(R"({"name":"single","metric":"loss",)") +
                     R"("max_length":{"batches":64})" + extra + "}");
}

void test_hparam_sampling() {
  Json space = Json::parse(R"({
    "lr": {"type": "log", "minval": -4, "maxval": -1},
    "width": {"type": "int", "minval": 8, "maxval": 64},
    "act": {"type": "categorical", "vals": ["relu", "gelu"]},
    "nested": {"dropout": {"type": "double", "minval": 0.0, "maxval": 0.5}},
    "const_v": 7
  })");
  std::mt19937_64 rng(42);
  for (int i = 0; i < 50; ++i) {
    Json s = sample_hparams(space, rng);
    double lr = s["lr"].as_number();
    CHECK(lr >= 1e-4 - 1e-12 && lr <= 1e-1 + 1e-12);
    int64_t w = s["width"].as_int();
    CHECK(w >= 8 && w <= 64);
    const std::string& act = s["act"].as_string();
    CHECK(act == "relu" || act == "gelu");
    double d = s["nested"]["dropout"].as_number();
    CHECK(d >= 0.0 && d <= 0.5);
    CHECK(s["const_v"].as_int() == 7);
  }
  Json grid_space = Json::parse(R"({
    "a": {"type": "categorical", "vals": [1, 2, 3]},
    "b": {"type": "double", "minval": 0.0, "maxval": 1.0, "count": 2}
  })");
  auto points = grid_hparams(grid_space);
  CHECK(points.size() == 6);
  std::set<std::string> distinct;
  for (const auto& p : points) distinct.insert(p.dump());
  CHECK(distinct.size() == 6);
}

// drive a method to completion against a synthetic metric
struct SimOutcome {
  std::map<int64_t, int64_t> units;
  std::map<int64_t, Json> hparams;
  bool shutdown = false;
};

SimOutcome drive(SearchMethodCpp* method,
                 double (*metric)(const Json&, int64_t)) {
  SimOutcome out;
  int64_t next_id = 0;
  std::vector<SearchOp> queue = method->initial_operations();
  std::set<int64_t> closed;
  size_t head = 0;
  int guard = 0;
  while (head < queue.size() && ++guard < 100000) {
    SearchOp op = queue[head++];
    if (op.kind == SearchOp::Kind::Create) {
      int64_t rid = next_id++;
      out.hparams[rid] = op.hparams;
      auto more = method->on_trial_created(rid);
      queue.insert(queue.end(), more.begin(), more.end());
    } else if (op.kind == SearchOp::Kind::ValidateAfter) {
      if (closed.count(op.request_id)) continue;
      out.units[op.request_id] = std::max(out.units[op.request_id], op.units);
      double m = metric(out.hparams[op.request_id], op.units);
      auto more = method->on_validation_completed(op.request_id, m, op.units);
      queue.insert(queue.end(), more.begin(), more.end());
    } else if (op.kind == SearchOp::Kind::Close) {
      closed.insert(op.request_id);
    } else if (op.kind == SearchOp::Kind::Shutdown) {
      out.shutdown = true;
      break;
    }
  }
  return out;
}

double lr_metric(const Json& hp, int64_t units) {
  double lr = hp["lr"].as_number();
  return std::abs(std::log10(lr) + 2.0) + 1.0 / (1.0 + units / 8.0);
}

void test_search_methods() {
  Json space = Json::parse(
      R"({"lr": {"type": "log", "minval": -4, "maxval": -1}})");

  {  // single
    auto m = build_search_method(searcher_cfg(""), space, 1);
    auto out = drive(m.get(), lr_metric);
    CHECK(out.shutdown);
    CHECK(out.units.size() == 1);
    CHECK(out.units[0] == 64);
  }
  {  // random
    auto m = build_search_method(
        searcher_cfg(R"(,"name":"random","max_trials":7,"max_concurrent_trials":3)"),
        space, 2);
    auto out = drive(m.get(), lr_metric);
    CHECK(out.shutdown);
    CHECK(out.hparams.size() == 7);
    for (auto& [rid, u] : out.units) CHECK(u == 64);
  }
  {  // grid
    Json gspace = Json::parse(
        R"({"lr": {"type": "log", "minval": -4, "maxval": -1, "count": 5}})");
    auto m = build_search_method(
        searcher_cfg(R"(,"name":"grid","max_trials":100)"), gspace, 3);
    auto out = drive(m.get(), lr_metric);
    CHECK(out.shutdown);
    CHECK(out.hparams.size() == 5);
  }
  {  // asha: early stopping structure
    auto m = build_search_method(
        searcher_cfg(
            R"(,"name":"asha","max_trials":16,"divisor":4,"num_rungs":3,"max_concurrent_trials":4)"),
        space, 4);
    auto out = drive(m.get(), lr_metric);
    CHECK(out.shutdown);
    CHECK(out.hparams.size() == 16);
    int64_t total = 0, top = 0;
    for (auto& [rid, u] : out.units) {
      total += u;
      if (u == 64) ++top;
    }
    CHECK(top >= 1 && top <= 6);
    CHECK(total < 16 * 64 / 2);
  }
  {  // adaptive asha
    auto m = build_search_method(
        searcher_cfg(
            R"(,"name":"adaptive_asha","max_trials":12,"divisor":4,"num_rungs":3,"mode":"standard","max_concurrent_trials":6)"),
        space, 5);
    auto out = drive(m.get(), lr_metric);
    CHECK(out.shutdown);
    CHECK(out.hparams.size() == 12);
  }
  {  // snapshot roundtrip mid-run
    auto cfg = searcher_cfg(
        R"(,"name":"asha","max_trials":8,"divisor":2,"num_rungs":3,"max_concurrent_trials":2)");
    auto m1 = build_search_method(cfg, space, 6);
    auto ops = m1->initial_operations();
    int64_t rid = 0;
    for (auto& op : ops) {
      if (op.kind == SearchOp::Kind::Create) m1->on_trial_created(rid++);
    }
    Json snap = Json::parse(m1->snapshot().dump());
    auto m2 = build_search_method(cfg, space, 6);
    m2->restore(snap);
    CHECK(m2->snapshot().dump() == m1->snapshot().dump());
  }
  {  // unknown searcher name
    bool threw = false;
    try {
      build_search_method(Json::parse(R"({"name":"bogus"})"), space, 0);
    } catch (const std::exception&) {
      threw = true;
    }
    CHECK(threw);
  }
}

// ---------------------------------------------------------------------------

Agent make_agent(const std::string& id, int slots, const std::string& topo) {
  Agent a;
  a.id = id;
  a.slots = slots;
  a.topology = topo;
  a.enabled = true;
  return a;
}

Allocation make_alloc(const std::string& id, int slots, int priority = 42,
                      double queued_at = 0) {
  Allocation a;
  a.id = id;
  a.slots = slots;
  a.priority = priority;
  a.queued_at = queued_at;
  a.state = RunState::Queued;
  return a;
}

void test_scheduler() {
  std::vector<Agent> agents = {
      make_agent("a1", 8, "v5e-8"), make_agent("a2", 8, "v5e-8"),
      make_agent("a3", 4, "v5e-4")};
  std::map<std::string, int> free = {{"a1", 8}, {"a2", 8}, {"a3", 4}};

  {  // single-agent fit prefers minimal surplus (4-chip job → a3)
    auto fit = find_fit(make_alloc("x", 4), agents, free, "");
    CHECK(fit);
    CHECK(fit->count("a3") == 1);
  }
  {  // whole-slice fit
    auto fit = find_fit(make_alloc("x", 8), agents, free, "");
    CHECK(fit);
    CHECK(fit->size() == 1 && fit->begin()->second == 8);
  }
  {  // multi-agent gang: 16 chips = both v5e-8 agents
    auto fit = find_fit(make_alloc("x", 16), agents, free, "");
    CHECK(fit);
    CHECK(fit->size() == 2 && fit->count("a1") && fit->count("a2"));
  }
  {  // unfittable
    auto fit = find_fit(make_alloc("x", 32), agents, free, "");
    CHECK(!fit);
  }
  {  // topology constraint
    Allocation a = make_alloc("x", 4);
    a.topology = "v5e-8";
    auto fit = find_fit(a, agents, free, "");
    CHECK(fit && fit->count("a3") == 0);  // must land on a v5e-8 agent
  }
  {  // blocked node excluded (logpattern)
    std::vector<Agent> blocked = agents;
    blocked[2].blocked_by.insert("exp-1");
    auto fit = find_fit(make_alloc("x", 4), blocked, free, "exp-1");
    CHECK(fit && fit->count("a3") == 0);
  }
  {  // zero-slot task lands on least-loaded agent
    auto fit = find_fit(make_alloc("x", 0), agents, free, "");
    CHECK(fit && fit->begin()->second == 0);
  }
  {  // priority scheduling + preemption
    PoolPolicy pol;
    pol.type = "priority";
    Allocation running = make_alloc("low", 8, 60, 1);
    running.state = RunState::Running;
    running.reservations = {{"a1", 8}};
    std::map<std::string, int> free2 = {{"a1", 0}, {"a2", 8}, {"a3", 4}};
    // high-priority 16-chip gang can't fit → preempt the low-priority job
    auto dec = schedule_pool(pol, agents, free2,
                             {make_alloc("high", 16, 10, 2)}, {running}, {},
                             {});
    CHECK(dec.assignments.empty());
    CHECK(dec.preemptions.size() == 1 && dec.preemptions[0] == "low");
  }
  {  // fifo ordering respected
    PoolPolicy pol;
    pol.type = "fifo";
    auto dec = schedule_pool(pol, agents, free,
                             {make_alloc("b", 8, 42, 2.0),
                              make_alloc("a", 8, 42, 1.0),
                              make_alloc("c", 8, 42, 3.0)},
                             {}, {}, {});
    CHECK(dec.assignments.count("a") && dec.assignments.count("b"));
    CHECK(!dec.assignments.count("c"));  // only two v5e-8 agents
  }
  {  // round robin: owners interleave — A's 2nd job waits for B's 1st
    PoolPolicy pol;
    pol.type = "round_robin";
    std::map<std::string, std::string> owners = {
        {"a-1", "exp-A"}, {"a-2", "exp-A"}, {"b-1", "exp-B"}};
    std::map<std::string, int> free3 = {{"a1", 8}, {"a2", 8}};
    std::vector<Agent> two = {make_agent("a1", 8, "v5e-8"),
                              make_agent("a2", 8, "v5e-8")};
    // arrival order: a-1, a-2, b-1 — fifo would starve B's first job
    auto dec = schedule_pool(pol, two, free3,
                             {make_alloc("a-1", 8, 42, 1.0),
                              make_alloc("a-2", 8, 42, 2.0),
                              make_alloc("b-1", 8, 42, 3.0)},
                             {}, {}, owners);
    CHECK(dec.assignments.count("a-1"));
    CHECK(dec.assignments.count("b-1"));  // round 0 of B beats round 1 of A
    CHECK(!dec.assignments.count("a-2"));
  }
  {  // fair share: owner with less usage goes first
    PoolPolicy pol;
    pol.type = "fair_share";
    std::map<std::string, int> usage = {{"exp-1", 16}, {"exp-2", 0}};
    std::map<std::string, std::string> owners = {{"e1", "exp-1"},
                                                 {"e2", "exp-2"}};
    std::map<std::string, int> free3 = {{"a1", 8}};
    std::vector<Agent> one = {make_agent("a1", 8, "v5e-8")};
    auto dec = schedule_pool(pol, one, free3,
                             {make_alloc("e1", 8, 42, 1.0),
                              make_alloc("e2", 8, 42, 2.0)},
                             {}, usage, owners);
    CHECK(dec.assignments.count("e2"));  // less-used owner wins
    CHECK(!dec.assignments.count("e1"));
  }
}

// ---------------------------------------------------------------------------

HttpRequest make_req(const std::string& method, const std::string& path,
                     const std::string& body = "") {
  HttpRequest r;
  r.method = method;
  r.path = path;
  r.body = body;
  std::istringstream stream(path);
  std::string part;
  while (std::getline(stream, part, '/')) {
    if (!part.empty()) r.path_parts.push_back(part);
  }
  return r;
}

void test_master_api() {
  MasterConfig config;
  config.port = 0;
  config.data_dir = "/tmp/dct-master-test";
  ::system("rm -rf /tmp/dct-master-test");
  Master master(config);  // not start()ed: handle() directly (no tick thread)

  // create experiment
  auto resp = master.handle(make_req("POST", "/api/v1/experiments", R"({
    "config": {
      "name": "t", "entrypoint": "model:Trial",
      "searcher": {"name": "random", "metric": "loss", "max_trials": 2,
                    "max_length": {"batches": 8}, "max_concurrent_trials": 2},
      "resources": {"slots_per_trial": 4},
      "hyperparameters": {"lr": {"type": "double", "minval": 0.1, "maxval": 1.0}}
    }})"));
  CHECK(resp.status == 201);
  Json exp = Json::parse(resp.body)["experiment"];
  CHECK(exp["id"].as_int() == 1);
  CHECK(exp["state"].as_string() == "RUNNING");

  // two trials were created by the searcher
  resp = master.handle(make_req("GET", "/api/v1/experiments/1"));
  CHECK(resp.status == 200);
  Json detail = Json::parse(resp.body);
  CHECK(detail["trials"].elements().size() == 2);
  int64_t t1 = detail["trials"].elements()[0]["id"].as_int();
  CHECK(detail["trials"].elements()[0]["target_units"].as_int() == 8);

  // register an agent and heartbeat: should receive a start command after a
  // manual tick (invoked via the public start? we call handle-only mode, so
  // scheduling happens in tick; emulate by registering + ticking through
  // heartbeat)
  resp = master.handle(make_req("POST", "/api/v1/agents/register",
                                R"({"id": "ag1", "slots": 8, "topology": "v5e-8"})"));
  CHECK(resp.status == 200);

  // no tick thread running: call tick via a heartbeat-triggered path —
  // Master::handle doesn't tick, so run one manual master with start()
  // for the full flow test below instead. Here check queue state:
  resp = master.handle(make_req("GET", "/api/v1/job-queue"));
  CHECK(Json::parse(resp.body)["queue"].elements().size() == 2);

  // report metrics + searcher completion for trial 1
  resp = master.handle(make_req(
      "POST", "/api/v1/trials/" + std::to_string(t1) + "/metrics",
      R"({"group": "training", "steps_completed": 8, "metrics": {"loss": 0.5}})"));
  CHECK(resp.status == 200);
  resp = master.handle(make_req(
      "POST", "/api/v1/trials/" + std::to_string(t1) + "/searcher/completed_op",
      R"({"metric": 0.5, "units": 8})"));
  CHECK(resp.status == 200);
  CHECK(Json::parse(resp.body)["trial"]["state"].as_string() == "COMPLETED");

  // checkpoint report
  resp = master.handle(make_req(
      "POST", "/api/v1/trials/" + std::to_string(t1) + "/checkpoints",
      R"({"uuid": "ck-1", "metadata": {"steps_completed": 8}, "resources": {}})"));
  CHECK(resp.status == 200);
  resp = master.handle(make_req("GET", "/api/v1/checkpoints/ck-1"));
  CHECK(resp.status == 200);
  CHECK(Json::parse(resp.body)["trial_id"].as_int() == t1);

  // searcher operation poll for remaining trial
  resp = master.handle(make_req("GET", "/api/v1/experiments/1"));
  detail = Json::parse(resp.body);
  int64_t t2 = 0;
  for (const auto& t : detail["trials"].elements()) {
    if (t["state"].as_string() != "COMPLETED") t2 = t["id"].as_int();
  }
  CHECK(t2 != 0);
  resp = master.handle(make_req(
      "GET", "/api/v1/trials/" + std::to_string(t2) + "/searcher/operation"));
  Json op = Json::parse(resp.body);
  CHECK(!op["closed"].as_bool());
  CHECK(op["target_units"].as_int() == 8);

  // complete second trial → experiment completes
  resp = master.handle(make_req(
      "POST", "/api/v1/trials/" + std::to_string(t2) + "/searcher/completed_op",
      R"({"metric": 0.4, "units": 8})"));
  CHECK(resp.status == 200);
  resp = master.handle(make_req("GET", "/api/v1/experiments/1"));
  CHECK(Json::parse(resp.body)["experiment"]["state"].as_string() ==
        "COMPLETED");

  // unknown routes 404
  resp = master.handle(make_req("GET", "/api/v1/nonsense"));
  CHECK(resp.status == 404);
  resp = master.handle(make_req("GET", "/api/v1/trials/999"));
  CHECK(resp.status == 404);
  // malformed body 400/500-contained
  resp = master.handle(make_req("POST", "/api/v1/experiments", "{broken"));
  CHECK(resp.status >= 400);
}

void test_master_snapshot_restore() {
  ::system("rm -rf /tmp/dct-master-test2");
  MasterConfig config;
  config.port = 0;
  config.data_dir = "/tmp/dct-master-test2";
  {
    Master master(config);
    master.start();
    auto resp = master.handle(make_req("POST", "/api/v1/experiments", R"({
      "config": {
        "name": "persist", "entrypoint": "m:T",
        "searcher": {"name": "single", "metric": "loss",
                      "max_length": {"batches": 4}},
        "hyperparameters": {"lr": 0.1}
      }})"));
    CHECK(resp.status == 201);
    master.stop();
  }
  {
    Master master(config);
    master.start();
    auto resp = master.handle(make_req("GET", "/api/v1/experiments/1"));
    CHECK(resp.status == 200);
    Json detail = Json::parse(resp.body);
    CHECK(detail["experiment"]["name"].as_string() == "persist");
    CHECK(detail["trials"].elements().size() == 1);
    // searcher still live: completing the op completes the experiment
    int64_t tid = detail["trials"].elements()[0]["id"].as_int();
    resp = master.handle(make_req(
        "POST",
        "/api/v1/trials/" + std::to_string(tid) + "/searcher/completed_op",
        R"({"metric": 1.0, "units": 4})"));
    CHECK(resp.status == 200);
    resp = master.handle(make_req("GET", "/api/v1/experiments/1"));
    CHECK(Json::parse(resp.body)["experiment"]["state"].as_string() ==
          "COMPLETED");
    master.stop();
  }
}

void test_crypto() {
  // SHA-256 FIPS 180-4 test vectors
  uint8_t d[32];
  crypto::sha256(reinterpret_cast<const uint8_t*>(""), 0, d);
  CHECK(crypto::to_hex(d, 32) ==
        "e3b0c44298fc1c149afbf4c8996fb92427ae41e4649b934ca495991b7852b855");
  crypto::sha256(reinterpret_cast<const uint8_t*>("abc"), 3, d);
  CHECK(crypto::to_hex(d, 32) ==
        "ba7816bf8f01cfea414140de5dae2223b00361a396177a9cb410ff61f20015ad");
  // multi-block message (exercises buffering across the 64-byte boundary)
  const std::string two_block =
      "abcdbcdecdefdefgefghfghighijhijkijkljklmklmnlmnomnopnopq";
  crypto::sha256(reinterpret_cast<const uint8_t*>(two_block.data()),
                 two_block.size(), d);
  CHECK(crypto::to_hex(d, 32) ==
        "248d6a61d20638b8e5c026930c3e6039a33ce45964ff2167f6ecedd419db06c1");
  // HMAC-SHA256 (RFC 4231 test case 2)
  crypto::hmac_sha256(reinterpret_cast<const uint8_t*>("Jefe"), 4,
                      reinterpret_cast<const uint8_t*>(
                          "what do ya want for nothing?"), 28, d);
  CHECK(crypto::to_hex(d, 32) ==
        "5bdcc146bf60754e6a042426089575c75a003f089d2739839dec58b964ec3843");
  // PBKDF2-HMAC-SHA256 (RFC 7914 §11 test vector, 1 iter + iterated)
  crypto::pbkdf2_sha256("passwd", "salt", 1, d);
  CHECK(crypto::to_hex(d, 32) ==
        "55ac046e56e3089fec1691c22544b605f94185216dde0465e68b9d57c20dacbc");
  crypto::pbkdf2_sha256("passwd", "salt", 10000, d);
  CHECK(crypto::to_hex(d, 32) ==
        "891ba7f6f871dbadd932fa3b35a3a07054eadd85b47aa470399b3521aaa5b686");
  // constant-time compare
  CHECK(crypto::constant_time_eq("abc", "abc"));
  CHECK(!crypto::constant_time_eq("abc", "abd"));
  CHECK(!crypto::constant_time_eq("abc", "ab"));
  // KDF round-trip + legacy verify + rehash detection
  std::string h = crypto::hash_password("admin", "hunter2");
  CHECK(h.rfind("pbkdf2_sha256$", 0) == 0);
  CHECK(crypto::verify_password(h, "admin", "hunter2"));
  CHECK(!crypto::verify_password(h, "admin", "hunter3"));
  CHECK(!crypto::password_needs_rehash(h));
  // two hashes of the same password differ (random salt)
  CHECK(h != crypto::hash_password("admin", "hunter2"));
  // legacy FNV-1a entry (pre-KDF snapshot format)
  CHECK(crypto::password_needs_rehash("0123456789abcdef"));
  // random tokens are 32 hex chars and distinct
  std::string t1 = crypto::random_token(), t2 = crypto::random_token();
  CHECK(t1.size() == 32 && t2.size() == 32 && t1 != t2);
}

void test_custom_search() {
  // the event queue: callbacks record events and emit no ops
  auto method = build_search_method(
      Json::parse(R"({"name": "custom"})"), Json::object(), 1);
  auto* custom = dynamic_cast<CustomSearchCpp*>(method.get());
  CHECK(custom != nullptr);
  CHECK(custom->initial_operations().empty());
  CHECK(custom->on_trial_created(0).empty());
  CHECK(custom->on_validation_completed(0, 0.5, 4).empty());
  CHECK(custom->on_trial_exited_early(1).empty());
  Json evs = custom->events_after(0);
  CHECK(evs.elements().size() == 4);
  CHECK(evs.elements()[0]["type"].as_string() == "initial_operations");
  CHECK(evs.elements()[2]["type"].as_string() == "validation_completed");
  CHECK(std::abs(evs.elements()[2]["metric"].as_number() - 0.5) < 1e-12);
  // cursor semantics: only events past `since`
  int64_t second = evs.elements()[1]["id"].as_int();
  CHECK(custom->events_after(second).elements().size() == 2);
  // progress + snapshot/restore round-trip
  custom->set_progress(0.25);
  Json snap = custom->snapshot();
  auto method2 = build_search_method(
      Json::parse(R"({"name": "custom"})"), Json::object(), 1);
  auto* custom2 = dynamic_cast<CustomSearchCpp*>(method2.get());
  custom2->restore(snap);
  CHECK(custom2->events_after(0).elements().size() == 4);
  CHECK(std::abs(custom2->progress() - 0.25) < 1e-12);
  custom2->on_trial_created(7);  // ids keep increasing after restore
  Json evs2 = custom2->events_after(0);
  CHECK(evs2.elements().back()["id"].as_int() ==
        evs.elements().back()["id"].as_int() + 1);
  // trial_closed records an event too (remote runners rely on it)
  CHECK(custom2->on_trial_closed(7).empty());
  CHECK(custom2->events_after(0).elements().back()["type"].as_string() ==
        "trial_closed");
  // opt-in trim: acked events drop; later ones stay
  int64_t cut = evs2.elements().back()["id"].as_int();
  custom2->trim_events(cut);
  Json left = custom2->events_after(0);
  CHECK(left.elements().size() == 1);
  CHECK(left.elements()[0]["type"].as_string() == "trial_closed");
  // shutdown op carries cancel distinct from failure
  SearchOp sd = SearchOp::shutdown(false, true);
  CHECK(sd.cancel && !sd.failure);
}

void test_provisioner() {
  ProvisionerConfig cfg;
  cfg.enabled = true;
  cfg.slots_per_instance = 8;
  cfg.max_instances = 3;
  cfg.min_instances = 0;
  cfg.idle_timeout_sec = 10;
  cfg.cooldown_sec = 0;
  cfg.startup_grace_sec = 100;

  // --- pure decisions ---
  ClusterView view;
  view.pending_slots = 12;  // needs ceil(12/8) = 2 slices
  auto d = Provisioner::decide(cfg, view, 0, {});
  CHECK(d.launch.size() == 2 && d.terminate.empty());
  // in-flight capacity counts: 1 starting slice covers 8 of the 12
  d = Provisioner::decide(cfg, view, 1, {});
  CHECK(d.launch.size() == 1);
  // max_instances caps the fleet
  view.pending_slots = 100;
  d = Provisioner::decide(cfg, view, 1, {});
  CHECK(d.launch.size() == 2);  // 1 starting + 2 new = max 3
  // free capacity suppresses launches; idle agents are NOT terminated
  // while the queue is starved
  view.pending_slots = 4;
  view.free_slots = 8;
  d = Provisioner::decide(cfg, view, 0, {"a1"});
  CHECK(d.launch.empty() && d.terminate.empty());
  // empty queue: idle candidates terminate down to min_instances
  view.pending_slots = 0;
  view.free_slots = 16;
  view.agent_ids = {"a1", "a2"};
  cfg.min_instances = 1;
  d = Provisioner::decide(cfg, view, 0, {"a1", "a2"});
  CHECK(d.terminate.size() == 1);
  // below the floor: top back up
  ClusterView empty_view;
  d = Provisioner::decide(cfg, empty_view, 0, {});
  CHECK(d.launch.size() == 1);
  cfg.min_instances = 0;

  // --- stateful lifecycle over a recording client ---
  auto client = std::make_unique<RecordingClient>();
  auto* rec = client.get();
  Provisioner prov(cfg, std::move(client));
  ClusterView v;
  v.now = 1000;
  v.pending_slots = 8;
  auto s = prov.step(v);
  CHECK(s.launch.size() == 1);
  CHECK(rec->commands.size() == 1);
  CHECK(rec->commands[0].find("gcloud compute tpus tpu-vm create") == 0);
  CHECK(rec->commands[0].find("--accelerator-type v5litepod-8") !=
        std::string::npos);
  const std::string instance = s.launch[0];
  // same view next tick: the starting instance covers the demand
  v.now = 1001;
  s = prov.step(v);
  CHECK(s.launch.empty());
  // the instance's agent registers: demand satisfied, nothing to do
  v.now = 1002;
  v.pending_slots = 0;
  v.free_slots = 8;
  v.agent_ids = {instance};
  v.idle_agent_ids = {instance};
  s = prov.step(v);
  CHECK(s.launch.empty() && s.terminate.empty());
  // idle past the timeout: terminated
  v.now = 1013;
  s = prov.step(v);
  CHECK(s.terminate.size() == 1 && s.terminate[0] == instance);
  CHECK(rec->commands.back().find("tpu-vm delete " + instance) !=
        std::string::npos);
  // startup-grace expiry: a launch whose agent never shows stops counting
  ClusterView v2;
  v2.now = 2000;
  v2.pending_slots = 8;
  Provisioner prov2(cfg, std::make_unique<RecordingClient>());
  auto s2 = prov2.step(v2);
  CHECK(s2.launch.size() == 1);
  v2.now = 2050;  // within grace: no relaunch
  CHECK(prov2.step(v2).launch.empty());
  v2.now = 2101;  // grace (100s) expired: presumed failed, relaunch
  CHECK(prov2.step(v2).launch.size() == 1);

  // reconciliation: a registered instance whose agent vanishes (heartbeat
  // timeout) is deleted — slices must never leak without an owner
  auto client3 = std::make_unique<RecordingClient>();
  auto* rec3 = client3.get();
  Provisioner prov3(cfg, std::move(client3));
  ClusterView v3;
  v3.now = 3000;
  v3.pending_slots = 8;
  auto s3 = prov3.step(v3);
  CHECK(s3.launch.size() == 1);
  const std::string inst3 = s3.launch[0];
  CHECK(inst3.rfind("dct-tpu-v5litepod-8-", 0) == 0);
  v3.now = 3001;
  v3.pending_slots = 0;
  v3.free_slots = 8;
  v3.agent_ids = {inst3};
  v3.idle_agent_ids = {};
  prov3.step(v3);  // registers
  v3.now = 3002;
  v3.agent_ids.clear();
  v3.free_slots = 0;
  prov3.step(v3);  // agent gone -> reclaim
  CHECK(rec3->commands.back().find("tpu-vm delete " + inst3) !=
        std::string::npos);
}

void test_docker_argv() {
  auto argv = docker_run_argv(
      "trial-7.0", "dct-harness:latest", "/work", "/work/run-trial-7.0",
      {{"DCT_ALLOCATION_ID", "trial-7.0"}, {"DCT_RANK", "0"}},
      {"/dev/accel0", "/dev/accel1"},
      {"python", "-m", "determined_clone_tpu.exec.trial", "m:T"});
  std::string joined;
  for (const auto& a : argv) joined += a + " ";
  CHECK(joined.find("docker run --rm --name dct-task-trial-7.0") == 0);
  CHECK(joined.find("--network host") != std::string::npos);
  CHECK(joined.find("-v /work:/work") != std::string::npos);
  CHECK(joined.find("-w /work/run-trial-7.0") != std::string::npos);
  CHECK(joined.find("--device /dev/accel0") != std::string::npos);
  CHECK(joined.find("--device /dev/accel1") != std::string::npos);
  CHECK(joined.find("-e DCT_ALLOCATION_ID=trial-7.0") != std::string::npos);
  // image comes after all flags, then the in-container argv verbatim
  CHECK(joined.find("dct-harness:latest python -m "
                    "determined_clone_tpu.exec.trial m:T") !=
        std::string::npos);
}

void test_topology() {
  // slice shapes
  auto s8 = parse_topology("v5e-8");
  CHECK(s8.gen == "v5e" && s8.rows == 2 && s8.cols == 4);
  auto s16 = parse_topology("v5e-16");
  CHECK(s16.rows == 4 && s16.cols == 4);
  CHECK(parse_topology("v5e-1").chips() == 1);
  auto s32 = parse_topology("v4-32");
  CHECK(s32.gen == "v4" && s32.rows == 4 && s32.cols == 8);
  auto flat = parse_topology("cpu", 3);  // unknown: flat row
  CHECK(flat.gen.empty() && flat.rows == 1 && flat.cols == 3);
  // containment: v5e-4 (2x2) fits in v5e-8 (2x4); generations must match
  CHECK(shape_fits(parse_topology("v5e-4"), s8));
  CHECK(!shape_fits(parse_topology("v4-4"), s8));
  CHECK(!shape_fits(s16, s8));
  CHECK(shape_fits(parse_topology("v5e-8"), s16));

  // contiguous placement on a 2x4 torus
  ChipGrid g(s8);
  CHECK(g.place(4, "a"));           // 2x2 (squarest)
  CHECK(g.place(4, "b"));           // remaining 2x2
  CHECK(!g.can_place(1));
  g.release("a");
  CHECK(g.free_chips() == 4);
  CHECK(g.place(2, "c") && g.place(2, "d"));
  // non-rectangular counts never fit a sub-slice
  ChipGrid g2(s8);
  CHECK(!g2.can_place(5));          // no rectangle of area 5 in 2x4
  CHECK(g2.can_place(3));           // 1x3 is contiguous
  // fragmentation: free count 4 but no free rectangle of 4
  ChipGrid g3(s8);
  CHECK(g3.place(2, "p1") && g3.place(2, "p2") &&
        g3.place(2, "p3") && g3.place(2, "p4"));
  g3.release("p1");                 // opposite corners free
  g3.release("p4");
  CHECK(g3.free_chips() == 4);
  CHECK(!g3.can_place(4));          // count-feasible, shape-infeasible
  CHECK(g3.can_place(2));
  // shape-specific reservation
  ChipGrid g4(s16);
  CHECK(g4.place_shape(parse_topology("v5e-8"), "x"));  // 2x4 in 4x4
  CHECK(g4.place_shape(parse_topology("v5e-8"), "y"));
  CHECK(!g4.can_place_shape(parse_topology("v5e-4")));

  // property: random place/release sequences keep invariants (no overlap,
  // in-bounds, counts consistent)
  std::mt19937_64 rng(42);
  ChipGrid pg(s16);
  std::map<std::string, int> live;  // owner -> chips
  int next = 0;
  for (int step = 0; step < 500; ++step) {
    if (live.empty() || rng() % 2 == 0) {
      int n = static_cast<int>(rng() % 8) + 1;
      std::string owner = "o" + std::to_string(next++);
      int before = pg.free_chips();
      if (pg.place(n, owner)) {
        CHECK(pg.free_chips() == before - n);
        live[owner] = n;
      } else {
        CHECK(pg.free_chips() == before);  // failed place mutates nothing
      }
    } else {
      auto it = live.begin();
      std::advance(it, rng() % live.size());
      int before = pg.free_chips();
      pg.release(it->first);
      CHECK(pg.free_chips() == before + it->second);
      live.erase(it);
    }
    int held = 0;
    for (const auto& [o, n] : live) held += n;
    CHECK(pg.free_chips() == 16 - held);
  }

  // scheduler level: fragmentation-aware single-agent fitting
  Agent agent;
  agent.id = "a1";
  agent.slots = 8;
  agent.topology = "v5e-8";
  Allocation mk;
  mk.task_type = "trial";
  auto fit_with = [&](int slots, const std::string& topo,
                      const std::vector<Allocation>& running) {
    Allocation a = mk;
    a.id = "want";
    a.slots = slots;
    a.topology = topo;
    std::map<std::string, int> free = {{"a1", agent.slots}};
    for (const auto& r : running) {
      for (const auto& [aid, n] : r.reservations) free[aid] -= n;
    }
    auto grids = build_chip_grids({agent}, running);
    return find_fit(a, {agent}, free, "", &grids).has_value();
  };
  CHECK(fit_with(8, "", {}));
  CHECK(!fit_with(5, "", {}));       // non-rectangular: rejected up front
  Allocation r1 = mk;
  r1.id = "r1";
  r1.slots = 6;
  r1.queued_at = 1;
  r1.reservations = {{"a1", 6}};     // 2x3 rectangle
  CHECK(fit_with(2, "", {r1}));      // 2x1 fits beside it
  CHECK(!fit_with(4, "", {r1}));     // only 2 chips free
  // sub-slice topology request fits inside a larger slice
  CHECK(fit_with(4, "v5e-4", {}));
  CHECK(!fit_with(4, "v4-4", {}));   // generation mismatch
  // unknown generation is NOT a wildcard: a TPU gang must not land on a
  // topology-less (CPU) host
  Agent cpu_agent;
  cpu_agent.id = "cpu1";
  cpu_agent.slots = 4;
  cpu_agent.topology = "cpu";
  Allocation want_tpu = mk;
  want_tpu.id = "wt";
  want_tpu.slots = 2;
  want_tpu.topology = "v5e-2";
  std::map<std::string, int> cpu_free = {{"cpu1", 4}};
  auto cpu_grids = build_chip_grids({cpu_agent}, {});
  CHECK(!find_fit(want_tpu, {cpu_agent}, cpu_free, "", &cpu_grids));
  // ...while a topology-less request still uses any host
  want_tpu.topology = "";
  CHECK(find_fit(want_tpu, {cpu_agent}, cpu_free, "", &cpu_grids));
}

void test_config_file_parser() {
  const char* path = "/tmp/dct-configfile-test.yaml";
  {
    FILE* f = fopen(path, "w");
    fputs("# comment\n"
          "port: 9000\n"
          "data_dir: \"/data/#shared\"  # quoted hash survives\n"
          "empty: \"\"\n"
          "kube:\n"
          "  namespace: prod\n"
          "  image: 'img:tag'\n"
          "flat_after: x\n",
          f);
    fclose(f);
  }
  auto kv = configfile::parse(path);
  CHECK(kv.at("port") == "9000");
  CHECK(kv.at("data_dir") == "/data/#shared");  // comment strip is quote-aware
  CHECK(kv.at("empty") == "");                  // quoted empty != section
  CHECK(kv.at("kube.namespace") == "prod");
  CHECK(kv.at("kube.image") == "img:tag");
  CHECK(kv.at("flat_after") == "x");            // section closed by outdent
  CHECK(kv.count("empty") == 1);
  ::remove(path);

  bool threw = false;
  try {
    configfile::parse("/nonexistent/nope.yaml");
  } catch (const std::exception&) {
    threw = true;
  }
  CHECK(threw);

  std::string host;
  int port = 0;
  CHECK(split_host_port("idp.example:8443", &host, &port));
  CHECK(host == "idp.example" && port == 8443);
  CHECK(!split_host_port("nocolon", &host, &port));
  CHECK(!split_host_port("host:", &host, &port));
  CHECK(!split_host_port("host:99999", &host, &port));
  CHECK(!split_host_port("host:8a0", &host, &port));
}

void test_kubernetesrm_manifest() {
  KubeRmConfig cfg;
  cfg.ns = "tpu-ns";
  cfg.image = "dct:1";
  cfg.master_host = "dct-master";
  cfg.master_port = 8080;
  cfg.slots_per_pod = 8;
  KubernetesRM rm(cfg, std::make_unique<DryRunKubectl>(
                           "/tmp/dct-kube-unit-test"));

  Allocation alloc;
  alloc.id = "trial-9.0";
  alloc.task_type = "trial";
  alloc.slots = 12;  // 2 pods: 8 + 4
  alloc.topology = "v5e-16";
  alloc.world_size = 2;
  alloc.token = "tok";
  alloc.spec.set("entrypoint", "m:T");

  Json cmd = Json::object();
  cmd.set("alloc_token", alloc.token).set("slots", 8)
      .set("world_size", 2).set("task_type", alloc.task_type)
      .set("spec", alloc.spec);
  Json pod = rm.pod_manifest(alloc, cmd, 0, 2, 8);
  CHECK(pod["kind"].as_string() == "Pod");
  CHECK(pod["metadata"]["namespace"].as_string() == "tpu-ns");
  CHECK(pod["metadata"]["name"].as_string() == "dct-trial-9-0-0");
  CHECK(pod["metadata"]["labels"]["dct-managed"].as_string() == "true");
  CHECK(pod["spec"]["restartPolicy"].as_string() == "Never");
  CHECK(pod["spec"]["containers"].elements()[0]["resources"]["limits"]
           ["google.com/tpu"].as_string() == "8");
  CHECK(pod["spec"]["nodeSelector"]["cloud.google.com/gke-tpu-topology"]
            .as_string() == "v5e-16");
  // trial argv derives from the entrypoint
  const auto& argv =
      pod["spec"]["containers"].elements()[0]["command"].elements();
  CHECK(argv.size() == 4 && argv[3].as_string() == "m:T");
  // env carries the data-plane credentials
  bool saw_token = false;
  for (const auto& e :
       pod["spec"]["containers"].elements()[0]["env"].elements()) {
    if (e["name"].as_string() == "DCT_ALLOC_TOKEN") {
      saw_token = e["value"].as_string() == "tok";
    }
  }
  CHECK(saw_token);
  ::system("rm -rf /tmp/dct-kube-unit-test");
}

int run_all() {
  test_config_file_parser();
  test_kubernetesrm_manifest();
  test_crypto();
  test_custom_search();
  test_provisioner();
  test_docker_argv();
  test_topology();
  test_json();
  test_hparam_sampling();
  test_search_methods();
  test_scheduler();
  test_master_api();
  test_master_snapshot_restore();
  std::cout << "all C++ unit tests passed (" << tests_run << " checks)"
            << std::endl;
  return 0;
}

int main() { return run_all(); }
