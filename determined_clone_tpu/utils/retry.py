"""Unified retry/backoff: one policy type for every transient-failure loop.

Replaces the ad-hoc ``time.sleep`` retry loops that used to live in
``api/client.py`` (and that dctlint's RETRY001 now rejects elsewhere).
Every policy gives exponential backoff with *full jitter* — delay drawn
uniformly from ``[0, min(max_delay, base * mult**(failures-1))]`` — which
decorrelates a gang of workers hammering the same recovering dependency.
Deadlines are monotonic-clock, so NTP steps can't make a retry loop spin
forever or give up early.

Retries are observable: each policy name gets a ``retries_<name>`` counter
in the registry handed to :func:`set_registry` (the telemetry registry when
observability is on), plus a module-local :func:`stats` dict for tests.

Test seams: ``_sleep`` and ``_rng`` are module globals looked up at call
time — monkeypatch them to capture exact backoff sequences without waiting.
"""
from __future__ import annotations

import dataclasses
import random
import time
from typing import Any, Callable, Dict, Optional, Tuple

DEFAULT_RETRYABLE: Tuple[type, ...] = (ConnectionError, TimeoutError, OSError)

_sleep = time.sleep
_rng = random.Random()
_registry = None
_stats: Dict[str, int] = {}


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """How a named class of operations retries. Frozen: share instances."""

    name: str
    max_attempts: int = 4
    base_delay_s: float = 0.1
    multiplier: float = 2.0
    max_delay_s: float = 5.0
    jitter: str = "full"  # "full" | "none"
    deadline_s: Optional[float] = None
    retryable: Tuple[type, ...] = DEFAULT_RETRYABLE

    def backoff(self, failures: int,
                rng: Optional[random.Random] = None) -> float:
        """Delay before the retry that follows the Nth failure (1-based)."""
        cap = min(self.max_delay_s,
                  self.base_delay_s * self.multiplier ** max(failures - 1, 0))
        if self.jitter == "none":
            return cap
        return (rng if rng is not None else _rng).uniform(0.0, cap)


def set_registry(registry: Any) -> None:
    """Route per-policy retry counters into a MetricsRegistry (or None)."""
    global _registry
    _registry = registry


def stats() -> Dict[str, int]:
    """{policy name: retries recorded} since the last reset (tests)."""
    return dict(_stats)


def reset_stats() -> None:
    _stats.clear()


def _record(name: str) -> None:
    _stats[name] = _stats.get(name, 0) + 1
    if _registry is not None:
        _registry.counter(f"retries_{name}",
                          f"retries under policy {name!r}").inc()


def retry_call(fn: Callable[..., Any], *args: Any,
               policy: RetryPolicy,
               rng: Optional[random.Random] = None,
               sleep: Optional[Callable[[float], None]] = None,
               on_retry: Optional[Callable[[BaseException, int, float],
                                           None]] = None,
               **kwargs: Any) -> Any:
    """Call ``fn`` under ``policy``; re-raise on exhaustion or deadline.

    Only ``policy.retryable`` exceptions are retried; anything else
    propagates immediately. ``on_retry(exc, failures, delay)`` runs before
    each backoff sleep.
    """
    deadline = (time.monotonic() + policy.deadline_s
                if policy.deadline_s is not None else None)
    failures = 0
    while True:
        try:
            return fn(*args, **kwargs)
        except policy.retryable as exc:
            failures += 1
            if failures >= policy.max_attempts:
                raise
            delay = policy.backoff(failures, rng)
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise
                delay = min(delay, remaining)
            _record(policy.name)
            if on_retry is not None:
                on_retry(exc, failures, delay)
            (sleep if sleep is not None else _sleep)(delay)


def sleep_backoff(policy: RetryPolicy, failures: int,
                  rng: Optional[random.Random] = None) -> float:
    """Backoff sleep for loops whose retry structure lives elsewhere
    (e.g. the experiment runner's restart queue). Records the retry under
    the policy's name; returns the delay actually slept."""
    delay = policy.backoff(max(failures, 1), rng)
    _record(policy.name)
    _sleep(delay)
    return delay
