"""Data utilities: in-memory datasets, batch iterators, MNIST loading.

The reference wraps torch DataLoaders (harness/determined/pytorch/_data.py,
samplers.py); here data reaches the device as whole global batches that
``device_put`` scatters across the mesh's (dp, fsdp) axes. Determinism comes
from seeding the shuffle with (seed, epoch) — the reference's
reproducibility.experiment_seed contract.

No egress in the build environment, so ``synthetic_mnist`` provides a
deterministic learnable stand-in (class-prototype images + noise); real
MNIST IDX files are loaded when a path is available.
"""
from __future__ import annotations

import gzip
import os
import struct
from typing import Iterator, Optional, Tuple

import numpy as np


_PROTO_SEED = 1234  # class prototypes are fixed across splits


def synthetic_mnist(n: int = 8192, seed: int = 0, image: bool = False
                    ) -> Tuple[np.ndarray, np.ndarray]:
    """A learnable 10-class stand-in for MNIST: each class is a fixed random
    prototype in 784-d (shared across train/val splits), samples are
    prototype + gaussian noise. ``seed`` only varies the samples. Separable
    enough that the reference's 0.97-accuracy gate
    (e2e_tests/tests/nightly/test_convergence.py:25) is meaningful."""
    protos = np.random.RandomState(_PROTO_SEED).randn(10, 784).astype(np.float32)
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, 10, size=n).astype(np.int32)
    x = protos[labels] + 0.9 * rng.randn(n, 784).astype(np.float32)
    if image:
        x = x.reshape(n, 28, 28, 1)
    return x, labels


def load_mnist_idx(data_dir: str, split: str = "train", image: bool = False
                   ) -> Tuple[np.ndarray, np.ndarray]:
    """Load MNIST from IDX files (raw or .gz) if present."""
    prefix = "train" if split == "train" else "t10k"
    imgs = _read_idx(os.path.join(data_dir, f"{prefix}-images-idx3-ubyte"))
    labels = _read_idx(os.path.join(data_dir, f"{prefix}-labels-idx1-ubyte"))
    x = imgs.astype(np.float32) / 255.0
    y = labels.astype(np.int32)
    if image:
        x = x.reshape(-1, 28, 28, 1)
    else:
        x = x.reshape(-1, 784)
    return x, y


def _read_idx(path: str) -> np.ndarray:
    opener = open
    if not os.path.exists(path) and os.path.exists(path + ".gz"):
        path, opener = path + ".gz", gzip.open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        shape = struct.unpack(f">{ndim}I", f.read(4 * ndim))
        return np.frombuffer(f.read(), dtype=np.uint8).reshape(shape)


def digits_dataset(split: str = "train", image: bool = False
                   ) -> Tuple[np.ndarray, np.ndarray]:
    """Real handwritten-digit data without egress: sklearn's bundled digits
    set (1,797 8×8 scans from UCI), upsampled to the 28×28 mnist geometry
    (×4 nearest-neighbour, centre-crop). This backs the offline equivalent
    of the reference's real-mnist convergence gate
    (e2e_tests/tests/nightly/test_convergence.py:25) — same task family,
    genuinely held-out test split, accuracy comparable to mnist's."""
    from sklearn.datasets import load_digits

    d = load_digits()
    x = d.images.astype(np.float32) / 16.0          # [N, 8, 8] in [0, 1]
    y = d.target.astype(np.int32)
    x = np.repeat(np.repeat(x, 4, axis=1), 4, axis=2)[:, 2:30, 2:30]
    idx = np.random.RandomState(_PROTO_SEED).permutation(len(x))
    n_train = int(0.8 * len(x))
    sel = idx[:n_train] if split == "train" else idx[n_train:]
    x, y = x[sel], y[sel]
    if image:
        x = x[..., None]
    else:
        x = x.reshape(len(x), -1)
    return np.ascontiguousarray(x), np.ascontiguousarray(y)


def mnist_dataset(data_dir: Optional[str] = None, split: str = "train",
                  image: bool = False, synthetic_n: int = 8192,
                  seed: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    """Real MNIST if data_dir has IDX files, else the synthetic stand-in."""
    if data_dir:
        try:
            return load_mnist_idx(data_dir, split, image)
        except FileNotFoundError:
            pass
    return synthetic_mnist(
        synthetic_n if split == "train" else max(1024, synthetic_n // 8),
        seed=seed if split == "train" else seed + 1,
        image=image,
    )


def batch_iterator(x: np.ndarray, y: np.ndarray, batch_size: int, *,
                   seed: int = 0, epoch: int = 0, shuffle: bool = True,
                   drop_remainder: bool = True
                   ) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Deterministic shuffled batches of (x, y)."""
    n = len(x)
    idx = np.arange(n)
    if shuffle:
        np.random.RandomState((seed * 1_000_003 + epoch) % (2**31)).shuffle(idx)
    end = n - (n % batch_size) if drop_remainder else n
    for i in range(0, end, batch_size):
        sel = idx[i:i + batch_size]
        yield x[sel], y[sel]
