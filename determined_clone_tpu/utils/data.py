"""Data utilities: in-memory datasets, batch iterators, MNIST loading.

The reference wraps torch DataLoaders (harness/determined/pytorch/_data.py,
samplers.py); here data reaches the device as whole global batches that
``device_put`` scatters across the mesh's (dp, fsdp) axes. Determinism comes
from seeding the shuffle with (seed, epoch) — the reference's
reproducibility.experiment_seed contract.

No egress in the build environment, so ``synthetic_mnist`` provides a
deterministic learnable stand-in (class-prototype images + noise); real
MNIST IDX files are loaded when a path is available.
"""
from __future__ import annotations

import gzip
import os
import queue
import struct
import threading
import time
from typing import Any, Callable, Iterable, Iterator, Optional, Tuple

import numpy as np

from determined_clone_tpu import faults
from determined_clone_tpu.telemetry.spans import null_span


_PROTO_SEED = 1234  # class prototypes are fixed across splits


def synthetic_mnist(n: int = 8192, seed: int = 0, image: bool = False
                    ) -> Tuple[np.ndarray, np.ndarray]:
    """A learnable 10-class stand-in for MNIST: each class is a fixed random
    prototype in 784-d (shared across train/val splits), samples are
    prototype + gaussian noise. ``seed`` only varies the samples. Separable
    enough that the reference's 0.97-accuracy gate
    (e2e_tests/tests/nightly/test_convergence.py:25) is meaningful."""
    protos = np.random.RandomState(_PROTO_SEED).randn(10, 784).astype(np.float32)
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, 10, size=n).astype(np.int32)
    x = protos[labels] + 0.9 * rng.randn(n, 784).astype(np.float32)
    if image:
        x = x.reshape(n, 28, 28, 1)
    return x, labels


def load_mnist_idx(data_dir: str, split: str = "train", image: bool = False
                   ) -> Tuple[np.ndarray, np.ndarray]:
    """Load MNIST from IDX files (raw or .gz) if present."""
    prefix = "train" if split == "train" else "t10k"
    imgs = _read_idx(os.path.join(data_dir, f"{prefix}-images-idx3-ubyte"))
    labels = _read_idx(os.path.join(data_dir, f"{prefix}-labels-idx1-ubyte"))
    x = imgs.astype(np.float32) / 255.0
    y = labels.astype(np.int32)
    if image:
        x = x.reshape(-1, 28, 28, 1)
    else:
        x = x.reshape(-1, 784)
    return x, y


def _read_idx(path: str) -> np.ndarray:
    opener = open
    if not os.path.exists(path) and os.path.exists(path + ".gz"):
        path, opener = path + ".gz", gzip.open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        shape = struct.unpack(f">{ndim}I", f.read(4 * ndim))
        return np.frombuffer(f.read(), dtype=np.uint8).reshape(shape)


def digits_dataset(split: str = "train", image: bool = False
                   ) -> Tuple[np.ndarray, np.ndarray]:
    """Real handwritten-digit data without egress: sklearn's bundled digits
    set (1,797 8×8 scans from UCI), upsampled to the 28×28 mnist geometry
    (×4 nearest-neighbour, centre-crop). This backs the offline equivalent
    of the reference's real-mnist convergence gate
    (e2e_tests/tests/nightly/test_convergence.py:25) — same task family,
    genuinely held-out test split, accuracy comparable to mnist's."""
    from sklearn.datasets import load_digits

    d = load_digits()
    x = d.images.astype(np.float32) / 16.0          # [N, 8, 8] in [0, 1]
    y = d.target.astype(np.int32)
    x = np.repeat(np.repeat(x, 4, axis=1), 4, axis=2)[:, 2:30, 2:30]
    idx = np.random.RandomState(_PROTO_SEED).permutation(len(x))
    n_train = int(0.8 * len(x))
    sel = idx[:n_train] if split == "train" else idx[n_train:]
    x, y = x[sel], y[sel]
    if image:
        x = x[..., None]
    else:
        x = x.reshape(len(x), -1)
    return np.ascontiguousarray(x), np.ascontiguousarray(y)


def mnist_dataset(data_dir: Optional[str] = None, split: str = "train",
                  image: bool = False, synthetic_n: int = 8192,
                  seed: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    """Real MNIST if data_dir has IDX files, else the synthetic stand-in."""
    if data_dir:
        try:
            return load_mnist_idx(data_dir, split, image)
        except FileNotFoundError:
            pass
    return synthetic_mnist(
        synthetic_n if split == "train" else max(1024, synthetic_n // 8),
        seed=seed if split == "train" else seed + 1,
        image=image,
    )


class BatchIterator:
    """Deterministic shuffled batches of (x, y) with an index-skip fast path.

    The shuffle order is fixed up front from (seed, epoch), so skipping n
    already-consumed batches (checkpoint-restore replay) is pure arithmetic
    on the cursor — no gather, no copy — via :meth:`skip_batches`. The
    Trainer probes for that method when fast-forwarding a restored run.
    """

    def __init__(self, x: np.ndarray, y: np.ndarray, batch_size: int, *,
                 seed: int = 0, epoch: int = 0, shuffle: bool = True,
                 drop_remainder: bool = True) -> None:
        self._x, self._y = x, y
        self._batch_size = batch_size
        n = len(x)
        idx = np.arange(n)
        if shuffle:
            np.random.RandomState(
                (seed * 1_000_003 + epoch) % (2**31)).shuffle(idx)
        self._idx = idx
        self._end = n - (n % batch_size) if drop_remainder else n
        self._pos = 0

    def __iter__(self) -> "BatchIterator":
        return self

    def __next__(self) -> Tuple[np.ndarray, np.ndarray]:
        if self._pos >= self._end:
            raise StopIteration
        sel = self._idx[self._pos:self._pos + self._batch_size]
        self._pos += self._batch_size
        return self._x[sel], self._y[sel]

    def __len__(self) -> int:
        """Batches remaining (partial final batch counts when kept)."""
        left = max(self._end - self._pos, 0)
        return -(-left // self._batch_size)

    def skip_batches(self, n: int) -> int:
        """Advance past up to ``n`` batches without materializing them;
        returns how many were actually skipped (< n once exhausted)."""
        k = min(max(n, 0), len(self))
        self._pos += k * self._batch_size
        return k


def batch_iterator(x: np.ndarray, y: np.ndarray, batch_size: int, *,
                   seed: int = 0, epoch: int = 0, shuffle: bool = True,
                   drop_remainder: bool = True) -> BatchIterator:
    """Deterministic shuffled batches of (x, y)."""
    return BatchIterator(x, y, batch_size, seed=seed, epoch=epoch,
                         shuffle=shuffle, drop_remainder=drop_remainder)


# ---------------------------------------------------------------------------
# Device feeding: async prefetch so host input overlaps device compute
# ---------------------------------------------------------------------------

_ITEM, _DONE, _ERROR = "item", "done", "error"


class DevicePrefetcher:
    """Background-thread device feeder with a bounded queue (double-buffering).

    A producer thread pulls host batches from ``iterator``, applies ``put``
    (typically the sharded ``jax.device_put``), and parks up to ``depth``
    device-resident batches in a queue. The consumer's ``next()`` then only
    blocks when the device is outrunning the host — input transfer overlaps
    XLA compute instead of serializing before every dispatch.

    Shutdown is cooperative and deadlock-free in both directions:

    - the producer never blocks forever on a full queue (it offers with a
      timeout and re-checks the stop flag), so a consumer that dies
      mid-chunk cannot strand the thread;
    - ``close()`` signals stop, drains the queue to unwedge the producer,
      and joins it — preemption/exception paths leak nothing.

    Exceptions raised by the host iterator or by ``put`` are forwarded to
    the consumer and re-raised from ``next()``.
    """

    def __init__(self, iterator: Iterable[Any],
                 put: Optional[Callable[[Any], Any]] = None, *,
                 depth: int = 2, name: str = "device-prefetch",
                 tracer: Optional[Any] = None,
                 registry: Optional[Any] = None) -> None:
        self._it = iter(iterator)
        self._put = put if put is not None else (lambda b: b)
        # telemetry is opt-in: without a tracer every span is the shared
        # no-op and the producer body is unchanged
        self._span = tracer.span if tracer is not None else null_span
        self._put_hist = (registry.histogram(
            "device_put_seconds",
            "host→device transfer time per batch (producer thread)")
            if registry is not None else None)
        self._queue: "queue.Queue" = queue.Queue(maxsize=max(1, int(depth)))
        self._stop = threading.Event()
        self._finished = False           # consumer saw done/error
        self._closed = False
        # observability counters (reported via take_*): host_time is the
        # producer's true input cost (pull + device_put) even when hidden by
        # overlap; queue_wait is the consumer-visible stall.
        self._host_time_s = 0.0
        self._host_time_taken = 0.0
        self._queue_wait_s = 0.0
        self._thread = threading.Thread(target=self._producer, daemon=True,
                                        name=name)
        self._thread.start()

    # -- producer -----------------------------------------------------------

    def _offer(self, msg: Tuple[str, Any]) -> bool:
        """Bounded put that never outlives a dead consumer."""
        while not self._stop.is_set():
            try:
                self._queue.put(msg, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _producer(self) -> None:
        span = self._span
        while not self._stop.is_set():
            t0 = time.perf_counter()
            # the produce_batch span covers pull + device_put only — queue
            # offers (back-pressure from a full queue is the *healthy*
            # state) are excluded, matching host_time accounting
            with span("produce_batch") as sp:
                try:
                    # injected errors ride the normal forwarding path: the
                    # consumer re-raises at its next __next__
                    faults.point("data.produce")
                    with span("dataload_next"):
                        batch = next(self._it)
                except StopIteration:
                    sp.set(end="exhausted")
                    self._offer((_DONE, None))
                    return
                except BaseException as exc:  # noqa: BLE001 - forwarded
                    sp.set(end="error")
                    self._offer((_ERROR, exc))
                    return
                t1 = time.perf_counter()
                try:
                    with span("device_put"):
                        batch = self._put(batch)
                except BaseException as exc:  # noqa: BLE001 - forwarded
                    sp.set(end="error")
                    self._offer((_ERROR, exc))
                    return
                t2 = time.perf_counter()
                if self._put_hist is not None:
                    self._put_hist.observe(t2 - t1)
                self._host_time_s += t2 - t0
            if not self._offer((_ITEM, batch)):
                return

    # -- consumer -----------------------------------------------------------

    def __iter__(self) -> "DevicePrefetcher":
        return self

    def __next__(self) -> Any:
        if self._finished or self._closed:
            raise StopIteration
        t0 = time.perf_counter()
        tag, payload = self._queue.get()
        self._queue_wait_s += time.perf_counter() - t0
        if tag == _ITEM:
            return payload
        self._finished = True
        if tag == _ERROR:
            raise payload
        raise StopIteration

    # -- accounting ---------------------------------------------------------

    def take_queue_wait(self) -> float:
        """Consumer stall time since the last call (the overlap residue)."""
        out, self._queue_wait_s = self._queue_wait_s, 0.0
        return out

    def take_host_time(self) -> float:
        """Producer-side input time since the last call (may be hidden)."""
        cur = self._host_time_s  # float read is atomic under the GIL
        out = cur - self._host_time_taken
        self._host_time_taken = cur
        return out

    # -- lifecycle ----------------------------------------------------------

    def close(self, timeout: float = 5.0) -> None:
        """Stop the producer and join it. Idempotent; safe mid-stream."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        # drain so a producer blocked in _offer's put() wakes immediately
        while True:
            try:
                self._queue.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout=timeout)

    @property
    def thread_alive(self) -> bool:
        return self._thread.is_alive()

    def __enter__(self) -> "DevicePrefetcher":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()


class SyncDeviceFeeder:
    """Drop-in synchronous twin of :class:`DevicePrefetcher` (depth 0):
    pulls and ``put``s inline on the consumer thread. Keeps the trainer's
    hot loop shape identical whether prefetch is on or off."""

    def __init__(self, iterator: Iterable[Any],
                 put: Optional[Callable[[Any], Any]] = None, *,
                 tracer: Optional[Any] = None,
                 registry: Optional[Any] = None) -> None:
        self._it = iter(iterator)
        self._put = put if put is not None else (lambda b: b)
        self._span = tracer.span if tracer is not None else null_span
        self._put_hist = (registry.histogram(
            "device_put_seconds",
            "host→device transfer time per batch (consumer thread, sync)")
            if registry is not None else None)
        self._host_time_s = 0.0
        self._taken = {"wait": 0.0, "host": 0.0}

    def __iter__(self) -> "SyncDeviceFeeder":
        return self

    def __next__(self) -> Any:
        t0 = time.perf_counter()
        faults.point("data.produce")  # parity with the prefetching producer
        with self._span("dataload_next"):
            batch = next(self._it)
        t1 = time.perf_counter()
        with self._span("device_put"):
            batch = self._put(batch)
        t2 = time.perf_counter()
        if self._put_hist is not None:
            self._put_hist.observe(t2 - t1)
        self._host_time_s += t2 - t0
        return batch

    def _take(self, key: str) -> float:
        out = self._host_time_s - self._taken[key]
        self._taken[key] = self._host_time_s
        return out

    def take_queue_wait(self) -> float:
        """Synchronous path: the whole input time is consumer-visible."""
        return self._take("wait")

    def take_host_time(self) -> float:
        return self._take("host")

    def close(self, timeout: float = 0.0) -> None:
        pass

    def __enter__(self) -> "SyncDeviceFeeder":
        return self

    def __exit__(self, *exc: Any) -> None:
        pass


def make_device_feeder(iterator: Iterable[Any],
                       put: Optional[Callable[[Any], Any]] = None, *,
                       depth: int = 2, name: str = "device-prefetch",
                       tracer: Optional[Any] = None,
                       registry: Optional[Any] = None):
    """``depth >= 1`` → async :class:`DevicePrefetcher`; ``depth == 0`` →
    :class:`SyncDeviceFeeder` (the old blocking behaviour, for debugging
    and strict-determinism comparisons). ``tracer``/``registry`` opt the
    feeder into telemetry spans + metrics (see determined_clone_tpu.telemetry)."""
    if depth and depth > 0:
        return DevicePrefetcher(iterator, put, depth=depth, name=name,
                                tracer=tracer, registry=registry)
    return SyncDeviceFeeder(iterator, put, tracer=tracer, registry=registry)
