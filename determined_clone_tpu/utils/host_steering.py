"""Steer JAX onto a virtual multi-device CPU host platform.

Single home for the axon-to-CPU steering dance used by tests/conftest.py,
__graft_entry__.dryrun_multichip and bench.py. The axon sitecustomize
registers a tunneled TPU PJRT plugin at interpreter startup whose backend
init can fail or block indefinitely behind the pool grant; code that wants
virtual CPU devices (the reference's "artificial slots" trick,
agent/internal/detect/detect.go:39-56, recast as XLA host devices) must
clear the tunnel handshake AND steer the platform via ``jax.config``,
because the plugin pre-registers before any env mutation in user code.

Must be called before any JAX backend initializes (before the first
``jax.devices()``-like call); importing jax beforehand is fine.
"""
from __future__ import annotations

import os
import re


def steer_to_host_cpu(n_devices: int = 8) -> None:
    """Force the CPU platform with ``n_devices`` virtual devices."""
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    flags = os.environ.get("XLA_FLAGS", "")
    flag = f"--xla_force_host_platform_device_count={n_devices}"
    if "--xla_force_host_platform_device_count" in flags:
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+", flag,
                       flags)
    else:
        flags = f"{flags} {flag}".strip()
    os.environ["XLA_FLAGS"] = flags
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        import jax

        # Effective as long as no backend has initialized yet; if one has,
        # callers observe the actual device list and report the mismatch.
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
