"""Named fault points + process-global plan activation.

Call sites sprinkle ``faults.point("storage.upload")`` at the places where
real deployments fail; with no plan active that is one global load and a
``None`` check — free. Activating a seeded :class:`FaultPlan` (from a
config ``faults:`` block or the ``DCT_FAULT_PLAN`` env var) turns chosen
points into deterministic failures. See docs/fault_tolerance.md for the
point catalog and the rule schema.

Plans are cached by their defining payload so that re-activation across
training legs (the experiment runner re-enters ``core.init`` after every
restart) keeps hit counters — a ``nth: 1, times: 1`` rule fires once per
*process*, not once per leg, which is what makes "fail the first attempt,
succeed after restart" scenarios expressible.
"""
from __future__ import annotations

import contextlib
import json
import os
from typing import Any, Dict, Iterator, Optional

from determined_clone_tpu.faults.core import (  # noqa: F401  (re-exports)
    ACTIONS,
    FaultInjected,
    FaultPlan,
    FaultRule,
    InjectedConnectionError,
    InjectedIOError,
)

_PLAN: Optional[FaultPlan] = None
# payload-keyed caches: same faults block / env string -> same plan object,
# so rule counters survive repeated activation (see module docstring)
_CONFIG_PLANS: Dict[str, FaultPlan] = {}
_ENV_PLANS: Dict[str, FaultPlan] = {}


def point(name: str) -> None:
    """A named fault point. No-op (one None check) unless a plan is active."""
    plan = _PLAN
    if plan is not None:
        plan.hit(name)


def truncate_bytes(name: str) -> Optional[int]:
    """Bytes to keep if an active truncate rule fires at ``name``.

    Only call sites that can express a torn write (storage per-file copy)
    consult this; ``point()`` ignores truncate rules entirely.
    """
    plan = _PLAN
    if plan is None:
        return None
    return plan.truncate_bytes(name)


def active_plan() -> Optional[FaultPlan]:
    return _PLAN


def activate(plan: FaultPlan, registry: Any = None) -> FaultPlan:
    """Make ``plan`` the process-wide active plan."""
    global _PLAN
    if registry is not None:
        plan.registry = registry
    _PLAN = plan
    return plan


def deactivate(plan: Optional[FaultPlan] = None) -> None:
    """Clear the active plan (only if it is ``plan``, when given)."""
    global _PLAN
    if plan is None or _PLAN is plan:
        _PLAN = None


def plan_from_dict(raw: Dict[str, Any]) -> FaultPlan:
    return FaultPlan(list(raw.get("rules") or []), seed=int(raw.get("seed", 0)))


def activate_from_config(block: Dict[str, Any],
                         registry: Any = None) -> FaultPlan:
    """Activate the (cached) plan for a config ``faults:`` block."""
    key = json.dumps(block, sort_keys=True)
    plan = _CONFIG_PLANS.get(key)
    if plan is None:
        plan = _CONFIG_PLANS[key] = plan_from_dict(block)
    return activate(plan, registry)


def install_from_env(env: Optional[Dict[str, str]] = None
                     ) -> Optional[FaultPlan]:
    """Activate a plan from ``DCT_FAULT_PLAN`` (inline JSON, or a file path).

    Idempotent per payload: repeated calls (one per training leg) reuse the
    cached plan, keeping counters. Returns None when the var is unset.
    """
    raw = (env if env is not None else os.environ).get(
        "DCT_FAULT_PLAN", "").strip()
    if not raw:
        return None
    plan = _ENV_PLANS.get(raw)
    if plan is None:
        text = raw
        if not text.startswith("{"):
            with open(text) as f:
                text = f.read()
        plan = _ENV_PLANS[raw] = plan_from_dict(json.loads(text))
    return activate(plan)


@contextlib.contextmanager
def plan_active(raw: Dict[str, Any], registry: Any = None
                ) -> Iterator[FaultPlan]:
    """Test helper: activate a fresh plan for the duration of a block."""
    plan = activate(plan_from_dict(raw), registry)
    try:
        yield plan
    finally:
        deactivate(plan)


def reset() -> None:
    """Deactivate and drop all cached plans (tests only)."""
    global _PLAN
    _PLAN = None
    _CONFIG_PLANS.clear()
    _ENV_PLANS.clear()
