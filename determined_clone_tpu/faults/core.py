"""Deterministic fault injection: rules, plans, and injected exception types.

A :class:`FaultPlan` is a seeded list of :class:`FaultRule`\\ s. Each rule
matches a named fault point (exact name or ``fnmatch`` pattern), counts how
often that point is hit, and *fires* — raises, sleeps, truncates, or hard-
exits — starting at the Nth hit, for a bounded number of times, optionally
gated by a seeded per-rule coin. Everything is deterministic for a given
(seed, rule order, hit sequence), which is what lets chaos tests assert
exact outcomes (tests/test_fault_tolerance.py).

This module is import-light on purpose (stdlib only): ``exec/trial.py`` and
``utils/data.py`` import it at module top, before the heavy JAX imports.
"""
from __future__ import annotations

import fnmatch
import os
import random
import threading
import time
from typing import Any, Dict, List, Optional


class FaultInjected(RuntimeError):
    """Base for every injected failure (``exc: fault`` — non-retryable)."""


class InjectedIOError(FaultInjected, OSError):
    """Injected storage/filesystem failure (``exc: io`` — retryable)."""


class InjectedConnectionError(FaultInjected, ConnectionError):
    """Injected network failure (``exc: conn`` — retryable)."""


_EXC_TYPES = {
    "fault": FaultInjected,
    "io": InjectedIOError,
    "conn": InjectedConnectionError,
}

ACTIONS = ("error", "delay", "truncate", "exit")


class FaultRule:
    """One match rule. See docs/fault_tolerance.md for the field reference."""

    def __init__(self, raw: Dict[str, Any], seed: int, index: int) -> None:
        self.point = str(raw["point"])
        self.action = str(raw.get("action", "error"))
        if self.action not in ACTIONS:
            raise ValueError(
                f"fault rule {index}: unknown action {self.action!r} "
                f"(expected one of {ACTIONS})")
        self.exc = str(raw.get("exc", "fault"))
        if self.exc not in _EXC_TYPES:
            raise ValueError(
                f"fault rule {index}: unknown exc {self.exc!r} "
                f"(expected one of {tuple(_EXC_TYPES)})")
        self.nth = int(raw.get("nth", 1))
        self.times = int(raw.get("times", 1))  # 0 = unlimited
        self.probability = float(raw.get("probability", 1.0))
        if not 0.0 <= self.probability <= 1.0:
            raise ValueError(
                f"fault rule {index}: probability {self.probability} "
                f"outside [0, 1]")
        self.delay_s = float(raw.get("delay_s", 0.05))
        self.exit_code = int(raw.get("exit_code", 137))
        self.keep_bytes = int(raw.get("keep_bytes", 0))
        self.message = str(raw.get("message", ""))
        # per-rule RNG so adding/removing one rule doesn't shift the coin
        # sequence of its neighbors
        self._rng = random.Random(seed * 1_000_003 + index)
        self.hits = 0
        self.fires = 0

    def matches(self, name: str) -> bool:
        return self.point == name or fnmatch.fnmatchcase(name, self.point)

    def should_fire(self) -> bool:
        """Count a hit; decide (deterministically) whether this one fires."""
        self.hits += 1
        if self.hits < self.nth:
            return False
        if self.times and self.fires >= self.times:
            return False
        if self.probability < 1.0 and self._rng.random() >= self.probability:
            return False
        self.fires += 1
        return True


class FaultPlan:
    """A seeded set of rules, activated process-wide via the module API."""

    def __init__(self, rules: List[Dict[str, Any]], seed: int = 0) -> None:
        self.seed = seed
        self.rules = [FaultRule(r, seed, i) for i, r in enumerate(rules)]
        self.registry = None  # optional MetricsRegistry, set on activate()
        self._lock = threading.Lock()

    def hit(self, name: str) -> None:
        """Run every non-truncate rule matching ``name``. May raise/sleep/exit."""
        for rule in self.rules:
            if rule.action == "truncate" or not rule.matches(name):
                continue
            with self._lock:
                fire = rule.should_fire()
            if not fire:
                continue
            self._count(name)
            if rule.action == "delay":
                time.sleep(rule.delay_s)
            elif rule.action == "exit":
                # simulates kill -9 / node loss: no atexit hooks, no flushes
                os._exit(rule.exit_code)
            else:
                msg = rule.message or (
                    f"injected fault at {name!r} (hit {rule.hits})")
                raise _EXC_TYPES[rule.exc](msg)

    def truncate_bytes(self, name: str) -> Optional[int]:
        """Bytes to keep if a truncate rule fires at ``name``, else None."""
        for rule in self.rules:
            if rule.action != "truncate" or not rule.matches(name):
                continue
            with self._lock:
                fire = rule.should_fire()
            if fire:
                self._count(name)
                return rule.keep_bytes
        return None

    def _count(self, name: str) -> None:
        if self.registry is not None:
            self.registry.counter(
                "faults_injected_total",
                "fault-plan rules fired (all points)").inc()

    def stats(self) -> List[Dict[str, Any]]:
        """Per-rule hit/fire counters (for tests and debugging)."""
        with self._lock:
            return [{"point": r.point, "action": r.action,
                     "hits": r.hits, "fires": r.fires} for r in self.rules]
