"""Sharded offline batch processing with checkpointed progress.

≈ the reference's batch inference API (harness/determined/pytorch/
experimental/_torch_batch_process.py: `TorchBatchProcessor` :194 +
`torch_batch_process` :366): split a dataset across the gang, run a
user-defined processor over batches, checkpoint progress so a preempted or
restarted job resumes where it left off, cooperate with preemption.

TPU-native shape: the processor gets the whole Core API context (so it can
jit/shard its model over the mesh); rank r owns batches r, r+size, ...

    class Embedder(BatchProcessor):
        def __init__(self, context):
            self.fn = jax.jit(model.apply)
        def process_batch(self, batch, batch_idx):
            out = self.fn(params, batch)
            ...write out...

    jax_batch_process(Embedder, dataset, batch_size=32,
                      checkpoint_interval=10)
"""
from __future__ import annotations

import contextlib
import logging
import math
from typing import Any, Dict, Optional, Sequence, Type

from determined_clone_tpu import core

logger = logging.getLogger(__name__)

# warn-once guard for dropped-example reporting (same contract as the
# trainer's eval_examples_dropped warning): the counter is always exact
# in the summary/metric, the log line fires once per process
_dropped_warned = False


class BatchProcessor:
    """User subclass (≈ TorchBatchProcessor :194). Override process_batch;
    the hooks are optional."""

    def __init__(self, context: "core.Context") -> None:
        self.context = context

    def process_batch(self, batch: Any, batch_idx: int) -> None:
        raise NotImplementedError

    def on_checkpoint_start(self) -> None:
        """Called before each progress checkpoint (flush outputs here)."""

    def on_finish(self) -> None:
        """Called once after this rank's final batch."""


def _progress_key(rank: int) -> str:
    return f"rank_{rank}_batches_completed"


def jax_batch_process(
    processor_cls: Type[BatchProcessor],
    dataset: Sequence[Any],
    *,
    batch_size: int = 1,
    checkpoint_interval: int = 10,
    core_context: Optional["core.Context"] = None,
    latest_checkpoint: Optional[str] = None,
    max_batches: Optional[int] = None,
) -> Dict[str, Any]:
    """Run the processor over the dataset; returns a summary dict.

    ``dataset`` needs ``len()`` + slicing. Progress is checkpointed every
    ``checkpoint_interval`` processed batches per rank (sharded metadata
    merge: each rank reports its own high-water mark); pass the returned
    ``storage_id`` back as ``latest_checkpoint`` to resume (the reference's
    skip-completed-batches semantics, _torch_batch_process.py:366).
    """
    with contextlib.ExitStack() as stack:
        ctx = core_context
        if ctx is None:
            ctx = stack.enter_context(core.init())
        dist = ctx.distributed
        rank, size = dist.rank, dist.size

        n_batches = math.ceil(len(dataset) / batch_size)
        if max_batches is not None:
            n_batches = min(n_batches, max_batches)
        # Examples beyond the planned batch range are DROPPED, and used
        # to be dropped silently: max_batches clips the tail here (on a
        # fresh run or a resume whose plan tightened alike) and nothing
        # ever revisits the difference. Count them exactly and surface
        # via the trainer's eval_examples_dropped contract: warn once,
        # always report.
        examples_dropped = max(0, len(dataset) - n_batches * batch_size)

        # resume: skip this rank's already-completed batches. The sharding
        # arithmetic (idx = rank + pos*size, slice = idx*batch_size) only
        # lines up if batch_size and gang size match the original run —
        # silently shifted boundaries would drop/duplicate data.
        completed = 0
        if latest_checkpoint:
            meta = ctx.checkpoint.get_metadata(latest_checkpoint)
            old_bs = meta.get("batch_size")
            if old_bs is not None and int(old_bs) != batch_size:
                raise ValueError(
                    f"resume batch_size {batch_size} != checkpointed "
                    f"{old_bs}; progress indices would not line up")
            old_size = meta.get("world_size")
            if old_size is not None and int(old_size) != size:
                raise ValueError(
                    f"resume world size {size} != checkpointed {old_size}; "
                    f"per-rank progress would map to different data")
            # meta["n_batches"] records the original plan; a resume whose
            # plan shrank (max_batches tightened) drops the difference,
            # which the examples_dropped formula above already counts —
            # the tail examples are still in the dataset, just unplanned
            completed = int(meta.get(_progress_key(rank), 0))

        processor = processor_cls(ctx)
        processed = completed
        storage_id: Optional[str] = latest_checkpoint
        preempted = False
        since_ckpt = 0

        def save_progress() -> Optional[str]:
            # COLLECTIVE: metadata.json is chief-written, so per-rank
            # progress is allgathered and the chief persists the merge
            # (≈ _upload_sharded + merge_resources, core/_checkpoint.py:280)
            processor.on_checkpoint_start()
            merged: Dict[str, Any] = {"batch_size": batch_size,
                                      "world_size": size,
                                      "n_batches": n_batches}
            for d in dist.allgather({_progress_key(rank): processed}):
                merged.update(d)
            with ctx.checkpoint.store_path(
                metadata=merged, shard=size > 1,
            ) as (path, holder):
                # progress lives in the metadata; the dir carries a marker
                # file so single-rank saves are never empty
                with open(f"{path}/progress-rank-{rank}.txt", "w") as f:
                    f.write(str(processed))
            return holder.get("storage_id")

        # Every rank runs the SAME trip count even when n_batches % size != 0
        # — save_progress and should_preempt are collectives, so trip counts
        # (and break decisions) must be identical on every rank.
        steps = math.ceil(n_batches / size)
        for local_pos in range(steps):
            idx = rank + local_pos * size
            if local_pos >= completed and idx < n_batches:
                lo = idx * batch_size
                batch = dataset[lo:min(lo + batch_size, len(dataset))]
                processor.process_batch(batch, idx)
                processed += 1
            since_ckpt += 1

            if since_ckpt >= checkpoint_interval:
                storage_id = save_progress() or storage_id
                since_ckpt = 0
            if ctx.preempt.should_preempt():  # chief-coordinated: same
                preempted = True              # answer on every rank
                break

        if since_ckpt > 0 or preempted:
            storage_id = save_progress() or storage_id
        if not preempted:
            processor.on_finish()

        if examples_dropped:
            global _dropped_warned
            if not _dropped_warned:
                _dropped_warned = True
                logger.warning(
                    "batch inference dropped %d examples outside the "
                    "processed batch range (max_batches clipping or a "
                    "shrunken dataset on resume); raise max_batches or "
                    "re-run without a stale checkpoint for full coverage",
                    examples_dropped)
            tel = getattr(ctx, "telemetry", None)
            if tel is not None and getattr(tel, "registry", None) is not None:
                tel.registry.gauge(
                    "batch_inference_examples_dropped",
                    "examples outside the processed batch range this run "
                    "(max_batches clipping / shrunken dataset on resume)"
                ).set(examples_dropped)

        return {
            "rank": rank,
            "batches_processed": processed,
            "total_batches": n_batches,
            "examples_dropped": examples_dropped,
            "preempted": preempted,
            "storage_id": storage_id,
        }
