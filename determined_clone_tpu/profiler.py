"""Trial profiler — system metrics + per-batch timings shipped to the master.

≈ the reference's ProfilerAgent (harness/determined/profiler.py:238):
a sampling thread collects system metrics (CPU, memory, disk, network —
pynvml GPU sampling becomes device-memory stats from JAX on TPU), a batcher
thread flushes batched measurements to the master's profiler endpoints
(common/api/profiler.py), and the trainer feeds per-batch timings
(dataloading / to-device / compute, _pytorch_trial.py:34 dataloader_next).

Opt-in per experiment via the ``profiling: {enabled: true}`` config block
(expconf RawProfiling, master/pkg/schemas/expconf/profiling.go).
"""
from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

SYSTEM_SAMPLE_PERIOD_SEC = 1.0
FLUSH_PERIOD_SEC = 5.0
MAX_BATCHED = 100
DROP_WARN_PERIOD_SEC = 60.0  # at most one dropped-samples warning a minute


def _read_proc_stat() -> Optional[List[int]]:
    try:
        with open("/proc/stat") as f:
            line = f.readline()
        return [int(x) for x in line.split()[1:]]
    except (OSError, ValueError):
        return None


def _read_meminfo() -> Dict[str, int]:
    out: Dict[str, int] = {}
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                key, _, rest = line.partition(":")
                out[key.strip()] = int(rest.split()[0])  # kB
    except (OSError, ValueError, IndexError):
        pass
    return out


def _read_net_bytes() -> Dict[str, int]:
    rx = tx = 0
    try:
        with open("/proc/net/dev") as f:
            for line in f.readlines()[2:]:
                name, _, rest = line.partition(":")
                if name.strip() == "lo":
                    continue
                fields = rest.split()
                rx += int(fields[0])
                tx += int(fields[8])
    except (OSError, ValueError, IndexError):
        pass
    return {"rx": rx, "tx": tx}


def _device_memory_stats() -> Dict[str, float]:
    """Accelerator memory via JAX (the pynvml analogue on TPU).

    Delegates to :mod:`determined_clone_tpu.telemetry.device`: sums across
    ALL local devices (the old sample read ``jax.devices()[0]`` only — an
    8x under-report on a multi-chip host that also hid per-device skew)
    and falls back to process RSS on CPU. Every call raises the process
    peak watermark the trainer publishes per chunk.
    """
    try:
        from determined_clone_tpu.telemetry.device import device_memory_stats

        return device_memory_stats()
    except Exception:
        return {}


class SystemMetricsThread(threading.Thread):
    """≈ SysMetricCollectorThread (profiler.py:602)."""

    def __init__(self, sink: "ProfilerAgent") -> None:
        super().__init__(daemon=True, name="profiler-sysmetrics")
        self._sink = sink
        # NOT named _stop: threading.Thread has an internal _stop() method
        # that an attribute by that name would shadow (join() calls it)
        self._stop_event = threading.Event()
        self._prev_cpu: Optional[List[int]] = None
        self._prev_net = _read_net_bytes()
        # rate denominators use the monotonic clock (TIME001); the shipped
        # sample's "time" field stays wall clock for the master's axes
        self._prev_t = time.monotonic()

    def stop(self) -> None:
        self._stop_event.set()

    def run(self) -> None:
        while not self._stop_event.wait(SYSTEM_SAMPLE_PERIOD_SEC):
            self.sample_once()

    def sample_once(self) -> None:
        now = time.monotonic()
        sample: Dict[str, Any] = {"time": time.time(), "group": "system"}

        cpu = _read_proc_stat()
        if cpu and self._prev_cpu:
            deltas = [a - b for a, b in zip(cpu, self._prev_cpu)]
            total = sum(deltas)
            idle = deltas[3] + (deltas[4] if len(deltas) > 4 else 0)
            if total > 0:
                sample["cpu_util_pct"] = round(100.0 * (total - idle) / total, 2)
        self._prev_cpu = cpu

        mem = _read_meminfo()
        if mem.get("MemTotal"):
            used = mem["MemTotal"] - mem.get("MemAvailable", 0)
            sample["memory_used_gb"] = round(used / 1048576, 3)
            sample["memory_util_pct"] = round(100.0 * used / mem["MemTotal"], 2)

        net = _read_net_bytes()
        dt = max(now - self._prev_t, 1e-6)
        sample["net_rx_bps"] = round((net["rx"] - self._prev_net["rx"]) / dt, 1)
        sample["net_tx_bps"] = round((net["tx"] - self._prev_net["tx"]) / dt, 1)
        self._prev_net = net
        self._prev_t = now

        sample.update(_device_memory_stats())
        self._sink.record(sample)


class ProfilerAgent:
    """Collects measurements and flushes batches to the master
    (≈ profiler.py:238 ProfilerAgent + :732 MetricsBatcherThread)."""

    def __init__(self, session: Any, trial_id: int, *,
                 enabled: bool = True,
                 sample_system: bool = True,
                 registry: Optional[Any] = None) -> None:
        self._session = session
        self._trial_id = trial_id
        self.enabled = enabled
        self._buffer: List[Dict[str, Any]] = []
        self._lock = threading.Lock()
        self._sys_thread: Optional[SystemMetricsThread] = None
        self._flush_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._flush_now = threading.Event()
        self._sample_system = sample_system
        # dropped-sample accounting: lossiness is by design (shedding +
        # non-retryable posts) but must be *visible* — a counter in the
        # telemetry registry (when wired) plus a rate-limited warning
        self._dropped = (registry.counter(
            "profiler_samples_dropped",
            "profiler samples lost to buffer shedding or failed posts")
            if registry is not None else None)
        self._dropped_total = 0
        self._last_drop_warn = 0.0

    def _count_dropped(self, n: int, why: str) -> None:
        self._dropped_total += n
        if self._dropped is not None:
            self._dropped.inc(n)
        now = time.monotonic()
        if now - self._last_drop_warn >= DROP_WARN_PERIOD_SEC:
            self._last_drop_warn = now
            logger.warning(
                "profiler dropped %d samples (%s); %d dropped total this "
                "trial", n, why, self._dropped_total)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "ProfilerAgent":
        if not self.enabled:
            return self
        if self._sample_system:
            self._sys_thread = SystemMetricsThread(self)
            self._sys_thread.start()
        self._flush_thread = threading.Thread(
            target=self._flush_loop, daemon=True, name="profiler-flush")
        self._flush_thread.start()
        return self

    def stop(self) -> None:
        if not self.enabled:
            return
        self._stop.set()
        self._flush_now.set()  # wake the flush loop so join() is prompt
        if self._sys_thread:
            self._sys_thread.stop()
            self._sys_thread.join(timeout=5)
        if self._flush_thread:
            self._flush_thread.join(timeout=10)
        self.flush()

    def __enter__(self) -> "ProfilerAgent":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.stop()

    # -- recording ---------------------------------------------------------

    def record(self, sample: Dict[str, Any]) -> None:
        """Never blocks on the network: a full buffer only signals the flush
        thread (posting inline here would stall the trainer's hot loop when
        the master is slow — profiling must never take down training)."""
        if not self.enabled:
            return
        shed = 0
        with self._lock:
            self._buffer.append(sample)
            if len(self._buffer) >= 10 * MAX_BATCHED:
                # master unreachable for a long stretch: shed oldest samples
                del self._buffer[:MAX_BATCHED]
                shed = MAX_BATCHED
            full = len(self._buffer) >= MAX_BATCHED
        if shed:
            self._count_dropped(shed, "buffer full, shed oldest")
        if full:
            self._flush_now.set()

    def record_batch_timing(self, batches_trained: int, *,
                            dataloading_s: float, compute_s: float,
                            queue_wait_s: Optional[float] = None,
                            steps_per_dispatch: Optional[int] = None,
                            prefetch_depth: Optional[int] = None) -> None:
        """Per-batch (or per-chunk) timings from the trainer's hot loop —
        the dataloader_next/compute split (profiler.py timings). How to
        read ``dataloading_s`` vs ``queue_wait_s``: docs/observability.md
        ("Interpreting the input-pipeline numbers")."""
        sample = {
            "time": time.time(),
            "group": "timing",
            "batches_trained": batches_trained,
            "dataloading_s": round(dataloading_s, 6),
            "compute_s": round(compute_s, 6),
        }
        if queue_wait_s is not None:
            sample["queue_wait_s"] = round(queue_wait_s, 6)
        if steps_per_dispatch is not None:
            sample["steps_per_dispatch"] = int(steps_per_dispatch)
        if prefetch_depth is not None:
            sample["prefetch_depth"] = int(prefetch_depth)
        self.record(sample)

    # -- flushing ----------------------------------------------------------

    def _flush_loop(self) -> None:
        while not self._stop.is_set():
            self._flush_now.wait(FLUSH_PERIOD_SEC)
            self._flush_now.clear()
            if self._stop.is_set():
                break
            self.flush()

    def flush(self) -> None:
        with self._lock:
            batch = self._buffer
            self._buffer = []
        if batch:
            self._post(batch)

    def _post(self, batch: List[Dict[str, Any]]) -> None:
        try:
            # NOT retryable: the master append has no dedup key, so a lost
            # response + retry would duplicate samples; telemetry is lossy
            self._session.post(
                f"/api/v1/trials/{self._trial_id}/profiler",
                {"samples": batch}, retryable=False)
        except Exception:
            # profiling must never take down training — but the loss is
            # counted and warned about, not silent
            self._count_dropped(len(batch), "post to master failed")

    @property
    def samples_dropped(self) -> int:
        return self._dropped_total


def from_config(session: Any, trial_id: int,
                experiment_config: Dict[str, Any], *,
                registry: Optional[Any] = None) -> ProfilerAgent:
    """Build from the experiment's ``profiling`` block; disabled by default
    like the reference (expconf profiling.go). ``registry`` (the telemetry
    MetricsRegistry, when observability is on) receives drop counters."""
    profiling = experiment_config.get("profiling") or {}
    enabled = bool(profiling.get("enabled", False))
    if os.environ.get("DCT_PROFILING") == "1":
        enabled = True
    return ProfilerAgent(session, trial_id, enabled=enabled,
                         registry=registry)
