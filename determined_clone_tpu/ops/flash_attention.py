"""Pallas TPU flash attention — the fused hot-path kernel.

The reference has no custom kernels (SURVEY.md: no CUDA anywhere; attention
lives inside torch). On TPU the idiomatic equivalent is a Pallas kernel that
keeps the O(T²) score matrix out of HBM AND out of VMEM: the grid is
(batch·head, q_block, k_block) with k innermost, so only one
[block_q, D] q tile and one [block_k, D] k/v tile are resident per step
while the online-softmax state (m, l, acc — the flash recurrence) lives in
VMEM scratch that persists across the k iterations. Memory is O(block²),
sequences bound only by HBM, and the MXU sees back-to-back
[block_q, D]×[D, block_k] matmuls.

Backward pass: custom VJP that recomputes attention with the XLA blockwise
path (ops/attention.py) — fwd gets the fused kernel + no residual scores,
bwd stays memory-efficient via rematerialization (jax.checkpoint-style).

Falls back to interpret mode off-TPU so tests exercise the same code path.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from determined_clone_tpu.ops.attention import causal_blockwise_attention

NEG_INF = -1e30


def _should_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref, *,
                scale: float, causal: bool, block_q: int, block_k: int,
                n_kb: int):
    """Grid (BH, q_blocks, k_blocks), k innermost. Scratch (m/l/acc)
    persists across the k iterations of one (bh, qi)."""
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale      # [bq, D]
        k_blk = k_ref[0].astype(jnp.float32)          # [bk, D]
        v_blk = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(                      # [bq, bk] on the MXU
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if causal:
            q_pos = (qi * block_q +
                     jax.lax.broadcasted_iota(jnp.int32, s.shape, 0))
            k_pos = (ki * block_k +
                     jax.lax.broadcasted_iota(jnp.int32, s.shape, 1))
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_prev = m_ref[:, 0]                          # [bq]
        l_prev = l_ref[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        # fully-masked-so-far rows: exp(NEG_INF - NEG_INF) must not be 1
        alpha = jnp.exp(jnp.where(m_prev > NEG_INF / 2,
                                  m_prev - m_new, NEG_INF))
        p = jnp.exp(s - m_new[:, None])
        if causal:
            p = jnp.where(q_pos >= k_pos, p, 0.0)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)
        acc_ref[:] = acc_ref[:] * alpha[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = m_new[:, None]
        l_ref[:] = l_new[:, None]

    if causal:
        # skip K blocks strictly above this q block's last row
        pl.when((qi * block_q + block_q - 1) >= ki * block_k)(_compute)
    else:
        _compute()

    @pl.when(ki == n_kb - 1)
    def _finalize():
        o_ref[0] = (acc_ref[:] /
                    jnp.maximum(l_ref[:, 0], 1e-30)[:, None]).astype(
                        o_ref.dtype)


def _flash_fwd(q: jax.Array, k: jax.Array, v: jax.Array, *, causal: bool,
               block_q: int, block_k: int,
               interpret: Optional[bool]) -> jax.Array:
    """q,k,v: [B, T, H, D] (the mha layout); returns [B, Tq, H, D]."""
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    scale = 1.0 / (D ** 0.5)
    if interpret is None:
        interpret = _should_interpret()
    n_kb = Tk // block_k

    # [B, T, H, D] -> [B*H, T, D]: one grid row per (batch·head)
    qf = q.transpose(0, 2, 1, 3).reshape(B * H, Tq, D)
    kf = k.transpose(0, 2, 1, 3).reshape(B * H, Tk, D)
    vf = v.transpose(0, 2, 1, 3).reshape(B * H, Tk, D)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, n_kb=n_kb,
    )
    out = pl.pallas_call(
        kernel,
        grid=(B * H, Tq // block_q, n_kb),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, qi, ki: (bh, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D),
                               lambda bh, qi, ki: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, Tq, D), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),   # m (row max)
            pltpu.VMEM((block_q, 1), jnp.float32),   # l (row denominator)
            pltpu.VMEM((block_q, D), jnp.float32),   # acc (unnormalized out)
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(B, H, Tq, D).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_attention_cvjp(q, k, v, causal, block_q, block_k, interpret):
    return _flash_fwd(q, k, v, causal=causal, block_q=block_q,
                      block_k=block_k, interpret=interpret)


def _vjp_fwd(q, k, v, causal, block_q, block_k, interpret):
    out = _flash_fwd(q, k, v, causal=causal, block_q=block_q,
                     block_k=block_k, interpret=interpret)
    return out, (q, k, v)


def _vjp_bwd(causal, block_q, block_k, interpret, residuals, g):
    q, k, v = residuals
    # rematerialize with the XLA blockwise path: same math (online softmax
    # in fp32), O(T·block) memory — causal or not — and XLA differentiates
    # the scan cleanly
    ref = functools.partial(causal_blockwise_attention, block_size=block_k,
                            causal=causal)
    _, pullback = jax.vjp(ref, q, k, v)
    return pullback(g)


_flash_attention_cvjp.defvjp(_vjp_fwd, _vjp_bwd)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = True, block_q: int = 128,
                    block_k: int = 128,
                    interpret: Optional[bool] = None) -> jax.Array:
    """Fused attention. q,k,v: [B, T, H, D]; matches ``mha`` numerically
    (fp32 softmax). Block sizes clamp to the sequence lengths, which must
    then divide evenly (static shapes; the grid can't tile ragged tails)."""
    block_q = min(block_q, q.shape[1])
    block_k = min(block_k, k.shape[1])
    if q.shape[1] % block_q != 0:
        raise ValueError(
            f"q length {q.shape[1]} not divisible by block_q {block_q}")
    if k.shape[1] % block_k != 0:
        raise ValueError(
            f"k length {k.shape[1]} not divisible by block_k {block_k}")
    return _flash_attention_cvjp(q, k, v, causal, block_q, block_k,
                                 interpret)
