"""Attention ops, including the sequence-parallel paths the reference lacks.

Three implementations, one semantic:
 - ``mha``: plain XLA attention (einsum + softmax). XLA fuses this well on
   TPU; correct reference implementation for tests.
 - ``causal_blockwise_attention``: lax.scan over key/value blocks with a
   streaming (online-softmax) accumulator — the memory-efficient form that
   long sequences need; the basis for ring attention.
 - ``ring_attention``: context-parallel attention over the mesh's ``sp``
   axis: each shard holds a sequence slice, K/V blocks rotate around the
   ring via ppermute while compute overlaps (SURVEY.md §5.7 — absent in the
   reference, first-class here).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _causal_mask(q_len: int, k_len: int, q_offset: int = 0, k_offset: int = 0):
    """[q_len, k_len] bool mask; True = attendable."""
    q_pos = q_offset + jnp.arange(q_len)[:, None]
    k_pos = k_offset + jnp.arange(k_len)[None, :]
    return q_pos >= k_pos


def mha(q: jax.Array, k: jax.Array, v: jax.Array, *, causal: bool = True,
        mask: Optional[jax.Array] = None) -> jax.Array:
    """Multi-head attention. q,k,v: [B, T, H, D]. Softmax in fp32."""
    d = q.shape[-1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
    scores = scores / jnp.sqrt(jnp.float32(d))
    if causal:
        cm = _causal_mask(q.shape[1], k.shape[1])
        scores = jnp.where(cm[None, None], scores, NEG_INF)
    if mask is not None:
        scores = jnp.where(mask, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _online_softmax_block(carry, qkv_block, *, scale):
    """One streaming-softmax step: merge a new K/V block into (acc, m, l).

    acc: running unnormalized output [B, Tq, H, D] (fp32)
    m:   running row max           [B, H, Tq]     (fp32)
    l:   running row denominator   [B, H, Tq]     (fp32)
    """
    acc, m, l = carry
    q, k_blk, v_blk, block_mask = qkv_block
    # f32 accumulation ON the dot (the MXU's native bf16-in/f32-out mode),
    # not a bf16 dot cast afterwards: under jit, XLA fuses the cast into
    # the scan backward in a way that overflows bf16 intermediates
    # (non-finite dq/dk on real TPU); preferred_element_type sidesteps the
    # bf16 intermediate entirely and is faster
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk,
                   preferred_element_type=jnp.float32) * scale
    s = jnp.where(block_mask, s, NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    # guard fully-masked rows: exp(NEG_INF - NEG_INF) would be 1
    alpha = jnp.exp(jnp.where(m > NEG_INF / 2, m - m_new, NEG_INF))
    p = jnp.exp(s - m_new[..., None])
    p = jnp.where(block_mask, p, 0.0)
    l_new = l * alpha + jnp.sum(p, axis=-1)
    acc_new = acc * alpha.transpose(0, 2, 1)[..., None] + jnp.einsum(
        "bhqk,bkhd->bqhd", p, v_blk.astype(jnp.float32)
    )
    return (acc_new, m_new, l_new)


def causal_blockwise_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                               block_size: int = 512,
                               causal: bool = True) -> jax.Array:
    """Streaming attention over K/V blocks via lax.scan; O(T·block) memory
    instead of O(T²). Matches ``mha`` numerically (fp32 softmax); pass
    causal=False for the unmasked variant (same streaming memory)."""
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    block_size = min(block_size, Tk)
    if Tk % block_size != 0:
        raise ValueError(
            f"block_size {block_size} must evenly divide the K/V sequence length {Tk}"
        )
    n_blocks = Tk // block_size
    scale = 1.0 / (D ** 0.5)

    k_blocks = k.reshape(B, n_blocks, block_size, H, D).transpose(1, 0, 2, 3, 4)
    v_blocks = v.reshape(B, n_blocks, block_size, H, D).transpose(1, 0, 2, 3, 4)

    def step(carry, inputs):
        idx, k_blk, v_blk = inputs
        if causal:
            bmask = _causal_mask(Tq, block_size, q_offset=0,
                                 k_offset=idx * block_size)
        else:
            bmask = jnp.ones((Tq, block_size), bool)
        carry = _online_softmax_block(
            carry, (q, k_blk, v_blk, bmask[None, None]), scale=scale
        )
        return carry, None

    acc0 = jnp.zeros((B, Tq, H, D), jnp.float32)
    m0 = jnp.full((B, H, Tq), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Tq), jnp.float32)
    (acc, m, l), _ = jax.lax.scan(
        step, (acc0, m0, l0), (jnp.arange(n_blocks), k_blocks, v_blocks)
    )
    out = acc / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, *, axis_name: str,
                   axis_index: jax.Array, axis_size: int) -> jax.Array:
    """Causal ring attention inside shard_map: the sequence axis is sharded
    over ``axis_name``; K/V shards rotate via ppermute so every query shard
    sees the full sequence with only neighbor ICI traffic.

    q,k,v: [B, T_local, H, D] — the local sequence slice. Global positions of
    this shard's queries are axis_index*T_local + [0, T_local).
    """
    B, T, H, D = q.shape
    scale = 1.0 / (D ** 0.5)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def body(i, state):
        acc, m, l, k_cur, v_cur = state
        # K/V currently held arrived from shard (axis_index - i) mod size.
        src = (axis_index - i) % axis_size
        bmask = _causal_mask(T, T, q_offset=axis_index * T, k_offset=src * T)
        acc, m, l = _online_softmax_block(
            (acc, m, l), (q, k_cur, v_cur, bmask[None, None]), scale=scale
        )
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (acc, m, l, k_nxt, v_nxt)

    acc0 = jnp.zeros((B, T, H, D), jnp.float32)
    m0 = jnp.full((B, H, T), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, T), jnp.float32)
    # the zero-init carry is a replicated constant but every loop output
    # varies over the sp axis — mark it varying or shard_map's vma check
    # rejects the fori_loop carry. pvary is deprecated in favour of pcast
    # on current JAX; keep the fallback for older versions.
    if hasattr(jax.lax, "pcast"):
        acc0, m0, l0 = jax.tree.map(
            lambda x: jax.lax.pcast(x, axis_name, to="varying"),
            (acc0, m0, l0))
    else:
        acc0, m0, l0 = jax.tree.map(
            lambda x: jax.lax.pvary(x, axis_name), (acc0, m0, l0))
    acc, m, l, _, _ = jax.lax.fori_loop(
        0, axis_size, body, (acc0, m0, l0, k, v)
    )
    out = acc / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                      axis_name: str, causal: bool = True) -> jax.Array:
    """All-to-all (DeepSpeed-Ulysses-style) sequence parallelism inside
    shard_map: the complement of ring_attention for long sequences.

    The sequence axis arrives sharded over ``axis_name``; all-to-alls
    reshard q/k/v to head-parallel layout ([B, T, H/sp, D] — every device
    holds the FULL sequence for a slice of heads), attention runs locally
    with zero communication, and a final all-to-all reshards the output
    back. Four all-to-alls total (the standard Ulysses accounting) versus
    the ring's axis_size ppermute hops per K/V tensor — the better trade
    when the head count divides the axis and the full sequence fits per
    device.

    q,k,v: [B, T_local, H, D]; H must be divisible by the axis size.
    """

    sp = jax.lax.axis_size(axis_name)
    if q.shape[2] % sp != 0:
        raise ValueError(
            f"ulysses_attention requires the head count ({q.shape[2]}) to "
            f"be divisible by the '{axis_name}' axis size ({sp}); use "
            f"ring_attention for indivisible head counts")

    def a2a(x, scatter_dim, concat_dim):
        return jax.lax.all_to_all(x, axis_name, split_axis=scatter_dim,
                                  concat_axis=concat_dim, tiled=True)

    # [B, T/sp, H, D] -> [B, T, H/sp, D]: scatter heads, gather sequence
    qh = a2a(q, 2, 1)
    kh = a2a(k, 2, 1)
    vh = a2a(v, 2, 1)
    out = mha(qh, kh, vh, causal=causal)
    # [B, T, H/sp, D] -> [B, T/sp, H, D]
    return a2a(out, 1, 2)


def rotary_embedding(x: jax.Array, positions: jax.Array, *,
                     base: float = 10000.0) -> jax.Array:
    """RoPE. x: [B, T, H, D] (D even), positions: [T] or [B, T]."""
    D = x.shape[-1]
    half = D // 2
    freqs = base ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    if positions.ndim == 1:
        positions = positions[None, :]
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B, T, half]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = x[..., :half].astype(jnp.float32), x[..., half:].astype(jnp.float32)
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1
    ).astype(x.dtype)
