"""Functional NN layers: pure init/apply functions over parameter pytrees.

The reference delegates all numerics to PyTorch/TF (SURVEY.md preamble); this
framework owns them, XLA-first: params are plain pytrees of jnp arrays,
every layer is a pure function, and dtype policy is bf16-compute/fp32-params
by default (the TPU analogue of the reference's AMP path,
harness/determined/pytorch/_pytorch_trial.py:872).
"""
from __future__ import annotations

import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

Params = Dict[str, Any]


# ---------------------------------------------------------------------------
# Initializers
# ---------------------------------------------------------------------------

def trunc_normal(key: jax.Array, shape: Tuple[int, ...], stddev: float = 0.02,
                 dtype=jnp.float32) -> jax.Array:
    return stddev * jax.random.truncated_normal(key, -2.0, 2.0, shape, dtype)

def lecun_normal(key: jax.Array, shape: Tuple[int, ...], fan_in: Optional[int] = None,
                 dtype=jnp.float32) -> jax.Array:
    fan_in = fan_in if fan_in is not None else shape[0]
    return trunc_normal(key, shape, stddev=math.sqrt(1.0 / max(1, fan_in)), dtype=dtype)

def he_normal(key: jax.Array, shape: Tuple[int, ...], fan_in: Optional[int] = None,
              dtype=jnp.float32) -> jax.Array:
    fan_in = fan_in if fan_in is not None else shape[0]
    return trunc_normal(key, shape, stddev=math.sqrt(2.0 / max(1, fan_in)), dtype=dtype)


# ---------------------------------------------------------------------------
# Dense / embedding / norms
# ---------------------------------------------------------------------------

def dense_init(key: jax.Array, in_dim: int, out_dim: int, *, bias: bool = True,
               dtype=jnp.float32) -> Params:
    p: Params = {"kernel": lecun_normal(key, (in_dim, out_dim), dtype=dtype)}
    if bias:
        p["bias"] = jnp.zeros((out_dim,), dtype)
    return p

def dense(params: Params, x: jax.Array, *, compute_dtype=None) -> jax.Array:
    k = params["kernel"]
    if compute_dtype is not None:
        x = x.astype(compute_dtype)
        k = k.astype(compute_dtype)
    y = x @ k
    if "bias" in params:
        y = y + params["bias"].astype(y.dtype)
    return y


def embedding_init(key: jax.Array, vocab: int, dim: int, dtype=jnp.float32) -> Params:
    return {"table": trunc_normal(key, (vocab, dim), dtype=dtype)}

def embedding(params: Params, ids: jax.Array, *, compute_dtype=None) -> jax.Array:
    t = params["table"]
    if compute_dtype is not None:
        t = t.astype(compute_dtype)
    return jnp.take(t, ids, axis=0)


def layernorm_init(dim: int, dtype=jnp.float32) -> Params:
    return {"scale": jnp.ones((dim,), dtype), "bias": jnp.zeros((dim,), dtype)}

def layernorm(params: Params, x: jax.Array, eps: float = 1e-5) -> jax.Array:
    # Norm statistics in fp32 regardless of activation dtype (TPU numerics rule).
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mu), axis=-1, keepdims=True)
    y = (xf - mu) * jax.lax.rsqrt(var + eps)
    y = y * params["scale"].astype(jnp.float32) + params["bias"].astype(jnp.float32)
    return y.astype(x.dtype)


def rmsnorm_init(dim: int, dtype=jnp.float32) -> Params:
    return {"scale": jnp.ones((dim,), dtype)}

def rmsnorm(params: Params, x: jax.Array, eps: float = 1e-6) -> jax.Array:
    xf = x.astype(jnp.float32)
    ms = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    y = xf * jax.lax.rsqrt(ms + eps) * params["scale"].astype(jnp.float32)
    return y.astype(x.dtype)


# ---------------------------------------------------------------------------
# Convolutions (for the ResNet / mnist-CNN families)
# ---------------------------------------------------------------------------

def conv_init(key: jax.Array, in_ch: int, out_ch: int, kernel: int, *,
              dtype=jnp.float32) -> Params:
    shape = (kernel, kernel, in_ch, out_ch)  # HWIO
    return {"kernel": he_normal(key, shape, fan_in=kernel * kernel * in_ch, dtype=dtype)}

def conv2d(params: Params, x: jax.Array, *, stride: int = 1, padding: str = "SAME",
           compute_dtype=None) -> jax.Array:
    """NHWC conv — the TPU-native layout (channels on the 128-lane minor dim)."""
    k = params["kernel"]
    if compute_dtype is not None:
        x = x.astype(compute_dtype)
        k = k.astype(compute_dtype)
    return jax.lax.conv_general_dilated(
        x, k, window_strides=(stride, stride), padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def batchnorm_init(ch: int, dtype=jnp.float32) -> Params:
    return {
        "scale": jnp.ones((ch,), dtype), "bias": jnp.zeros((ch,), dtype),
        "mean": jnp.zeros((ch,), dtype), "var": jnp.ones((ch,), dtype),
    }

def batchnorm(params: Params, x: jax.Array, *, training: bool, momentum: float = 0.9,
              eps: float = 1e-5, axis_name: Optional[str] = None,
              ) -> Tuple[jax.Array, Params]:
    """BatchNorm with functional running-stat updates. Under pjit the batch
    dims are sharded; statistics computed with jnp.mean are automatically
    global because XLA inserts the cross-device reduction (no explicit psum
    needed unless inside shard_map, where axis_name applies)."""
    xf = x.astype(jnp.float32)
    if training:
        axes = tuple(range(x.ndim - 1))
        mean = jnp.mean(xf, axis=axes)
        var = jnp.mean(jnp.square(xf), axis=axes) - jnp.square(mean)
        if axis_name is not None:
            mean = jax.lax.pmean(mean, axis_name)
            var = jax.lax.pmean(var, axis_name)
        new_stats = {
            **params,
            "mean": momentum * params["mean"] + (1 - momentum) * mean,
            "var": momentum * params["var"] + (1 - momentum) * var,
        }
    else:
        mean, var = params["mean"], params["var"]
        new_stats = params
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    y = y * params["scale"] + params["bias"]
    return y.astype(x.dtype), new_stats


def groupnorm_init(ch: int, dtype=jnp.float32) -> Params:
    return {"scale": jnp.ones((ch,), dtype), "bias": jnp.zeros((ch,), dtype)}


def groupnorm(params: Params, x: jax.Array, *, groups: int = 32,
              eps: float = 1e-5) -> jax.Array:
    """GroupNorm over NHWC. Batch-size independent — the TPU-friendly norm
    for conv nets: no running stats to thread functionally and no
    cross-replica sync dependence, so per-device batch size never changes
    the math (the reason ResNet-50-GN recipes exist)."""
    B, H, W, C = x.shape
    g = min(groups, C)
    while C % g != 0:  # channel counts not divisible by 32 (stems, tests)
        g -= 1
    xf = x.astype(jnp.float32).reshape(B, H, W, g, C // g)
    mean = jnp.mean(xf, axis=(1, 2, 4), keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=(1, 2, 4), keepdims=True)
    y = ((xf - mean) * jax.lax.rsqrt(var + eps)).reshape(B, H, W, C)
    y = y * params["scale"] + params["bias"]
    return y.astype(x.dtype)


# ---------------------------------------------------------------------------
# Activations / misc
# ---------------------------------------------------------------------------

def gelu(x: jax.Array) -> jax.Array:
    return jax.nn.gelu(x, approximate=True)

def dropout(key: Optional[jax.Array], x: jax.Array, rate: float,
            training: bool) -> jax.Array:
    if not training or rate <= 0.0 or key is None:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(key, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0).astype(x.dtype)


def softmax_cross_entropy(logits: jax.Array, labels: jax.Array,
                          label_smoothing: float = 0.0) -> jax.Array:
    """Per-example loss; logits [..., C], integer labels [...]. Computed in
    fp32 (logit dtype may be bf16)."""
    logits = logits.astype(jnp.float32)
    n_classes = logits.shape[-1]
    logz = jax.nn.logsumexp(logits, axis=-1)
    label_logit = jnp.take_along_axis(
        logits, labels[..., None], axis=-1
    ).squeeze(-1)
    loss = logz - label_logit
    if label_smoothing > 0.0:
        smooth = -jnp.mean(logits, axis=-1) + logz
        loss = (1 - label_smoothing) * loss + label_smoothing * smooth
    return loss


def accuracy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    return jnp.mean((jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32))
