"""Functional NN ops and Pallas TPU kernels."""
from determined_clone_tpu.ops import attention, layers, moe

__all__ = ["attention", "layers", "moe"]
