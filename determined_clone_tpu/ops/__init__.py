"""Functional NN ops and Pallas TPU kernels."""
from determined_clone_tpu.ops import attention, layers

__all__ = ["attention", "layers"]
