"""Mixture-of-Experts FFN with expert parallelism over the mesh's ``ep`` axis.

Absent in the reference (SURVEY.md §2.7: "Expert parallel (EP / MoE) — ❌
absent"); first-class here. The design is the TPU-idiomatic einsum-dispatch
form (Switch-Transformer style): routing is expressed as dense one-hot
dispatch/combine tensors so every op is a static-shaped einsum the MXU can
tile — no gather/scatter, no dynamic shapes. When expert weights carry an
``ep`` PartitionSpec, XLA lowers the dispatch einsum to an all-to-all over the
ep axis automatically.

Capacity semantics: each expert processes at most C = ceil(tokens/E ·
capacity_factor) tokens; overflow tokens fall through the residual connection
(standard drop-token behavior). The router adds the load-balancing auxiliary
loss E · Σ_e f_e·P_e from the Switch paper.
"""
from __future__ import annotations

import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from determined_clone_tpu.ops.layers import trunc_normal

Params = Dict[str, Any]


def moe_init(key: jax.Array, n_experts: int, d_model: int, d_ff: int,
             dtype=jnp.float32, out_stddev: float = 0.02) -> Params:
    """Expert-stacked FFN params: leading [E] expert dim (sharded over ep)."""
    k_r, k_up, k_dn = jax.random.split(key, 3)
    return {
        "router": {"kernel": trunc_normal(k_r, (d_model, n_experts),
                                          stddev=0.02, dtype=dtype)},
        "up": {"kernel": trunc_normal(k_up, (n_experts, d_model, d_ff),
                                      stddev=0.02, dtype=dtype),
               "bias": jnp.zeros((n_experts, d_ff), dtype)},
        "down": {"kernel": trunc_normal(k_dn, (n_experts, d_ff, d_model),
                                        stddev=out_stddev, dtype=dtype),
                 "bias": jnp.zeros((n_experts, d_model), dtype)},
    }


def expert_capacity(n_tokens: int, n_experts: int,
                    capacity_factor: float) -> int:
    return max(1, math.ceil(n_tokens / n_experts * capacity_factor))


def moe_ffn(
    params: Params,
    x: jax.Array,
    *,
    k: int = 2,
    capacity_factor: float = 1.25,
    compute_dtype=jnp.bfloat16,
) -> Tuple[jax.Array, jax.Array]:
    """Top-k routed expert FFN. x: [B, T, D] → ([B, T, D], aux_loss scalar).

    All shapes static: dispatch/combine are [N, E, C] one-hot tensors, expert
    compute is batched einsum over the [E] dim (ep-shardable).
    """
    B, T, D = x.shape
    N = B * T
    E = params["router"]["kernel"].shape[-1]
    C = expert_capacity(N, E, capacity_factor)
    k = min(k, E)

    tokens = x.reshape(N, D)
    # Router in fp32 for a stable softmax.
    logits = tokens.astype(jnp.float32) @ params["router"]["kernel"].astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)                       # [N, E]

    # Top-k choices, processed in priority order so earlier choices claim
    # capacity first (running per-expert token counts carry between choices).
    top_probs, top_idx = jax.lax.top_k(probs, k)                  # [N, k]
    # Renormalize the chosen gates so combine weights sum to 1 per token.
    top_probs = top_probs / jnp.maximum(
        jnp.sum(top_probs, axis=-1, keepdims=True), 1e-9)

    dispatch = jnp.zeros((N, E, C), jnp.bool_)
    combine = jnp.zeros((N, E, C), jnp.float32)
    counts = jnp.zeros((E,), jnp.int32)                           # claimed slots
    for i in range(k):
        mask_i = jax.nn.one_hot(top_idx[:, i], E, dtype=jnp.int32)   # [N, E]
        pos_i = jnp.cumsum(mask_i, axis=0) - mask_i + counts[None, :]
        pos = jnp.sum(pos_i * mask_i, axis=-1)                    # [N] slot per token
        keep = pos < C
        counts = counts + jnp.sum(mask_i, axis=0)
        onehot_pos = jax.nn.one_hot(pos, C, dtype=jnp.float32)    # [N, C]
        d_i = (mask_i.astype(jnp.float32)[:, :, None] * onehot_pos[:, None, :]
               * keep.astype(jnp.float32)[:, None, None])
        dispatch = dispatch | (d_i > 0)
        combine = combine + d_i * top_probs[:, i][:, None, None]

    # Dispatch → expert compute → combine. XLA turns the E-dim contractions
    # into an all-to-all when up/down kernels are sharded over ep.
    xe = jnp.einsum("nec,nd->ecd", dispatch.astype(compute_dtype),
                    tokens.astype(compute_dtype))                 # [E, C, D]
    h = jnp.einsum("ecd,edf->ecf", xe,
                   params["up"]["kernel"].astype(compute_dtype))
    h = h + params["up"]["bias"].astype(compute_dtype)[:, None, :]
    h = jax.nn.gelu(h, approximate=True)
    ye = jnp.einsum("ecf,efd->ecd", h,
                    params["down"]["kernel"].astype(compute_dtype))
    ye = ye + params["down"]["bias"].astype(compute_dtype)[:, None, :]
    y = jnp.einsum("nec,ecd->nd", combine.astype(compute_dtype), ye)

    # Switch load-balancing loss: E · Σ_e (dispatch fraction · router prob).
    # First-choice assignment fractions, as in the paper.
    first = jax.nn.one_hot(top_idx[:, 0], E, dtype=jnp.float32)
    f = jnp.mean(first, axis=0)
    p = jnp.mean(probs, axis=0)
    aux = E * jnp.sum(f * p)

    return y.reshape(B, T, D).astype(x.dtype), aux.astype(jnp.float32)
