"""Trial API + Trainer (≈ harness/determined/pytorch)."""
from determined_clone_tpu.training.metrics import MetricAccumulator
from determined_clone_tpu.training.train_step import (
    TrainState,
    create_train_state,
    make_eval_step,
    make_train_step,
    state_shardings,
)
from determined_clone_tpu.training.trainer import Trainer
from determined_clone_tpu.training.trial import JaxTrial, TrialContext

__all__ = [
    "MetricAccumulator",
    "TrainState",
    "create_train_state",
    "make_eval_step",
    "make_train_step",
    "state_shardings",
    "Trainer",
    "JaxTrial",
    "TrialContext",
]
