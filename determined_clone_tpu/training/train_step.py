"""The jitted training step: loss → grads → optax update, fully sharded.

This is the TPU replacement for the reference's per-batch hot loop
(_PyTorchTrialController._train_batch, harness/determined/pytorch/
_pytorch_trial.py:877): instead of eager torch ops + NCCL allreduce, the
whole step is one XLA program over the mesh — gradient reductions,
ZeRO-style reduce-scatters and TP collectives are inserted by the
partitioner from the shardings alone.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from determined_clone_tpu.parallel.sharding import ShardingRules

LossFn = Callable[..., Any]  # (params, batch, rng) -> loss | (loss, metrics)


@dataclasses.dataclass
class TrainState:
    """Functional train state (params + optimizer state + step + rng)."""

    params: Any
    opt_state: Any
    step: jax.Array
    rng: jax.Array

    def tree_flatten(self):  # pragma: no cover - registered below
        return (self.params, self.opt_state, self.step, self.rng), None


jax.tree_util.register_pytree_node(
    TrainState,
    lambda s: ((s.params, s.opt_state, s.step, s.rng), None),
    lambda _, c: TrainState(*c),
)


def create_train_state(params: Any, tx: optax.GradientTransformation,
                       rng: jax.Array) -> TrainState:
    return TrainState(
        params=params,
        opt_state=tx.init(params),
        step=jnp.zeros((), jnp.int32),
        rng=rng,
    )


def state_shardings(state: TrainState, mesh: Mesh,
                    rules: ShardingRules) -> TrainState:
    """Shardings for a whole TrainState. Optimizer-state leaves mirror their
    parameter's sharding (the ZeRO-1/2 property: Adam moments are sharded
    exactly like the params they track); scalars replicate."""
    param_sh = rules.shardings_for(state.params, mesh)
    param_struct = jax.tree_util.tree_structure(state.params)
    rep = NamedSharding(mesh, P())

    def is_params_like(node: Any) -> bool:
        """A subtree congruent with params (optax moment buffers: Adam mu/nu,
        etc. — they carry the params' own shardings)."""
        try:
            return jax.tree_util.tree_structure(node) == param_struct
        except Exception:
            return False

    def opt_sharding(opt_state):
        return jax.tree.map(
            lambda node: param_sh if is_params_like(node) else rep,
            opt_state,
            is_leaf=is_params_like,
        )
    return TrainState(
        params=param_sh,
        opt_state=opt_sharding(state.opt_state),
        step=rep,
        rng=rep,
    )


def make_train_step(
    loss_fn: LossFn,
    tx: optax.GradientTransformation,
    *,
    mesh: Optional[Mesh] = None,
    state_sharding: Optional[TrainState] = None,
    batch_sharding: Optional[Any] = None,
    donate: bool = True,
    steps_per_dispatch: int = 1,
) -> Callable[..., Tuple[TrainState, Dict[str, jax.Array]]]:
    """Build the jitted train step.

    ``loss_fn(params, batch, rng)`` returns a scalar loss or
    ``(loss, metrics_dict)``. Gradient reduction across dp/fsdp is implicit:
    the batch is sharded over those axes, so XLA emits the reduce-scatter /
    all-reduce the specs imply.

    With ``steps_per_dispatch=k > 1`` the returned callable takes
    ``(state, batch_0, ..., batch_{k-1})`` and runs all k optimizer steps
    inside ONE jitted program: the batches are stacked device-side and
    ``lax.scan``ned through the step body with the train state as donated
    carry, and per-step metrics are summed on device. One Python dispatch
    (and one donation round-trip) then covers k batches — semantically
    identical to k sequential calls of the k=1 step, including the per-step
    rng split chain, so seeded runs are bit-compatible modulo the metric
    re-association. Pair with ``MetricAccumulator.add(metrics, count=k)``.
    """

    def step_fn(state: TrainState, batch: Any):
        rng, step_rng = jax.random.split(state.rng)

        def wrapped(params):
            out = loss_fn(params, batch, step_rng)
            if isinstance(out, tuple):
                loss, metrics = out
            else:
                loss, metrics = out, {}
            return loss, metrics

        (loss, metrics), grads = jax.value_and_grad(wrapped, has_aux=True)(
            state.params
        )
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        new_state = TrainState(
            params=params, opt_state=opt_state, step=state.step + 1, rng=rng
        )
        gnorm = optax.global_norm(grads)
        out_metrics = {"loss": loss.astype(jnp.float32),
                       "grad_norm": gnorm.astype(jnp.float32), **metrics}
        return new_state, out_metrics

    k = int(steps_per_dispatch)
    if k < 1:
        raise ValueError(f"steps_per_dispatch must be >= 1, got {k}")

    if k == 1:
        fn: Callable[..., Any] = step_fn
        n_batch_args = 1
    else:
        def fused_fn(state: TrainState, *batches: Any):
            # stack the k batches device-side: the scan's leading axis
            stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *batches)

            def body(carry: TrainState, batch: Any):
                return step_fn(carry, batch)

            new_state, per_step = jax.lax.scan(body, state, stacked)
            # sum (not mean) so the accumulator's count-weighted mean stays
            # exact when a chunk mixes fused and single-step dispatches
            summed = jax.tree.map(lambda m: jnp.sum(m, axis=0), per_step)
            return new_state, summed

        fn = fused_fn
        n_batch_args = k

    kwargs: Dict[str, Any] = {}
    if state_sharding is not None:
        in_shardings = (state_sharding,) + (batch_sharding,) * n_batch_args
        out_shardings = (state_sharding, None)
        kwargs = dict(in_shardings=in_shardings, out_shardings=out_shardings)
    if donate:
        kwargs["donate_argnums"] = (0,)
    return jax.jit(fn, **kwargs)


def capture_compile(
    step: Callable[..., Any],
    example_args: Tuple[Any, ...],
    *,
    program: str = "train_step",
    registry: Optional[Any] = None,
    tracer: Optional[Any] = None,
    mesh: Optional[Mesh] = None,
    exec_cache: Optional[Any] = None,
) -> Tuple[Callable[..., Any], Optional[Any]]:
    """Explicit ``lower()``/``compile()`` capture for a built step.

    Replaces the implicit first-call compile with a measured one: compile
    wall time, a sha256 fingerprint of the lowered StableHLO, and the
    compiled program's cost/memory analysis land in the registry/tracer
    (telemetry/xla.py has the mechanics). With ``mesh``, the compiled
    (post-SPMD) HLO is additionally parsed for collectives — op counts
    and byte volumes per mesh axis (telemetry/collectives.py). The
    returned callable runs the AOT executable — the program that was
    measured is the program that executes — and falls back to ``step``'s
    jit cache on a shape mismatch (remainder batches). ``example_args``
    contribute shapes only; nothing runs during lowering. On any failure
    the original ``step`` comes back with a ``None`` record.

    With a persistent executable cache — explicit ``exec_cache``, or the
    ambient default a ``DCT_EXEC_CACHE=1`` CAS-backed run installs
    (core/_context.py) — the capture is cache-first: a restart leg loads
    the serialized train-step executable from ``cas/exec/`` instead of
    recompiling, and the goodput ``compile`` category collapses to the
    load time (``record.cache_hit``/``compile_time_saved_s`` say so).
    """
    from determined_clone_tpu.telemetry import xla as xla_telemetry

    return xla_telemetry.aot_compile(
        step, example_args, program=program,
        registry=registry, tracer=tracer, mesh=mesh,
        exec_cache=exec_cache)


def param_count(tree: Any) -> int:
    """Total parameter count of a pytree — the N in the 6*N FLOPs
    approximation (telemetry/flops.py) when a trial provides no analytic
    per-step count."""
    return sum(int(x.size) for x in jax.tree.leaves(tree))


def program_cache_size(fn: Any) -> Optional[int]:
    """Best-effort size of a jitted callable's compilation cache, or None
    when this jax version doesn't expose it. Growth between two reads means
    a (re)trace+compile happened — ``telemetry.Telemetry.wrap_jit`` and
    ``bench.py`` use this to count XLA compiles; traced wrappers propagate
    the probe so the count survives instrumentation."""
    probe = getattr(fn, "_cache_size", None)
    if probe is None:
        return None
    try:
        return int(probe())
    except Exception:
        return None


def make_eval_step(
    eval_fn: Callable[..., Dict[str, jax.Array]],
    *,
    state_sharding: Optional[TrainState] = None,
    batch_sharding: Optional[Any] = None,
    rng: Optional[jax.Array] = None,
) -> Callable[[TrainState, Any], Dict[str, jax.Array]]:
    """Jitted evaluation step over params only.

    When ``rng`` is given and ``eval_fn`` declares an ``rng`` parameter,
    each call receives ``fold_in(rng, state.step)`` — derived from the
    experiment's seeded chain and fresh per validation boundary, never the
    constant-key-per-eval antipattern (JAX002). Trials with the plain
    ``(params, batch)`` signature are called unchanged.
    """
    import inspect

    wants_rng = False
    if rng is not None:
        try:
            wants_rng = "rng" in inspect.signature(eval_fn).parameters
        except (TypeError, ValueError):
            wants_rng = False

    def step_fn(state: TrainState, batch: Any):
        if wants_rng:
            return eval_fn(state.params, batch,
                           rng=jax.random.fold_in(rng, state.step))
        return eval_fn(state.params, batch)

    kwargs: Dict[str, Any] = {}
    if state_sharding is not None:
        kwargs = dict(in_shardings=(state_sharding, batch_sharding))
    return jax.jit(step_fn, **kwargs)
