"""Metric accumulation without host syncs.

The reference reduces metrics eagerly per batch (pytorch _reducer.py); under
XLA that would force a device→host transfer every step. Here metrics stay on
device: scalars are appended to a running (sum, count) device accumulator and
only converted to floats at reporting boundaries.
"""
from __future__ import annotations

from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np


class MetricAccumulator:
    """Running mean of per-batch scalar metrics, device-side."""

    def __init__(self) -> None:
        self._sums: Dict[str, jax.Array] = {}
        self._counts: Dict[str, int] = {}

    def add(self, metrics: Dict[str, jax.Array], count: int = 1) -> None:
        """Accumulate per-batch scalars. ``count`` is how many batches the
        values already sum over — a fused k-step dispatch hands in
        device-side summed metrics with ``count=k`` so the reported mean
        stays a true per-batch mean."""
        for k, v in metrics.items():
            if k in self._sums:
                self._sums[k] = self._sums[k] + v
                self._counts[k] += count
            else:
                self._sums[k] = v
                self._counts[k] = count

    def result(self) -> Dict[str, float]:
        """Host sync point: returns means and resets. All sums cross the
        device boundary in ONE ``jax.device_get`` of the whole dict — a
        reporting boundary costs one host sync, not one per metric key."""
        host_sums = jax.device_get(self._sums)
        out = {
            k: float(np.asarray(s)) / self._counts[k]
            for k, s in host_sums.items()
        }
        self._sums.clear()
        self._counts.clear()
        return out

    def __len__(self) -> int:
        return len(self._sums)


def mean_over_batches(per_batch: List[Dict[str, jax.Array]]) -> Dict[str, float]:
    acc = MetricAccumulator()
    for m in per_batch:
        acc.add(m)
    return acc.result()
