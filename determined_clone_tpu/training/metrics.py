"""Metric accumulation without host syncs.

The reference reduces metrics eagerly per batch (pytorch _reducer.py); under
XLA that would force a device→host transfer every step. Here metrics stay on
device: scalars are appended to a running (sum, count) device accumulator and
only converted to floats at reporting boundaries.
"""
from __future__ import annotations

from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np


class MetricAccumulator:
    """Running mean of per-batch scalar metrics, device-side."""

    def __init__(self) -> None:
        self._sums: Dict[str, jax.Array] = {}
        self._counts: Dict[str, int] = {}

    def add(self, metrics: Dict[str, jax.Array]) -> None:
        for k, v in metrics.items():
            if k in self._sums:
                self._sums[k] = self._sums[k] + v
                self._counts[k] += 1
            else:
                self._sums[k] = v
                self._counts[k] = 1

    def result(self) -> Dict[str, float]:
        """Host sync point: returns means and resets."""
        out = {
            k: float(np.asarray(jax.device_get(s))) / self._counts[k]
            for k, s in self._sums.items()
        }
        self._sums.clear()
        self._counts.clear()
        return out

    def __len__(self) -> int:
        return len(self._sums)


def mean_over_batches(per_batch: List[Dict[str, jax.Array]]) -> Dict[str, float]:
    acc = MetricAccumulator()
    for m in per_batch:
        acc.add(m)
    return acc.result()
