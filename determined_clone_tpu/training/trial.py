"""JaxTrial — the high-level trial API (PyTorchTrial re-imagined for XLA).

The reference's PyTorchTrial (harness/determined/pytorch/_pytorch_trial.py:1416)
is a class of eager-mode hooks called per batch. Under jit that inversion
doesn't work — the framework must trace the user's functions instead. A
JaxTrial therefore declares pure functions over pytrees:

  initial_params(rng)            ≈ __init__ wrap_model
  optimizer()                    ≈ wrap_optimizer (an optax transformation —
                                    LR schedules are optax schedules, ≈ wrap_lr_scheduler)
  loss(params, batch, rng)       ≈ train_batch (traced; returns loss, metrics)
  eval_metrics(params, batch[, rng])  ≈ evaluate_batch (traced)
  sharding_rules()               parallelism layout (≈ DeepSpeed config / MPU)
  training_data()/validation_data()  ≈ build_training_data_loader

The TrialContext carries what trial code may read: hparams, the experiment
config, the mesh, and the Core API context.
"""
from __future__ import annotations

import abc
from typing import Any, Dict, Iterable, Optional, Tuple

import jax
import optax

from determined_clone_tpu import core as core_mod
from determined_clone_tpu.config.experiment import ExperimentConfig
from determined_clone_tpu.parallel.mesh import MeshSpec, make_mesh
from determined_clone_tpu.parallel.sharding import ShardingRules, batch_spec


class TrialContext:
    def __init__(self, *, config: ExperimentConfig, hparams: Dict[str, Any],
                 core: core_mod.Context, mesh: Optional[Any] = None) -> None:
        self.config = config
        self.hparams = hparams
        self.core = core
        if mesh is None:
            mesh_hp = hparams.get("mesh")
            spec = MeshSpec.from_dict(mesh_hp) if mesh_hp else MeshSpec()
            n = config.resources.slots_per_trial or 1
            devices = jax.devices()[:n] if n <= len(jax.devices()) else jax.devices()
            mesh = make_mesh(spec.resolve(len(devices)), devices)
        self.mesh = mesh

    @property
    def distributed(self) -> core_mod.DistributedContext:
        return self.core.distributed

    def get_hparam(self, name: str, default: Any = None) -> Any:
        node: Any = self.hparams
        for part in name.split("."):
            if not isinstance(node, dict) or part not in node:
                return default
            node = node[part]
        return node


class JaxTrial(abc.ABC):
    """Subclass and implement the pure functions; the Trainer does the rest."""

    def __init__(self, context: TrialContext) -> None:
        self.context = context

    # -- required -----------------------------------------------------------

    @abc.abstractmethod
    def initial_params(self, rng: jax.Array) -> Any:
        ...

    @abc.abstractmethod
    def optimizer(self) -> optax.GradientTransformation:
        ...

    @abc.abstractmethod
    def loss(self, params: Any, batch: Any, rng: jax.Array
             ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        """Traced. Returns (scalar loss, metrics dict of device scalars)."""

    @abc.abstractmethod
    def training_data(self) -> Iterable[Any]:
        """Yield host-side batches (numpy pytrees) with GLOBAL batch dim."""

    # -- optional -----------------------------------------------------------

    def eval_metrics(self, params: Any, batch: Any,
                     rng: Optional[jax.Array] = None
                     ) -> Dict[str, jax.Array]:
        """Traced. Per-batch validation metrics (mean-reduced across batches).

        ``rng`` is threaded by the Trainer off the experiment's seeded key
        chain (``make_eval_step`` folds the train step count in, so every
        validation sees fresh randomness — never a constant reused key).
        Direct callers that pass no key get one derived from the
        experiment seed. Overrides with the plain ``(params, batch)``
        signature keep working; declare ``rng`` to receive the key."""
        if rng is None:
            rng = jax.random.PRNGKey(self.context.config.experiment_seed)
        loss, metrics = self.loss(params, batch, rng)
        return {"loss": loss, **metrics}

    def validation_data(self) -> Optional[Iterable[Any]]:
        return None

    def train_step_flops(self) -> Optional[Any]:
        """Analytic FLOPs for ONE optimizer step over one global batch —
        a :class:`telemetry.flops.StepFlops` or a plain float. Model
        trials that know their architecture should override (e.g. via
        ``telemetry.flops.gpt_train_step_flops``); None makes the Trainer
        fall back to the ``6 * n_params * tokens`` approximation."""
        return None

    def tokens_per_sample(self) -> Optional[int]:
        """Tokens per sample (sequence length) for the 6N fallback;
        None → counted as 1 token per sample."""
        return None

    def sharding_rules(self) -> ShardingRules:
        return ShardingRules()

    def batch_spec(self, batch: Any) -> Any:
        """PartitionSpec pytree for one batch; default: leading dim over
        (dp, fsdp) on every leaf."""
        return jax.tree.map(
            lambda x: batch_spec(extra_dims=max(0, x.ndim - 1)), batch
        )

    @property
    def global_batch_size(self) -> int:
        return int(self.context.get_hparam("global_batch_size", 32))
