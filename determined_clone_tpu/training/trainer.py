"""Trainer — the training loop (≈ _PyTorchTrialController + Trainer.fit,
harness/determined/pytorch/_pytorch_trial.py:183,631 and _trainer.py:83).

Loop shape mirrors the reference's searcher-driven boundaries
(_train_with_boundaries :695): train in scheduling_unit chunks, report
training metrics per chunk, validate/checkpoint on period boundaries,
cooperate with preemption — but each batch is one jitted XLA program and
metrics stay on device until a boundary (no per-batch host syncs).

Hot-loop performance (config ``optimizations:`` block, docs/
training_loop_performance.md):

- **Async device prefetch** (``prefetch_depth``, default 2): a background
  thread pulls host batches and applies the sharded ``device_put`` into a
  bounded queue, so input transfer overlaps device compute instead of
  blocking every dispatch. Depth 0 restores the synchronous path.
- **Fused multi-step dispatch** (``steps_per_dispatch=k``): k batches are
  ``lax.scan``ned through the step body inside one jitted program — one
  Python dispatch per k optimizer steps, metrics summed device-side.
  Chunk/target remainders smaller than k fall back to the k=1 program, so
  batch order and the rng chain match the unfused loop exactly.
"""
from __future__ import annotations

import logging
import os
import time
from typing import Any, Dict, Iterator, Optional, Tuple

import jax
import numpy as np
from jax.sharding import NamedSharding

from determined_clone_tpu import faults
from determined_clone_tpu.config.length import Length
from determined_clone_tpu.core._checkpoint import CheckpointCorruptError
from determined_clone_tpu.core._serialization import load_pytree, save_pytree
from determined_clone_tpu.telemetry import flops as flops_mod
from determined_clone_tpu.telemetry.device import DeviceMemoryMonitor
from determined_clone_tpu.telemetry.spans import null_span
from determined_clone_tpu.telemetry.xla import (
    MfuComparator,
    StepTimeAnomalyDetector,
)
from determined_clone_tpu.training.metrics import MetricAccumulator
from determined_clone_tpu.training.train_step import (
    TrainState,
    capture_compile,
    create_train_state,
    make_eval_step,
    make_train_step,
    param_count,
    state_shardings,
)
from determined_clone_tpu.training.trial import JaxTrial
from determined_clone_tpu.utils.data import make_device_feeder

CKPT_STATE_DIR = "state"

logger = logging.getLogger(__name__)


def _skip_batches(it: Iterator[Any], n: int) -> int:
    """Fast-forward ``n`` batches of ``it``; returns how many were skipped
    (< n once exhausted). Iterators exposing ``skip_batches`` (e.g.
    ``utils.data.BatchIterator``) skip by index arithmetic; anything else
    falls back to materialize-and-discard."""
    if n <= 0:
        return 0
    fast = getattr(it, "skip_batches", None)
    if fast is not None:
        return int(fast(n))
    skipped = 0
    while skipped < n:
        try:
            next(it)
        except StopIteration:
            break
        skipped += 1
    return skipped


class Trainer:
    def __init__(self, trial: JaxTrial) -> None:
        self.trial = trial
        self.context = trial.context
        self.config = trial.context.config
        self.core = trial.context.core
        self.mesh = trial.context.mesh

    # -- length resolution --------------------------------------------------

    def _to_batches(self, length: Optional[Any], default: int = 0) -> int:
        if length is None:
            return default
        if isinstance(length, int):
            return length
        if isinstance(length, Length):
            return length.to_batches(
                self.trial.global_batch_size, self.config.records_per_epoch
            )
        raise TypeError(f"cannot resolve training length {length!r}")

    # -- checkpoint save/restore -------------------------------------------

    def _save(self, state: TrainState, batches_trained: int,
              reason: str, metric=None) -> str:
        """Every host writes its addressable shard files; sharded upload
        merges the manifests (multi-host pjit state is never fully
        addressable on one host). ``metric`` (the searcher metric at save
        time) feeds the master's save_trial_best GC policy."""
        faults.point("training.checkpoint_save")
        dist = self.core.distributed
        ck = self.core.checkpoint
        sharded = dist.size > 1
        metadata = {
            "steps_completed": batches_trained,
            "reason": reason,
            "global_batch_size": self.trial.global_batch_size,
        }
        if metric is not None:
            metadata["validation_metric"] = float(metric)
        with self._span("checkpoint_save", reason=reason):
            with ck.store_path(
                metadata=metadata,
                shard=sharded,
            ) as (path, holder):
                save_pytree(f"{path}/{CKPT_STATE_DIR}", state,
                            host_id=dist.rank)
        return holder.get("storage_id", "")

    def _restore(self, storage_id: str, like: TrainState,
                 shardings: TrainState) -> tuple:
        """Restore with fallback: a checkpoint refused by commit-protocol
        validation (crash mid-upload, torn write) falls back through the
        registry's committed checkpoints, newest first. The registry only
        holds committed ones, so the first candidate that validates is the
        newest safe state."""
        ck = self.core.checkpoint
        candidates = [storage_id] + [
            sid for sid in ck.committed_checkpoints() if sid != storage_id]
        first_err: Optional[CheckpointCorruptError] = None
        for sid in candidates:
            try:
                return self._restore_one(sid, like, shardings)
            except CheckpointCorruptError as e:
                if first_err is None:
                    first_err = e
                logger.warning(
                    "checkpoint %s refused (%s); falling back to the "
                    "previous committed checkpoint", sid, e.reason)
                tel = self._telemetry
                if tel is not None:
                    tel.registry.counter(
                        "checkpoint_restore_fallbacks",
                        "restores that fell back past an uncommitted/"
                        "corrupt checkpoint").inc()
        raise first_err if first_err is not None else RuntimeError(
            f"no restorable checkpoint for {storage_id}")

    def _restore_one(self, storage_id: str, like: TrainState,
                     shardings: TrainState) -> tuple:
        ck = self.core.checkpoint
        with self._span("checkpoint_restore"):
            with ck.restore_path(storage_id) as path:
                state = load_pytree(f"{path}/{CKPT_STATE_DIR}", like,
                                    shardings=shardings)
                mpath = f"{path}/metadata.json"
                meta: dict = {}
                if os.path.exists(mpath):
                    with open(mpath) as f:
                        import json

                        meta = json.load(f)
        return state, int(meta.get("steps_completed", 0))

    @property
    def _telemetry(self):
        return getattr(self.core, "telemetry", None)

    @staticmethod
    def _resolve_step_flops(trial: JaxTrial, state: TrainState
                            ) -> Tuple[float, str]:
        """(FLOPs per optimizer step, source label). Prefers the trial's
        analytic count; falls back to 6*N_params*tokens. A trial hook that
        raises downgrades to the fallback — FLOPs accounting must never
        fail training."""
        try:
            f = trial.train_step_flops()
        except Exception:  # noqa: BLE001 - observability is best-effort
            f = None
        if f is not None:
            return float(getattr(f, "total", f)), "analytic"
        n_params = param_count(state.params)
        try:
            tokens_per_sample = int(trial.tokens_per_sample() or 1)
        except Exception:  # noqa: BLE001 - observability is best-effort
            tokens_per_sample = 1
        tokens = trial.global_batch_size * max(1, tokens_per_sample)
        return flops_mod.dense_train_flops_per_token(n_params) * tokens, \
            "dense_6n"

    @property
    def _span(self):
        """The tracer's span factory, or the shared no-op when telemetry is
        off — boundary-only call sites (save/restore/sync), never per batch."""
        tel = self._telemetry
        return tel.tracer.span if tel is not None else null_span

    # -- the loop -----------------------------------------------------------

    def fit(self, latest_checkpoint: Optional[str] = None) -> Dict[str, Any]:
        try:
            return self._fit_inner(latest_checkpoint)
        except BaseException:
            # join local uploader threads so the crash doesn't kill them
            # mid-upload — WITHOUT collectives (other ranks may be mid-loop
            # or dead; a collective here would hang or corrupt their
            # exchanges). Nothing is published; the error stays primary.
            try:
                self.core.checkpoint.abort_async()
            except Exception:
                pass
            raise

    def _fit_inner(self, latest_checkpoint: Optional[str] = None
                   ) -> Dict[str, Any]:
        trial, config = self.trial, self.config
        dist = self.core.distributed
        mesh = self.mesh

        rng = jax.random.PRNGKey(config.experiment_seed)
        init_rng, state_rng = jax.random.split(rng)
        # eval keys branch off the same seeded chain via fold_in (not a
        # 3-way split) so init/state keys — and restored runs — are
        # unchanged from earlier versions
        eval_rng = jax.random.fold_in(rng, 1)
        params = trial.initial_params(init_rng)
        tx = trial.optimizer()
        state = create_train_state(params, tx, state_rng)
        shardings = state_shardings(state, mesh, trial.sharding_rules())

        data_iter = iter(trial.training_data())
        try:
            first_batch = next(data_iter)
        except StopIteration:
            raise RuntimeError("training_data() yielded no batches") from None
        batch_sharding = jax.tree.map(
            lambda spec: NamedSharding(mesh, spec),
            trial.batch_spec(first_batch),
        )

        batches_trained = 0
        if latest_checkpoint:
            state, batches_trained = self._restore(latest_checkpoint, state,
                                                   shardings)
        else:
            state = jax.device_put(state, shardings)

        opt = config.optimizations
        k = max(1, int(opt.steps_per_dispatch))
        prefetch_depth = max(0, int(opt.prefetch_depth))

        train_step = make_train_step(
            trial.loss, tx, mesh=mesh, state_sharding=shardings,
            batch_sharding=batch_sharding,
        )
        # k batches through one jitted lax.scan program; remainders smaller
        # than k use the single-step program above, so batch order and the
        # rng chain are identical to the unfused loop
        fused_step = None
        if k > 1:
            fused_step = make_train_step(
                trial.loss, tx, mesh=mesh, state_sharding=shardings,
                batch_sharding=batch_sharding, steps_per_dispatch=k,
            )
        eval_step = make_eval_step(
            trial.eval_metrics, state_sharding=shardings,
            batch_sharding=batch_sharding, rng=eval_rng,
        )

        # telemetry (observability: block; None when disabled — the hot loop
        # below then runs the *unwrapped* callables and feeder, so the
        # disabled path adds nothing per step). The sync makes each
        # train_dispatch span cover device completion, not just enqueue.
        tel = self._telemetry
        span = tel.tracer.span if tel is not None else null_span
        step_record = fused_record = None
        anomaly = None
        memmon = None
        if tel is not None:
            # explicit lower()/compile() capture (telemetry/xla.py): the
            # compile that runs is the compile that was measured, and the
            # program fingerprint + cost_analysis FLOPs land in the
            # registry before the first step dispatches
            train_step, step_record = capture_compile(
                train_step, (state, first_batch),
                program="train_step",
                registry=tel.registry, tracer=tel.tracer)
            if fused_step is not None:
                fused_step, fused_record = capture_compile(
                    fused_step, (state,) + (first_batch,) * k,
                    program=f"train_step_fused_k{k}",
                    registry=tel.registry, tracer=tel.tracer)
            # rolling median/MAD straggler detection over steady-state
            # dispatch durations (compiles are excluded by wrap_jit)
            anomaly = StepTimeAnomalyDetector(
                tel.registry, tracer=tel.tracer,
                window=tel.anomaly_window,
                threshold=tel.anomaly_threshold,
                min_samples=tel.anomaly_min_samples)
            memmon = DeviceMemoryMonitor(tel.registry)
            train_step = tel.wrap_jit("train_dispatch", train_step,
                                      sync=jax.block_until_ready,
                                      observe=anomaly.observe)
            if fused_step is not None:
                fused_step = tel.wrap_jit("train_dispatch", fused_step,
                                          sync=jax.block_until_ready,
                                          observe=anomaly.observe)
            eval_step = tel.wrap_jit("eval_dispatch", eval_step,
                                     sync=jax.block_until_ready)

        # analytic FLOPs/MFU accounting (telemetry/flops.py) — resolved
        # once here, reported per chunk. Only when telemetry is on: the
        # disabled hot loop must stay byte-identical.
        step_flops = 0.0
        flops_source = peak_label = ""
        peak_total = 0.0
        mfu_cmp = None
        measured_step_flops = 0.0
        if tel is not None:
            step_flops, flops_source = self._resolve_step_flops(trial, state)
            n_devices = (int(mesh.devices.size) if mesh is not None
                         else jax.device_count())
            peak, peak_label = flops_mod.peak_flops_estimate()
            peak_total = peak * max(1, n_devices)
            # measured MFU: the compiled program's own cost_analysis FLOPs
            # (per single-step batch — the fused program covers k batches)
            if fused_record is not None and fused_record.flops:
                measured_step_flops = fused_record.flops / k
            elif step_record is not None and step_record.flops:
                measured_step_flops = step_record.flops
            if measured_step_flops:
                mfu_cmp = MfuComparator(tel.registry,
                                        peak_flops_total=peak_total)

        sched_unit = config.scheduling_unit
        val_period = self._to_batches(config.min_validation_period, 0)
        ckpt_period = self._to_batches(config.min_checkpoint_period, 0)
        policy = config.checkpoint_policy
        smaller = config.searcher.smaller_is_better
        searcher_metric = config.searcher.metric

        # skip already-trained batches on restore so data order lines up;
        # index-capable iterators (BatchIterator.skip_batches) fast-forward
        # by arithmetic instead of materializing every replayed batch
        restored = batches_trained > 0
        if restored:
            # spanned so the goodput ledger books replay as restore badput,
            # not unattributed time (the restore itself is already spanned
            # as checkpoint_restore in _restore_one)
            with self._span("restore_replay", batches=batches_trained - 1):
                to_skip = batches_trained - 1  # first_batch discarded below
                while to_skip > 0:
                    skipped = _skip_batches(data_iter, to_skip)
                    to_skip -= skipped
                    if to_skip > 0:
                        # epoch exhausted mid-replay: roll into the next one
                        data_iter = iter(trial.training_data())
                        if skipped == 0:
                            # the previous epoch was already drained, so a
                            # zero-progress round means the fresh epoch must
                            # move — probe one batch to rule out an empty
                            # dataset (would otherwise loop forever)
                            if _skip_batches(data_iter, 1) == 0:
                                raise RuntimeError(
                                    "training_data() yielded no batches "
                                    "while replaying restored progress")
                            to_skip -= 1

        def batches() -> Iterator[Any]:
            if not restored:
                yield first_batch
            yield from data_iter
            while True:  # repeat dataset
                yield from iter(trial.training_data())

        batch_gen = batches()

        def to_device(batch: Any) -> Any:
            return jax.device_put(batch, batch_sharding)

        # async device prefetch: a producer thread overlaps host input +
        # device_put with XLA compute (depth 0 = the old synchronous path);
        # fused dispatch consumes k batches at once, so scale the buffer
        feed = make_device_feeder(
            batch_gen, to_device,
            depth=prefetch_depth * k if prefetch_depth else 0,
            name="train-prefetch",
            tracer=tel.tracer if tel is not None else None,
            registry=tel.registry if tel is not None else None,
        )
        if tel is not None:
            feed = tel.wrap_feeder(feed)

        acc = MetricAccumulator()
        last_val: Dict[str, float] = {}
        best_val: Optional[float] = None
        last_val_at = batches_trained
        last_ckpt_at = batches_trained
        preempted = False
        result: Dict[str, Any] = {}

        # optional observability wired by the exec layer (None in
        # local/unmanaged runs): profiler (≈ ProfilerAgent) + tensorboard
        profiler = self.core.profiler
        tb = self.core.tensorboard

        # truncated validation must be visible: dropped remainder batches
        # are counted (examples, not batches), surfaced once per fit in the
        # log and continuously in a telemetry gauge
        eval_dropped = {"examples": 0, "warned": False}

        def validate() -> Dict[str, float]:
            vdata = trial.validation_data()
            if vdata is None:
                return {}

            def full_batches() -> Iterator[Any]:
                # drop the shape-mismatched remainder batch (the
                # drop_remainder contract): a second batch shape would mean
                # a second eval_step compile every validation — eval stays
                # a single compiled program
                first_shapes = None
                for vb in vdata:
                    shapes = tuple(
                        np.shape(leaf) for leaf in jax.tree.leaves(vb))
                    if first_shapes is None:
                        first_shapes = shapes
                    elif shapes != first_shapes:
                        leaves = jax.tree.leaves(vb)
                        n = int(np.shape(leaves[0])[0]) if (
                            leaves and np.ndim(leaves[0])) else 1
                        eval_dropped["examples"] += n
                        continue
                    yield vb

            with span("validate"):
                vacc = MetricAccumulator()
                vfeed = make_device_feeder(
                    full_batches(), to_device,
                    depth=prefetch_depth, name="eval-prefetch",
                    tracer=tel.tracer if tel is not None else None)
                try:
                    for vbatch in vfeed:
                        vacc.add(eval_step(state, vbatch))
                finally:
                    vfeed.close()
                metrics = vacc.result() if len(vacc) else {}
            if eval_dropped["examples"]:
                if not eval_dropped["warned"]:
                    eval_dropped["warned"] = True
                    logger.warning(
                        "validation dropped %d examples in shape-mismatched "
                        "remainder batches (drop_remainder contract); pad "
                        "or size the eval set to a batch multiple for full "
                        "coverage", eval_dropped["examples"])
                if tel is not None:
                    tel.registry.gauge(
                        "eval_examples_dropped",
                        "eval examples lost to shape-mismatched remainder "
                        "batches this fit").set(eval_dropped["examples"])
            if metrics:
                self.core.train.report_validation_metrics(batches_trained, metrics)
                if tb is not None:
                    tb.add_scalars("validation", metrics, batches_trained)
            return metrics

        # the prefetcher must join on EVERY exit — normal completion,
        # preemption, or a mid-chunk exception (no leaked producer
        # threads, no deadlock on a dead consumer)
        try:
            for op in self.core.searcher.operations():
                if op.length is None:
                    raise RuntimeError(
                        "searcher.max_length is not set: the searcher operation "
                        "has no training target. Set searcher.max_length in the "
                        "experiment config (e.g. {'batches': 1000}) or provide a "
                        "searcher_source."
                    )
                target = self._to_batches(op.length, 0)
                while batches_trained < target and not preempted:
                    chunk_end = min(
                        target,
                        (batches_trained // sched_unit + 1) * sched_unit,
                    )
                    t0 = time.perf_counter()
                    n0 = batches_trained
                    while batches_trained < chunk_end:
                        # one pair per dispatch (fused counts as one); a
                        # None check each when no plan is active
                        faults.point("training.pre_step")
                        if (fused_step is not None
                                and chunk_end - batches_trained >= k):
                            # k prefetched device batches → ONE dispatch
                            group = [next(feed) for _ in range(k)]
                            state, metrics = fused_step(state, *group)
                            acc.add(metrics, count=k)
                            batches_trained += k
                        else:
                            state, metrics = train_step(state, next(feed))
                            acc.add(metrics)
                            batches_trained += 1
                        faults.point("training.post_step")
                    # ---- reporting boundary (one host sync per chunk) ----
                    with span("host_sync"):
                        train_metrics = acc.result()
                    dt = time.perf_counter() - t0
                    # queue-wait is the consumer-visible input stall (the
                    # overlap residue); host-time is the producer's true input
                    # cost even when hidden under compute
                    t_wait = feed.take_queue_wait()
                    t_host = feed.take_host_time()
                    train_metrics["batches_per_second"] = (batches_trained - n0) / dt
                    train_metrics["samples_per_second"] = (
                        (batches_trained - n0) * trial.global_batch_size / dt
                    )
                    if tel is not None and step_flops:
                        # FLOPs throughput + MFU against the (measured or
                        # assumed) peak; the provenance labels travel with
                        # the number so an assumed-peak MFU can't pass as
                        # a measured one (docs/observability.md)
                        fps = step_flops * train_metrics["batches_per_second"]
                        mfu_val = flops_mod.mfu(fps, peak_total)
                        train_metrics["flops_per_sec"] = fps
                        train_metrics["mfu"] = mfu_val
                        reg = tel.registry
                        reg.gauge("samples_per_sec",
                                  "training throughput").set(
                            train_metrics["samples_per_second"])
                        reg.gauge("flops_per_sec",
                                  "analytic model FLOPs per second").set(fps)
                        reg.gauge("mfu",
                                  "model FLOPs utilization vs peak "
                                  "(provenance: mfu_peak_info labels)").set(
                            mfu_val)
                        reg.gauge("mfu_peak_flops",
                                  "peak FLOPs the MFU denominator assumes "
                                  "(all participating devices)").set(
                            peak_total)
                        reg.gauge(
                            "mfu_peak_info",
                            "constant 1; labels carry the peak provenance "
                            "and FLOPs-count source",
                            labels={"assumed": peak_label,
                                    "flops_source": flops_source}).set(1)
                        if mfu_cmp is not None:
                            train_metrics["mfu_measured"] = mfu_cmp.report(
                                measured_flops_per_batch=measured_step_flops,
                                batches_per_second=train_metrics[
                                    "batches_per_second"],
                                analytic_mfu=mfu_val)
                    if memmon is not None:
                        # per-device gauges + the between-boundary peak
                        # watermark (profiler's sampler thread feeds the
                        # same monitor path at 1 Hz when profiling is on)
                        memmon.sample()
                        tel.registry.gauge(
                            "device_memory_peak_bytes",
                            "peak summed device bytes_in_use since the "
                            "previous chunk boundary").set(memmon.take_peak())
                    self.core.train.report_training_metrics(batches_trained,
                                                            train_metrics)
                    if profiler is not None:
                        # chunk-level split of the hot loop: input stall vs the
                        # rest (dispatch + device compute up to the acc sync)
                        profiler.record_batch_timing(
                            batches_trained, dataloading_s=t_host,
                            compute_s=max(dt - t_wait, 0.0),
                            queue_wait_s=t_wait, steps_per_dispatch=k,
                            prefetch_depth=prefetch_depth)
                    if tel is not None:
                        # batched telemetry shipping rides the chunk
                        # boundary (and the profiler's flush thread)
                        tel.publish(profiler, batches_trained)
                    if tb is not None:
                        tb.add_scalars("training", train_metrics, batches_trained)
                    op.report_progress(batches_trained)

                    if val_period and batches_trained - last_val_at >= val_period:
                        last_val = validate()
                        last_val_at = batches_trained
                        if searcher_metric in last_val:
                            v = last_val[searcher_metric]
                            is_best = best_val is None or (
                                v < best_val if smaller else v > best_val
                            )
                            if is_best:
                                best_val = v
                                if policy == "best":
                                    self._save(state, batches_trained, "best",
                                               metric=v)
                                    last_ckpt_at = batches_trained

                    # a metric only describes the saved weights when validation
                    # ran at THIS batch count — a stale value would misattribute
                    # quality to drifted weights (and mislead best-checkpoint GC)
                    def fresh_metric():
                        if last_val_at == batches_trained:
                            return last_val.get(searcher_metric)
                        return None

                    if ckpt_period and batches_trained - last_ckpt_at >= ckpt_period:
                        if policy != "none":
                            self._save(state, batches_trained, "periodic",
                                       metric=fresh_metric())
                        last_ckpt_at = batches_trained

                    if self.core.preempt.should_preempt():
                        preempted = True

                if preempted:
                    self._save(state, batches_trained, "preemption",
                               metric=fresh_metric())
                    self.core.train.report_early_exit("preempted")
                    break

                # op complete: ensure a fresh validation at the boundary
                final_val = validate()
                if final_val:
                    last_val = final_val
                    last_val_at = batches_trained
                    if searcher_metric in final_val:
                        v = final_val[searcher_metric]
                        if best_val is None or (v < best_val if smaller else v > best_val):
                            best_val = v
                op.complete(last_val.get(searcher_metric, float("nan")))
        finally:
            feed.close()

        if not preempted and policy != "none" and batches_trained > last_ckpt_at:
            metric = (last_val.get(searcher_metric)
                      if last_val_at == batches_trained else None)
            self._save(state, batches_trained, "final", metric=metric)

        # drain any in-flight async checkpoint uploads before the process
        # can exit — the flush-then-exit rule (SURVEY §7 hard parts); a
        # preempted run must not lose the checkpoint it just handed off
        self.core.checkpoint.wait_async()

        result.update(
            batches_trained=batches_trained,
            last_validation=last_val,
            best_validation=best_val,
            preempted=preempted,
        )
        self._final_state = state
        return result
