"""`det deploy gcp` — provision a cluster on GCP TPU VMs.

≈ the reference's `det deploy aws/gcp` (harness/determined/deploy/gcp:
Terraform-driven master+agents). TPU-native redesign: the master runs on a
plain GCE VM, each agent is a `gcloud compute tpus tpu-vm` instance whose
startup script launches dct-agent against the master's address. Every
gcloud invocation goes through a runner seam — dry-run (default, records
the exact argv plan) or live subprocess — matching the zero-egress test
environment and the C++ provisioner's gcloud seam.
"""
from __future__ import annotations

import shlex
import subprocess
from typing import Any, Dict, List, Optional


class CommandRunner:
    """Seam for gcloud invocations."""

    def run(self, argv: List[str]) -> None:
        raise NotImplementedError


class DryRunRunner(CommandRunner):
    def __init__(self) -> None:
        self.commands: List[List[str]] = []

    def run(self, argv: List[str]) -> None:
        self.commands.append(list(argv))


class SubprocessRunner(CommandRunner):  # pragma: no cover - needs gcloud
    def run(self, argv: List[str]) -> None:
        subprocess.run(argv, check=True)


MASTER_STARTUP = """#!/bin/bash
set -e
cd /opt/dct
make -C determined_clone_tpu/master
nohup determined_clone_tpu/master/build/dct-master \\
  --port {port} --data-dir /var/lib/dct {extra_flags} \\
  > /var/log/dct-master.log 2>&1 &
"""

AGENT_STARTUP = """#!/bin/bash
set -e
cd /opt/dct
make -C determined_clone_tpu/master
nohup determined_clone_tpu/master/build/dct-agent \\
  --master-host {master_host} --master-port {port} \\
  --id $(hostname) --resource-pool {pool} \\
  > /var/log/dct-agent.log 2>&1 &
"""


def _master_name(cluster: str) -> str:
    return f"{cluster}-master"


def _agent_name(cluster: str, i: int) -> str:
    return f"{cluster}-agent-{i}"


def gcp_up(*, cluster_name: str = "dct", project: str, zone: str,
           accelerator_type: str = "v5litepod-8",
           runtime_version: str = "tpu-ubuntu2204-base",
           n_agents: int = 1, master_machine_type: str = "n2-standard-8",
           master_port: int = 8080, master_address: Optional[str] = None,
           auth_required: bool = False, resource_pool: str = "default",
           api_source_ranges: str = "10.128.0.0/9",
           runner: Optional[CommandRunner] = None) -> Dict[str, Any]:
    """Returns the executed plan; with the default dry-run runner nothing
    leaves this machine — the plan is the deliverable."""
    runner = runner or DryRunRunner()
    # master lands on a GCE VM; agents find it by instance name (internal
    # DNS resolves <name>.<zone>.c.<project>.internal; a static address can
    # be passed instead)
    master_host = master_address or _master_name(cluster_name)
    extra = "--auth-required" if auth_required else ""
    runner.run([
        "gcloud", "compute", "instances", "create",
        _master_name(cluster_name),
        "--project", project, "--zone", zone,
        "--machine-type", master_machine_type,
        "--tags", cluster_name,  # the firewall rule below targets this tag
        "--metadata", "startup-script=" + MASTER_STARTUP.format(
            port=master_port, extra_flags=extra),
    ])
    runner.run([
        "gcloud", "compute", "firewall-rules", "create",
        f"{cluster_name}-master-api",
        "--project", project,
        "--allow", f"tcp:{master_port}",
        "--target-tags", cluster_name,
        # never default to 0.0.0.0/0: auth is off unless requested, and the
        # API submits arbitrary task argv — internal VPC only unless the
        # operator widens it deliberately
        "--source-ranges", api_source_ranges,
    ])
    for i in range(n_agents):
        runner.run([
            "gcloud", "compute", "tpus", "tpu-vm", "create",
            _agent_name(cluster_name, i),
            "--project", project, "--zone", zone,
            "--accelerator-type", accelerator_type,
            "--version", runtime_version,
            "--metadata", "startup-script=" + AGENT_STARTUP.format(
                master_host=master_host, port=master_port,
                pool=resource_pool),
        ])
    plan = {
        "cluster_name": cluster_name,
        "project": project,
        "zone": zone,
        "master": _master_name(cluster_name),
        "agents": [_agent_name(cluster_name, i) for i in range(n_agents)],
        "accelerator_type": accelerator_type,
        "dry_run": isinstance(runner, DryRunRunner),
    }
    if isinstance(runner, DryRunRunner):
        plan["commands"] = [" ".join(shlex.quote(a) for a in argv)
                            for argv in runner.commands]
    return plan


def gcp_down(*, cluster_name: str = "dct", project: str, zone: str,
             n_agents: int = 1,
             runner: Optional[CommandRunner] = None) -> Dict[str, Any]:
    runner = runner or DryRunRunner()
    for i in range(n_agents):
        runner.run([
            "gcloud", "compute", "tpus", "tpu-vm", "delete",
            _agent_name(cluster_name, i),
            "--project", project, "--zone", zone, "--quiet",
        ])
    runner.run([
        "gcloud", "compute", "instances", "delete",
        _master_name(cluster_name),
        "--project", project, "--zone", zone, "--quiet",
    ])
    runner.run([
        "gcloud", "compute", "firewall-rules", "delete",
        f"{cluster_name}-master-api", "--project", project, "--quiet",
    ])
    plan = {"dry_run": isinstance(runner, DryRunRunner)}
    if isinstance(runner, DryRunRunner):
        plan["commands"] = [" ".join(shlex.quote(a) for a in argv)
                            for argv in runner.commands]
    return plan
