"""`det deploy gke` — run the master on GKE with the kubernetes RM.

≈ the reference's `det deploy gke` + helm chart (helm/charts/determined):
manifests for the master Deployment/Service plus the RBAC the kubernetes
resource manager needs to create TPU pods, and the gcloud commands that
create the cluster's TPU node pool. Manifests are emitted as dicts (the
deliverable in a zero-egress environment); `gke_up` records/executes the
kubectl + gcloud plan through the same runner seam as deploy.gcp.
"""
from __future__ import annotations

import json
import os
import shlex
import tempfile
from typing import Any, Dict, List, Optional

from determined_clone_tpu.deploy.gcp import CommandRunner, DryRunRunner


def gke_manifests(*, namespace: str = "dct",
                  image: str = "determined-clone-tpu:latest",
                  master_port: int = 8080,
                  accelerator: str = "tpu-v5-lite-podslice",
                  slots_per_pod: int = 8,
                  auth_required: bool = False) -> List[Dict[str, Any]]:
    """The k8s objects for a master running `--rm kubernetes` in-cluster."""
    labels = {"app": "dct-master"}
    args = [
        "--port", str(master_port),
        "--data-dir", "/var/lib/dct",
        "--rm", "kubernetes",
        "--kube-live",
        "--kube-namespace", namespace,
        "--kube-image", image,
        "--kube-master-host", "dct-master",  # the Service name below
        "--kube-accelerator", accelerator,
        "--kube-slots-per-pod", str(slots_per_pod),
    ]
    if auth_required:
        args.append("--auth-required")
    return [
        {"apiVersion": "v1", "kind": "Namespace",
         "metadata": {"name": namespace}},
        # the RM creates/lists/deletes task pods in its namespace
        {"apiVersion": "v1", "kind": "ServiceAccount",
         "metadata": {"name": "dct-master", "namespace": namespace}},
        {"apiVersion": "rbac.authorization.k8s.io/v1", "kind": "Role",
         "metadata": {"name": "dct-master-pods", "namespace": namespace},
         "rules": [{"apiGroups": [""], "resources": ["pods"],
                    "verbs": ["create", "get", "list", "watch", "delete"]}]},
        {"apiVersion": "rbac.authorization.k8s.io/v1", "kind": "RoleBinding",
         "metadata": {"name": "dct-master-pods", "namespace": namespace},
         "subjects": [{"kind": "ServiceAccount", "name": "dct-master",
                       "namespace": namespace}],
         "roleRef": {"apiGroup": "rbac.authorization.k8s.io", "kind": "Role",
                     "name": "dct-master-pods"}},
        {"apiVersion": "apps/v1", "kind": "Deployment",
         "metadata": {"name": "dct-master", "namespace": namespace,
                      "labels": labels},
         "spec": {
             "replicas": 1,
             "selector": {"matchLabels": labels},
             "template": {
                 "metadata": {"labels": labels},
                 "spec": {
                     "serviceAccountName": "dct-master",
                     "containers": [{
                         "name": "master",
                         "image": image,
                         "command": ["dct-master"] + args,
                         "ports": [{"containerPort": master_port}],
                         "volumeMounts": [{"name": "data",
                                           "mountPath": "/var/lib/dct"}],
                     }],
                     "volumes": [{"name": "data", "emptyDir": {}}],
                 },
             },
         }},
        {"apiVersion": "v1", "kind": "Service",
         "metadata": {"name": "dct-master", "namespace": namespace},
         "spec": {"selector": labels,
                  "ports": [{"port": master_port,
                             "targetPort": master_port}]}},
    ]


def gke_up(*, cluster: str = "dct", project: str, zone: str,
           namespace: str = "dct", image: str = "determined-clone-tpu:latest",
           accelerator_type: str = "v5litepod-8",
           tpu_topology: str = "2x4", n_tpu_nodes: int = 1,
           master_port: int = 8080, auth_required: bool = False,
           manifest_path: Optional[str] = None,
           runner: Optional[CommandRunner] = None) -> Dict[str, Any]:
    runner = runner or DryRunRunner()
    # ct5lp-hightpu hosts come in 1t/4t/8t; multi-host slices use 8t hosts
    # with a larger --tpu-topology, so derive the HOST chip count, not the
    # slice total
    try:
        slice_chips = int(accelerator_type.rsplit("-", 1)[-1])
    except ValueError:
        slice_chips = 8
    host_chips = 8 if slice_chips >= 8 else (4 if slice_chips >= 4 else 1)
    n_nodes = max(n_tpu_nodes, (slice_chips + host_chips - 1) // host_chips)
    runner.run([
        "gcloud", "container", "node-pools", "create", f"{cluster}-tpus",
        "--cluster", cluster, "--project", project, "--zone", zone,
        "--machine-type", f"ct5lp-hightpu-{host_chips}t",
        "--tpu-topology", tpu_topology,
        "--num-nodes", str(n_nodes),
    ])
    # the master's per-pod slot count must match the node pool's host size
    # or every task pod requests more chips than any node has
    manifests = gke_manifests(namespace=namespace, image=image,
                              master_port=master_port,
                              slots_per_pod=host_chips,
                              auth_required=auth_required)
    # the manifests must exist on disk for kubectl (streaming to `-f -`
    # would hang a live run with no stdin wired)
    if manifest_path is None:
        fd, manifest_path = tempfile.mkstemp(prefix="dct-gke-",
                                             suffix=".json")
        os.close(fd)
    with open(manifest_path, "w") as f:
        json.dump(manifests, f, indent=2)
    # pin kubectl to the cluster we just modified — the operator's current
    # context may point anywhere
    runner.run([
        "gcloud", "container", "clusters", "get-credentials", cluster,
        "--project", project, "--zone", zone,
    ])
    runner.run(["kubectl", "apply", "-f", manifest_path])
    plan = {
        "cluster": cluster,
        "namespace": namespace,
        "manifests": manifests,
        "dry_run": isinstance(runner, DryRunRunner),
    }
    if isinstance(runner, DryRunRunner):
        plan["commands"] = [" ".join(shlex.quote(a) for a in argv)
                            for argv in runner.commands]
    return plan


def gke_down(*, cluster: str = "dct", project: str, zone: str,
             namespace: str = "dct",
             runner: Optional[CommandRunner] = None) -> Dict[str, Any]:
    runner = runner or DryRunRunner()
    runner.run(["kubectl", "delete", "namespace", namespace,
                "--ignore-not-found"])
    runner.run([
        "gcloud", "container", "node-pools", "delete", f"{cluster}-tpus",
        "--cluster", cluster, "--project", project, "--zone", zone,
        "--quiet",
    ])
    plan = {"dry_run": isinstance(runner, DryRunRunner)}
    if isinstance(runner, DryRunRunner):
        plan["commands"] = [" ".join(shlex.quote(a) for a in argv)
                            for argv in runner.commands]
    return plan
