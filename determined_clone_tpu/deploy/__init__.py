"""Deploy tooling (≈ harness/determined/deploy): local process cluster
(the devcluster analogue); cloud TPU-VM provisioning is config-generation
only in this environment (zero egress)."""
from determined_clone_tpu.deploy.local import (
    cluster_down,
    cluster_status,
    cluster_up,
)

__all__ = ["cluster_down", "cluster_status", "cluster_up"]
