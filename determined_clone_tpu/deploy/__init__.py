"""Deploy tooling (≈ harness/determined/deploy): local process cluster
(the devcluster analogue), GCP TPU-VM provisioning, and GKE manifests —
cloud modes run through a dry-run seam in this zero-egress environment."""
from determined_clone_tpu.deploy.gcp import (
    DryRunRunner,
    SubprocessRunner,
    gcp_down,
    gcp_up,
)
from determined_clone_tpu.deploy.gke import gke_down, gke_manifests, gke_up
from determined_clone_tpu.deploy.local import (
    cluster_down,
    cluster_status,
    cluster_up,
)

__all__ = [
    "DryRunRunner",
    "SubprocessRunner",
    "cluster_down",
    "cluster_status",
    "cluster_up",
    "gcp_down",
    "gcp_up",
    "gke_down",
    "gke_manifests",
    "gke_up",
]
