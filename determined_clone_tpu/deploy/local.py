"""`det deploy local` — boot a master + N agents on this machine.

≈ the reference's devcluster (tools/devcluster.yaml: db+master+agent from
source) + `det deploy local` (harness/determined/deploy/local): one command
brings up a working cluster, state is tracked in a JSON file so
`cluster-down` can tear it down later. Multiple agent processes on one host
is also how the reference fakes multi-node (managed_cluster.py:16).
"""
from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import time
from pathlib import Path
from typing import Any, Dict, List, Optional

REPO = Path(__file__).resolve().parent.parent
MASTER_DIR = REPO / "master"
MASTER_BIN = MASTER_DIR / "build" / "dct-master"
AGENT_BIN = MASTER_DIR / "build" / "dct-agent"


def default_state_path() -> str:
    return os.path.join(os.path.expanduser("~"), ".dct", "local-cluster.json")


def ensure_binaries() -> None:
    if MASTER_BIN.exists() and AGENT_BIN.exists():
        return
    proc = subprocess.run(["make", "-C", str(MASTER_DIR)],
                          capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(f"building master/agent failed:\n{proc.stderr}")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def cluster_up(*, n_agents: int = 1, slots_per_agent: int = 1,
               port: Optional[int] = None, base_dir: Optional[str] = None,
               topology: str = "", scheduler: str = "fifo",
               auth_required: bool = False,
               state_path: Optional[str] = None,
               wait_sec: float = 30.0) -> Dict[str, Any]:
    """Start dct-master + agents; returns the cluster state dict."""
    state_path = state_path or default_state_path()
    if os.path.exists(state_path):
        state = cluster_status(state_path=state_path)
        if state.get("alive"):
            raise RuntimeError(
                f"a local cluster is already up (master pid "
                f"{state['master_pid']}); run cluster-down first")
        # stale state (dead master, possibly surviving agents): tear the
        # remnants down so their pids aren't leaked by the overwrite below
        cluster_down(state_path=state_path)
    ensure_binaries()
    port = port or _free_port()
    base = Path(base_dir or os.path.join(
        os.path.expanduser("~"), ".dct", "local-cluster"))
    base.mkdir(parents=True, exist_ok=True)
    (base / "logs").mkdir(exist_ok=True)

    master_args = [str(MASTER_BIN), "--port", str(port),
                   "--data-dir", str(base / "master-data"),
                   "--scheduler", scheduler,
                   # absolute: the default "webui" is cwd-relative and the
                   # deployed master's cwd is wherever the user launched from
                   "--webui-dir",
                   str(MASTER_DIR.parent.parent / "webui")]
    if auth_required:
        master_args.append("--auth-required")
    master_log = open(base / "logs" / "master.log", "ab")
    master = subprocess.Popen(master_args, stdout=master_log,
                              stderr=subprocess.STDOUT,
                              start_new_session=True)

    env = {
        **os.environ,
        "PYTHONPATH": str(REPO.parent) + os.pathsep +
                      os.environ.get("PYTHONPATH", ""),
        "DCT_AGENT_SLOTS": str(slots_per_agent),
    }
    if topology:
        env["DCT_AGENT_TOPOLOGY"] = topology
    agents: List[Dict[str, Any]] = []
    for i in range(n_agents):
        workdir = base / f"agent-{i}"
        workdir.mkdir(exist_ok=True)
        log = open(base / "logs" / f"agent-{i}.log", "ab")
        proc = subprocess.Popen(
            [str(AGENT_BIN), "--master-port", str(port),
             "--id", f"local-agent-{i}", "--work-dir", str(workdir)],
            cwd=str(workdir), env=env, stdout=log,
            stderr=subprocess.STDOUT, start_new_session=True)
        agents.append({"pid": proc.pid, "id": f"local-agent-{i}",
                       "workdir": str(workdir)})

    # wait for the cluster to report all agents
    from determined_clone_tpu.api.client import MasterSession
    from determined_clone_tpu.utils import retry as retry_util

    session = MasterSession("127.0.0.1", port, timeout=5, retries=2)

    def _agents_up() -> bool:
        if len(session.list_agents()) < n_agents:
            raise ConnectionError("not all agents registered yet")
        return True

    # Fixed-interval poll (multiplier 1.0, no jitter) bounded by wait_sec:
    # a boot wait wants steady sampling, not exponential growth.
    poll = retry_util.RetryPolicy(
        name="deploy_wait", max_attempts=1_000_000,
        base_delay_s=0.3, multiplier=1.0, max_delay_s=0.3,
        jitter="none", deadline_s=wait_sec,
        retryable=(Exception,))  # master still booting raises URLError too
    try:
        up = retry_util.retry_call(_agents_up, policy=poll)
    except Exception:
        up = False

    state = {
        "port": port,
        "master_pid": master.pid,
        "agents": agents,
        "base_dir": str(base),
        "started_at": time.time(),
        "came_up": up,
    }
    os.makedirs(os.path.dirname(state_path), exist_ok=True)
    with open(state_path, "w") as f:
        json.dump(state, f, indent=2)
    if not up:
        cluster_down(state_path=state_path)
        raise RuntimeError(
            f"cluster did not come up within {wait_sec}s; see "
            f"{base}/logs/")
    return state


def _alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except OSError:
        return False


def cluster_status(*, state_path: Optional[str] = None) -> Dict[str, Any]:
    state_path = state_path or default_state_path()
    if not os.path.exists(state_path):
        return {"alive": False, "error": "no local cluster state"}
    with open(state_path) as f:
        state = json.load(f)
    state["alive"] = _alive(state.get("master_pid", -1))
    state["agents_alive"] = sum(
        1 for a in state.get("agents", []) if _alive(a["pid"]))
    return state


def cluster_down(*, state_path: Optional[str] = None) -> Dict[str, Any]:
    state_path = state_path or default_state_path()
    if not os.path.exists(state_path):
        return {"stopped": 0}
    with open(state_path) as f:
        state = json.load(f)
    stopped = 0
    pids = [a["pid"] for a in state.get("agents", [])]
    pids.append(state.get("master_pid", -1))
    for pid in pids:
        if pid > 0 and _alive(pid):
            try:
                os.kill(pid, signal.SIGTERM)
                stopped += 1
            except OSError:
                pass
    # grace period, then hard-kill stragglers
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and any(_alive(p) for p in pids if p > 0):
        time.sleep(0.2)
    for pid in pids:
        if pid > 0 and _alive(pid):
            try:
                os.kill(pid, signal.SIGKILL)
            except OSError:
                pass
    os.unlink(state_path)
    return {"stopped": stopped}
