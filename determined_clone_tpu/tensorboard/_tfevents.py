"""Minimal tfevents (TensorBoard event file) writer/reader — no TF needed.

≈ the reference's metric writers (harness/determined/tensorboard/
metric_writers/) which delegate to torch/TF summary writers; here the
TFRecord framing (length + masked crc32c) and the Event/Summary protobuf
wire format are emitted directly, so TPU images need no tensorflow install.

Format notes (TensorBoard's record_writer.cc + event.proto):
- record: u64le(len) | u32le(masked_crc32c(len_bytes)) | data |
  u32le(masked_crc32c(data))
- Event: 1=wall_time(double) 2=step(int64) 3=file_version(string)
  5=summary(Summary); Summary: repeated 1=Value; Value: 1=tag(string)
  2=simple_value(float)
"""
from __future__ import annotations

import os
import socket
import struct
import time
from typing import Any, Dict, Iterator, List, Tuple

# -- crc32c (Castagnoli, reflected poly 0x82F63B78) -------------------------

_CRC_TABLE: List[int] = []


def _build_table() -> None:
    poly = 0x82F63B78
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
        _CRC_TABLE.append(crc)


_build_table()


def crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def masked_crc32c(data: bytes) -> int:
    crc = crc32c(data)
    return ((crc >> 15 | crc << 17) + 0xA282EAD8) & 0xFFFFFFFF


# -- protobuf wire helpers ---------------------------------------------------

def _varint(n: int) -> bytes:
    if n < 0:
        # proto int64 negatives need 10-byte two's-complement varints; no
        # caller here has a negative (steps are batch counts), so reject
        # loudly instead of looping forever on `n >>= 7`
        raise ValueError(f"negative varint {n} not supported")
    out = bytearray()
    while True:
        bits = n & 0x7F
        n >>= 7
        if n:
            out.append(bits | 0x80)
        else:
            out.append(bits)
            return bytes(out)


def _key(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _len_delim(field: int, payload: bytes) -> bytes:
    return _key(field, 2) + _varint(len(payload)) + payload


def encode_scalar_event(wall_time: float, step: int, tag: str,
                        value: float) -> bytes:
    tag_b = tag.encode()
    value_msg = (_len_delim(1, tag_b) +
                 _key(2, 5) + struct.pack("<f", float(value)))
    summary = _len_delim(1, value_msg)
    return (_key(1, 1) + struct.pack("<d", wall_time) +
            _key(2, 0) + _varint(step) +
            _len_delim(5, summary))


def encode_file_version(wall_time: float) -> bytes:
    return (_key(1, 1) + struct.pack("<d", wall_time) +
            _len_delim(3, b"brain.Event:2"))


def frame_record(data: bytes) -> bytes:
    header = struct.pack("<Q", len(data))
    return (header + struct.pack("<I", masked_crc32c(header)) +
            data + struct.pack("<I", masked_crc32c(data)))


# -- writer ------------------------------------------------------------------

class EventFileWriter:
    """One tfevents file; append scalar summaries, flush on demand."""

    def __init__(self, logdir: str, suffix: str = "") -> None:
        os.makedirs(logdir, exist_ok=True)
        name = (f"events.out.tfevents.{int(time.time())}."
                f"{socket.gethostname()}{suffix}")
        self.path = os.path.join(logdir, name)
        self._f = open(self.path, "ab")
        self._f.write(frame_record(encode_file_version(time.time())))

    def add_scalar(self, tag: str, value: float, step: int) -> None:
        if step < 0:
            raise ValueError(f"step must be >= 0, got {step}")
        self._f.write(frame_record(
            encode_scalar_event(time.time(), step, tag, value)))

    def flush(self) -> None:
        self._f.flush()

    def close(self) -> None:
        if not self._f.closed:
            self._f.flush()
            self._f.close()


# -- reader (for tests and the TB task's JSON view) --------------------------

def _read_varint(data: bytes, pos: int) -> Tuple[int, int]:
    out = shift = 0
    while True:
        b = data[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7


def _parse_fields(data: bytes) -> Dict[int, List[Any]]:
    fields: Dict[int, List[Any]] = {}
    pos = 0
    while pos < len(data):
        key, pos = _read_varint(data, pos)
        field, wire = key >> 3, key & 7
        if wire == 0:
            val, pos = _read_varint(data, pos)
        elif wire == 1:
            val = data[pos:pos + 8]
            pos += 8
        elif wire == 2:
            length, pos = _read_varint(data, pos)
            val = data[pos:pos + length]
            pos += length
        elif wire == 5:
            val = data[pos:pos + 4]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")
        fields.setdefault(field, []).append(val)
    return fields


def read_tfevents(path: str) -> Iterator[Dict[str, Any]]:
    """Yield {wall_time, step, scalars: {tag: value}} per event record,
    verifying record CRCs."""
    with open(path, "rb") as f:
        blob = f.read()
    pos = 0
    while pos + 12 <= len(blob):
        header = blob[pos:pos + 8]
        (length,) = struct.unpack("<Q", header)
        (hcrc,) = struct.unpack("<I", blob[pos + 8:pos + 12])
        if masked_crc32c(header) != hcrc:
            raise ValueError(f"bad length crc at offset {pos}")
        if pos + 16 + length > len(blob):
            break  # truncated tail: file was synced mid-append — normal
        data = blob[pos + 12:pos + 12 + length]
        (dcrc,) = struct.unpack("<I",
                                blob[pos + 12 + length:pos + 16 + length])
        if masked_crc32c(data) != dcrc:
            raise ValueError(f"bad data crc at offset {pos}")
        pos += 16 + length

        fields = _parse_fields(data)
        event: Dict[str, Any] = {"scalars": {}}
        if 1 in fields:
            event["wall_time"] = struct.unpack("<d", fields[1][0])[0]
        if 2 in fields:
            event["step"] = fields[2][0]
        for summary in fields.get(5, []):
            for value_msg in _parse_fields(summary).get(1, []):
                vf = _parse_fields(value_msg)
                if 1 in vf and 2 in vf:
                    tag = vf[1][0].decode()
                    event["scalars"][tag] = struct.unpack("<f", vf[2][0])[0]
        yield event
