"""TensorboardManager — background upload of tfevents to checkpoint storage.

≈ harness/determined/tensorboard/base.py:22 (TensorboardManager: watches a
local logdir, ships event files to the experiment's checkpoint storage) and
the per-backend fetch path (fetch_events below) that the `det tensorboard`
task uses to pull them back down. Both directions ride the StorageManager
abstraction, so every backend (shared_fs/gcs/s3/directory) works unchanged.
"""
from __future__ import annotations

import os
import threading
from typing import Any, Dict, List, Optional

from determined_clone_tpu.config.experiment import CheckpointStorageConfig
from determined_clone_tpu.storage import StorageManager, build
from determined_clone_tpu.tensorboard._tfevents import EventFileWriter

SYNC_PERIOD_SEC = 10.0


def tb_storage_id(experiment_id: int, trial_id: int) -> str:
    """Storage location for one trial's event files (≈ the reference's
    tensorboard path layout under checkpoint storage). Flat id: storage
    managers reject separators (path-traversal guard, storage/base.py)."""
    return f"tensorboard-exp{experiment_id}-trial{trial_id}"


class TensorboardManager:
    """Owns a local logdir + writer; syncs changed files to storage."""

    def __init__(self, storage: StorageManager, storage_id: str,
                 logdir: str, *, rank: int = 0) -> None:
        self._storage = storage
        self._storage_id = storage_id
        self.logdir = logdir
        self.writer = EventFileWriter(logdir, suffix=f".rank{rank}")
        self._synced_sizes: Dict[str, int] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @staticmethod
    def from_config(storage_raw: Dict[str, Any], experiment_id: int,
                    trial_id: int, logdir: str, *,
                    rank: int = 0) -> "TensorboardManager":
        storage = build(CheckpointStorageConfig.from_dict(storage_raw))
        return TensorboardManager(
            storage, tb_storage_id(experiment_id, trial_id), logdir,
            rank=rank)

    # -- metric writing (chief) --------------------------------------------

    def add_scalars(self, prefix: str, metrics: Dict[str, Any],
                    step: int) -> None:
        for name, value in metrics.items():
            try:
                self.writer.add_scalar(f"{prefix}/{name}", float(value), step)
            except (TypeError, ValueError):
                continue  # non-scalar metric values are skipped
        self.writer.flush()

    # -- sync --------------------------------------------------------------

    def start(self) -> "TensorboardManager":
        self._thread = threading.Thread(
            target=self._sync_loop, daemon=True, name="tb-sync")
        self._thread.start()
        return self

    def _sync_loop(self) -> None:
        while not self._stop.wait(SYNC_PERIOD_SEC):
            self.sync()

    def sync(self) -> None:
        """Upload files that grew since the last sync (tfevents are
        append-only, so re-uploading the whole file is always correct)."""
        with self._lock:
            self.writer.flush()
            changed: List[str] = []
            for name in os.listdir(self.logdir):
                full = os.path.join(self.logdir, name)
                if not os.path.isfile(full):
                    continue
                size = os.path.getsize(full)
                if self._synced_sizes.get(name) != size:
                    changed.append(name)
                    self._synced_sizes[name] = size
            if changed:
                try:
                    self._storage.upload(self.logdir, self._storage_id,
                                         paths=changed)
                except Exception:
                    # storage hiccups must not kill training; next sync
                    # retries (sizes were recorded, so force a full pass)
                    self._synced_sizes.clear()

    def close(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=10)
        self.sync()
        self.writer.close()


def fetch_trial_events(storage_raw: Dict[str, Any], experiment_id: int,
                       trial_id: int, dst_dir: str) -> List[str]:
    """Download one trial's event files (the fetcher side,
    the reference's tensorboard/fetchers package). Returns the fetched file paths."""
    paths, _ = sync_trial_events(storage_raw, experiment_id, trial_id,
                                 dst_dir, prev_sizes=None)
    return paths


def sync_trial_events(storage_raw: Dict[str, Any], experiment_id: int,
                      trial_id: int, dst_dir: str, *,
                      prev_sizes: Optional[Dict[str, int]] = None
                      ) -> tuple:
    """Incremental fetch: only files whose size changed since ``prev_sizes``
    are re-downloaded (the size-delta scheme sync() uses on the upload side
    — tfevents are append-only). Returns (paths, sizes) where ``sizes``
    feeds the next call; pass prev_sizes=None for a full fetch."""
    storage = build(CheckpointStorageConfig.from_dict(storage_raw))
    sid = tb_storage_id(experiment_id, trial_id)
    try:
        sizes = storage.list_files(sid)
    except FileNotFoundError:
        return [], {}
    if not sizes:
        return [], {}
    os.makedirs(dst_dir, exist_ok=True)
    changed = [name for name, size in sizes.items()
               if prev_sizes is None or prev_sizes.get(name) != size]
    if changed:
        storage.download(sid, dst_dir, paths=changed)
    return [os.path.join(dst_dir, name) for name in sizes], dict(sizes)
