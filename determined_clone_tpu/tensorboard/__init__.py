"""TensorBoard subsystem (≈ harness/determined/tensorboard): tfevents
writers with no TF dependency, background upload manager, storage fetchers."""
from determined_clone_tpu.tensorboard._tfevents import (
    EventFileWriter,
    read_tfevents,
)
from determined_clone_tpu.tensorboard.manager import (
    TensorboardManager,
    fetch_trial_events,
    sync_trial_events,
    tb_storage_id,
)

__all__ = [
    "EventFileWriter",
    "TensorboardManager",
    "fetch_trial_events",
    "read_tfevents",
    "sync_trial_events",
    "tb_storage_id",
]
