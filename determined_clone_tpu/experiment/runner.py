"""Local experiment runner — searcher-driven multi-trial orchestration.

The single-process counterpart of the reference's experiment orchestrator
(master/internal/experiment.go:751 processOperations + trial.go): consumes
searcher operations, runs trials, feeds validation results back, snapshots
searcher state for crash-consistency. The C++ master implements this same
loop for the cluster; this runner is the off-cluster / single-host mode
(≈ det experiment create --local).

Trials pause/resume between ValidateAfter boundaries via checkpoints — the
same mechanism the cluster uses when ASHA pauses a trial and later promotes
it on a different slice.
"""
from __future__ import annotations

import dataclasses
import json
import os
import time
import uuid
from typing import Any, Callable, Dict, List, Optional, Type

from determined_clone_tpu import core as core_mod
from determined_clone_tpu.config.experiment import ExperimentConfig
from determined_clone_tpu.config.length import Length
from determined_clone_tpu.searcher import (
    Close,
    Create,
    Searcher,
    Shutdown,
    ValidateAfter,
    build_method,
)
from determined_clone_tpu.training.trainer import Trainer
from determined_clone_tpu.training.trial import JaxTrial, TrialContext
from determined_clone_tpu.telemetry import MetricsRegistry, Telemetry
from determined_clone_tpu.utils import retry as retry_util


class _SampleCollector:
    """Duck-typed ProfilerAgent stand-in: ``Telemetry.publish`` feeds it,
    the runner forwards the collected batch to the in-process master."""

    def __init__(self) -> None:
        self.samples: List[Dict[str, Any]] = []

    def record(self, sample: Dict[str, Any]) -> None:
        self.samples.append(sample)

# Restart pacing (≈ the reference's trial restart delay): small enough that
# single-host test runs stay fast, but each consecutive failure doubles the
# wait so a persistently-broken trial doesn't spin the orchestration loop.
RESTART_BACKOFF = retry_util.RetryPolicy(
    name="runner_restart",
    max_attempts=1,  # the runner tracks attempts itself via max_restarts
    base_delay_s=0.25,
    max_delay_s=10.0,
)


@dataclasses.dataclass
class TrialRecord:
    request_id: int
    hparams: Dict[str, Any]
    units_done: int = 0
    latest_checkpoint: Optional[str] = None
    last_metric: Optional[float] = None
    best_metric: Optional[float] = None
    state: str = "active"  # active | paused | completed | errored
    restarts: int = 0
    metrics_path: Optional[str] = None


@dataclasses.dataclass
class ExperimentResult:
    trials: Dict[int, TrialRecord]
    best_trial: Optional[TrialRecord]
    shutdown: bool

    @property
    def n_trials(self) -> int:
        return len(self.trials)


class LocalExperimentRunner:
    def __init__(self, config: ExperimentConfig,
                 trial_cls: Type[JaxTrial], *,
                 storage_path: str,
                 mesh: Optional[Any] = None,
                 max_events: int = 10_000,
                 method: Optional[Any] = None,
                 registry: Optional[MetricsRegistry] = None,
                 restart_backoff: Optional[retry_util.RetryPolicy] = None,
                 master: Optional[Any] = None,
                 experiment_id: int = 1,
                 trace_id: Optional[str] = None,
                 ) -> None:
        self.config = config
        self.trial_cls = trial_cls
        self.storage_path = storage_path
        self.mesh = mesh
        self.max_events = max_events
        self.registry = registry if registry is not None else MetricsRegistry()
        # observability plane: when an InProcessMaster is attached, trial
        # telemetry ships there after every leg (deduped by idempotency
        # key) and the runner contributes its own trace lane so `dct trace
        # export --experiment` can stitch runner + trials into one trace
        self.master = master
        self.experiment_id = int(experiment_id)
        self.trace_id = trace_id or (uuid.uuid4().hex
                                     if master is not None else None)
        self.telemetry: Optional[Telemetry] = None
        if master is not None:
            self.telemetry = Telemetry(
                enabled=True, max_events=max_events, ship_spans=True,
                ship_metrics=False, trace_id=self.trace_id,
                process_name="runner")
        self.restart_backoff = (restart_backoff if restart_backoff is not None
                                else RESTART_BACKOFF)
        self._restarts_total = self.registry.counter(
            "trial_restarts_total", "trial legs restarted after a failure")
        # method override: a user-provided SearchMethod (custom search via
        # searcher.LocalSearchRunner) instead of the built-in factory
        self.engine = Searcher(method if method is not None else build_method(
            config.searcher, config.hyperparameters, seed=config.experiment_seed
        ))
        self.trials: Dict[int, TrialRecord] = {}
        self._snapshot_path = os.path.join(storage_path, "experiment_snapshot.json")

    # -- crash consistency (≈ master/internal/restore.go) -------------------

    def _snapshot(self) -> None:
        snap = {
            "searcher": self.engine.snapshot(),
            "trials": {
                str(rid): dataclasses.asdict(t) for rid, t in self.trials.items()
            },
        }
        tmp = self._snapshot_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(snap, f)
        os.replace(tmp, self._snapshot_path)

    def _units_to_length(self, units: int) -> Length:
        ml = self.config.searcher.max_length
        unit = ml.unit if ml is not None else None
        if unit is None:
            return Length.batches(units)
        return Length(unit, units)

    # -- one training leg ---------------------------------------------------

    def _run_to(self, rec: TrialRecord, target_units: int) -> float:
        """Train trial ``rec`` up to cumulative target_units; return the
        searcher metric from its final validation."""
        cfg = self.config
        metrics_backend = core_mod.LocalMetricsBackend(
            os.path.join(self.storage_path, f"trial-{rec.request_id}-metrics.jsonl")
        )
        rec.metrics_path = metrics_backend.path
        searcher_source = core_mod.LocalSearcherSource(
            self._units_to_length(target_units)
        )
        # export the experiment trace id through the env so the trial's
        # telemetry (built inside core.init) joins this experiment's
        # trace — the same contract exec/trial.py uses across a real
        # process boundary
        prev_trace_env = os.environ.get("DCT_TRACE_ID")
        if self.trace_id:
            os.environ["DCT_TRACE_ID"] = self.trace_id
        leg_span = (self.telemetry.tracer.span(
            "trial_leg", trial_id=rec.request_id, restart=rec.restarts,
            target_units=target_units)
            if self.telemetry is not None else None)
        try:
            with core_mod.init(
                config=cfg,
                storage_path=self.storage_path,
                metrics_backend=metrics_backend,
                searcher_source=searcher_source,
                trial_id=rec.request_id,
            ) as cctx:
                if cctx.telemetry is not None:
                    cctx.telemetry.set_identity(
                        trace_id=self.trace_id,
                        process_name=f"trial-{rec.request_id}")
                try:
                    if leg_span is not None:
                        leg_span.__enter__()
                    tctx = TrialContext(config=cfg, hparams=rec.hparams,
                                        core=cctx, mesh=self.mesh)
                    trial = self.trial_cls(tctx)
                    trainer = Trainer(trial)
                    result = trainer.fit(
                        latest_checkpoint=rec.latest_checkpoint)
                finally:
                    if leg_span is not None:
                        leg_span.__exit__(None, None, None)
                    self._ship_trial_telemetry(rec, cctx)
        finally:
            if self.trace_id:
                if prev_trace_env is None:
                    os.environ.pop("DCT_TRACE_ID", None)
                else:
                    os.environ["DCT_TRACE_ID"] = prev_trace_env
        rec.units_done = target_units
        reg = core_mod.LocalCheckpointRegistry(self._registry_path())
        mine = [r for r in reg.list() if r.get("trial_id") == rec.request_id]
        if mine:
            rec.latest_checkpoint = mine[-1]["storage_id"]
        metric_name = cfg.searcher.metric
        last_val = result.get("last_validation") or {}
        if metric_name in last_val:
            return float(last_val[metric_name])
        raise RuntimeError(
            f"trial {rec.request_id} reported no searcher metric "
            f"{metric_name!r} (validation metrics: {sorted(last_val) or 'none'}). "
            f"Check searcher.metric against the trial's eval_metrics keys and "
            f"that validation_data()/min_validation_period are set."
        )

    def _ship_trial_telemetry(self, rec: TrialRecord, cctx: Any) -> None:
        """Forward the leg's telemetry snapshot + spans to the attached
        master. Failures never fail the leg (telemetry is lossy by
        contract); the batch carries an idempotency key so a replayed
        restart leg can't double-count."""
        if self.master is None or cctx.telemetry is None:
            return
        try:
            collector = _SampleCollector()
            cctx.telemetry.publish(collector)
            if collector.samples:
                self.master.ingest_trial(
                    rec.request_id, collector.samples,
                    idempotency_key=uuid.uuid4().hex)
        except Exception:  # noqa: BLE001 - observability is best-effort
            pass

    def _ship_runner_telemetry(self) -> None:
        """Contribute the runner's own lane (registry + spans) to the
        master so restarts and orchestration time show up cluster-wide."""
        if self.master is None:
            return
        try:
            self.master.ingest_component("runner", self.registry)
            if self.telemetry is not None:
                collector = _SampleCollector()
                self.telemetry.publish(collector)
                spans = [s for s in collector.samples
                         if s.get("group") == "span"]
                if spans:
                    self.master.ingest_component_spans(
                        "runner", spans, experiment_id=self.experiment_id)
        except Exception:  # noqa: BLE001 - observability is best-effort
            pass

    def _registry_path(self) -> str:
        """The checkpoint registry lives next to the checkpoint storage —
        same resolution as core.init (core/_context.py)."""
        cs = self.config.checkpoint_storage
        base = self.storage_path
        if cs is not None:
            base = cs.host_path or cs.container_path or self.storage_path
        return os.path.join(base, "checkpoints.jsonl")

    # -- the orchestration loop --------------------------------------------

    def run(self) -> ExperimentResult:
        exp_span = (self.telemetry.tracer.span(
            "experiment", experiment_id=self.experiment_id)
            if self.telemetry is not None else None)
        if exp_span is not None:
            exp_span.__enter__()
        try:
            return self._run_loop()
        finally:
            if exp_span is not None:
                exp_span.__exit__(None, None, None)
            self._ship_runner_telemetry()

    def _run_loop(self) -> ExperimentResult:
        queue = list(self.engine.initial_operations())
        events = 0
        shutdown = False
        while queue and events < self.max_events:
            events += 1
            op = queue.pop(0)
            if isinstance(op, Create):
                self.trials[op.request_id] = TrialRecord(
                    op.request_id, op.hparams
                )
                if self.master is not None:
                    self.master.register_trial(op.request_id,
                                               self.experiment_id)
                queue.extend(self.engine.trial_created(op.request_id))
            elif isinstance(op, ValidateAfter):
                rec = self.trials[op.request_id]
                if rec.state in ("completed", "errored"):
                    continue
                rec.state = "active"
                try:
                    metric = self._run_to(rec, op.length)
                except Exception as e:  # trial failure → searcher event
                    rec.restarts += 1
                    if rec.restarts > self.config.max_restarts:
                        rec.state = "errored"
                        queue.extend(self.engine.trial_exited_early(
                            op.request_id, f"error: {e}"
                        ))
                        self._snapshot()
                        continue
                    # Back off before the retry (exponential + full jitter)
                    # so a trial failing on shared-resource contention isn't
                    # immediately thrown back at the same hot spot, and
                    # snapshot first so a crash mid-backoff still records
                    # the restart count.
                    self._restarts_total.inc()
                    self._snapshot()
                    t0 = time.perf_counter()
                    retry_util.sleep_backoff(self.restart_backoff,
                                             rec.restarts)
                    if (self.telemetry is not None
                            and self.telemetry.goodput is not None):
                        # the backoff sleep is restart badput in the
                        # runner's own wall-clock account (the trial-side
                        # inter-leg gap is booked by the journal merge)
                        self.telemetry.goodput.note(
                            "restart_backoff", time.perf_counter() - t0)
                    queue.insert(0, op)  # retry from latest checkpoint
                    continue
                rec.last_metric = metric
                smaller = self.config.searcher.smaller_is_better
                if rec.best_metric is None or (
                    metric < rec.best_metric if smaller else metric > rec.best_metric
                ):
                    rec.best_metric = metric
                rec.state = "paused"
                queue.extend(self.engine.validation_completed(
                    op.request_id, metric, op.length
                ))
                self._snapshot()
            elif isinstance(op, Close):
                rec = self.trials.get(op.request_id)
                if rec and rec.state != "completed":
                    rec.state = "completed"
                    queue.extend(self.engine.trial_closed(op.request_id))
                self._snapshot()
            elif isinstance(op, Shutdown):
                shutdown = True
                break

        smaller = self.config.searcher.smaller_is_better
        scored = [t for t in self.trials.values() if t.best_metric is not None]
        best = None
        if scored:
            best = (min if smaller else max)(scored, key=lambda t: t.best_metric)
        return ExperimentResult(trials=self.trials, best_trial=best,
                                shutdown=shutdown)
