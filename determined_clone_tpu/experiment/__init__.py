"""Experiment orchestration (≈ master/internal/experiment.go, local mode)."""
from determined_clone_tpu.experiment.runner import (
    ExperimentResult,
    LocalExperimentRunner,
    TrialRecord,
)

__all__ = ["ExperimentResult", "LocalExperimentRunner", "TrialRecord"]
