"""Search-method engine: event-driven hyperparameter search.

Re-design of the reference's searcher core (master/pkg/searcher/searcher.go:48,
search_method.go:17, operations.go:111-295): a ``SearchMethod`` reacts to
trial lifecycle events by emitting operations —

  Create(request_id, hparams)      start a new trial
  ValidateAfter(request_id, units) train trial to a cumulative unit target,
                                    then validate & report
  Close(request_id)                stop a trial (checkpoint + finish)
  Shutdown()                       experiment complete

The engine is deliberately host-language-agnostic state-machine logic: the
same protocol is spoken by the Python trial harness (core/_searcher.py) and
by the C++ master's experiment orchestrator. Snapshot/restore makes search
crash-consistent (reference: searcher snapshots, restore.go).
"""
from __future__ import annotations

import abc
import dataclasses
import random as _random
from typing import Any, Dict, List, Optional

from determined_clone_tpu.config.experiment import SearcherConfig
from determined_clone_tpu.config.hyperparameters import HyperparameterSpace


# ---------------------------------------------------------------------------
# Operations
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Operation:
    pass


@dataclasses.dataclass(frozen=True)
class Create(Operation):
    request_id: int
    hparams: Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class ValidateAfter(Operation):
    request_id: int
    length: int  # cumulative target, in searcher units (scheduling units)


@dataclasses.dataclass(frozen=True)
class Close(Operation):
    request_id: int


@dataclasses.dataclass(frozen=True)
class Shutdown(Operation):
    cancel: bool = False
    failure: bool = False


# ---------------------------------------------------------------------------
# Method interface + engine
# ---------------------------------------------------------------------------

class SearchMethod(abc.ABC):
    """Implementations are pure state machines over events.

    Built-in methods take (config, space, seed); user-defined custom methods
    (searcher/custom.py runners) may define any constructor — the base
    snapshot/restore only covers ``self.rng`` when present.
    """

    def __init__(self, config: Optional[SearcherConfig] = None,
                 space: Optional[HyperparameterSpace] = None,
                 seed: int = 0) -> None:
        self.config = config
        self.space = space
        self.rng = _random.Random(seed)

    @abc.abstractmethod
    def initial_operations(self) -> List[Operation]:
        ...

    @abc.abstractmethod
    def on_validation_completed(self, request_id: int, metric: float,
                                units: int) -> List[Operation]:
        ...

    def on_trial_created(self, request_id: int) -> List[Operation]:
        return []

    def on_trial_closed(self, request_id: int) -> List[Operation]:
        return []

    def on_trial_exited_early(self, request_id: int,
                              reason: str) -> List[Operation]:
        return []

    @abc.abstractmethod
    def progress(self) -> float:
        """0..1 completion estimate."""

    # crash-consistency (reference: searcher state snapshots)
    def snapshot(self) -> Dict[str, Any]:
        rng = getattr(self, "rng", None)
        return {"rng": rng.getstate()} if rng is not None else {}

    def restore(self, snap: Dict[str, Any]) -> None:
        state = snap.get("rng")
        if state is not None and getattr(self, "rng", None) is not None:
            # JSON roundtrips tuples to lists; normalize back
            a, b, c = state
            self.rng.setstate((a, tuple(b), c))


class Searcher:
    """Drives a SearchMethod; allocates request ids; tracks liveness.

    ≈ master/pkg/searcher/searcher.go:48 — the thin engine between the
    experiment orchestrator and the method.
    """

    def __init__(self, method: SearchMethod) -> None:
        self.method = method
        self.next_id = 0
        self.outstanding: Dict[int, Dict[str, Any]] = {}  # live trials
        self.closed: set = set()
        self.shutdown = False

    def _assign_ids(self, ops: List[Operation]) -> List[Operation]:
        out: List[Operation] = []
        for op in ops:
            if isinstance(op, Create):
                if op.request_id < 0:  # method asks engine to number it
                    op = Create(self.next_id, op.hparams)
                self.next_id = max(self.next_id, op.request_id + 1)
                self.outstanding[op.request_id] = {"hparams": op.hparams}
            elif isinstance(op, Close):
                self.closed.add(op.request_id)
                self.outstanding.pop(op.request_id, None)
            elif isinstance(op, Shutdown):
                self.shutdown = True
            out.append(op)
        return out

    def initial_operations(self) -> List[Operation]:
        return self._assign_ids(self.method.initial_operations())

    def trial_created(self, request_id: int) -> List[Operation]:
        return self._assign_ids(self.method.on_trial_created(request_id))

    def validation_completed(self, request_id: int, metric: float,
                             units: int) -> List[Operation]:
        return self._assign_ids(
            self.method.on_validation_completed(request_id, metric, units)
        )

    def trial_closed(self, request_id: int) -> List[Operation]:
        self.closed.add(request_id)
        self.outstanding.pop(request_id, None)
        return self._assign_ids(self.method.on_trial_closed(request_id))

    def trial_exited_early(self, request_id: int, reason: str) -> List[Operation]:
        self.outstanding.pop(request_id, None)
        return self._assign_ids(
            self.method.on_trial_exited_early(request_id, reason)
        )

    def progress(self) -> float:
        return self.method.progress()

    def snapshot(self) -> Dict[str, Any]:
        return {
            "method": self.method.snapshot(),
            "next_id": self.next_id,
            "closed": list(self.closed),
            "outstanding": {str(k): v for k, v in self.outstanding.items()},
            "shutdown": self.shutdown,
        }

    def restore(self, snap: Dict[str, Any]) -> None:
        self.method.restore(snap["method"])
        self.next_id = snap["next_id"]
        self.closed = set(snap["closed"])
        self.outstanding = {int(k): v for k, v in snap["outstanding"].items()}
        self.shutdown = snap["shutdown"]
