"""ASHA — asynchronous successive halving (reference: master/pkg/searcher/
asha.go:56 promote-based, asha_stopping.go stopping-based).

Rung r (r = 0..num_rungs-1) has a cumulative unit target of
``max_units / divisor^(num_rungs-1-r)`` — the bottom rung trains briefly,
the top rung to max_length. Trials that finish rung r pause; whenever a rung
has recorded ``divisor × (promoted_so_far + 1)`` results, its best unpromoted
trial is promoted (ValidateAfter the next rung's target). The stopping
variant (``stop_once``) never pauses: a trial continues unless it is in the
bottom (1 - 1/divisor) of its rung.
"""
from __future__ import annotations

import math
from typing import Any, Dict, List, Optional

from determined_clone_tpu.searcher.base import (
    Close,
    Create,
    Operation,
    SearchMethod,
    Shutdown,
    ValidateAfter,
)


class ASHASearch(SearchMethod):
    def __init__(self, config, space, seed=0, *,
                 max_units: Optional[int] = None,
                 num_rungs: Optional[int] = None,
                 max_trials: Optional[int] = None,
                 max_concurrent: Optional[int] = None):
        super().__init__(config, space, seed)
        if max_units is None:
            if config.max_time is not None:
                max_units = int(config.max_time)
            elif config.max_length is not None:
                max_units = config.max_length.value
            else:
                raise ValueError("asha needs max_time or max_length")
        self.max_units = max_units
        self.divisor = config.divisor
        self.num_rungs = num_rungs if num_rungs is not None else config.num_rungs
        self.max_trials = max_trials if max_trials is not None else config.max_trials
        self.max_concurrent = (
            max_concurrent if max_concurrent is not None
            else min(config.max_concurrent_trials or 16, self.max_trials)
        )
        self.smaller_is_better = config.smaller_is_better
        self.stop_once = config.stop_once

        self.rung_targets = [
            max(1, int(round(self.max_units / self.divisor ** (self.num_rungs - 1 - r))))
            for r in range(self.num_rungs)
        ]
        # dedupe targets that collide after rounding
        for r in range(1, self.num_rungs):
            if self.rung_targets[r] <= self.rung_targets[r - 1]:
                self.rung_targets[r] = self.rung_targets[r - 1] + 1
        self.rung_targets[-1] = max(self.rung_targets[-1], self.max_units)

        # state
        self.created = 0
        self.started = 0  # on_trial_created calls; guards premature shutdown
        self.closed: set = set()
        # per rung: list of [signed_metric, rid] sorted best-first lazily
        self.rungs: List[List[List[float]]] = [[] for _ in range(self.num_rungs)]
        self.promoted: List[set] = [set() for _ in range(self.num_rungs)]
        self.trial_rung: Dict[int, int] = {}
        self.done = False

    # -- helpers ------------------------------------------------------------

    def _sign(self, metric: float) -> float:
        return metric if self.smaller_is_better else -metric

    def _rung_of(self, units: int) -> int:
        for r, t in enumerate(self.rung_targets):
            if units <= t:
                return r
        return self.num_rungs - 1

    def _create_trial(self) -> List[Operation]:
        self.created += 1
        return [Create(-1, self.space.sample(self.rng))]

    def _promotions(self, r: int) -> List[Operation]:
        """Emit promotions a rung is now entitled to (async rule)."""
        if r >= self.num_rungs - 1:
            return []
        ops: List[Operation] = []
        records = sorted(self.rungs[r], key=lambda m: m[0])
        allowed = len(records) // self.divisor
        while len(self.promoted[r]) < allowed:
            candidate = next(
                (rid for metric, rid in records
                 if rid not in self.promoted[r] and rid not in self.closed),
                None,
            )
            if candidate is None:
                break
            self.promoted[r].add(int(candidate))
            self.trial_rung[int(candidate)] = r + 1
            ops.append(ValidateAfter(int(candidate), self.rung_targets[r + 1]))
        return ops

    def _maybe_finish(self) -> List[Operation]:
        """When the budget is spent and nothing can promote, close paused
        trials and shut down."""
        if (self.done or self.created < self.max_trials
                or self.started < self.created):
            return []
        live = set(self.trial_rung) - self.closed
        # a trial is 'active' if it still has an outstanding ValidateAfter:
        # i.e. it was promoted into its current rung but hasn't reported there.
        pending = {
            rid for rid in live
            if not any(rid == int(rec[1]) for rec in self.rungs[self.trial_rung[rid]])
        }
        if pending:
            return []
        # all live trials are paused; no promotions were possible
        ops: List[Operation] = [Close(rid) for rid in sorted(live)]
        self.closed |= live
        ops.append(Shutdown())
        self.done = True
        return ops

    # -- SearchMethod -------------------------------------------------------

    def initial_operations(self) -> List[Operation]:
        ops: List[Operation] = []
        for _ in range(min(self.max_concurrent, self.max_trials)):
            ops.extend(self._create_trial())
        return ops

    def on_trial_created(self, request_id: int) -> List[Operation]:
        self.started += 1
        self.trial_rung[request_id] = 0
        return [ValidateAfter(request_id, self.rung_targets[0])]

    def on_validation_completed(self, request_id: int, metric: float,
                                units: int) -> List[Operation]:
        r = self._rung_of(units)
        self.trial_rung[request_id] = r
        self.rungs[r].append([self._sign(metric), request_id])
        ops: List[Operation] = []

        if r == self.num_rungs - 1:
            # finished the top rung: done
            self.closed.add(request_id)
            ops.append(Close(request_id))
            if self.created < self.max_trials:
                ops.extend(self._create_trial())
        elif self.stop_once:
            # stopping rule: continue iff in the top 1/divisor of this rung
            records = sorted(self.rungs[r], key=lambda m: m[0])
            rank = next(i for i, rec in enumerate(records)
                        if int(rec[1]) == request_id)
            keep = max(1, len(records) // self.divisor)
            if rank < keep:
                self.trial_rung[request_id] = r + 1
                ops.append(ValidateAfter(request_id, self.rung_targets[r + 1]))
            else:
                self.closed.add(request_id)
                ops.append(Close(request_id))
                if self.created < self.max_trials:
                    ops.extend(self._create_trial())
        else:
            # promote-based: this trial pauses; promotions may release it or
            # a better-paused peer. A paused (not promoted) trial frees its
            # slot for a new create.
            promotions = self._promotions(r)
            ops.extend(promotions)
            if (self.created < self.max_trials
                    and not any(isinstance(o, ValidateAfter)
                                and o.request_id == request_id
                                for o in promotions)):
                ops.extend(self._create_trial())

        ops.extend(self._maybe_finish())
        return ops

    def on_trial_exited_early(self, request_id: int, reason: str
                              ) -> List[Operation]:
        self.closed.add(request_id)
        ops: List[Operation] = []
        if self.created < self.max_trials:
            ops.extend(self._create_trial())
        ops.extend(self._maybe_finish())
        return ops

    def progress(self) -> float:
        if self.done:
            return 1.0
        total_units = self.max_trials * self.rung_targets[0]  # lower bound
        spent = sum(
            self.rung_targets[self.trial_rung.get(int(rid), 0)]
            for rung in self.rungs for _, rid in rung
        )
        return min(0.99, spent / max(1, total_units * 2))

    def snapshot(self) -> Dict[str, Any]:
        return {
            **super().snapshot(),
            "created": self.created,
            "started": self.started,
            "closed": list(self.closed),
            "rungs": self.rungs,
            "promoted": [list(p) for p in self.promoted],
            "trial_rung": {str(k): v for k, v in self.trial_rung.items()},
            "done": self.done,
        }

    def restore(self, snap: Dict[str, Any]) -> None:
        super().restore(snap)
        self.created = snap["created"]
        self.started = snap.get("started", snap["created"])
        self.closed = set(snap["closed"])
        self.rungs = snap["rungs"]
        self.promoted = [set(p) for p in snap["promoted"]]
        self.trial_rung = {int(k): v for k, v in snap["trial_rung"].items()}
        self.done = snap["done"]
