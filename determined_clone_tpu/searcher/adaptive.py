"""Adaptive ASHA — a tournament of ASHA brackets with different early-stopping
aggressiveness (reference: master/pkg/searcher/adaptive_asha.go:71 +
tournament.go).

mode: aggressive → 1 bracket (max rungs, maximal early stopping);
      standard   → up to 3 brackets (num_rungs, -1, -2);
      conservative → one bracket per rung count down to 1.
The trial budget is split across brackets; each bracket is a full ASHASearch
and events are routed by request-id ownership.

NOTE: the orchestrator must call ``trial_created`` in the same order Creates
were emitted (both the Python driver and C++ master do) — bracket ownership
of new ids is assigned FIFO.
"""
from __future__ import annotations

from typing import Any, Dict, List

from determined_clone_tpu.searcher.asha import ASHASearch
from determined_clone_tpu.searcher.base import (
    Create,
    Operation,
    SearchMethod,
    Shutdown,
)


class AdaptiveASHASearch(SearchMethod):
    def __init__(self, config, space, seed=0):
        super().__init__(config, space, seed)
        if config.bracket_rungs:
            rung_counts = list(config.bracket_rungs)
        elif config.mode == "aggressive":
            rung_counts = [config.num_rungs]
        elif config.mode == "conservative":
            rung_counts = list(range(config.num_rungs, 0, -1))
        else:  # standard
            rung_counts = [
                r for r in range(config.num_rungs, config.num_rungs - 3, -1)
                if r >= 1
            ]
        n = len(rung_counts)
        base, rem = divmod(config.max_trials, n)
        trials_per = [base + (1 if i < rem else 0) for i in range(n)]
        conc = max(1, (config.max_concurrent_trials or 16))
        conc_base, conc_rem = divmod(max(conc, n), n)
        conc_per = [conc_base + (1 if i < conc_rem else 0) for i in range(n)]

        self.brackets: List[ASHASearch] = []
        for i, rungs in enumerate(rung_counts):
            if trials_per[i] == 0:
                continue
            self.brackets.append(ASHASearch(
                config, space, seed=seed + i,
                num_rungs=rungs,
                max_trials=trials_per[i],
                max_concurrent=min(conc_per[i], trials_per[i]),
            ))
        self.owner: Dict[int, int] = {}       # rid -> bracket idx
        self._pending: List[int] = []         # FIFO of bracket idx per Create
        self._shut: set = set()

    def _route(self, bracket_idx: int, ops: List[Operation]) -> List[Operation]:
        out: List[Operation] = []
        for op in ops:
            if isinstance(op, Create):
                self._pending.append(bracket_idx)
                out.append(op)
            elif isinstance(op, Shutdown):
                self._shut.add(bracket_idx)
                if len(self._shut) == len(self.brackets):
                    out.append(op)
            else:
                out.append(op)
        return out

    def initial_operations(self) -> List[Operation]:
        ops: List[Operation] = []
        for i, b in enumerate(self.brackets):
            ops.extend(self._route(i, b.initial_operations()))
        return ops

    def on_trial_created(self, request_id: int) -> List[Operation]:
        if not self._pending:
            raise RuntimeError(
                f"trial_created({request_id}) with no pending bracket create"
            )
        i = self._pending.pop(0)
        self.owner[request_id] = i
        return self._route(i, self.brackets[i].on_trial_created(request_id))

    def on_validation_completed(self, request_id, metric, units):
        i = self.owner[request_id]
        return self._route(
            i, self.brackets[i].on_validation_completed(request_id, metric, units)
        )

    def on_trial_closed(self, request_id):
        i = self.owner.get(request_id)
        if i is None:
            return []
        return self._route(i, self.brackets[i].on_trial_closed(request_id))

    def on_trial_exited_early(self, request_id, reason):
        i = self.owner[request_id]
        return self._route(
            i, self.brackets[i].on_trial_exited_early(request_id, reason)
        )

    def progress(self) -> float:
        if not self.brackets:
            return 1.0
        return sum(b.progress() for b in self.brackets) / len(self.brackets)

    def snapshot(self) -> Dict[str, Any]:
        return {
            **super().snapshot(),
            "brackets": [b.snapshot() for b in self.brackets],
            "owner": {str(k): v for k, v in self.owner.items()},
            "pending": self._pending,
            "shut": list(self._shut),
        }

    def restore(self, snap: Dict[str, Any]) -> None:
        super().restore(snap)
        for b, bs in zip(self.brackets, snap["brackets"]):
            b.restore(bs)
        self.owner = {int(k): v for k, v in snap["owner"].items()}
        self._pending = list(snap["pending"])
        self._shut = set(snap["shut"])
