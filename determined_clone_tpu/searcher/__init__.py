"""Hyperparameter search engine (≈ master/pkg/searcher — SURVEY.md §2.1)."""
from determined_clone_tpu.config.experiment import SearcherConfig
from determined_clone_tpu.config.hyperparameters import HyperparameterSpace
from determined_clone_tpu.searcher.adaptive import AdaptiveASHASearch
from determined_clone_tpu.searcher.asha import ASHASearch
from determined_clone_tpu.searcher.base import (
    Close,
    Create,
    Operation,
    Searcher,
    SearchMethod,
    Shutdown,
    ValidateAfter,
)
from determined_clone_tpu.searcher.custom import (
    LocalSearchRunner,
    RemoteSearchRunner,
)
from determined_clone_tpu.searcher.methods import (
    GridSearch,
    RandomSearch,
    SingleSearch,
)
from determined_clone_tpu.searcher.simulate import SimResult, SimTrial, simulate


def build_method(config: SearcherConfig, space: HyperparameterSpace,
                 seed: int = 0) -> SearchMethod:
    """Factory over the searcher union (≈ expconf searcher_config.go:16-28)."""
    if config.name == "single":
        return SingleSearch(config, space, seed)
    if config.name == "random":
        return RandomSearch(config, space, seed)
    if config.name == "grid":
        return GridSearch(config, space, seed)
    if config.name == "asha":
        return ASHASearch(config, space, seed)
    if config.name == "adaptive_asha":
        return AdaptiveASHASearch(config, space, seed)
    raise ValueError(
        f"searcher {config.name!r} has no built-in method — custom searchers "
        f"pass their SearchMethod to searcher.RemoteSearchRunner (cluster, "
        f"via the master's event queue) or searcher.LocalSearchRunner"
    )


__all__ = [
    "AdaptiveASHASearch",
    "ASHASearch",
    "Close",
    "Create",
    "GridSearch",
    "LocalSearchRunner",
    "RemoteSearchRunner",
    "Operation",
    "RandomSearch",
    "Searcher",
    "SearchMethod",
    "Shutdown",
    "SimResult",
    "SimTrial",
    "SingleSearch",
    "ValidateAfter",
    "build_method",
    "simulate",
]
