"""Custom searcher runners — user-defined SearchMethods driving experiments.

≈ the reference's custom-search client (harness/determined/searcher/
_search_runner.py + _remote_search_runner.py over master/pkg/searcher/
custom_search.go:15-23): the user subclasses :class:`SearchMethod`
(searcher/base.py — the same interface the built-in methods implement) and a
runner connects it to an experiment:

- :class:`RemoteSearchRunner` — the method runs in the user's process and
  steers a CLUSTER experiment through the master's custom-search event
  queue (GET /api/v1/experiments/<id>/searcher/events →
  POST .../searcher/operations).
- :class:`LocalSearchRunner` — the method drives a single-process local
  experiment (experiment/runner.py), no master involved.

Events mirror the C++ CustomSearchCpp record types: initial_operations,
trial_created, validation_completed, trial_exited_early.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Type

from determined_clone_tpu.searcher.base import (
    Close,
    Create,
    Operation,
    Searcher,
    SearchMethod,
    Shutdown,
    ValidateAfter,
)

TERMINAL_STATES = {"COMPLETED", "ERRORED", "CANCELED"}


def ops_to_json(ops: List[Operation]) -> List[Dict[str, Any]]:
    """Serialize engine operations onto the master's wire format."""
    out: List[Dict[str, Any]] = []
    for op in ops:
        if isinstance(op, Create):
            out.append({"type": "create", "request_id": op.request_id,
                        "hparams": op.hparams})
        elif isinstance(op, ValidateAfter):
            out.append({"type": "validate_after", "request_id": op.request_id,
                        "units": op.length})
        elif isinstance(op, Close):
            out.append({"type": "close", "request_id": op.request_id})
        elif isinstance(op, Shutdown):
            out.append({"type": "shutdown", "failure": op.failure,
                        "cancel": op.cancel})
        else:  # pragma: no cover - exhaustive over the Operation union
            raise TypeError(f"unknown operation {op!r}")
    return out


class RemoteSearchRunner:
    """Runs a SearchMethod against a cluster experiment's event queue.

    The runner is resumable: it re-polls from event id 0 on restart and the
    master applies replayed operations idempotently (duplicate creates for
    existing request ids are no-ops; closes of terminal trials likewise).
    With ``trim_events=True`` the runner acknowledges processed events so the
    master drops them — bounding the event log for long searches — at the
    cost of replay-based resume (persist your own method state instead).
    """

    def __init__(self, method: SearchMethod, session: Any, *,
                 poll_interval: float = 0.5,
                 trim_events: bool = False) -> None:
        self.method = method
        self.engine = Searcher(method)
        self.session = session
        self.poll_interval = poll_interval
        self.trim_events = trim_events

    def run(self, config: Dict[str, Any], *,
            context: Optional[List[Dict[str, str]]] = None) -> int:
        """Create the experiment (config must say searcher.name=custom) and
        drive it to a terminal state; returns the experiment id."""
        searcher = config.get("searcher", {})
        if searcher.get("name") != "custom":
            raise ValueError("RemoteSearchRunner requires searcher.name="
                             f"'custom', got {searcher.get('name')!r}")
        exp = self.session.create_experiment(config, context=context)
        self.run_experiment(exp["id"])
        return exp["id"]

    def run_experiment(self, experiment_id: int) -> str:
        """Attach to an existing custom-search experiment; poll events, feed
        the method, post operations; returns the terminal state."""
        last_event = 0
        while True:
            out = self.session.request(
                "GET",
                f"/api/v1/experiments/{experiment_id}/searcher/events"
                f"?since={last_event}")
            state = out.get("state", "")
            events = out.get("events", [])
            ops: List[Operation] = []
            for event in events:
                last_event = max(last_event, int(event["id"]))
                ops.extend(self._dispatch(event))
            if ops or events:
                body: Dict[str, Any] = {"ops": ops_to_json(ops),
                                        "progress": self.method.progress()}
                if self.trim_events:
                    body["ack_through"] = last_event
                self.session.request(
                    "POST",
                    f"/api/v1/experiments/{experiment_id}/searcher/operations",
                    body)
            if state in TERMINAL_STATES:
                return state
            if not events:
                time.sleep(self.poll_interval)

    def _dispatch(self, event: Dict[str, Any]) -> List[Operation]:
        etype = event["type"]
        if etype == "initial_operations":
            return self.engine.initial_operations()
        if etype == "trial_created":
            return self.engine.trial_created(int(event["request_id"]))
        if etype == "validation_completed":
            return self.engine.validation_completed(
                int(event["request_id"]), float(event["metric"]),
                int(event["units"]))
        if etype == "trial_exited_early":
            return self.engine.trial_exited_early(
                int(event["request_id"]), "exited_early")
        if etype == "trial_closed":
            return self.engine.trial_closed(int(event["request_id"]))
        return []  # forward-compat: ignore unknown event types


class LocalSearchRunner:
    """Runs a SearchMethod over the single-process local orchestrator
    (≈ LocalSearchRunner, harness/determined/searcher/_search_runner.py:214)."""

    def __init__(self, method: SearchMethod) -> None:
        self.method = method

    def run(self, config: Any, trial_cls: Type[Any], *,
            storage_path: str, mesh: Optional[Any] = None) -> Any:
        from determined_clone_tpu.experiment.runner import (
            LocalExperimentRunner,
        )

        runner = LocalExperimentRunner(
            config, trial_cls, storage_path=storage_path, mesh=mesh,
            method=self.method,
        )
        return runner.run()
