"""Search simulation — run a method against a synthetic metric landscape
without training anything (reference: master/pkg/searcher/simulate.go, used
by asha_test.go-style behavior tests)."""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional

from determined_clone_tpu.searcher.base import (
    Close,
    Create,
    Operation,
    Searcher,
    SearchMethod,
    Shutdown,
    ValidateAfter,
)

MetricFn = Callable[[Dict[str, Any], int], float]  # (hparams, units) -> metric


@dataclasses.dataclass
class SimTrial:
    request_id: int
    hparams: Dict[str, Any]
    target_units: Optional[int] = None
    trained_units: int = 0
    closed: bool = False
    metrics: List[float] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class SimResult:
    trials: Dict[int, SimTrial]
    shutdown: bool
    events: int
    max_concurrent_seen: int

    @property
    def n_trials(self) -> int:
        return len(self.trials)

    def units_by_trial(self) -> Dict[int, int]:
        return {rid: t.trained_units for rid, t in self.trials.items()}


def simulate(method: SearchMethod, metric_fn: MetricFn, *,
             max_events: int = 100_000) -> SimResult:
    """Drive the method to completion: trials 'train' instantly and report
    metric_fn(hparams, units) at each ValidateAfter boundary."""
    engine = Searcher(method)
    trials: Dict[int, SimTrial] = {}
    queue: List[Operation] = list(engine.initial_operations())
    events = 0
    max_concurrent = 0

    def live_count() -> int:
        return sum(
            1 for t in trials.values() if not t.closed
        )

    while queue and events < max_events:
        events += 1
        op = queue.pop(0)
        if isinstance(op, Create):
            trials[op.request_id] = SimTrial(op.request_id, op.hparams)
            queue.extend(engine.trial_created(op.request_id))
            max_concurrent = max(max_concurrent, live_count())
        elif isinstance(op, ValidateAfter):
            t = trials[op.request_id]
            if t.closed:
                continue
            t.target_units = op.length
            t.trained_units = max(t.trained_units, op.length)
            m = metric_fn(t.hparams, t.trained_units)
            t.metrics.append(m)
            queue.extend(
                engine.validation_completed(op.request_id, m, t.trained_units)
            )
        elif isinstance(op, Close):
            t = trials.get(op.request_id)
            if t and not t.closed:
                t.closed = True
                queue.extend(engine.trial_closed(op.request_id))
        elif isinstance(op, Shutdown):
            return SimResult(trials, True, events, max_concurrent)
    return SimResult(trials, False, events, max_concurrent)
