"""Search methods: single, random, grid (reference: master/pkg/searcher/
random.go, grid.go). ASHA lives in asha.py; adaptive ASHA in adaptive.py."""
from __future__ import annotations

from typing import Any, Dict, List

from determined_clone_tpu.searcher.base import (
    Close,
    Create,
    Operation,
    SearchMethod,
    Shutdown,
    ValidateAfter,
)


class _MaxLengthMixin:
    @property
    def max_units(self) -> int:
        """searcher.max_length resolved to scheduling units by the caller;
        stored in config.extra by the engine wiring, or derived simply."""
        cfg = self.config
        if cfg.max_length is None:
            raise ValueError(f"searcher '{cfg.name}' requires max_length")
        # units here are abstract: the trial-side resolves Length→batches.
        # For engine bookkeeping we use the raw value.
        return cfg.max_length.value


class SingleSearch(_MaxLengthMixin, SearchMethod):
    """One trial, one validation at max_length (reference single searcher)."""

    def __init__(self, config, space, seed=0):
        super().__init__(config, space, seed)
        self._done = False

    def initial_operations(self) -> List[Operation]:
        return [
            Create(-1, self.space.sample(self.rng)),
            ValidateAfter(0, self.max_units),
        ]

    def on_validation_completed(self, request_id, metric, units):
        self._done = True
        return [Close(request_id), Shutdown()]

    def on_trial_exited_early(self, request_id, reason):
        self._done = True
        return [Shutdown(failure=True)]

    def progress(self) -> float:
        return 1.0 if self._done else 0.0


class RandomSearch(_MaxLengthMixin, SearchMethod):
    """max_trials independent random trials (reference random.go)."""

    def __init__(self, config, space, seed=0):
        super().__init__(config, space, seed)
        self.created = 0
        self.completed = 0

    def initial_operations(self) -> List[Operation]:
        n = min(self.config.max_trials,
                self.config.max_concurrent_trials or self.config.max_trials)
        ops: List[Operation] = []
        for _ in range(n):
            ops.append(Create(-1, self.space.sample(self.rng)))
        self.created = n
        return ops

    def on_trial_created(self, request_id) -> List[Operation]:
        return [ValidateAfter(request_id, self.max_units)]

    def on_validation_completed(self, request_id, metric, units):
        self.completed += 1
        return [Close(request_id)] + self._refill_or_shutdown()

    def on_trial_exited_early(self, request_id, reason):
        # an errored trial still consumes its budget slot (reference
        # semantics: the search continues around failures)
        self.completed += 1
        return self._refill_or_shutdown()

    def _refill_or_shutdown(self) -> List[Operation]:
        if self.created < self.config.max_trials:
            self.created += 1
            return [Create(-1, self.space.sample(self.rng))]
        if self.completed >= self.config.max_trials:
            return [Shutdown()]
        return []

    def progress(self) -> float:
        return self.completed / max(1, self.config.max_trials)

    def snapshot(self) -> Dict[str, Any]:
        return {**super().snapshot(), "created": self.created,
                "completed": self.completed}

    def restore(self, snap) -> None:
        super().restore(snap)
        self.created = snap["created"]
        self.completed = snap["completed"]


class GridSearch(_MaxLengthMixin, SearchMethod):
    """Exhaustive cartesian grid (reference grid.go); max_trials caps it."""

    def __init__(self, config, space, seed=0):
        super().__init__(config, space, seed)
        self.points = list(space.grid())
        if config.max_trials > 1:
            self.points = self.points[: config.max_trials]
        self.completed = 0

    def initial_operations(self) -> List[Operation]:
        limit = self.config.max_concurrent_trials or len(self.points)
        ops: List[Operation] = []
        for hp in self.points[:limit]:
            ops.append(Create(-1, hp))
        self._launched = min(limit, len(self.points))
        return ops

    def on_trial_created(self, request_id) -> List[Operation]:
        return [ValidateAfter(request_id, self.max_units)]

    def on_validation_completed(self, request_id, metric, units):
        self.completed += 1
        return [Close(request_id)] + self._refill_or_shutdown()

    def on_trial_exited_early(self, request_id, reason):
        self.completed += 1
        return self._refill_or_shutdown()

    def _refill_or_shutdown(self) -> List[Operation]:
        if self._launched < len(self.points):
            op = Create(-1, self.points[self._launched])
            self._launched += 1
            return [op]
        if self.completed >= len(self.points):
            return [Shutdown()]
        return []

    def progress(self) -> float:
        return self.completed / max(1, len(self.points))

    def snapshot(self) -> Dict[str, Any]:
        return {**super().snapshot(), "completed": self.completed,
                "launched": self._launched}

    def restore(self, snap) -> None:
        super().restore(snap)
        self.completed = snap["completed"]
        self._launched = snap["launched"]
