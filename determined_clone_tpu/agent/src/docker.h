// Docker runtime command construction (pure; unit-tested).
//
// ≈ the reference agent's docker runner (agent/pkg/docker/docker.go:87-244):
// tasks run as containers instead of host processes. On TPU-VMs the
// container needs the accelerator device files, host networking (the
// harness rendezvous announces host addresses) and the agent work dir
// mounted (task logs + model-def run dirs live there).
#pragma once

#include <map>
#include <string>
#include <vector>

namespace dct {

// argv for `docker run` of one task. `env` is the DCT_* task environment;
// `argv` the in-container command (the task argv or the trial harness
// invocation); `devices` e.g. {"/dev/accel0", ...}.
inline std::vector<std::string> docker_run_argv(
    const std::string& alloc_id, const std::string& image,
    const std::string& work_dir, const std::string& task_cwd,
    const std::map<std::string, std::string>& env,
    const std::vector<std::string>& devices,
    const std::vector<std::string>& argv) {
  std::vector<std::string> out = {
      "docker", "run", "--rm", "--name", "dct-task-" + alloc_id,
      "--network", "host",           // rendezvous addresses are host addresses
      "-v", work_dir + ":" + work_dir,  // logs + run dirs
      "-w", task_cwd,
  };
  for (const auto& d : devices) {
    out.push_back("--device");
    out.push_back(d);
  }
  for (const auto& [k, v] : env) {
    out.push_back("-e");
    out.push_back(k + "=" + v);
  }
  out.push_back(image);
  out.insert(out.end(), argv.begin(), argv.end());
  return out;
}

}  // namespace dct
