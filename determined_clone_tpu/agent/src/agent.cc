// determined-clone-tpu agent — TPU-VM node daemon.
//
// C++ equivalent of the reference agent (agent/cmd/determined-agent,
// agent/internal/agent.go): detects TPU chips, registers with the master,
// heartbeats (HTTP long-poll replaces the reference websocket — same
// reconnect-with-backoff semantics, agent.go:330), launches task processes
// (process runner first; container runtimes are a later layer), forwards
// exit events and log batches.
//
// TPU detection (replaces nvidia-smi/rocm-smi parsing, detect/detect.go:19):
//   1. DCT_AGENT_SLOTS / DCT_AGENT_TOPOLOGY env (explicit + artificial slots
//      for tests — detect.go:39's trick)
//   2. /dev/accel* device files (TPU VM runtime)
//   3. fallback: 0 chips (cpu-only agent, zero-slot aux tasks)
#include <dirent.h>
#include <signal.h>
#include <sys/prctl.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>

#include <chrono>
#include <cstring>
#include <fstream>
#include <iostream>
#include <map>
#include <sstream>
#include <string>
#include <thread>
#include <vector>

#include "../../master/src/http.h"
#include "../../master/src/json.h"

namespace dct {
namespace {

struct AgentConfig {
  std::string master_host = "127.0.0.1";
  int master_port = 8080;
  std::string id;
  std::string resource_pool = "default";
  int slots = -1;           // -1 = autodetect
  std::string topology;
  double heartbeat_sec = 1.0;
  std::string work_dir = ".";
};

int detect_tpu_chips(std::string* topology) {
  if (const char* env = std::getenv("DCT_AGENT_SLOTS")) {
    if (const char* topo = std::getenv("DCT_AGENT_TOPOLOGY")) *topology = topo;
    return std::atoi(env);
  }
  int count = 0;
  if (DIR* dev = ::opendir("/dev")) {
    while (dirent* entry = ::readdir(dev)) {
      if (std::strncmp(entry->d_name, "accel", 5) == 0) ++count;
    }
    ::closedir(dev);
  }
  if (count > 0 && topology->empty()) {
    const char* gen = std::getenv("PALLAS_AXON_TPU_GEN");
    *topology = std::string(gen ? gen : "tpu") + "-" + std::to_string(count);
  }
  return count;
}

struct RunningTask {
  pid_t pid = 0;
  std::string allocation_id;
  std::string log_path;
  bool preempt_sent = false;
};

int b64_value(char c) {
  if (c >= 'A' && c <= 'Z') return c - 'A';
  if (c >= 'a' && c <= 'z') return c - 'a' + 26;
  if (c >= '0' && c <= '9') return c - '0' + 52;
  if (c == '+') return 62;
  if (c == '/') return 63;
  return -1;
}

std::string b64_decode(const std::string& in) {
  std::string out;
  int buf = 0, bits = 0;
  for (char c : in) {
    int v = b64_value(c);
    if (v < 0) continue;  // padding / whitespace
    buf = (buf << 6) | v;
    bits += 6;
    if (bits >= 8) {
      bits -= 8;
      out += static_cast<char>((buf >> bits) & 0xFF);
    }
  }
  return out;
}

void mkdirs_for(const std::string& file_path) {
  std::string cur;
  for (size_t i = 0; i < file_path.size(); ++i) {
    if (file_path[i] == '/' && !cur.empty()) ::mkdir(cur.c_str(), 0755);
    cur += file_path[i];
  }
}

class Agent {
 public:
  explicit Agent(AgentConfig config) : config_(std::move(config)) {}

  int run() {
    if (config_.id.empty()) {
      char host[256] = "agent";
      ::gethostname(host, sizeof(host));
      config_.id = std::string(host) + "-" + std::to_string(::getpid());
    }
    if (config_.slots < 0) {
      config_.slots = detect_tpu_chips(&config_.topology);
    }
    // absolute work dir: children chdir into per-task run dirs, so every
    // path derived from work_dir (task logs) must not be cwd-relative
    if (!config_.work_dir.empty() && config_.work_dir[0] != '/') {
      char cwd[4096];
      if (::getcwd(cwd, sizeof(cwd))) {
        config_.work_dir = std::string(cwd) + "/" + config_.work_dir;
      }
    }
    std::cerr << "[agent] id=" << config_.id << " slots=" << config_.slots
              << " topology=" << config_.topology << std::endl;

    // register with reconnect+backoff (≈ agent.go:246,330)
    int backoff_ms = 500;
    while (true) {
      if (register_with_master()) break;
      std::cerr << "[agent] master unreachable; retrying in "
                << backoff_ms << "ms" << std::endl;
      std::this_thread::sleep_for(std::chrono::milliseconds(backoff_ms));
      backoff_ms = std::min(backoff_ms * 2, 15000);
    }

    while (true) {
      reap_tasks();
      if (!heartbeat()) {
        // lost master: back off, re-register (reservations survive on the
        // master until its agent_timeout — the amnesia window)
        std::this_thread::sleep_for(std::chrono::seconds(1));
        register_with_master();
        continue;
      }
      std::this_thread::sleep_for(std::chrono::milliseconds(
          static_cast<int>(config_.heartbeat_sec * 1000)));
    }
  }

 private:
  bool register_with_master() {
    Json body = Json::object();
    char host[256] = "127.0.0.1";
    ::gethostname(host, sizeof(host));
    body.set("id", config_.id).set("slots", config_.slots)
        .set("topology", config_.topology)
        .set("resource_pool", config_.resource_pool)
        .set("address", std::string(host));
    auto resp = http_request(config_.master_host, config_.master_port, "POST",
                             "/api/v1/agents/register", body.dump(), 10);
    return resp && resp->status == 200;
  }

  bool heartbeat() {
    Json running = Json::array();
    for (const auto& [aid, task] : tasks_) running.push_back(aid);
    Json body = Json::object();
    body.set("running", running);
    // at-least-once exit reporting: a lost task_event POST must not leave
    // the master thinking the task still runs (it would re-issue a start);
    // exits ride every heartbeat until one succeeds, master side is
    // idempotent
    size_t exits_sent = pending_exits_.size();
    Json exited = Json::array();
    for (const auto& e : pending_exits_) exited.push_back(e);
    body.set("exited", exited);
    auto resp = http_request(
        config_.master_host, config_.master_port, "POST",
        "/api/v1/agents/" + config_.id + "/heartbeat", body.dump(), 10);
    if (!resp || resp->status != 200) return false;
    pending_exits_.erase(pending_exits_.begin(),
                         pending_exits_.begin() + exits_sent);
    Json j = Json::parse(resp->body);
    for (const auto& cmd : j["commands"].elements()) {
      const std::string& type = cmd["type"].as_string();
      if (type == "start") {
        start_task(cmd);
      } else if (type == "preempt") {
        preempt_task(cmd["allocation_id"].as_string());
      } else if (type == "kill") {
        kill_task(cmd["allocation_id"].as_string());
      }
    }
    return true;
  }

  // Materialize the experiment's model-def context directory for a trial
  // (≈ prep_container.py:29 --download_context_directory). Returns the run
  // dir to chdir into, or "" to inherit the agent's cwd.
  std::string prepare_context(const Json& cmd, const std::string& alloc_id) {
    if (!cmd.has("trial")) return "";
    int64_t exp_id = cmd["trial"]["experiment_id"].as_int();
    // authenticate with the allocation token: under --auth-required the
    // experiments root only opens reads to holders of a live alloc token
    std::map<std::string, std::string> headers;
    if (!cmd["alloc_token"].as_string().empty()) {
      headers["Authorization"] = "Bearer " + cmd["alloc_token"].as_string();
    }
    auto resp = http_request(
        config_.master_host, config_.master_port, "GET",
        "/api/v1/experiments/" + std::to_string(exp_id) + "/context", "", 30,
        headers);
    if (!resp || resp->status != 200) return "";
    Json ctx;
    try {
      ctx = Json::parse(resp->body);
    } catch (const std::exception&) {
      return "";
    }
    if (!ctx["context"].is_array() || ctx["context"].size() == 0) return "";
    std::string run_dir = config_.work_dir + "/run-" + alloc_id;
    ::mkdir(run_dir.c_str(), 0755);
    for (const auto& f : ctx["context"].elements()) {
      const std::string& rel = f["path"].as_string();
      if (rel.empty() || rel[0] == '/' ||
          rel.find("..") != std::string::npos) {
        continue;  // master validates too; belt-and-braces
      }
      std::string full = run_dir + "/" + rel;
      mkdirs_for(full);
      std::ofstream out(full, std::ios::binary);
      out << b64_decode(f["content_b64"].as_string());
    }
    return run_dir;
  }

  void start_task(const Json& cmd) {
    const std::string& alloc_id = cmd["allocation_id"].as_string();
    if (tasks_.count(alloc_id)) return;  // duplicate start

    std::string log_path =
        config_.work_dir + "/task-" + alloc_id + ".log";
    std::string run_dir = prepare_context(cmd, alloc_id);
    pid_t pid = ::fork();
    if (pid == 0) {
      // child: run the harness entrypoint with the task env
      // (≈ container Entrypoint + DET_* env, tasks/task.go:236)
      // fate-sharing: if the agent dies (even SIGKILL), its tasks must not
      // become orphans (≈ pid_server/pid_client, harness ipc.py:264-553)
      ::prctl(PR_SET_PDEATHSIG, SIGKILL);
      if (::getppid() == 1) std::_Exit(83);  // agent died before prctl
      ::setenv("DCT_MASTER_HOST", config_.master_host.c_str(), 1);
      ::setenv("DCT_MASTER_PORT",
               std::to_string(config_.master_port).c_str(), 1);
      ::setenv("DCT_ALLOCATION_ID", alloc_id.c_str(), 1);
      // allocation-scoped credential: the task server requires it on every
      // request, and harness→master calls authenticate with it
      ::setenv("DCT_ALLOC_TOKEN", cmd["alloc_token"].as_string().c_str(), 1);
      ::setenv("DCT_AGENT_ID", config_.id.c_str(), 1);
      ::setenv("DCT_SLOTS", std::to_string(cmd["slots"].as_int()).c_str(), 1);
      ::setenv("DCT_RANK", std::to_string(cmd["rank"].as_int()).c_str(), 1);
      ::setenv("DCT_WORLD_SIZE",
               std::to_string(cmd["world_size"].as_int()).c_str(), 1);
      if (cmd.has("trial")) {
        ::setenv("DCT_TRIAL_ID",
                 std::to_string(cmd["trial"]["id"].as_int()).c_str(), 1);
        ::setenv("DCT_EXPERIMENT_ID",
                 std::to_string(cmd["trial"]["experiment_id"].as_int()).c_str(),
                 1);
        ::setenv("DCT_HPARAMS", cmd["trial"]["hparams"].dump().c_str(), 1);
        ::setenv("DCT_TARGET_UNITS",
                 std::to_string(cmd["trial"]["target_units"].as_int()).c_str(),
                 1);
        ::setenv("DCT_LATEST_CHECKPOINT",
                 cmd["trial"]["latest_checkpoint"].as_string().c_str(), 1);
        ::setenv("DCT_EXPERIMENT_CONFIG", cmd["config"].dump().c_str(), 1);
      }
      // stdout/stderr → log file (shipped to master on exit; live shipping
      // is the harness's log-batch POST)
      // task cwd is the run dir (uploaded context) or the agent work dir —
      // never the agent's own cwd (trials import model code from cwd)
      const std::string& task_cwd =
          run_dir.empty() ? config_.work_dir : run_dir;
      if (::chdir(task_cwd.c_str()) != 0) {
        std::cerr << "chdir " << task_cwd << " failed" << std::endl;
        std::_Exit(82);
      }
      ::setenv("DCT_TASK_TYPE", cmd["task_type"].as_string().c_str(), 1);
      if (cmd["spec"]["env"].is_object()) {
        for (const auto& [k, v] : cmd["spec"]["env"].items()) {
          ::setenv(k.c_str(), v.as_string().c_str(), 1);
        }
      }
      FILE* log = ::freopen(log_path.c_str(), "a", stdout);
      (void)log;
      ::dup2(::fileno(stdout), ::fileno(stderr));

      // NTSC tasks carry an explicit argv (≈ the reference's generic task
      // container spec, tasks/task_command.go); trials exec the harness.
      const Json& argv = cmd["spec"]["argv"];
      if (argv.is_array() && argv.size() > 0) {
        std::vector<std::string> args;
        for (const auto& e : argv.elements()) args.push_back(e.as_string());
        std::vector<char*> cargs;
        for (auto& a : args) cargs.push_back(a.data());
        cargs.push_back(nullptr);
        ::execvp(cargs[0], cargs.data());
        std::cerr << "execvp failed: " << std::strerror(errno) << std::endl;
        std::_Exit(81);
      }
      std::string entrypoint = cmd["spec"]["entrypoint"].as_string();
      if (entrypoint.empty()) {
        std::cerr << "no entrypoint for " << alloc_id << std::endl;
        std::_Exit(80);
      }
      ::execlp("python", "python", "-m", "determined_clone_tpu.exec.trial",
               entrypoint.c_str(), nullptr);
      std::cerr << "execlp failed: " << std::strerror(errno) << std::endl;
      std::_Exit(81);
    }
    if (pid > 0) {
      tasks_[alloc_id] = RunningTask{pid, alloc_id, log_path, false};
      send_event(alloc_id, "running", 0, "");
      std::cerr << "[agent] started " << alloc_id << " pid=" << pid << std::endl;
    }
  }

  void preempt_task(const std::string& alloc_id) {
    auto it = tasks_.find(alloc_id);
    if (it == tasks_.end() || it->second.preempt_sent) return;
    // cooperative: harness polls the preempt endpoint; SIGTERM is the
    // belt-and-braces (exec/launch.py:18's SLURM SIGTERM semantics)
    ::kill(it->second.pid, SIGTERM);
    it->second.preempt_sent = true;
  }

  void kill_task(const std::string& alloc_id) {
    auto it = tasks_.find(alloc_id);
    if (it == tasks_.end()) return;
    ::kill(it->second.pid, SIGKILL);
  }

  void reap_tasks() {
    for (auto it = tasks_.begin(); it != tasks_.end();) {
      int status = 0;
      pid_t done = ::waitpid(it->second.pid, &status, WNOHANG);
      if (done == it->second.pid) {
        int exit_code = WIFEXITED(status) ? WEXITSTATUS(status)
                                          : 128 + WTERMSIG(status);
        ship_logs(it->second);
        // fast path now; the heartbeat carries it again until acked
        send_event(it->first, "exited", exit_code,
                   exit_code ? "task failed" : "");
        Json rec = Json::object();
        rec.set("allocation_id", it->first).set("exit_code", exit_code)
            .set("error", exit_code ? "task failed" : "");
        pending_exits_.push_back(std::move(rec));
        std::cerr << "[agent] task " << it->first << " exited "
                  << exit_code << std::endl;
        it = tasks_.erase(it);
      } else {
        ++it;
      }
    }
  }

  void ship_logs(const RunningTask& task) {
    std::ifstream in(task.log_path);
    if (!in.good()) return;
    Json logs = Json::array();
    std::string line;
    int count = 0;
    while (std::getline(in, line) && count < 5000) {
      logs.push_back(line);
      ++count;
    }
    Json body = Json::object();
    body.set("logs", logs);
    http_request(config_.master_host, config_.master_port, "POST",
                 "/api/v1/allocations/" + task.allocation_id + "/logs",
                 body.dump(), 10);
  }

  void send_event(const std::string& alloc_id, const std::string& event,
                  int exit_code, const std::string& error) {
    Json body = Json::object();
    body.set("allocation_id", alloc_id).set("event", event)
        .set("exit_code", exit_code).set("error", error);
    http_request(config_.master_host, config_.master_port, "POST",
                 "/api/v1/agents/" + config_.id + "/task_event", body.dump(),
                 10);
  }

  AgentConfig config_;
  std::map<std::string, RunningTask> tasks_;
  std::vector<Json> pending_exits_;  // unacked exit reports
};

}  // namespace
}  // namespace dct

int main(int argc, char** argv) {
  dct::AgentConfig config;
  for (int i = 1; i < argc; ++i) {
    if (!std::strcmp(argv[i], "--master-host") && i + 1 < argc) {
      config.master_host = argv[++i];
    } else if (!std::strcmp(argv[i], "--master-port") && i + 1 < argc) {
      config.master_port = std::atoi(argv[++i]);
    } else if (!std::strcmp(argv[i], "--id") && i + 1 < argc) {
      config.id = argv[++i];
    } else if (!std::strcmp(argv[i], "--resource-pool") && i + 1 < argc) {
      config.resource_pool = argv[++i];
    } else if (!std::strcmp(argv[i], "--slots") && i + 1 < argc) {
      config.slots = std::atoi(argv[++i]);
    } else if (!std::strcmp(argv[i], "--topology") && i + 1 < argc) {
      config.topology = argv[++i];
    } else if (!std::strcmp(argv[i], "--work-dir") && i + 1 < argc) {
      config.work_dir = argv[++i];
    } else if (!std::strcmp(argv[i], "--help")) {
      std::cout << "usage: dct-agent [--master-host H] [--master-port P] "
                   "[--id ID] [--resource-pool POOL] [--slots N] "
                   "[--topology T] [--work-dir DIR]\n";
      return 0;
    }
  }
  dct::Agent agent(config);
  return agent.run();
}
