// determined-clone-tpu agent — TPU-VM node daemon.
//
// C++ equivalent of the reference agent (agent/cmd/determined-agent,
// agent/internal/agent.go): detects TPU chips, registers with the master,
// heartbeats (HTTP long-poll replaces the reference websocket — same
// reconnect-with-backoff semantics, agent.go:330), launches task processes
// (process runner first; container runtimes are a later layer), forwards
// exit events and log batches.
//
// TPU detection (replaces nvidia-smi/rocm-smi parsing, detect/detect.go:19):
//   1. DCT_AGENT_SLOTS / DCT_AGENT_TOPOLOGY env (explicit + artificial slots
//      for tests — detect.go:39's trick)
//   2. /dev/accel* device files (TPU VM runtime)
//   3. fallback: 0 chips (cpu-only agent, zero-slot aux tasks)
#include <dirent.h>
#include <fcntl.h>
#include <signal.h>
#include <sys/prctl.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>

#include <chrono>
#include <cstring>
#include <fstream>
#include <iostream>
#include <iterator>
#include <map>
#include <sstream>
#include <string>
#include <thread>
#include <vector>

#include "../../master/src/config_file.h"
#include "../../master/src/http.h"
#include "../../master/src/json.h"
#include "docker.h"

namespace dct {
namespace {

struct AgentConfig {
  std::string master_host = "127.0.0.1";
  int master_port = 8080;
  std::string id;
  std::string resource_pool = "default";
  int slots = -1;           // -1 = autodetect
  std::string topology;
  double heartbeat_sec = 1.0;
  std::string work_dir = ".";
  // task runtime (≈ agent/internal/containers + pkg/docker):
  //   process   — fate-shared child (PDEATHSIG; dies with the agent)
  //   container — detached supervisor+task; survives agent restarts and is
  //               reattached from the state file (manager.go:76 semantics)
  //   docker    — container semantics with the task inside `docker run`
  std::string runtime = "process";
  std::string docker_image = "python:3.11-slim";
};

std::vector<std::string> list_accel_devices() {
  std::vector<std::string> out;
  if (DIR* dev = ::opendir("/dev")) {
    while (dirent* entry = ::readdir(dev)) {
      if (std::strncmp(entry->d_name, "accel", 5) == 0) {
        out.push_back("/dev/" + std::string(entry->d_name));
      }
    }
    ::closedir(dev);
  }
  return out;
}

int detect_tpu_chips(std::string* topology) {
  if (const char* env = std::getenv("DCT_AGENT_SLOTS")) {
    if (const char* topo = std::getenv("DCT_AGENT_TOPOLOGY")) *topology = topo;
    return std::atoi(env);
  }
  int count = static_cast<int>(list_accel_devices().size());
  if (count > 0 && topology->empty()) {
    const char* gen = std::getenv("PALLAS_AXON_TPU_GEN");
    *topology = std::string(gen ? gen : "tpu") + "-" + std::to_string(count);
  }
  return count;
}

struct RunningTask {
  pid_t pid = 0;          // direct child (process) or supervisor (container)
  pid_t task_pid = 0;     // the actual task process (container runtimes)
  std::string allocation_id;
  std::string log_path;
  bool preempt_sent = false;
  bool adopted = false;   // reattached after an agent restart: `pid` is not
                          // our child, so liveness is polled and the exit
                          // code comes from the supervisor's exit file
  int dead_polls = 0;     // adopted: polls since the task vanished (grace
                          // for the supervisor's exit-file write)
  std::string alloc_token;  // data-plane credential: log shipping must
                            // authenticate under --auth-required (kept
                            // last: positional inits predate the field)
};

bool pid_alive(pid_t pid) {
  return pid > 0 && (::kill(pid, 0) == 0 || errno == EPERM);
}

std::string read_proc_file(pid_t pid, const char* name) {
  std::ifstream in("/proc/" + std::to_string(pid) + "/" + name,
                   std::ios::binary);
  if (!in.good()) return "";
  return std::string((std::istreambuf_iterator<char>(in)),
                     std::istreambuf_iterator<char>());
}

bool has_nul_delimited(const std::string& data, const std::string& needle) {
  size_t pos = 0;
  while ((pos = data.find(needle, pos)) != std::string::npos) {
    // whole entry: preceded by NUL/start, followed by NUL/end
    bool start_ok = pos == 0 || data[pos - 1] == '\0';
    size_t end = pos + needle.size();
    bool end_ok = end == data.size() || data[end] == '\0';
    if (start_ok && end_ok) return true;
    ++pos;
  }
  return false;
}

// pid-reuse-proof identity for a task process. The exec'd task carries
// DCT_ALLOCATION_ID in /proc/<pid>/environ (environ reflects the exec-time
// environment, which setenv-before-exec populates — NOT post-fork setenv,
// so a never-exec'd supervisor cannot carry it). The docker runtime's task
// pid is the docker CLI, whose env has no task vars but whose cmdline
// names the container: --name dct-task-<alloc>.
bool proc_matches_task(pid_t pid, const std::string& alloc_id) {
  if (has_nul_delimited(read_proc_file(pid, "environ"),
                        "DCT_ALLOCATION_ID=" + alloc_id)) {
    return true;
  }
  return has_nul_delimited(read_proc_file(pid, "cmdline"),
                           "dct-task-" + alloc_id);
}

int b64_value(char c) {
  if (c >= 'A' && c <= 'Z') return c - 'A';
  if (c >= 'a' && c <= 'z') return c - 'a' + 26;
  if (c >= '0' && c <= '9') return c - '0' + 52;
  if (c == '+') return 62;
  if (c == '/') return 63;
  return -1;
}

std::string b64_decode(const std::string& in) {
  std::string out;
  int buf = 0, bits = 0;
  for (char c : in) {
    int v = b64_value(c);
    if (v < 0) continue;  // padding / whitespace
    buf = (buf << 6) | v;
    bits += 6;
    if (bits >= 8) {
      bits -= 8;
      out += static_cast<char>((buf >> bits) & 0xFF);
    }
  }
  return out;
}

void mkdirs_for(const std::string& file_path) {
  std::string cur;
  for (size_t i = 0; i < file_path.size(); ++i) {
    if (file_path[i] == '/' && !cur.empty()) ::mkdir(cur.c_str(), 0755);
    cur += file_path[i];
  }
}

class Agent {
 public:
  explicit Agent(AgentConfig config) : config_(std::move(config)) {}

  int run() {
    if (config_.id.empty()) {
      char host[256] = "agent";
      ::gethostname(host, sizeof(host));
      config_.id = std::string(host) + "-" + std::to_string(::getpid());
    }
    if (config_.slots < 0) {
      config_.slots = detect_tpu_chips(&config_.topology);
    }
    // absolute work dir: children chdir into per-task run dirs, so every
    // path derived from work_dir (task logs) must not be cwd-relative
    if (!config_.work_dir.empty() && config_.work_dir[0] != '/') {
      char cwd[4096];
      if (::getcwd(cwd, sizeof(cwd))) {
        config_.work_dir = std::string(cwd) + "/" + config_.work_dir;
      }
    }
    std::cerr << "[agent] id=" << config_.id << " slots=" << config_.slots
              << " topology=" << config_.topology
              << " runtime=" << config_.runtime << std::endl;

    // reattach-after-restart (container/docker runtimes): adopt surviving
    // tasks BEFORE the first heartbeat so the master never sees them absent
    if (config_.runtime != "process") reattach_tasks();

    // register with reconnect+backoff (≈ agent.go:246,330)
    int backoff_ms = 500;
    while (true) {
      if (register_with_master()) break;
      std::cerr << "[agent] master unreachable; retrying in "
                << backoff_ms << "ms" << std::endl;
      std::this_thread::sleep_for(std::chrono::milliseconds(backoff_ms));
      backoff_ms = std::min(backoff_ms * 2, 15000);
    }

    while (true) {
      reap_tasks();
      if (!heartbeat()) {
        // lost master: back off, re-register (reservations survive on the
        // master until its agent_timeout — the amnesia window)
        std::this_thread::sleep_for(std::chrono::seconds(1));
        register_with_master();
        continue;
      }
      std::this_thread::sleep_for(std::chrono::milliseconds(
          static_cast<int>(config_.heartbeat_sec * 1000)));
    }
  }

 private:
  bool register_with_master() {
    Json body = Json::object();
    char host[256] = "127.0.0.1";
    ::gethostname(host, sizeof(host));
    body.set("id", config_.id).set("slots", config_.slots)
        .set("topology", config_.topology)
        .set("resource_pool", config_.resource_pool)
        .set("address", std::string(host));
    auto resp = http_request(config_.master_host, config_.master_port, "POST",
                             "/api/v1/agents/register", body.dump(), 10);
    return resp && resp->status == 200;
  }

  bool heartbeat() {
    Json running = Json::array();
    for (const auto& [aid, task] : tasks_) running.push_back(aid);
    Json body = Json::object();
    body.set("running", running);
    // at-least-once exit reporting: a lost task_event POST must not leave
    // the master thinking the task still runs (it would re-issue a start);
    // exits ride every heartbeat until one succeeds, master side is
    // idempotent
    size_t exits_sent = pending_exits_.size();
    Json exited = Json::array();
    for (const auto& e : pending_exits_) exited.push_back(e);
    body.set("exited", exited);
    auto resp = http_request(
        config_.master_host, config_.master_port, "POST",
        "/api/v1/agents/" + config_.id + "/heartbeat", body.dump(), 10);
    if (!resp || resp->status != 200) return false;
    pending_exits_.erase(pending_exits_.begin(),
                         pending_exits_.begin() + exits_sent);
    Json j = Json::parse(resp->body);
    for (const auto& cmd : j["commands"].elements()) {
      const std::string& type = cmd["type"].as_string();
      if (type == "start") {
        start_task(cmd);
      } else if (type == "preempt") {
        preempt_task(cmd["allocation_id"].as_string());
      } else if (type == "kill") {
        kill_task(cmd["allocation_id"].as_string());
      }
    }
    return true;
  }

  // Materialize the experiment's model-def context directory for a trial
  // (≈ prep_container.py:29 --download_context_directory). Returns the run
  // dir to chdir into, or "" to inherit the agent's cwd.
  std::string prepare_context(const Json& cmd, const std::string& alloc_id) {
    if (!cmd.has("trial")) return "";
    int64_t exp_id = cmd["trial"]["experiment_id"].as_int();
    // authenticate with the allocation token: under --auth-required the
    // experiments root only opens reads to holders of a live alloc token
    std::map<std::string, std::string> headers;
    if (!cmd["alloc_token"].as_string().empty()) {
      headers["Authorization"] = "Bearer " + cmd["alloc_token"].as_string();
    }
    auto resp = http_request(
        config_.master_host, config_.master_port, "GET",
        "/api/v1/experiments/" + std::to_string(exp_id) + "/context", "", 30,
        headers);
    if (!resp || resp->status != 200) return "";
    Json ctx;
    try {
      ctx = Json::parse(resp->body);
    } catch (const std::exception&) {
      return "";
    }
    if (!ctx["context"].is_array() || ctx["context"].size() == 0) return "";
    std::string run_dir = config_.work_dir + "/run-" + alloc_id;
    ::mkdir(run_dir.c_str(), 0755);
    for (const auto& f : ctx["context"].elements()) {
      const std::string& rel = f["path"].as_string();
      if (rel.empty() || rel[0] == '/' ||
          rel.find("..") != std::string::npos) {
        continue;  // master validates too; belt-and-braces
      }
      std::string full = run_dir + "/" + rel;
      mkdirs_for(full);
      std::ofstream out(full, std::ios::binary);
      out << b64_decode(f["content_b64"].as_string());
    }
    return run_dir;
  }

  // The DCT_* environment one task sees (≈ container Entrypoint + DET_*
  // env, tasks/task.go:236). Shared by all runtimes: process/container
  // apply it via setenv before exec; docker turns it into -e flags.
  std::map<std::string, std::string> task_env(const Json& cmd,
                                              const std::string& alloc_id) {
    std::map<std::string, std::string> env;
    env["DCT_MASTER_HOST"] = config_.master_host;
    env["DCT_MASTER_PORT"] = std::to_string(config_.master_port);
    env["DCT_ALLOCATION_ID"] = alloc_id;
    // allocation-scoped credential: the task server requires it on every
    // request, and harness→master calls authenticate with it
    env["DCT_ALLOC_TOKEN"] = cmd["alloc_token"].as_string();
    env["DCT_AGENT_ID"] = config_.id;
    env["DCT_SLOTS"] = std::to_string(cmd["slots"].as_int());
    env["DCT_RANK"] = std::to_string(cmd["rank"].as_int());
    env["DCT_WORLD_SIZE"] = std::to_string(cmd["world_size"].as_int());
    env["DCT_N_SLICES"] = std::to_string(cmd["n_slices"].as_int(1));
    env["DCT_TASK_TYPE"] = cmd["task_type"].as_string();
    if (cmd.has("trial")) {
      env["DCT_TRIAL_ID"] = std::to_string(cmd["trial"]["id"].as_int());
      env["DCT_EXPERIMENT_ID"] =
          std::to_string(cmd["trial"]["experiment_id"].as_int());
      env["DCT_HPARAMS"] = cmd["trial"]["hparams"].dump();
      env["DCT_TARGET_UNITS"] =
          std::to_string(cmd["trial"]["target_units"].as_int());
      env["DCT_LATEST_CHECKPOINT"] =
          cmd["trial"]["latest_checkpoint"].as_string();
      env["DCT_EXPERIMENT_CONFIG"] = cmd["config"].dump();
    }
    if (cmd["spec"]["env"].is_object()) {
      for (const auto& [k, v] : cmd["spec"]["env"].items()) {
        env[k] = v.as_string();
      }
    }
    return env;
  }

  // The in-container / in-process command for one task: NTSC argv, or the
  // trial-harness invocation.
  std::vector<std::string> task_argv(const Json& cmd) {
    const Json& argv = cmd["spec"]["argv"];
    std::vector<std::string> out;
    if (argv.is_array() && argv.size() > 0) {
      for (const auto& e : argv.elements()) out.push_back(e.as_string());
      return out;
    }
    const std::string entrypoint = cmd["spec"]["entrypoint"].as_string();
    if (!entrypoint.empty()) {
      out = {"python", "-m", "determined_clone_tpu.exec.trial", entrypoint};
    }
    return out;
  }

  // Child-side: apply env, chdir, redirect stdout/stderr to the log, exec.
  // Never returns.
  [[noreturn]] void exec_task_child(const Json& cmd,
                                    const std::string& alloc_id,
                                    const std::string& log_path,
                                    const std::string& run_dir) {
    for (const auto& [k, v] : task_env(cmd, alloc_id)) {
      ::setenv(k.c_str(), v.c_str(), 1);
    }
    // task cwd is the run dir (uploaded context) or the agent work dir —
    // never the agent's own cwd (trials import model code from cwd)
    const std::string& task_cwd = run_dir.empty() ? config_.work_dir : run_dir;
    if (::chdir(task_cwd.c_str()) != 0) {
      std::cerr << "chdir " << task_cwd << " failed" << std::endl;
      std::_Exit(82);
    }
    // stdout/stderr → log file (shipped to master on exit; live shipping
    // is the harness's log-batch POST)
    FILE* log = ::freopen(log_path.c_str(), "a", stdout);
    (void)log;
    ::dup2(::fileno(stdout), ::fileno(stderr));

    std::vector<std::string> args = task_argv(cmd);
    if (args.empty()) {
      std::cerr << "no argv/entrypoint for " << alloc_id << std::endl;
      std::_Exit(80);
    }
    std::vector<char*> cargs;
    for (auto& a : args) cargs.push_back(a.data());
    cargs.push_back(nullptr);
    ::execvp(cargs[0], cargs.data());
    std::cerr << "execvp failed: " << std::strerror(errno) << std::endl;
    std::_Exit(81);
  }

  std::string exit_file(const std::string& alloc_id) const {
    return config_.work_dir + "/task-" + alloc_id + ".exit";
  }
  std::string state_file() const {
    return config_.work_dir + "/agent-state.json";
  }

  // Detached supervisor+task pair: the supervisor (a new session, so it
  // survives the agent dying by any signal) waits for the task, records the
  // exit code to a file — readable after a reattach, when waitpid is
  // impossible — and exits with the same code for the normal path.
  void start_detached(const Json& cmd, const std::string& alloc_id,
                      const std::string& log_path, const std::string& run_dir,
                      bool docker) {
    int pipefd[2];
    if (::pipe(pipefd) != 0) return;
    ::unlink(exit_file(alloc_id).c_str());
    pid_t sup = ::fork();
    if (sup == 0) {
      ::setsid();  // detach: agent death must not take the task down
      ::close(pipefd[0]);
      pid_t task = ::fork();
      if (task == 0) {
        ::close(pipefd[1]);
        if (docker) {
          auto env = task_env(cmd, alloc_id);
          const std::string cwd = run_dir.empty() ? config_.work_dir : run_dir;
          auto argv = docker_run_argv(alloc_id, config_.docker_image,
                                      config_.work_dir, cwd, env,
                                      list_accel_devices(), task_argv(cmd));
          FILE* log = ::freopen(log_path.c_str(), "a", stdout);
          (void)log;
          ::dup2(::fileno(stdout), ::fileno(stderr));
          std::vector<char*> cargs;
          for (auto& a : argv) cargs.push_back(a.data());
          cargs.push_back(nullptr);
          ::execvp(cargs[0], cargs.data());
          std::_Exit(81);
        }
        exec_task_child(cmd, alloc_id, log_path, run_dir);
      }
      // supervisor: report the task pid, wait, persist the exit code
      ::write(pipefd[1], &task, sizeof(task));
      ::close(pipefd[1]);
      int code = 80;  // fork failure: the task never ran
      if (task > 0) {
        int status = 0;
        ::waitpid(task, &status, 0);
        code = WIFEXITED(status) ? WEXITSTATUS(status)
                                 : 128 + WTERMSIG(status);
      }
      {
        std::ofstream out(exit_file(alloc_id) + ".tmp");
        out << code;
      }
      ::rename((exit_file(alloc_id) + ".tmp").c_str(),
               exit_file(alloc_id).c_str());
      std::_Exit(code & 0xFF);
    }
    ::close(pipefd[1]);
    pid_t task_pid = 0;
    ssize_t n = ::read(pipefd[0], &task_pid, sizeof(task_pid));
    (void)n;
    ::close(pipefd[0]);
    if (sup > 0) {
      tasks_[alloc_id] = RunningTask{sup, task_pid, alloc_id, log_path,
                                     false, false, 0, ""};
      tasks_[alloc_id].alloc_token = cmd["alloc_token"].as_string();
      persist_state();
      send_event(alloc_id, "running", 0, "");
      std::cerr << "[agent] started " << alloc_id << " supervisor=" << sup
                << " task=" << task_pid
                << (docker ? " (docker)" : " (container)") << std::endl;
    }
  }

  void start_task(const Json& cmd) {
    const std::string& alloc_id = cmd["allocation_id"].as_string();
    if (tasks_.count(alloc_id)) return;  // duplicate start

    std::string log_path =
        config_.work_dir + "/task-" + alloc_id + ".log";
    std::string run_dir = prepare_context(cmd, alloc_id);
    if (config_.runtime == "container" || config_.runtime == "docker") {
      start_detached(cmd, alloc_id, log_path, run_dir,
                     config_.runtime == "docker");
      return;
    }
    pid_t pid = ::fork();
    if (pid == 0) {
      // fate-sharing: if the agent dies (even SIGKILL), its tasks must not
      // become orphans (≈ pid_server/pid_client, harness ipc.py:264-553)
      ::prctl(PR_SET_PDEATHSIG, SIGKILL);
      if (::getppid() == 1) std::_Exit(83);  // agent died before prctl
      exec_task_child(cmd, alloc_id, log_path, run_dir);
    }
    if (pid > 0) {
      tasks_[alloc_id] = RunningTask{pid, 0, alloc_id, log_path, false, false, 0, ""};
      tasks_[alloc_id].alloc_token = cmd["alloc_token"].as_string();
      send_event(alloc_id, "running", 0, "");
      std::cerr << "[agent] started " << alloc_id << " pid=" << pid << std::endl;
    }
  }

  void preempt_task(const std::string& alloc_id) {
    auto it = tasks_.find(alloc_id);
    if (it == tasks_.end() || it->second.preempt_sent) return;
    // cooperative: harness polls the preempt endpoint; SIGTERM is the
    // belt-and-braces (exec/launch.py:18's SLURM SIGTERM semantics).
    // Signal the task, not the supervisor (which must survive to record
    // the exit code). task_pid <= 0 (supervisor fork failure) must never
    // reach kill() — kill(-1, sig) signals everything we can.
    pid_t target = it->second.task_pid > 0 ? it->second.task_pid
                                           : it->second.pid;
    if (target > 0) ::kill(target, SIGTERM);
    it->second.preempt_sent = true;
  }

  void kill_task(const std::string& alloc_id) {
    auto it = tasks_.find(alloc_id);
    if (it == tasks_.end()) return;
    if (config_.runtime == "docker") {
      // the docker CLI process does not forward SIGKILL to the container;
      // double-fork so the helper can't accumulate as a zombie
      std::string name = "dct-task-" + alloc_id;
      pid_t helper = ::fork();
      if (helper == 0) {
        if (::fork() == 0) {
          ::execlp("docker", "docker", "kill", name.c_str(), nullptr);
          std::_Exit(127);
        }
        std::_Exit(0);
      }
      if (helper > 0) ::waitpid(helper, nullptr, 0);
    }
    pid_t target = it->second.task_pid > 0 ? it->second.task_pid
                                           : it->second.pid;
    if (target > 0) ::kill(target, SIGKILL);
  }

  // Reattach after an agent restart (≈ containers/manager.go:76): re-adopt
  // tasks from the state file whose processes still run; report exits for
  // those that finished while the agent was down.
  void reattach_tasks() {
    std::ifstream in(state_file());
    if (!in.good()) return;
    Json state;
    try {
      std::stringstream buf;
      buf << in.rdbuf();
      state = Json::parse(buf.str());
    } catch (const std::exception&) {
      return;
    }
    for (const auto& t : state["tasks"].elements()) {
      const std::string alloc_id = t["allocation_id"].as_string();
      pid_t sup = static_cast<pid_t>(t["supervisor_pid"].as_int());
      pid_t task = static_cast<pid_t>(t["task_pid"].as_int());
      // identity check beats pid reuse (env for exec'd tasks, container
      // name in cmdline for the docker CLI)
      bool alive = pid_alive(task) && proc_matches_task(task, alloc_id);
      if (alive) {
        tasks_[alloc_id] = RunningTask{sup, task, alloc_id,
                                       t["log_path"].as_string(), false,
                                       true, 0, ""};
        tasks_[alloc_id].alloc_token = t["alloc_token"].as_string();
        if (tasks_[alloc_id].alloc_token.empty()) {
          // pre-upgrade state file: under --auth-required the master will
          // 401 this task's log batches — say so rather than losing them
          std::cerr << "[agent] WARNING: reattached " << alloc_id
                    << " without an alloc token (pre-upgrade state file); "
                    << "log shipping will fail if the master requires auth"
                    << std::endl;
        }
        std::cerr << "[agent] reattached " << alloc_id << " task=" << task
                  << std::endl;
        continue;
      }
      // finished (or lost) while we were down: the supervisor's exit file
      // has the code; without it the outcome is unknown -> error
      int exit_code = 1;
      std::string error = "task lost across agent restart";
      std::ifstream ef(exit_file(alloc_id));
      if (ef.good()) {
        ef >> exit_code;
        error = exit_code ? "task failed" : "";
      }
      RunningTask lost{0, 0, alloc_id, t["log_path"].as_string(),
                       false, false, 0, ""};
      lost.alloc_token = t["alloc_token"].as_string();
      ship_logs(lost);
      Json rec = Json::object();
      rec.set("allocation_id", alloc_id).set("exit_code", exit_code)
          .set("error", error);
      pending_exits_.push_back(std::move(rec));
      std::cerr << "[agent] task " << alloc_id
                << " finished while agent was down: exit " << exit_code
                << std::endl;
    }
    persist_state();
  }

  void persist_state() {
    if (config_.runtime == "process") return;  // fate-shared: nothing survives
    Json tasks = Json::array();
    for (const auto& [aid, t] : tasks_) {
      Json j = Json::object();
      j.set("allocation_id", aid)
          .set("supervisor_pid", static_cast<int64_t>(t.pid))
          .set("task_pid", static_cast<int64_t>(t.task_pid))
          .set("log_path", t.log_path)
          // needed so a reattached task's logs can still authenticate;
          // the file is 0600 below — it now holds live credentials
          .set("alloc_token", t.alloc_token);
      tasks.push_back(j);
    }
    Json state = Json::object();
    state.set("tasks", tasks);
    // owner-only from the first byte: the state file carries alloc tokens,
    // which on a multi-user host must not be readable by other accounts
    std::string tmp = state_file() + ".tmp";
    int fd = ::open(tmp.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0600);
    if (fd < 0) return;
    std::string data = state.dump();
    ssize_t off = 0;
    while (off < static_cast<ssize_t>(data.size())) {
      ssize_t n = ::write(fd, data.data() + off, data.size() - off);
      if (n <= 0) break;
      off += n;
    }
    ::close(fd);
    ::rename(tmp.c_str(), state_file().c_str());
  }

  void finish_task(const std::string& alloc_id, const RunningTask& task,
                   int exit_code) {
    ship_logs(task);
    // fast path now; the heartbeat carries it again until acked
    send_event(alloc_id, "exited", exit_code,
               exit_code ? "task failed" : "");
    Json rec = Json::object();
    rec.set("allocation_id", alloc_id).set("exit_code", exit_code)
        .set("error", exit_code ? "task failed" : "");
    pending_exits_.push_back(std::move(rec));
    std::cerr << "[agent] task " << alloc_id << " exited " << exit_code
              << std::endl;
  }

  void reap_tasks() {
    bool changed = false;
    for (auto it = tasks_.begin(); it != tasks_.end();) {
      const RunningTask& task = it->second;
      if (task.adopted) {
        // not our child: poll the TASK's liveness with the identity check
        // (a bare kill(pid, 0) would follow a reused pid forever)
        if (pid_alive(task.task_pid) &&
            proc_matches_task(task.task_pid, it->first)) {
          it->second.dead_polls = 0;
          ++it;
          continue;
        }
        // task gone: the supervisor writes the exit file just before it
        // exits — give it a grace window before assuming a crash
        std::ifstream ef(exit_file(it->first));
        if (!ef.good() && ++it->second.dead_polls < 20) {
          ++it;
          continue;
        }
        int exit_code = 1;
        if (ef.good()) ef >> exit_code;
        finish_task(it->first, task, exit_code);
        it = tasks_.erase(it);
        changed = true;
        continue;
      }
      int status = 0;
      pid_t done = ::waitpid(task.pid, &status, WNOHANG);
      if (done == task.pid) {
        // process runtime: the child's status; container/docker: the
        // supervisor exits with the task's code
        int exit_code = WIFEXITED(status) ? WEXITSTATUS(status)
                                          : 128 + WTERMSIG(status);
        finish_task(it->first, task, exit_code);
        it = tasks_.erase(it);
        changed = true;
      } else {
        ++it;
      }
    }
    if (changed) persist_state();
  }

  void ship_logs(const RunningTask& task) {
    std::ifstream in(task.log_path);
    if (!in.good()) return;
    Json logs = Json::array();
    std::string line;
    int count = 0;
    while (std::getline(in, line) && count < 5000) {
      logs.push_back(line);
      ++count;
    }
    Json body = Json::object();
    body.set("logs", logs);
    std::map<std::string, std::string> headers;
    if (!task.alloc_token.empty()) {
      headers["Authorization"] = "Bearer " + task.alloc_token;
    }
    http_request(config_.master_host, config_.master_port, "POST",
                 "/api/v1/allocations/" + task.allocation_id + "/logs",
                 body.dump(), 10, headers);
  }

  void send_event(const std::string& alloc_id, const std::string& event,
                  int exit_code, const std::string& error) {
    Json body = Json::object();
    body.set("allocation_id", alloc_id).set("event", event)
        .set("exit_code", exit_code).set("error", error);
    http_request(config_.master_host, config_.master_port, "POST",
                 "/api/v1/agents/" + config_.id + "/task_event", body.dump(),
                 10);
  }

  AgentConfig config_;
  std::map<std::string, RunningTask> tasks_;
  std::vector<Json> pending_exits_;  // unacked exit reports
};

}  // namespace
}  // namespace dct

namespace {
// agent config file (≈ agent.yaml via viper, options.go:47); the parser is
// shared with the master (config_file.h) so the format cannot drift
int apply_agent_config_file(const std::string& path,
                            dct::AgentConfig* config) {
  std::map<std::string, std::string> values;
  try {
    values = dct::configfile::parse(path);
  } catch (const std::exception& e) {
    std::cerr << e.what() << "\n";
    return 2;
  }
  for (const auto& [key, value] : values) {
    if (key == "master_host") config->master_host = value;
    else if (key == "master_port") config->master_port = std::atoi(value.c_str());
    else if (key == "id") config->id = value;
    else if (key == "resource_pool") config->resource_pool = value;
    else if (key == "slots") config->slots = std::atoi(value.c_str());
    else if (key == "topology") config->topology = value;
    else if (key == "work_dir") config->work_dir = value;
    else if (key == "runtime") config->runtime = value;
    else if (key == "docker_image") config->docker_image = value;
    else {
      std::cerr << "unknown config key '" << key << "' in " << path << "\n";
      return 2;
    }
  }
  return 0;
}
}  // namespace

int main(int argc, char** argv) {
  dct::AgentConfig config;
  for (int i = 1; i < argc; ++i) {
    if (!std::strcmp(argv[i], "--config") && i + 1 < argc) {
      int rc = apply_agent_config_file(argv[i + 1], &config);
      if (rc) return rc;
    }
  }
  for (int i = 1; i < argc; ++i) {
    if (!std::strcmp(argv[i], "--config") && i + 1 < argc) {
      ++i;  // applied above; flags override
    } else if (!std::strcmp(argv[i], "--master-host") && i + 1 < argc) {
      config.master_host = argv[++i];
    } else if (!std::strcmp(argv[i], "--master-port") && i + 1 < argc) {
      config.master_port = std::atoi(argv[++i]);
    } else if (!std::strcmp(argv[i], "--id") && i + 1 < argc) {
      config.id = argv[++i];
    } else if (!std::strcmp(argv[i], "--resource-pool") && i + 1 < argc) {
      config.resource_pool = argv[++i];
    } else if (!std::strcmp(argv[i], "--slots") && i + 1 < argc) {
      config.slots = std::atoi(argv[++i]);
    } else if (!std::strcmp(argv[i], "--topology") && i + 1 < argc) {
      config.topology = argv[++i];
    } else if (!std::strcmp(argv[i], "--work-dir") && i + 1 < argc) {
      config.work_dir = argv[++i];
    } else if (!std::strcmp(argv[i], "--runtime") && i + 1 < argc) {
      config.runtime = argv[++i];
      if (config.runtime != "process" && config.runtime != "container" &&
          config.runtime != "docker") {
        std::cerr << "unknown runtime '" << config.runtime
                  << "' (process|container|docker)\n";
        return 2;
      }
    } else if (!std::strcmp(argv[i], "--docker-image") && i + 1 < argc) {
      config.docker_image = argv[++i];
    } else if (!std::strcmp(argv[i], "--help")) {
      std::cout << "usage: dct-agent [--config FILE] "
                   "[--master-host H] [--master-port P] "
                   "[--id ID] [--resource-pool POOL] [--slots N] "
                   "[--topology T] [--work-dir DIR] "
                   "[--runtime process|container|docker] "
                   "[--docker-image IMG]\n";
      return 0;
    }
  }
  dct::Agent agent(config);
  return agent.run();
}
