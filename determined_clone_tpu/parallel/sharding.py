"""Partition-spec machinery: how parameter/activation pytrees map onto the mesh.

This subsumes the reference's DeepSpeed-ZeRO integration
(harness/determined/pytorch/deepspeed/_deepspeed_trial.py): ZeRO stages become
PartitionSpecs on params/optimizer state instead of a launched engine —

  ZeRO-1  optimizer state sharded      → opt state gets fsdp specs
  ZeRO-2  + gradients sharded          → XLA reduce-scatters grads for us
  ZeRO-3  + parameters sharded         → params get fsdp specs, XLA
                                          all-gathers them per-layer

Two mechanisms:
 1. Rule-based: regex over the param path → PartitionSpec (models define
    megatron-style TP rules this way).
 2. Automatic FSDP: for leaves no rule matches, shard the largest
    fsdp-divisible axis (the ZeRO-3 default policy).
"""
from __future__ import annotations

import dataclasses
import re
from typing import Any, List, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _format_keypath(keypath: Any) -> str:
    parts = []
    for k in keypath:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        else:
            parts.append(str(k))
    return "/".join(parts)


def tree_paths_and_leaves(tree: Any) -> List[Tuple[str, Any]]:
    """Flatten a pytree into ('a/b/c', leaf) pairs using dict/list keys."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [(_format_keypath(kp), leaf) for kp, leaf in flat]


@dataclasses.dataclass
class ShardingRules:
    """Ordered (regex, PartitionSpec) rules; first match wins.

    ``fsdp_axis`` enables the automatic ZeRO-3 fallback for unmatched leaves;
    set it to None for pure-TP or replicated layouts.
    """

    rules: Sequence[Tuple[str, P]] = ()
    fsdp_axis: Optional[str] = "fsdp"

    def spec_for(self, path: str, leaf: Any, mesh: Mesh) -> P:
        for pattern, spec in self.rules:
            if re.search(pattern, path):
                return _drop_trivial_axes(spec, mesh)
        if self.fsdp_axis and self.fsdp_axis in mesh.shape:
            return _auto_fsdp_spec(leaf, mesh, self.fsdp_axis)
        return P()

    def shardings_for(self, tree: Any, mesh: Mesh) -> Any:
        """A pytree of NamedShardings congruent with ``tree``."""
        flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
        specs = []
        for keypath, leaf in flat:
            path = _format_keypath(keypath)
            specs.append(NamedSharding(mesh, self.spec_for(path, leaf, mesh)))
        return jax.tree_util.tree_unflatten(treedef, specs)


def _trim(entries: Sequence[Any]) -> P:
    """Build a P with trailing Nones stripped (PartitionSpec('x', None) and
    PartitionSpec('x') shard identically but compare unequal)."""
    entries = list(entries)
    while entries and entries[-1] is None:
        entries.pop()
    return P(*entries)


def _drop_trivial_axes(spec: P, mesh: Mesh) -> P:
    """Remove axes of size 1 from a spec: XLA would too, but pruning up front
    keeps sharding metadata (and donation warnings) clean."""
    def prune(entry):
        if entry is None:
            return None
        if isinstance(entry, (tuple, list)):
            kept = tuple(a for a in entry if mesh.shape.get(a, 1) > 1)
            return kept if len(kept) > 1 else (kept[0] if kept else None)
        return entry if mesh.shape.get(entry, 1) > 1 else None

    return _trim([prune(e) for e in spec])


def _auto_fsdp_spec(leaf: Any, mesh: Mesh, axis: str) -> P:
    """ZeRO-3 default policy: shard the largest dim divisible by the fsdp
    size; replicate small/indivisible leaves (biases, scalars, norms)."""
    n = mesh.shape[axis]
    shape = getattr(leaf, "shape", ())
    # Only matrix-shaped leaves are worth scattering; vectors (biases, norm
    # scales) are bandwidth-trivial and stay replicated.
    if n <= 1 or len(shape) < 2:
        return P()
    best_dim, best_size = -1, 0
    for i, s in enumerate(shape):
        if s % n == 0 and s > best_size:
            best_dim, best_size = i, s
    if best_dim < 0 or best_size < 2 * n:  # don't shard tiny leaves
        return P()
    entries: List[Any] = [None] * len(shape)
    entries[best_dim] = axis
    return _trim(entries)


def batch_spec(extra_dims: int = 0) -> P:
    """Sharding for data batches: leading batch dim split over (dp, fsdp) —
    fsdp ranks are data-parallel workers in ZeRO semantics."""
    return P(("dp", "fsdp"), *([None] * extra_dims))


def batch_seq_spec() -> P:
    """[batch, seq, ...] activations with sequence-parallel sharding of the
    sequence dim (the first-class SP axis the reference lacks, SURVEY.md §5.7)."""
    return P(("dp", "fsdp"), "sp")


def replicated(mesh: Mesh, tree: Any) -> Any:
    """NamedShardings that fully replicate ``tree``."""
    return jax.tree.map(lambda _: NamedSharding(mesh, P()), tree)


def shard_put(tree: Any, shardings: Any) -> Any:
    """device_put a pytree onto its shardings (host → HBM, sharded)."""
    return jax.device_put(tree, shardings)


def constrain(tree: Any, mesh: Mesh, spec: P) -> Any:
    """with_sharding_constraint over every leaf — the in-jit annotation that
    steers XLA's partitioner at activation boundaries."""
    sharding = NamedSharding(mesh, _drop_trivial_axes(spec, mesh))
    return jax.tree.map(lambda x: jax.lax.with_sharding_constraint(x, sharding), tree)
