"""Parallelism: meshes, partition specs, pipeline & sequence parallelism.

TPU-native superset of the reference's parallelism inventory (SURVEY.md §2.7):
DP / FSDP(ZeRO) / TP / PP plus first-class SP and EP.
"""
from determined_clone_tpu.parallel.mesh import (
    AXES,
    MeshSpec,
    data_parallel_submesh_size,
    make_mesh,
    make_multislice_mesh,
    mesh_axis_size,
    single_device_mesh,
)
from determined_clone_tpu.parallel.pipeline import (
    pipeline_apply,
    pipeline_bubble_fraction,
    pipeline_stage_spec,
)
from determined_clone_tpu.parallel.sharding import (
    ShardingRules,
    batch_spec,
    batch_seq_spec,
    constrain,
    replicated,
    shard_put,
    tree_paths_and_leaves,
)

__all__ = [
    "AXES",
    "MeshSpec",
    "data_parallel_submesh_size",
    "make_mesh",
    "make_multislice_mesh",
    "mesh_axis_size",
    "single_device_mesh",
    "pipeline_apply",
    "pipeline_bubble_fraction",
    "pipeline_stage_spec",
    "ShardingRules",
    "batch_spec",
    "batch_seq_spec",
    "constrain",
    "replicated",
    "shard_put",
    "tree_paths_and_leaves",
]
