"""Pipeline parallelism over the mesh's ``pp`` axis — GPipe-style microbatching.

The reference gets pipeline parallelism by launching DeepSpeed and wrapping its
``PipelineParallelGrid`` topology (harness/determined/pytorch/deepspeed/_mpu.py:38,
SURVEY.md §2.7). Here PP is a mesh axis like any other: model blocks keep their
stacked ``[L, ...]`` leading layer dim, the layer dim is sharded over ``pp``, and
a ``jax.shard_map`` that is *manual only over pp* (every other axis — dp/fsdp/
tp/sp/ep — stays under the automatic partitioner) rotates activations around
the stage ring with ``lax.ppermute`` while each stage runs its local layers.

Schedule: GPipe. For M microbatches and P stages the loop runs M + P - 1 ticks;
tick t has stage s working on microbatch t - s (when in range), so the steady
state keeps every stage busy and the bubble is the usual (P-1)/(M+P-1) fraction.
The whole schedule is one ``lax.scan`` — one compiled tick body, reverse-mode
differentiable, no Python-level unrolling.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

StageFn = Callable[[Any, Any], Any]


def pipeline_stage_spec() -> P:
    """PartitionSpec for stacked-layer params entering the pipeline: the
    leading [L] layer dim is split over pp into per-stage slices."""
    return P("pp")


def pipeline_apply(
    stage_fn: StageFn,
    stacked_params: Any,
    x: Any,
    *,
    mesh: Mesh,
    num_microbatches: int,
    axis_name: str = "pp",
) -> Any:
    """Run carrier ``x`` through all pipeline stages of a stacked-layer model.

    ``stage_fn(local_params, x_mb) -> y_mb`` applies ONE stage's layers: it
    receives the stage's slice of ``stacked_params`` (leading dim L/P) and one
    microbatch of the carrier, and must preserve the carrier's structure,
    shapes, and dtypes (residual-stream semantics — true of transformer
    blocks; side outputs like MoE aux losses ride along as extra leaves).

    ``stacked_params`` is any pytree whose every leaf has a leading layer dim
    divisible by the pp size. ``x`` is a pytree whose every leaf has a leading
    batch dim ``B`` divisible by ``num_microbatches``; leaves are split into
    microbatches along dim 0.

    Inside the shard_map only ``pp`` is manual; dp/fsdp/tp/sp/ep sharding of
    the batch and params keeps flowing through XLA's automatic partitioner, so
    PP composes with every other axis.
    """
    n_stages = mesh.shape[axis_name]
    if n_stages == 1:
        return stage_fn(stacked_params, x)
    M = num_microbatches
    for path, leaf in jax.tree_util.tree_flatten_with_path(x)[0]:
        if leaf.ndim == 0:
            raise ValueError(
                f"carrier leaf {jax.tree_util.keystr(path)} is 0-d; every "
                f"carrier leaf needs a leading batch dim to split into "
                f"microbatches (carry scalars as [B]-shaped rows instead)"
            )
        if leaf.shape[0] % M != 0:
            raise ValueError(
                f"carrier leaf batch dim {leaf.shape[0]} not divisible by "
                f"num_microbatches={M}"
            )

    # XLA's CPU backend check-fails on sub-f32 psums over a manual axis while
    # other axes stay auto ("Invalid binary instruction opcode copy" — hit by
    # both the output-collect psum and the implicit boundary psum that the
    # transpose inserts for replicated inputs). On CPU (tests, driver dryrun)
    # transport the carrier in f32 and hand stage_fn its original dtypes; on
    # TPU keep the carrier's own dtypes (bf16 ring transport at full rate).
    widen_cpu = jax.default_backend() == "cpu"
    carrier_dtypes = jax.tree.map(lambda a: a.dtype, x)

    def to_wire(tree):
        if not widen_cpu:
            return tree
        return jax.tree.map(
            lambda a: a.astype(jnp.float32)
            if jnp.issubdtype(a.dtype, jnp.inexact) else a,
            tree,
        )

    def from_wire(tree):
        if not widen_cpu:
            return tree
        return jax.tree.map(lambda a, dt: a.astype(dt), tree, carrier_dtypes)

    user_stage_fn = stage_fn

    def stage_fn(local, carrier):  # noqa: F811 — wire-dtype adapter
        return to_wire(user_stage_fn(local, from_wire(carrier)))

    def pipelined(params_local: Any, x_full: Any) -> Any:
        stage = jax.lax.axis_index(axis_name)
        mb = jax.tree.map(
            lambda a: a.reshape(M, a.shape[0] // M, *a.shape[1:]), x_full
        )
        ring = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(carry, t):
            recv, outbuf = carry
            # Stage 0 feeds fresh microbatches; others consume the ring.
            x_mb = jax.tree.map(
                lambda a: jax.lax.dynamic_index_in_dim(
                    a, jnp.clip(t, 0, M - 1), 0, keepdims=False
                ),
                mb,
            )
            inp = jax.tree.map(
                lambda fresh, r: jnp.where(stage == 0, fresh, r), x_mb, recv
            )
            out = stage_fn(params_local, inp)
            send = jax.tree.map(
                lambda a: jax.lax.ppermute(a, axis_name, ring), out
            )
            # Only the last stage's writes are kept (masked after the scan).
            # Clipping makes early garbage land in slot 0, overwritten at
            # t = P-1 by the real microbatch 0 (t ascending ⇒ last write wins).
            slot = jnp.clip(t - (n_stages - 1), 0, M - 1)
            outbuf = jax.tree.map(
                lambda buf, o: jax.lax.dynamic_update_index_in_dim(
                    buf, o, slot, 0
                ),
                outbuf, out,
            )
            return (send, outbuf), None

        # The carry becomes pp-varying after the first ppermute; mark the
        # zero-init that way up front so the scan's carry type is stable.
        def varying_zeros(a):
            return jax.lax.pcast(a, (axis_name,), to="varying")

        init = (
            jax.tree.map(lambda a: varying_zeros(jnp.zeros_like(a[0])), mb),
            jax.tree.map(lambda a: varying_zeros(jnp.zeros_like(a)), mb),
        )
        (_, outbuf), _ = jax.lax.scan(tick, init, jnp.arange(M + n_stages - 1))
        # Valid outputs live on the last stage; psum replicates them across pp
        # (cheap at [B, ...] size, and makes the result pp-invariant).
        def collect(a):
            masked = jnp.where(stage == n_stages - 1, a, jnp.zeros_like(a))
            return jax.lax.psum(masked, axis_name)

        outbuf = jax.tree.map(collect, outbuf)
        return jax.tree.map(
            lambda a: a.reshape(a.shape[0] * a.shape[1], *a.shape[2:]), outbuf
        )

    out = jax.shard_map(
        pipelined,
        mesh=mesh,
        in_specs=(
            jax.tree.map(lambda _: pipeline_stage_spec(), stacked_params),
            jax.tree.map(lambda _: P(), x),
        ),
        out_specs=jax.tree.map(lambda _: P(), x),
        axis_names=frozenset({axis_name}),
    )(stacked_params, to_wire(x))
    return from_wire(out)


def pipeline_bubble_fraction(num_microbatches: int, n_stages: int) -> float:
    """Idle fraction of the GPipe schedule — exposed for the autotuner."""
    return (n_stages - 1) / (num_microbatches + n_stages - 1)
