"""Device-mesh construction — the TPU-native heart of the parallelism story.

The reference has no mesh concept: its parallelism is launched NCCL worlds
(SURVEY.md §2.7-2.8). Here, every parallelism strategy is an axis of one
``jax.sharding.Mesh``:

  axis   meaning                            reference analogue
  -----  ---------------------------------  -------------------------------
  dp     data parallel (batch split)        horovod / torch DDP allreduce
  fsdp   fully-sharded data parallel        DeepSpeed ZeRO 1-3
  tp     tensor (megatron) parallel         DeepSpeed/Megatron slice ranks
  pp     pipeline parallel                  DeepSpeed PipelineParallelGrid
  sp     sequence/context parallel          (absent in reference — §5.7)
  ep     expert parallel (MoE)              (absent in reference)

Unused axes keep size 1 so a single PartitionSpec vocabulary works at every
scale; XLA's partitioner drops size-1 axes at compile time, so they are free.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Any, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

# Canonical axis order. dp and fsdp are outermost (gradient/param reduction
# scopes ride DCN across hosts if they must); tp/sp innermost (highest-traffic
# collectives stay on ICI neighbors).
AXES: Tuple[str, ...] = ("dp", "fsdp", "pp", "ep", "sp", "tp")


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Logical mesh shape. -1 for at most one axis means "absorb remaining
    devices" (like a -1 in a reshape)."""

    dp: int = -1
    fsdp: int = 1
    pp: int = 1
    ep: int = 1
    sp: int = 1
    tp: int = 1

    def axis_sizes(self) -> Tuple[int, ...]:
        return (self.dp, self.fsdp, self.pp, self.ep, self.sp, self.tp)

    def resolve(self, n_devices: int) -> "MeshSpec":
        """Fill in the -1 axis given a device count; validate the product."""
        sizes = list(self.axis_sizes())
        wild = [i for i, s in enumerate(sizes) if s == -1]
        if len(wild) > 1:
            raise ValueError(f"at most one mesh axis may be -1, got {self}")
        fixed = math.prod(s for s in sizes if s != -1)
        if wild:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"cannot fit mesh {self} on {n_devices} devices: fixed axes "
                    f"product {fixed} does not divide {n_devices}"
                )
            sizes[wild[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh {self} wants {fixed} devices but {n_devices} are available"
            )
        for name, s in zip(AXES, sizes):
            if s < 1:
                raise ValueError(f"mesh axis {name} must be >= 1, got {s}")
        return MeshSpec(*sizes)

    @staticmethod
    def from_dict(d: dict) -> "MeshSpec":
        unknown = set(d) - set(AXES)
        if unknown:
            raise ValueError(f"unknown mesh axes {sorted(unknown)}; valid: {AXES}")
        return MeshSpec(**{k: int(v) for k, v in d.items()})

    def to_dict(self) -> dict:
        return {a: s for a, s in zip(AXES, self.axis_sizes())}


def make_mesh(
    spec: Optional[MeshSpec] = None,
    devices: Optional[Sequence[Any]] = None,
) -> Mesh:
    """Build a Mesh laid out so the innermost logical axes map to physically
    adjacent devices (ICI neighbors on a real slice).

    jax.devices() on TPU enumerates chips in torus-major order, so reshaping
    that flat order with tp innermost keeps tp collectives on nearest
    neighbors — the layout rule from the scaling-book recipe.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    spec = (spec or MeshSpec()).resolve(len(devices))
    dev_array = np.asarray(devices, dtype=object).reshape(spec.axis_sizes())
    return Mesh(dev_array, AXES)


def make_multislice_mesh(
    ici_spec: MeshSpec,
    dcn_spec: MeshSpec,
    devices: Optional[Sequence[Any]] = None,
) -> Mesh:
    """Hybrid ICI×DCN mesh for multislice TPU (SURVEY.md §7 step 7).

    ``ici_spec`` factorizes the chips WITHIN one slice (tp/sp innermost —
    their collectives stay on the slice's ICI torus); ``dcn_spec``
    factorizes ACROSS slices (normally only dp/fsdp/pp > 1 — gradient
    reduction and pipeline hops are the traffic that tolerates DCN
    latency). Each combined mesh axis is dcn-major: neighboring indices
    stay within a slice, so XLA emits hierarchical collectives (intra-slice
    ICI reduce, inter-slice DCN exchange) from the same PartitionSpecs
    used single-slice.

    Devices must enumerate slice-major (jax.devices() does on multislice;
    tests model slices as contiguous groups of CPU devices).
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    n_slices = math.prod(s for s in dcn_spec.axis_sizes() if s != -1)
    if any(s == -1 for s in dcn_spec.axis_sizes()):
        raise ValueError("dcn_spec must be fully specified (no -1 axes)")
    if len(devices) % n_slices != 0:
        raise ValueError(
            f"{len(devices)} devices do not split into {n_slices} slices")
    per_slice = len(devices) // n_slices
    ici = ici_spec.resolve(per_slice)

    # [*dcn_sizes, *ici_sizes] -> interleave (dcn_i, ici_i) per axis ->
    # merge into combined per-axis sizes (dcn-major within each axis)
    dcn_sizes = dcn_spec.axis_sizes()
    ici_sizes = ici.axis_sizes()
    arr = np.asarray(devices, dtype=object).reshape(*dcn_sizes, *ici_sizes)
    n = len(AXES)
    order = []
    for i in range(n):
        order.extend([i, n + i])
    arr = arr.transpose(order)
    combined = tuple(d * s for d, s in zip(dcn_sizes, ici_sizes))
    return Mesh(arr.reshape(combined), AXES)


def single_device_mesh(device: Optional[Any] = None) -> Mesh:
    """A 1×1×…×1 mesh over one device; lets the same pjit code path run
    unsharded (the reference's single-slot trial case)."""
    if device is None:
        device = jax.devices()[0]
    return make_mesh(MeshSpec(dp=1), [device])


def mesh_axis_size(mesh: Mesh, *axes: str) -> int:
    return math.prod(mesh.shape[a] for a in axes)


def data_parallel_submesh_size(mesh: Mesh) -> int:
    """Total batch-sharding degree: dp × fsdp (fsdp shards the batch too —
    ZeRO semantics: data-parallel gradients, sharded params/optimizer)."""
    return mesh_axis_size(mesh, "dp", "fsdp")
