"""Measured multichip scaling lane: MULTICHIP promoted from dryrun to data.

The dryrun (``__graft_entry__.dryrun_multichip``) proves the sharded
train step *runs* on an ``--xla_force_host_platform_device_count`` mesh;
this module measures it. For each parallelism axis (dp / fsdp / tp) it
builds an all-devices-on-that-axis mesh, AOT-captures the train step
(telemetry/xla.py — fingerprint, cost analysis, and the post-SPMD
collective accounting of telemetry/collectives.py), runs a few timed
steps, and reports:

- **scaling efficiency** per axis: ``thr_N / (N * thr_1)`` against a
  single-device baseline measured in the same process. dp/fsdp scale
  weakly (global batch = per-device batch x N), tp strongly (fixed
  batch) — the uniform formula makes ideal scaling 1.0 in both regimes;
- **measured vs analytic MFU**: ``cost_analysis()`` FLOPs of the
  partitioned per-device module vs the flops.py formula, both over the
  same measured step rate;
- **collective structure**: op/byte counts per (kind, axis) and the
  structure fingerprint tools/bench_gate.py watches for drift;
- **per-device peak bytes** (live-buffer residency, telemetry/device.py)
  and a cross-device straggler summary (telemetry/mesh.py) over the
  timed steps.

The numbers are simulation numbers — virtual devices timeshare one host,
so absolute efficiency is pessimistic — but they are *stable* on a given
machine, which is all a regression gate needs: a sharding change that
halves dp efficiency on the simulated mesh will do worse on real ICI.

Device count is fixed at backend init, so ``bench.py`` runs this as a
subprocess per mesh size: ``python -m
determined_clone_tpu.parallel.scaling_bench --devices N --json``.
Emits one MULTICHIP_SCHEMA_VERSION artifact (telemetry/mesh.py) per run.
"""
from __future__ import annotations

import json
import time
from typing import Any, Dict, List, Optional, Sequence

DEFAULT_AXES = ("dp", "fsdp", "tp")
PER_DEVICE_BATCH = 4
SEQ_LEN = 64


def _bench_config(gpt_mod: Any) -> Any:
    """Tiny-but-shardable GPT: n_heads/d_ff divisible by every axis size
    this lane runs (tp up to 16), big enough to emit real collectives."""
    return gpt_mod.GPTConfig(
        vocab_size=256, n_layers=2, d_model=64, n_heads=16, d_ff=256,
        max_seq_len=SEQ_LEN, remat=True,
    )


def _measure_mesh(mesh: Any, batch_size: int, *, steps: int,
                  warmup: int, registry: Optional[Any] = None
                  ) -> Dict[str, Any]:
    """Build + AOT-capture + time the sharded train step on one mesh.

    Returns throughput, per-step seconds, the compile record's collective
    summary / fingerprint / comm fraction, measured + analytic MFU
    inputs, and per-device completion durations for the straggler view.
    """
    import jax
    import optax
    from jax.sharding import NamedSharding

    from determined_clone_tpu.models import gpt
    from determined_clone_tpu.parallel.sharding import shard_put
    from determined_clone_tpu.telemetry import flops as flops_mod
    from determined_clone_tpu.telemetry.mesh import (
        MeshStragglerDetector,
        per_device_completion_seconds,
    )
    from determined_clone_tpu.training.train_step import (
        capture_compile,
        create_train_state,
        make_train_step,
        state_shardings,
    )

    cfg = _bench_config(gpt)
    params = gpt.init(jax.random.PRNGKey(0), cfg)
    tx = optax.adamw(1e-3, weight_decay=0.01)
    state = create_train_state(params, tx, jax.random.PRNGKey(1))
    sharding = state_shardings(state, mesh, gpt.GPT_SHARDING_RULES)
    state = shard_put(state, sharding)
    batch_sharding = NamedSharding(mesh, gpt.TOKENS_SPEC)
    tokens = jax.random.randint(
        jax.random.PRNGKey(2), (batch_size, SEQ_LEN), 0, cfg.vocab_size)
    tokens = shard_put(tokens, batch_sharding)

    def loss_fn(p, batch, rng):
        return gpt.loss_fn(p, cfg, batch[:, :-1], batch[:, 1:]), {}

    step = make_train_step(
        loss_fn, tx, mesh=mesh, state_sharding=sharding,
        batch_sharding=batch_sharding)
    step, record = capture_compile(
        step, (state, tokens), program="scaling_bench",
        registry=registry, mesh=mesh)

    detector = MeshStragglerDetector(registry)
    for _ in range(max(0, warmup)):
        state, metrics = step(state, tokens)
        jax.block_until_ready(metrics)
    t_start = time.perf_counter()
    step_seconds: List[float] = []
    for _ in range(max(1, steps)):
        t0 = time.perf_counter()
        state, metrics = step(state, tokens)
        durations = per_device_completion_seconds(metrics, t0)
        jax.block_until_ready(metrics)
        step_seconds.append(time.perf_counter() - t0)
        if durations:
            detector.observe(durations)
    elapsed = time.perf_counter() - t_start

    n = mesh.devices.size
    sps = len(step_seconds) / elapsed if elapsed > 0 else 0.0
    platform = mesh.devices.flat[0].platform
    peak, peak_label = flops_mod.peak_flops_estimate(platform)
    analytic = flops_mod.gpt_train_step_flops(cfg, batch_size, SEQ_LEN - 1)
    mfu_analytic = flops_mod.mfu(analytic.total * sps, peak, n)
    mfu_measured = None
    if record is not None and record.flops:
        # cost_analysis flops describe the per-device partitioned module:
        # total program flops/exec = flops * n, over n devices of peak
        mfu_measured = flops_mod.mfu(record.flops * n * sps, peak, n)
    from determined_clone_tpu.telemetry.device import (
        live_buffer_bytes_by_device,
    )

    # captured while state/tokens are still live — per-device residency
    # of the sharded train state on THIS mesh
    live_bytes = {dev: b for dev, b in
                  live_buffer_bytes_by_device().items()}
    out: Dict[str, Any] = {
        "mesh_shape": {k: int(v) for k, v in dict(mesh.shape).items()},
        "per_device_live_bytes": dict(sorted(live_bytes.items())),
        "batch_size": int(batch_size),
        "steps_timed": len(step_seconds),
        "step_seconds_mean": elapsed / max(1, len(step_seconds)),
        "throughput_samples_per_sec": batch_size * sps,
        "mfu_analytic": mfu_analytic,
        "mfu_measured": mfu_measured,
        "peak_flops_provenance": peak_label,
        "straggler": detector.summary(),
    }
    if record is not None:
        out["program_fingerprint"] = record.fingerprint[:16]
        out["compile_seconds"] = (record.lower_seconds
                                  + record.compile_seconds)
        if record.collectives is not None:
            out["collectives"] = record.collectives.as_dict()
        if record.comm_fraction is not None:
            out["comm_compute_fraction"] = record.comm_fraction
    return out


def run_scaling_bench(n_devices: int, *,
                      axes: Sequence[str] = DEFAULT_AXES,
                      steps: int = 3, warmup: int = 1,
                      registry: Optional[Any] = None) -> Dict[str, Any]:
    """Measure per-axis scaling on an ``n_devices`` mesh (already forced
    via ``--xla_force_host_platform_device_count`` / host steering).

    Returns one MULTICHIP schema_version-1 artifact
    (``telemetry.mesh.validate_multichip`` is the contract).
    """
    import jax

    from determined_clone_tpu.parallel.mesh import MeshSpec, make_mesh
    from determined_clone_tpu.telemetry.mesh import MULTICHIP_SCHEMA_VERSION

    devices = jax.devices()[:n_devices]
    if len(devices) < n_devices:
        raise RuntimeError(
            f"need {n_devices} devices, have {len(devices)}; run under "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={n_devices}")

    baseline_mesh = make_mesh(MeshSpec(), devices[:1])
    baseline = _measure_mesh(baseline_mesh, PER_DEVICE_BATCH,
                             steps=steps, warmup=warmup)
    thr1 = baseline["throughput_samples_per_sec"]

    peaks: Dict[str, float] = {}
    meshes: Dict[str, Dict[str, Any]] = {}
    for axis in axes:
        # MeshSpec defaults dp to the -1 wildcard; pin it so the measured
        # axis is the only one absorbing the devices
        spec_kwargs = {"dp": 1, axis: n_devices}
        mesh = make_mesh(MeshSpec(**spec_kwargs), devices)
        # dp/fsdp scale weakly (batch grows with the mesh); tp strongly
        # (model dims shard, batch fixed) — efficiency thr_N/(N*thr_1)
        # targets 1.0 in both regimes
        batch = (PER_DEVICE_BATCH * n_devices if axis in ("dp", "fsdp")
                 else PER_DEVICE_BATCH)
        run = _measure_mesh(mesh, batch, steps=steps, warmup=warmup,
                            registry=registry)
        thr_n = run["throughput_samples_per_sec"]
        run["scaling_efficiency"] = (
            thr_n / (n_devices * thr1) if thr1 > 0 else None)
        meshes[axis] = run
        for dev, b in run.get("per_device_live_bytes", {}).items():
            peaks[dev] = max(peaks.get(dev, 0.0), b)

    return {
        "schema_version": MULTICHIP_SCHEMA_VERSION,
        "n_devices": int(n_devices),
        "platform": devices[0].platform,
        "baseline": baseline,
        "meshes": meshes,
        "per_device_peak_bytes": dict(sorted(peaks.items())),
    }


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        description="measured multichip scaling lane (simulated mesh)")
    parser.add_argument("--devices", type=int, default=8)
    parser.add_argument("--axes", default=",".join(DEFAULT_AXES),
                        help="comma-separated mesh axes to measure")
    parser.add_argument("--steps", type=int, default=3)
    parser.add_argument("--warmup", type=int, default=1)
    parser.add_argument("--json", action="store_true",
                        help="emit the artifact as one JSON line")
    args = parser.parse_args(argv)

    # steer before any backend init: device count is fixed at first use
    from determined_clone_tpu.utils.host_steering import steer_to_host_cpu

    steer_to_host_cpu(args.devices)
    result = run_scaling_bench(
        args.devices,
        axes=[a.strip() for a in args.axes.split(",") if a.strip()],
        steps=args.steps, warmup=args.warmup)
    if args.json:
        print(json.dumps(result))
    else:
        print(json.dumps(result, indent=2, sort_keys=True))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
